"""Mixture-of-Experts (expert parallelism).

Counterpart of the reference's ``deepspeed/moe/`` package (layer.py:17 MoE,
sharded_moe.py, experts.py, mappings.py)."""

from .sharded_moe import (TopKGate, moe_layer, top1gating, top2gating)
from .layer import MoE

__all__ = ["MoE", "TopKGate", "moe_layer", "top1gating", "top2gating"]
