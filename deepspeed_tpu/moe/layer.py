"""MoE module: owns expert params + gate (reference moe/layer.py:17 MoE).

Functional style matching the rest of the model zoo: ``init(rng) ->
params``, ``apply(params, x, rng=, train=) -> (y, l_aux, exp_counts)``.
Stackable: a leading layer dim on every param works under ``lax.scan``
(init with ``stack=L``).
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sharded_moe import (TopKGate, moe_layer, moe_layer_ragged,
                          moe_layer_ragged_ep)


class MoE:
    def __init__(self, hidden_size, ffn_hidden_size=None, num_experts=8,
                 k=1, capacity_factor=1.0, eval_capacity_factor=1.0,
                 min_capacity=4, noisy_gate_policy=None, drop_tokens=True,
                 top2_2nd_expert_sampling=True, activation=jax.nn.gelu,
                 dtype=jnp.bfloat16, backend="dense",
                 grouped_kernel="auto"):
        """backend: 'dense' = GShard static-capacity dispatch (the
        SPMD/EP-shaped path with token dropping at capacity); 'ragged' =
        DROPLESS grouped GEMM (megablox / reference cutlass moe_gemm) —
        under an expert-parallel mesh this routes through
        moe_layer_ragged_ep (shard_map + all_to_all + per-shard grouped
        product), single-shard otherwise.

        grouped_kernel: the ragged backend's expert-product engine —
        "auto" (default: the 'moe_grouped_mm' autotune winner cache; a
        cold cache keeps lax.ragged_dot) | True (the Pallas grouped-GEMM
        kernel, ops/pallas/grouped_matmul.py) | False (ragged_dot)."""
        self.hidden_size = hidden_size
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.num_experts = num_experts
        self.k = k
        self.backend = backend
        if grouped_kernel not in (True, False, "auto"):
            raise ValueError(
                f"grouped_kernel must be true|false|'auto', got "
                f"{grouped_kernel!r}")
        self.grouped_kernel = grouped_kernel
        if backend == "ragged":
            # dropless routing has no capacity knobs (vacuous) but noisy
            # gating would be silently ignored — reject, don't lie
            if noisy_gate_policy is not None:
                raise ValueError(
                    "backend='ragged' uses deterministic top-k routing; "
                    f"noisy_gate_policy={noisy_gate_policy!r} is not "
                    "supported (use backend='dense')")
            if k < 1:
                raise ValueError("k must be >= 1")
            self.gate = None
        else:
            self.gate = TopKGate(k, capacity_factor, eval_capacity_factor,
                                 min_capacity, noisy_gate_policy,
                                 drop_tokens, top2_2nd_expert_sampling)
        self.activation = activation
        self.dtype = dtype

    def init(self, rng, stack=None, std=0.02, out_std=None):
        M, F, E = self.hidden_size, self.ffn_hidden_size, self.num_experts
        lead = () if stack is None else (stack,)
        ks = jax.random.split(rng, 3)
        out_std = std if out_std is None else out_std

        def nrm(key, shape, s):
            return (jax.random.normal(key, shape, jnp.float32) * s).astype(
                self.dtype)

        return {
            # gate stays fp32: routing decisions are precision-sensitive
            # (reference keeps gate weights fp32 under fp16 training)
            "gate_w": jax.random.normal(ks[0], lead + (M, E),
                                        jnp.float32) * std,
            "wi": nrm(ks[1], lead + (E, M, F), std),
            "bi": jnp.zeros(lead + (E, F), self.dtype),
            "wo": nrm(ks[2], lead + (E, F, M), out_std),
            "bo": jnp.zeros(lead + (E, M), self.dtype),
        }

    def partition_specs(self, stacked=False):
        """Experts sharded on 'expert' (EP), FFN dim on 'tensor' (TP) —
        the reference's EP x TP expert sharding (module_inject MoE)."""
        lead = (None,) if stacked else ()
        return {
            "gate_w": P(*lead, None, None),
            "wi": P(*lead, "expert", None, "tensor"),
            "bi": P(*lead, "expert", "tensor"),
            "wo": P(*lead, "expert", "tensor", None),
            "bo": P(*lead, "expert", None),
        }

    def apply(self, params, x, *, rng=None, train=True, seq_sharded=False,
              grouped_kernel=None):
        """``grouped_kernel`` overrides the construction-time knob for
        this dispatch (None = keep it) — how an engine-level ``moe``
        config block reaches a layer built before the engine existed."""
        if self.backend == "ragged":
            knob = self.grouped_kernel if grouped_kernel is None \
                else grouped_kernel
            return moe_layer_ragged_ep(
                x, params["gate_w"], params["wi"], params["bi"],
                params["wo"], params["bo"], k=self.k,
                activation=self.activation, seq_sharded=seq_sharded,
                grouped_kernel=knob)
        return moe_layer(x, params["gate_w"], params["wi"], params["bi"],
                         params["wo"], params["bo"], self.gate, rng=rng,
                         train=train, activation=self.activation,
                         seq_sharded=seq_sharded)
