"""Mixture-of-Experts: gating + expert-parallel dispatch.

Counterpart of the reference's ``deepspeed/moe/sharded_moe.py`` (TopKGate
:348, MOELayer :425, top1gating :184, top2gating :282) and
``deepspeed/moe/experts.py``. TPU-first redesign:

  * Gating is pure jnp over the full (tokens, experts) matrix — top-1/top-2
    selection, capacity enforcement by cumsum position, auxiliary
    load-balance loss, gumbel (RSample) noisy gating — no host sync, no
    dynamic shapes.
  * Dispatch/combine are dense one-hot einsums (the Mesh-TensorFlow/GShard
    formulation): ``dispatch (S,E,C) x tokens (S,M) -> (E,C,M)``. On the MXU
    a dense einsum beats gather/scatter; XLA fuses the one-hot.
  * Expert parallelism is declarative: the (E,C,M) dispatched buffer and the
    (E,...) expert weights are sharded on the 'expert' mesh axis, so the
    contraction from batch-sharded tokens to expert-sharded buffers lowers
    to exactly the all_to_all pair the reference issues by hand
    (sharded_moe.py:505-520 _AllToAll), but fused and overlapped by XLA.
  * Experts compute as one grouped GEMM over the leading E dim (the
    megablox/ragged-dot pattern with static capacity), not a Python loop
    over expert modules (reference experts.py:13 loops; fine for GPUs,
    wasteful under jit).
"""

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..utils.groups import BATCH_AXES


def _constrain(x, spec):
    if jax.sharding.get_abstract_mesh().empty:
        return x
    return lax.with_sharding_constraint(x, spec)


# ------------------------------------------------- grouped expert FFNs
# The expert-FFN grouped product has two backends: 'ragged' =
# lax.ragged_dot (the generic-XLA path, and the parity reference) and
# 'kernel' = the Pallas grouped-GEMM launch (ops/pallas/
# grouped_matmul.py: per-group tile maps, each expert's weight tile
# streamed through VMEM once, fused SwiGLU epilogue, per-group fp32 dw).
# The choice and the tile sizes resolve per shape bucket through the
# measured-dispatch winner cache (registry op 'moe_grouped_mm') when the
# knob is "auto" — a cold cache is byte-identical to the ragged program.

def resolve_grouped_params(knob, rows, E_loc, M, F, dtype):
    """Trace-time backend/tile resolution for the grouped expert FFN.
    ``knob``: "auto" (winner cache) | True (kernel, default tiles) |
    False (ragged_dot) | dict (explicit params)."""
    from ..ops.pallas.grouped_matmul import TUNE_DEFAULTS
    if knob is False or knob is None:
        return dict(TUNE_DEFAULTS)
    if knob is True:
        return dict(TUNE_DEFAULTS, backend="kernel")
    if isinstance(knob, dict):
        return {**TUNE_DEFAULTS, **knob}
    from ..ops.pallas._common import (dispatch, dtype_name,
                                      moe_grouped_bucket)
    return dispatch("moe_grouped_mm",
                    moe_grouped_bucket(rows, E_loc, M, F),
                    dtype_name(dtype), TUNE_DEFAULTS)


def _grouped_dot(xs, w, group_sizes, params):
    if params.get("backend") == "kernel":
        from ..ops.pallas.grouped_matmul import grouped_matmul
        return grouped_matmul(xs, w, group_sizes,
                              block_m=int(params["block_m"]),
                              block_n=int(params["block_n"]),
                              block_k=int(params["block_k"]))
    return lax.ragged_dot(xs, w, group_sizes)


def _grouped_swiglu_ffn(xs, w1, w3, w2, group_sizes, params):
    from ..ops.int8_weights import _is_q
    if _is_q(w1):
        # weight-only quantized experts (serving): dequant fused into
        # the grouped kernel's flush epilogue — int8/int4 bytes stream
        # HBM->VMEM, no dequantized (E, K, N) tensor materializes
        from ..ops.pallas.grouped_matmul import grouped_swiglu_wq
        return grouped_swiglu_wq(xs, w1, w3, w2, group_sizes,
                                 block_m=int(params["block_m"]),
                                 block_n=int(params["block_n"]),
                                 block_k=int(params["block_k"]))
    if params.get("int8"):
        # dynamic int8 activation x weight compute (autotune lever
        # 'moe_grouped_int8'): per-row activation scales, int32
        # accumulate, straight-through fp backward
        from ..ops.pallas.quantization import grouped_int8_matmul
        g = grouped_int8_matmul(xs, w1, group_sizes)
        u = grouped_int8_matmul(xs, w3, group_sizes)
        return grouped_int8_matmul(jax.nn.silu(g) * u, w2, group_sizes)
    if params.get("backend") == "kernel":
        from ..ops.pallas.grouped_matmul import grouped_swiglu
        return grouped_swiglu(xs, w1, w3, w2, group_sizes,
                              block_m=int(params["block_m"]),
                              block_n=int(params["block_n"]),
                              block_k=int(params["block_k"]))
    g = lax.ragged_dot(xs, w1, group_sizes)
    u = lax.ragged_dot(xs, w3, group_sizes)
    return lax.ragged_dot(jax.nn.silu(g) * u, w2, group_sizes)


def resolve_moe_int8(knob, rows, E_loc, M, F, dtype):
    """Resolve the MoE int8-compute lever ("auto" consults the
    'moe_grouped_int8' winner cache; a cold cache resolves 0 — byte-
    identical program). Returns 0/1 to merge into the grouped params."""
    if knob in (False, None):
        return 0
    if knob is True:
        return 1
    from ..ops.pallas._common import (dispatch, dtype_name,
                                      moe_grouped_bucket)
    return int(dispatch("moe_grouped_int8",
                        moe_grouped_bucket(rows, E_loc, M, F),
                        dtype_name(dtype), {"int8": 0})["int8"])


def _capacity(num_tokens, num_experts, capacity_factor, min_capacity):
    """Static per-expert capacity (reference sharded_moe.py:_capacity)."""
    cap = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(cap, int(min_capacity))


def _gumbel(rng, shape):
    return -jnp.log(-jnp.log(
        jax.random.uniform(rng, shape, jnp.float32, 1e-20, 1.0 - 1e-20)))


def top1gating(logits, capacity_factor=1.0, min_capacity=4,
               noisy_gate_policy=None, rng=None, drop_tokens=True):
    """Switch-style top-1 gating (reference sharded_moe.py:184).

    logits: (S, E) fp32. Returns (l_aux, combine_weights (S,E,C) fp32,
    dispatch_mask (S,E,C) bool, exp_counts (E,)).
    """
    S, E = logits.shape
    C = _capacity(S, E, capacity_factor, min_capacity)
    if not drop_tokens:
        C = S  # full capacity: nothing dropped, memory = dense routing

    gates = jax.nn.softmax(logits, axis=-1)

    select_logits = logits
    if noisy_gate_policy == "RSample":
        if rng is None:
            raise ValueError("RSample noisy gating needs an rng")
        select_logits = logits + _gumbel(rng, logits.shape)
    elif noisy_gate_policy == "Jitter":
        if rng is None:
            raise ValueError("Jitter noisy gating needs an rng")
        select_logits = logits * jax.random.uniform(
            rng, logits.shape, jnp.float32, 0.99, 1.01)

    idx1 = jnp.argmax(select_logits, axis=-1)                   # (S,)
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)          # (S, E)
    exp_counts = jnp.sum(mask1, axis=0)

    # load-balance aux loss (reference :241): E * <fraction routed> . <prob>
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    # position of each token within its expert's queue; drop overflow
    locations1 = jnp.cumsum(mask1, axis=0) - mask1              # (S, E)
    mask1 = mask1 * (locations1 < C)
    loc1_s = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)  # (S,)

    gate1 = jnp.sum(gates * mask1, axis=-1)                     # (S,)
    cap_oh = jax.nn.one_hot(loc1_s, C, dtype=jnp.float32)       # (S, C)
    combine = (gate1[:, None] * mask1)[:, :, None] * cap_oh[:, None, :]
    dispatch = combine > 0
    return l_aux, combine, dispatch, exp_counts


def top2gating(logits, capacity_factor=1.0, min_capacity=4, rng=None,
               drop_tokens=True, top2_2nd_expert_sampling=True):
    """GShard top-2 gating (reference sharded_moe.py:282): capacity doubles,
    second expert chosen after masking the first (optionally with gumbel
    sampling), gate weights renormalized over the kept pair."""
    S, E = logits.shape
    C = _capacity(S, E, 2 * capacity_factor, min_capacity)
    if not drop_tokens:
        C = S

    gates = jax.nn.softmax(logits, axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)

    logits2 = logits
    if top2_2nd_expert_sampling:
        if rng is None:
            raise ValueError("top2 2nd-expert sampling needs an rng")
        logits2 = logits + _gumbel(rng, logits.shape)
    logits2 = jnp.where(mask1 > 0, -jnp.inf, logits2)
    idx2 = jnp.argmax(logits2, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)

    exp_counts = jnp.sum(mask1 + mask2, axis=0)
    me = jnp.mean(gates, axis=0)
    ce = jnp.mean(mask1, axis=0)
    l_aux = jnp.sum(me * ce) * E

    locations1 = jnp.cumsum(mask1, axis=0) - mask1
    # second-choice queue starts after all first choices (reference :300)
    locations2 = jnp.cumsum(mask2, axis=0) - mask2 + jnp.sum(mask1, axis=0)
    mask1 = mask1 * (locations1 < C)
    mask2 = mask2 * (locations2 < C)
    loc1_s = jnp.sum(locations1 * mask1, axis=-1).astype(jnp.int32)
    loc2_s = jnp.sum(locations2 * mask2, axis=-1).astype(jnp.int32)

    gate1 = jnp.sum(gates * mask1, axis=-1)
    gate2 = jnp.sum(gates * mask2, axis=-1)
    denom = jnp.clip(gate1 + gate2, 1e-9, None)
    gate1, gate2 = gate1 / denom, gate2 / denom

    cap1 = jax.nn.one_hot(loc1_s, C, dtype=jnp.float32)
    cap2 = jax.nn.one_hot(loc2_s, C, dtype=jnp.float32)
    combine = ((gate1[:, None] * mask1)[:, :, None] * cap1[:, None, :] +
               (gate2[:, None] * mask2)[:, :, None] * cap2[:, None, :])
    dispatch = combine > 0
    return l_aux, combine, dispatch, exp_counts


class TopKGate:
    """Gate config + apply (reference sharded_moe.py:348 TopKGate)."""

    def __init__(self, k=1, capacity_factor=1.0, eval_capacity_factor=1.0,
                 min_capacity=4, noisy_gate_policy=None, drop_tokens=True,
                 top2_2nd_expert_sampling=True):
        if k not in (1, 2):
            raise ValueError("only top-1 and top-2 gating supported")
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.top2_2nd_expert_sampling = top2_2nd_expert_sampling

    def __call__(self, logits, rng=None, train=True):
        cf = self.capacity_factor if train else self.eval_capacity_factor
        if self.k == 1:
            return top1gating(
                logits, cf, self.min_capacity,
                self.noisy_gate_policy if train else None, rng,
                self.drop_tokens)
        return top2gating(
            logits, cf, self.min_capacity, rng, self.drop_tokens,
            self.top2_2nd_expert_sampling and train and rng is not None)


def topk_routing(logits, k=1):
    """Capacity-free top-k routing: (weights (S, k), experts (S, k) int32,
    aux load-balance loss, counts (E,)). The aux term is the GShard/Switch
    loss — E * mean(router_prob_per_expert * first_choice_frac) — while
    ``counts`` reports ALL k dispatches per expert (the dense paths'
    exp_counts semantics)."""
    S, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    weights, experts = jax.lax.top_k(probs, k)
    if k > 1:
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)
    first = jnp.sum(jax.nn.one_hot(experts[:, 0], E), axis=0)
    l_aux = E * jnp.sum(jnp.mean(probs, axis=0) * first / S)
    counts = jnp.sum(jax.nn.one_hot(experts, E), axis=(0, 1))
    return weights, experts.astype(jnp.int32), l_aux, counts


def moe_layer_ragged(tokens, gate_w, wi, bi, wo, bo, k=1, *,
                     activation=jax.nn.gelu, seq_sharded=False,
                     grouped_kernel="auto"):
    """DROPLESS MoE via grouped GEMM (``lax.ragged_dot``) — the
    megablox pattern and the counterpart of the reference's CUTLASS
    ``moe_gemm`` (inference/v2/kernels/cutlass_ops): tokens sort by
    assigned expert, each expert multiplies exactly its contiguous group
    (no capacity padding, no dropped tokens), results unsort back.

    Single-shard expert compute: use under DP/TP (experts replicated or
    TP-sharded); under expert-parallel meshes the static-capacity dense
    dispatch in ``moe_layer`` is the SPMD-shaped path.
    Returns (y, l_aux, exp_counts) like ``moe_layer``.
    """
    orig_shape = tokens.shape
    M = orig_shape[-1]
    x = tokens.reshape(-1, M)
    S = x.shape[0]
    E = gate_w.shape[-1]

    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    weights, experts, l_aux, _ = topk_routing(logits, k)

    # replicate tokens k times, sort by expert for contiguous groups
    flat_exp = experts.reshape(-1)                      # (S*k,)
    flat_w = weights.reshape(-1).astype(tokens.dtype)
    x_rep = jnp.repeat(x, k, axis=0)                    # (S*k, M)
    order = jnp.argsort(flat_exp)
    xs = x_rep[order]
    exp_sorted = flat_exp[order]
    group_sizes = jnp.bincount(flat_exp, length=E).astype(jnp.int32)

    exp_counts = group_sizes
    gp = resolve_grouped_params(grouped_kernel, S * k, E, M,
                                wi.shape[-1], xs.dtype)
    h = _grouped_dot(xs, wi, group_sizes, gp)           # (S*k, F)
    h = activation(h + bi[exp_sorted])
    out = _grouped_dot(h, wo, group_sizes, gp)          # (S*k, M)
    out = out + bo[exp_sorted]

    # unsort and weighted-combine the k expert outputs per token
    unsorted = jnp.zeros_like(out).at[order].set(out)
    y = jnp.sum((unsorted * flat_w[:, None]).reshape(S, k, M), axis=1)
    y = y.astype(tokens.dtype).reshape(orig_shape)
    y = _constrain(
        y, P(BATCH_AXES, "seq" if seq_sharded else None, None)
        if len(orig_shape) == 3 else P(BATCH_AXES, None))
    return y, l_aux, exp_counts


def moe_layer(tokens, gate_w, wi, bi, wo, bo, gate: TopKGate, *, rng=None,
              train=True, activation=jax.nn.gelu, seq_sharded=False):
    """Full MoE layer over flattened tokens.

    tokens: (..., M) — leading dims flattened to S internally.
    gate_w: (M, E); wi: (E, M, F); bi: (E, F); wo: (E, F, M); bo: (E, M).

    Data flow (reference MOELayer.forward sharded_moe.py:505-520):
    gate -> dispatch einsum [all_to_all in] -> grouped expert FFN
    -> [all_to_all out] -> combine einsum. The all_to_alls materialize from
    the 'expert'-axis sharding constraints under GSPMD.
    """
    orig_shape = tokens.shape
    M = orig_shape[-1]
    x = tokens.reshape(-1, M)
    S = x.shape[0]
    E = gate_w.shape[-1]

    logits = (x.astype(jnp.float32) @ gate_w.astype(jnp.float32))
    l_aux, combine, dispatch, exp_counts = gate(logits, rng=rng, train=train)

    combine = combine.astype(tokens.dtype)
    dispatched = jnp.einsum("sec,sm->ecm", dispatch.astype(tokens.dtype), x,
                            preferred_element_type=tokens.dtype)
    # expert-sharded buffers: the einsum above becomes the first all_to_all
    dispatched = _constrain(dispatched, P("expert", None, None))
    h = activation(jnp.einsum("ecm,emf->ecf", dispatched, wi) + bi[:, None])
    h = _constrain(h, P("expert", None, "tensor"))
    out = jnp.einsum("ecf,efm->ecm", h, wo) + bo[:, None]
    out = _constrain(out, P("expert", None, None))
    # second all_to_all back to token sharding, then weighted combine
    y = jnp.einsum("sec,ecm->sm", combine, out,
                   preferred_element_type=tokens.dtype)
    y = _constrain(
        y.reshape(orig_shape),
        P(BATCH_AXES, "seq" if seq_sharded else None, None)
        if len(orig_shape) == 3 else P(BATCH_AXES, None))
    return y, l_aux, exp_counts


def resolve_hierarchical_a2a(knob, outer_size, E, ep, *, tokens=0,
                             model_dim=0, dtype=None):
    """Whether the EP exchange stages ICI -> DCN: "auto" engages iff the
    mesh has an outer (DCN) axis > 1 and the experts divide the combined
    shard grid — then defers to the 'a2a_staging' collective winner for
    this (device, topology, payload) bucket, whose cold-cache default IS
    that heuristic (a measured winner can only flip an admissible case
    back to flat, never force a non-dividing staging); True additionally
    *requires* divisibility (loud error instead of a silent flat
    fallback); False never stages."""
    if knob is False or knob is None:
        return False
    if outer_size <= 1:
        return False
    if E % (ep * outer_size) != 0:
        if knob is True:
            raise ValueError(
                f"hierarchical EP needs experts ({E}) divisible by "
                f"expert*outer shards ({ep}*{outer_size})")
        return False
    if knob == "auto":
        from ..ops.pallas._common import a2a_bucket, dispatch, dtype_name
        import jax.numpy as jnp
        win = dispatch(
            "a2a_staging", a2a_bucket(tokens, model_dim),
            dtype_name(dtype if dtype is not None else jnp.bfloat16),
            {"staged": int(outer_size > 1)})
        return bool(win["staged"])
    return True


def moe_swiglu_ragged_ep(tokens, gate_w, w1, w3, w2, k=2, *,
                         expert_axis="expert", outer_axis="data_outer",
                         hierarchical="auto", dcn_quantize=False,
                         grouped_kernel="auto", int8_matmul=False,
                         return_counts=False):
    """EXPERT-PARALLEL dropless SwiGLU MoE for the serving models
    (mixtral): the same pack / all_to_all / per-shard grouped-GEMM /
    exchange-back machinery as :func:`moe_layer_ragged_ep`, with the
    SwiGLU expert FFN (w1 gate, w3 up, w2 down, no biases) and mixtral's
    softmax-then-top-k renormalized combine weights. The expert product
    runs the Pallas grouped kernel or ``lax.ragged_dot`` per the
    ``grouped_kernel`` knob ("auto" = the 'moe_grouped_mm' winner cache;
    a cold cache keeps the ragged program).

    Exists because GSPMD cannot partition ``lax.ragged_dot`` over the
    expert (group) dim of the weights: with moe_w* sharded
    P('expert', ...) under plain jit, rows routed to off-shard experts
    silently come back as garbage (measured: identical shard-0 rows,
    O(1)-wrong rows elsewhere) — the root cause of the EPxTP mixtral
    serving mismatch. The expert axis must be MANUAL (shard_map) with an
    explicit exchange; any 'tensor' sharding of the FFN dim stays
    GSPMD-managed (that partitioning is sound — TP-only serving matched
    exactly).

    The region is FULL-manual (every mesh axis) rather than
    expert-subgroup-manual: jaxlib < 0.6's partitioner check-fails on
    manual subgroups (the SPMD-pipe limitation), and full manual also
    makes the TP composition explicit — the FFN dim stays 'tensor'-
    sharded inside the region and the down projection's partial sums
    psum over 'tensor' (the Megatron row-parallel reduction).

    POD SCALE — hierarchical ICI->DCN exchange: when the mesh carries a
    ``data_outer`` (cross-slice DCN) axis and ``hierarchical`` resolves
    on, experts shard over the combined (outer, expert) grid and the
    flat all_to_all splits into two tiled hops: an ICI-local exchange
    over ``expert_axis`` delivering each token to its target inner rank,
    then one DCN hop over ``outer_axis`` delivering it to its target
    slice — per-slice traffic aggregated per inner rank, the PR-3
    two-stage collective discipline. ``dcn_quantize`` applies the qgZ
    int8 block round trip (``comm.quantized.dcn_precision_clamp``) to
    the token payload of the DCN legs ONLY (both directions; the ICI
    hop and the int32 expert ids stay exact).

    tokens: (..., M); token count needn't divide the shard grid (zero
    rows pad the split, their gate weights are masked to zero and they
    ride with the invalid expert id so they can never skew
    ``group_sizes``, the FFN groups, or the combine). Returns y shaped
    like tokens (plus global per-expert dispatch counts when
    ``return_counts`` — the padding-audit observable).
    """
    mesh = jax.sharding.get_abstract_mesh()
    ep = 1 if mesh.empty else mesh.shape.get(expert_axis, 1)
    wo = 1 if mesh.empty else mesh.shape.get(outer_axis, 1)
    orig_shape = tokens.shape
    M = orig_shape[-1]
    flat = tokens.reshape(-1, M)
    S = flat.shape[0]
    E = gate_w.shape[-1]
    if ep == 1:
        raise ValueError("moe_swiglu_ragged_ep needs an expert mesh axis "
                         "> 1; use the dense ragged_dot path otherwise")
    hier = resolve_hierarchical_a2a(hierarchical, wo, E, ep,
                                    tokens=S, model_dim=M,
                                    dtype=tokens.dtype)
    if dcn_quantize == "auto":
        # qgZ on the DCN token legs: measured per payload bucket, OFF on
        # a cold cache (quantization changes numerics — never on blind)
        from ..ops.pallas._common import (dispatch, dtype_name,
                                          grad_comm_bucket)
        payload_mb = max(1, (S * M * flat.dtype.itemsize) >> 20)
        dcn_quantize = bool(dispatch(
            "dcn_quantize", grad_comm_bucket(payload_mb),
            dtype_name(flat.dtype), {"quantize": 0})["quantize"])
    ep_total = ep * wo if hier else ep
    assert E % ep_total == 0, \
        f"experts {E} not divisible by expert shards {ep_total}"
    E_loc = E // ep_total
    pad = (-S) % ep_total
    if pad:
        # jnp.pad, NOT concatenate-with-zeros: on jaxlib < 0.6 a traced
        # concatenate feeding a manual (shard_map) region gets its layout
        # mis-propagated by the SPMD partitioner and the shards read
        # transposed data (verified with an identity shard_map)
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
    tn = "tensor" if "tensor" in mesh.shape else None
    shard_axes = (outer_axis, expert_axis) if hier else (expert_axis,)

    def shard_fn(x, gate_w, w1, w3, w2):
        S_loc = x.shape[0]
        cap = S_loc * k                                  # exact transport
        shard = lax.axis_index(expert_axis)
        if hier:
            shard = lax.axis_index(outer_axis) * ep + shard
        # pad-row audit: rows past the true token count carry zero gate
        # weight and the invalid expert id — they occupy transport slots
        # (static capacity) but never enter group_sizes, the grouped
        # FFN, or the combine
        valid = (shard * S_loc + jnp.arange(S_loc)) < S
        valid_rep = jnp.repeat(valid, k)

        logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        weights, experts = jax.lax.top_k(probs, k)
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

        flat_exp = experts.reshape(-1).astype(jnp.int32)
        flat_w = jnp.where(valid_rep, weights.reshape(-1), 0.0) \
            .astype(x.dtype)
        dest = flat_exp // E_loc
        local_e = jnp.where(valid_rep, flat_exp % E_loc, E_loc)
        x_rep = jnp.repeat(x, k, axis=0)

        order = jnp.argsort(dest, stable=True)
        dest_s = dest[order]
        pos_in_bucket = jnp.arange(cap) - jnp.searchsorted(
            dest_s, dest_s, side="left")
        if hier:
            # buckets keyed (inner rank, outer slice): stage 1 exchanges
            # over the ICI expert axis, stage 2 moves each token's
            # aggregated per-slice bucket across DCN once
            i_dest_s = dest_s % ep
            o_dest_s = dest_s // ep
            send_x = jnp.zeros((ep, wo, cap, M), x.dtype)
            send_e = jnp.full((ep, wo, cap), E_loc, jnp.int32)
            send_x = send_x.at[i_dest_s, o_dest_s, pos_in_bucket].set(
                x_rep[order])
            send_e = send_e.at[i_dest_s, o_dest_s, pos_in_bucket].set(
                local_e[order])
            recv_x = lax.all_to_all(send_x, expert_axis, 0, 0,
                                    tiled=False)
            recv_e = lax.all_to_all(send_e, expert_axis, 0, 0,
                                    tiled=False)
            if dcn_quantize:
                from ..comm.quantized import dcn_precision_clamp
                recv_x = dcn_precision_clamp(recv_x)
            recv_x = lax.all_to_all(recv_x, outer_axis, 1, 1,
                                    tiled=False)
            recv_e = lax.all_to_all(recv_e, outer_axis, 1, 1,
                                    tiled=False)
        else:
            send_x = jnp.zeros((ep, cap, M), x.dtype)
            send_e = jnp.full((ep, cap), E_loc, jnp.int32)
            send_x = send_x.at[dest_s, pos_in_bucket].set(x_rep[order])
            send_e = send_e.at[dest_s, pos_in_bucket].set(local_e[order])
            recv_x = lax.all_to_all(send_x, expert_axis, 0, 0,
                                    tiled=False)
            recv_e = lax.all_to_all(send_e, expert_axis, 0, 0,
                                    tiled=False)
        rx = recv_x.reshape(ep_total * cap, M)
        re = recv_e.reshape(ep_total * cap)

        g_order = jnp.argsort(re, stable=True)
        xs = rx[g_order]
        es = re[g_order]
        group_sizes = jnp.bincount(re, length=E_loc).astype(jnp.int32)
        F_dim = w1.scale.shape[-1] if hasattr(w1, "scale") \
            else w1.shape[-1]
        gp = resolve_grouped_params(grouped_kernel, ep_total * cap,
                                    E_loc, M, F_dim, x.dtype)
        if int8_matmul:
            gp = dict(gp, int8=resolve_moe_int8(
                int8_matmul, ep_total * cap, E_loc, M, F_dim, x.dtype))
        out = _grouped_swiglu_ffn(xs, w1, w3, w2, group_sizes, gp)
        if tn is not None:
            # row-parallel down projection: F is 'tensor'-sharded, so
            # the local grouped product holds partial sums (no-op tp=1)
            out = lax.psum(out, tn)
        out = jnp.where((es < E_loc)[:, None], out, 0.0)

        back = jnp.zeros_like(out).at[g_order].set(out)
        if hier:
            back = back.reshape(ep, wo, cap, M)
            if dcn_quantize:
                from ..comm.quantized import dcn_precision_clamp
                back = dcn_precision_clamp(back)
            ret = lax.all_to_all(back, outer_axis, 1, 1, tiled=False)
            ret = lax.all_to_all(ret, expert_axis, 0, 0, tiled=False)
            ret_flat = ret[i_dest_s, o_dest_s, pos_in_bucket]
        else:
            back = back.reshape(ep, cap, M)
            ret = lax.all_to_all(back, expert_axis, 0, 0, tiled=False)
            ret_flat = ret[dest_s, pos_in_bucket]
        unsorted = jnp.zeros_like(ret_flat).at[order].set(ret_flat)
        y = jnp.sum(
            (unsorted * flat_w[:, None]).reshape(S_loc, k, M), axis=1)
        counts = lax.psum(
            lax.dynamic_update_slice(jnp.zeros((E,), jnp.int32),
                                     group_sizes, (shard * E_loc,)),
            shard_axes)
        return y.astype(tokens.dtype), counts

    y, counts = jax.shard_map(
        shard_fn,
        in_specs=(P(shard_axes), P(), P(shard_axes, None, tn),
                  P(shard_axes, None, tn), P(shard_axes, tn, None)),
        out_specs=(P(shard_axes), P()), check_vma=False,
    )(flat, gate_w, w1, w3, w2)
    if pad:
        y = y[:S]
    y = y.reshape(orig_shape)
    return (y, counts) if return_counts else y


def moe_layer_ragged_ep(tokens, gate_w, wi, bi, wo, bo, k=1, *,
                        activation=jax.nn.gelu, expert_axis="expert",
                        batch_axes=BATCH_AXES, seq_sharded=False,
                        grouped_kernel="auto"):
    """EXPERT-PARALLEL dropless MoE: shard_map over the expert axis with an
    explicit all_to_all exchange and per-shard grouped GEMM
    (``lax.ragged_dot``) — the reference's CUTLASS ``moe_gemm`` composed
    with its ``_AllToAll`` dispatch (sharded_moe.py:95,505), megablox
    style, with NO token dropping and NO capacity padding in the FFN.

    tokens: (..., M) with the leading (token) dim sharded over
    ``batch_axes``; wi/bi/wo/bo carry a leading E dim sharded over
    ``expert_axis`` (E % ep == 0); gate_w (M, E) replicated.

    Mechanics per expert-shard (manual over the batch axes): route the
    S_loc local tokens over all E experts; pack tokens destined for each
    expert shard into a (ep, S_loc*k) transport buffer (worst-case sized:
    transport pays for exactness — the FFN does not: after the
    all_to_all, rows sort by LOCAL expert and ``ragged_dot`` multiplies
    only the valid rows); all_to_all back and weighted-combine. Invalid
    rows ride with expert id E_loc so they sort last, outside every
    ragged group; their (undefined) outputs are masked before combine.

    Returns (y, l_aux, exp_counts(E,)) like ``moe_layer``.
    """
    mesh = jax.sharding.get_abstract_mesh()
    if mesh.empty or mesh.shape.get(expert_axis, 1) == 1:
        return moe_layer_ragged(tokens, gate_w, wi, bi, wo, bo, k=k,
                                activation=activation,
                                seq_sharded=seq_sharded,
                                grouped_kernel=grouped_kernel)
    ep = mesh.shape[expert_axis]
    E = gate_w.shape[-1]
    assert E % ep == 0, f"experts {E} not divisible by expert axis {ep}"
    E_loc = E // ep
    orig_shape = tokens.shape
    M = orig_shape[-1]
    # the region is FULL-manual (every mesh axis — jaxlib < 0.6's
    # partitioner check-fails on manual subgroups, and an EP x ring /
    # EP x TP composition would otherwise gather the non-manual axes):
    # the flat token dim is sharded over the batch axes plus, when the
    # caller runs sequence-parallel, the 'seq' axis (so EP x ring keeps
    # its sequence shards — the (B, T, M) -> (B*T, M) reshape is
    # batch-major, seq-minor); the FFN dim stays 'tensor'-sharded with
    # the down projection's partial sums psum'd (row-parallel).
    token_axes = tuple(a for a in (batch_axes if isinstance(
        batch_axes, tuple) else (batch_axes,)) if a in mesh.shape)
    if expert_axis not in token_axes:
        token_axes = token_axes + (expert_axis,)
    if seq_sharded and "seq" in mesh.shape:
        token_axes = token_axes + ("seq",)
    tn = "tensor" if "tensor" in mesh.shape else None

    def shard_fn(x, gate_w, wi, bi, wo, bo):
        x = x.reshape(-1, M)
        S_loc = x.shape[0]
        cap = S_loc * k                                  # exact transport
        logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        weights, experts, _, counts = topk_routing(logits, k)
        counts = lax.psum(counts, token_axes)
        # The GShard aux loss is nonlinear in the per-expert statistics,
        # so psum the raw sums (prob mass + first-choice counts) across
        # shards FIRST and form the loss once from global-batch values —
        # a pmean of per-shard losses biases the balance gradient
        # whenever routing differs across shards.
        probs = jax.nn.softmax(logits, axis=-1)
        probsum = lax.psum(jnp.sum(probs, axis=0), token_axes)
        first = lax.psum(
            jnp.sum(jax.nn.one_hot(experts[:, 0], E), axis=0),
            token_axes)
        n_shards = 1
        for a in token_axes:
            n_shards *= mesh.shape[a]
        S_glob = S_loc * n_shards
        l_aux = E * jnp.sum((probsum / S_glob) * (first / S_glob))

        flat_exp = experts.reshape(-1)                   # (S_loc*k,)
        flat_w = weights.reshape(-1).astype(tokens.dtype)
        dest = flat_exp // E_loc                         # target shard
        local_e = flat_exp % E_loc                       # expert on shard
        x_rep = jnp.repeat(x, k, axis=0)

        # pack per-destination: stable sort by dest, then position within
        # the destination bucket = rank among same-dest rows
        order = jnp.argsort(dest, stable=True)
        dest_s = dest[order]
        pos_in_bucket = jnp.arange(cap) - jnp.searchsorted(
            dest_s, dest_s, side="left")
        send_x = jnp.zeros((ep, cap, M), x.dtype)
        send_e = jnp.full((ep, cap), E_loc, jnp.int32)   # E_loc = invalid
        send_x = send_x.at[dest_s, pos_in_bucket].set(x_rep[order])
        send_e = send_e.at[dest_s, pos_in_bucket].set(local_e[order])

        # exchange: shard g receives every shard's bucket for g
        recv_x = lax.all_to_all(send_x, expert_axis, 0, 0, tiled=False)
        recv_e = lax.all_to_all(send_e, expert_axis, 0, 0, tiled=False)
        rx = recv_x.reshape(ep * cap, M)
        re = recv_e.reshape(ep * cap)

        # group by local expert (invalid rows sort last, outside groups)
        g_order = jnp.argsort(re, stable=True)
        xs = rx[g_order]
        es = re[g_order]
        group_sizes = jnp.bincount(re, length=E_loc).astype(jnp.int32)
        gp = resolve_grouped_params(grouped_kernel, ep * cap, E_loc, M,
                                    wi.shape[-1], xs.dtype)
        h = _grouped_dot(xs, wi, group_sizes, gp)
        safe_e = jnp.minimum(es, E_loc - 1)
        h = activation(h + bi[safe_e])
        out = _grouped_dot(h, wo, group_sizes, gp)
        if tn is not None:
            # row-parallel down projection: F is 'tensor'-sharded, so
            # the local grouped product holds partial sums (no-op tp=1);
            # bo is replicated and must land AFTER the reduction
            out = lax.psum(out, tn)
        out = out + bo[safe_e]
        out = jnp.where((es < E_loc)[:, None], out, 0.0)

        # unsort, exchange back, unpack to original (S_loc*k) order
        back = jnp.zeros_like(out).at[g_order].set(out)
        back = back.reshape(ep, cap, M)
        ret = lax.all_to_all(back, expert_axis, 0, 0, tiled=False)
        ret_flat = ret[dest_s, pos_in_bucket]            # sorted order
        unsorted = jnp.zeros_like(ret_flat).at[order].set(ret_flat)
        y = jnp.sum(
            (unsorted * flat_w[:, None]).reshape(S_loc, k, M), axis=1)
        return y.astype(tokens.dtype), l_aux, counts

    flat = tokens.reshape(-1, M)
    token_spec = P(tuple(token_axes))
    y, l_aux, counts = jax.shard_map(
        shard_fn,
        in_specs=(token_spec, P(), P(expert_axis, None, tn),
                  P(expert_axis, tn), P(expert_axis, tn, None),
                  P(expert_axis, None)),
        out_specs=(token_spec, P(), P()), check_vma=False,
    )(flat, gate_w, wi, bi, wo, bo)
    y = y.reshape(orig_shape)
    y = _constrain(
        y, P(BATCH_AXES, "seq" if seq_sharded else None, None)
        if len(orig_shape) == 3 else P(BATCH_AXES, None))
    return y, l_aux, counts
