"""Metric tag schema — the documented contract for every event the
production code writes into the MonitorMaster fan-out.

Every ``(tag, value, step)`` event emitted from ``deepspeed_tpu/``
must name a tag registered here, and every registered tag must be
emitted by production code — both directions are linted by
``tests/unit/test_telemetry.py`` (the test_fault_points_lint.py
discipline applied to metrics: a renamed emission site or a stale
registry entry cannot silently rot the schema dashboards are built on).

This module deliberately holds NOTHING but the registry: the lint
collects emitted-tag literals by grepping the package with this file
excluded, so the registry's own keys never count as "emissions".

Tag grammar: ``<Domain>/<Group>/<name>`` with domain ``Train`` or
``Serve``; values are floats (host ids / tiers are reported as numeric
indices). Steps are the engine's global step (Train) or the completed-
request count (Serve).
"""

# tag -> one-line meaning (the README "Observability" table is
# generated from the same entries)
TAG_SCHEMA = {
    # --- per-step training samples (engine._write_monitor_events) ---
    "Train/Samples/lr":
        "learning rate applied at this step",
    "Train/Samples/train_loss":
        "loss of the most recent train_batch",
    "Train/Samples/loss_scale":
        "dynamic loss scale (fp16 runs only)",

    # --- checkpoint health (engine._write_ckpt_monitor_events) ---
    "Train/Checkpoint/save_latency_ms":
        "wall time of the most recent save_checkpoint",
    "Train/Checkpoint/load_latency_ms":
        "wall time of the most recent load_checkpoint",
    "Train/Checkpoint/retries":
        "cumulative shard-write retries (retry/degrade policy)",
    "Train/Checkpoint/fallbacks":
        "cumulative writer degradations (native->python, async->sync)",
    "Train/Checkpoint/save_errors":
        "cumulative saves that failed after retry+degrade",
    "Train/Checkpoint/load_fallbacks":
        "cumulative corrupt-generation fallbacks on load",
    "Train/Checkpoint/gc_removed":
        "cumulative tags removed by retention GC",
    "Train/Checkpoint/hot_pushes":
        "cumulative hot-tier replica pushes completed",
    "Train/Checkpoint/hot_push_errors":
        "cumulative advisory hot-tier push failures",
    "Train/Checkpoint/hot_restores":
        "cumulative loads served from in-memory replicas",
    "Train/Checkpoint/hot_fallbacks":
        "hot tier present but load degraded to durable",
    "Train/Checkpoint/durable_restores":
        "cumulative loads that read persistent storage",
    "Train/Checkpoint/replica_pushes":
        "cumulative cross-slice replica pushes (DCN peer writes + "
        "MiCS zero-replica registrations)",
    "Train/Checkpoint/replica_restores":
        "cumulative loads served by the cross-slice replica tier",
    "Train/Checkpoint/replica_fallbacks":
        "replica tier present but load degraded to durable",
    "Train/Checkpoint/reshape":
        "1 when this resume re-partitioned onto a new topology",

    # --- step analytics (monitor/telemetry.py, every interval_steps) ---
    "Train/Telemetry/step_time_ms_p50":
        "median per-step wall time over the interval (this host)",
    "Train/Telemetry/step_time_ms_p99":
        "p99 per-step wall time over the interval (this host)",
    "Train/Telemetry/tokens_per_sec_chip":
        "interval token throughput / participating chips",
    "Train/Telemetry/mfu_pct":
        "model-flops utilization: step FLOPs (XLA cost_analysis) "
        "/ step time / per-chip peak",
    "Train/Telemetry/collectives":
        "logical collectives in the compiled step program (an async "
        "start/done pair counts once; HLO parse)",
    "Train/Telemetry/exposed_comm_pct":
        "share of step collectives with no async start/done pair "
        "(comm the schedule left exposed)",
    "Train/Telemetry/goodput_pct":
        "productive share of wall time: 100 * (1 - ckpt/restore/"
        "reshape/restart overhead / elapsed)",

    # --- pipeline parallelism (telemetry._flush when a pipelined
    #     engine armed set_pipeline; engine.pipeline_report is the
    #     source) ---
    "Train/Pipeline/bubble_pct":
        "analytic executor bubble fraction of the active schedule "
        "(lock-step wall model, runtime/pipe/schedule.py)",
    "Train/Pipeline/steady_tick_ms":
        "mean step wall time / schedule tick count — the microbatch "
        "steady-state tick wall",
    "Train/Pipeline/offload_bytes_per_step":
        "D2H+H2D activation-ring payload host offload stages per step "
        "(0 = offload off) — the copy overhead the schedule must hide",

    # --- modeled-vs-measured reconciliation (telemetry._flush after a
    #     ProfilerControl capture; autotuning/reconcile.py is the
    #     source) ---
    "Train/Reconcile/wall_err_pct":
        "abs(modeled - measured) step wall / measured, pct — how far "
        "off-model the pod is running",
    "Train/Reconcile/top_drift_ms":
        "largest absolute modeled-vs-measured drift across planner "
        "_score terms (per step, ms)",
    "Train/Reconcile/top_drift_term":
        "index of the worst-drift term in planner.SCORE_TERMS "
        "(-1 = none)",
    "Train/Reconcile/coverage_pct":
        "share of measured device time the step decomposition "
        "attributed to a term",

    # --- pod-wide aggregation (rank 0 only; cluster_agg transports) ---
    "Train/Telemetry/cluster_step_ms_p50":
        "p50 of per-host mean step time across the pod",
    "Train/Telemetry/cluster_step_ms_p99":
        "p99 of per-host mean step time across the pod",
    "Train/Telemetry/straggler_delta_ms":
        "slowest host's mean step time minus the pod median",
    "Train/Telemetry/straggler_host":
        "index (ring order) of the slowest host",
    "Train/Telemetry/cluster_hosts":
        "hosts whose metrics reached this aggregation round",

    # --- serving (inference/v2 engine; step = completed requests) ---
    "Serve/Telemetry/ttft_ms_p50":
        "median time-to-first-token over the sample window",
    "Serve/Telemetry/ttft_ms_p99":
        "p99 time-to-first-token over the sample window",
    "Serve/Telemetry/tpot_ms_p50":
        "median time-per-output-token (dispatch-amortized)",
    "Serve/Telemetry/tpot_ms_p99":
        "p99 time-per-output-token (dispatch-amortized)",
    "Serve/Telemetry/completed":
        "requests completed since engine construction",
    "Serve/Telemetry/active":
        "sequences decoding when the window was emitted",

    # --- prefix cache (inference/v2/prefix_cache.py radix tree;
    #     emitted only when the engine runs with prefix_cache on) ---
    "Serve/Telemetry/prefix_hit_rate_pct":
        "admissions whose prompt matched a cached prefix, pct of all "
        "admissions since engine construction",
    "Serve/Telemetry/cached_tokens_per_sec":
        "prompt tokens served from cached KV blocks (prefill skipped) "
        "per wall second since engine construction",
    "Serve/Telemetry/prefix_evictions":
        "cumulative cold tree blocks reclaimed by LRU eviction",
    "Serve/Telemetry/cow_copies":
        "cumulative copy-on-write block copies (partial-tail prefix "
        "hits that diverge inside a shared block)",

    # --- speculative decoding (inference/v2/speculative.py; emitted
    #     only once the engine has run a verify round) ---
    "Serve/Telemetry/spec_rounds":
        "cumulative speculative verify rounds since engine construction",
    "Serve/Telemetry/spec_acceptance_pct":
        "draft tokens accepted by greedy verification, pct of all "
        "proposed since engine construction",
    "Serve/Telemetry/spec_tokens_per_verify_step":
        "tokens committed per verify round (accepted prefix + bonus "
        "token; 1.0 would mean speculation is pure overhead)",

    # --- serving fleet router (inference/v2/router.py; step = completed
    #     router requests) ---
    "Serve/Router/shed":
        "cumulative requests rejected at admission or shed under "
        "overload (typed Overloaded, surfaced through get())",
    "Serve/Router/expired":
        "cumulative requests flushed at a deadline boundary (typed "
        "DeadlineExceeded; unref-without-insert, never served late)",
    "Serve/Router/replayed":
        "cumulative in-flight requests re-enqueued and replayed on a "
        "survivor after a replica death",
    "Serve/Router/failovers":
        "cumulative replica deaths the router recovered from",
    "Serve/Router/queue_depth":
        "router queue depth when the window was emitted",
    "Serve/Router/draining":
        "replicas in the draining state when the window was emitted",

    # --- disaggregated prefill/decode serving (router handoff path;
    #     emitted only when the fleet runs phase-specialized roles) ---
    "Serve/Router/handoffs":
        "cumulative prefill->decode KV handoffs completed",
    "Serve/Router/kv_stream_bytes":
        "cumulative KV wire bytes streamed across completed handoffs",
    "Serve/Router/kv_stream_ms":
        "cumulative wall time spent exporting/streaming/importing KV "
        "across completed handoffs",
    "Serve/Router/prefill_inflight":
        "requests in flight on prefill-role replicas when the window "
        "was emitted (per-role queue depth)",
    "Serve/Router/decode_inflight":
        "requests in flight on decode-role replicas when the window "
        "was emitted (per-role queue depth)",
}


def check_tag(tag):
    """Raise on a tag the schema does not document. This is the
    TEST-SIDE enforcement (the schema lint and unit tests call it);
    the production emit path (``TelemetryCollector._emit``) only
    warns on an undocumented tag — telemetry must never kill a run
    over a dashboard label."""
    if tag not in TAG_SCHEMA:
        raise KeyError(
            f"metric tag {tag!r} is not registered in "
            f"monitor/tag_schema.py TAG_SCHEMA — document it there "
            f"(and the lint in tests/unit/test_telemetry.py will hold "
            f"both directions)")
    return tag
