"""Experiment monitors: TensorBoard / W&B / CSV fan-out.

Counterpart of reference ``monitor/monitor.py:29 MonitorMaster`` +
``tensorboard.py`` / ``wandb.py`` / ``csv_monitor.py``. Events are
``(tag, value, step)`` triples; only process 0 writes (reference gates on
rank via dist; here jax.process_index()).
"""

import os

from ..utils.logging import logger


class Monitor:
    def write_events(self, event_list):
        raise NotImplementedError

    def flush(self):
        pass


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        from torch.utils.tensorboard import SummaryWriter  # may raise
        path = os.path.join(config.output_path or "runs", config.job_name)
        self.writer = SummaryWriter(log_dir=path)

    def write_events(self, event_list):
        for tag, value, step in event_list:
            self.writer.add_scalar(tag, float(value), int(step))

    def flush(self):
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        import wandb  # may raise
        self.wandb = wandb
        wandb.init(project=config.project or None,
                   group=config.group or None,
                   entity=config.team or None)

    def write_events(self, event_list):
        """One ``wandb.log`` call per step, with every tag of that step
        batched into a single dict. The reference's per-tag loop issues
        N sequential calls whose ``step`` kwargs conflict (wandb treats
        a repeated step as out-of-order and silently drops rows) —
        batching is both the supported API shape and ~N times fewer
        RPCs."""
        by_step = {}
        for tag, value, step in event_list:
            by_step.setdefault(int(step), {})[tag] = float(value)
        for step in sorted(by_step):
            self.wandb.log(by_step[step], step=step)


class csvMonitor(Monitor):  # noqa: N801 - reference class name
    """One csv file per tag: ``{output_path}/{job_name}/{tag}.csv`` with
    ``step,value`` rows (reference csv_monitor.py layout)."""

    def __init__(self, config):
        self.dir = os.path.join(config.output_path or "csv_out",
                                config.job_name)
        os.makedirs(self.dir, exist_ok=True)
        self._files = {}

    def _file(self, tag):
        if tag not in self._files:
            # tags carry '/' (Train/Samples/lr) — sanitized into the
            # flat one-file-per-tag layout; an unsanitized tag would be
            # an open() into a nonexistent subdirectory (regression
            # covered in tests/unit/test_monitor.py)
            safe = tag.replace("/", "_").replace(os.sep, "_")
            # line-buffered: rows survive preemption/SIGKILL mid-run
            self._files[tag] = open(
                os.path.join(self.dir, f"{safe}.csv"), "a", buffering=1)
        return self._files[tag]

    def write_events(self, event_list):
        for tag, value, step in event_list:
            self._file(tag).write(f"{int(step)},{float(value)}\n")

    def flush(self):
        for f in self._files.values():
            f.flush()


class MonitorMaster(Monitor):
    """Instantiates every enabled writer; failures to import optional
    backends degrade to a warning (reference hard-requires the package)."""

    def __init__(self, config):
        import jax
        self.enabled = config.enabled and jax.process_index() == 0
        self.monitors = []
        if not self.enabled:
            return
        for flag, cls, sub in [
                (config.tensorboard.enabled, TensorBoardMonitor,
                 config.tensorboard),
                (config.wandb.enabled, WandbMonitor, config.wandb),
                (config.csv_monitor.enabled, csvMonitor,
                 config.csv_monitor)]:
            if not flag:
                continue
            try:
                self.monitors.append(cls(sub))
            except Exception as e:  # noqa: BLE001 - optional backend
                logger.warning(f"monitor {cls.__name__} unavailable: {e}")
        self.enabled = bool(self.monitors)

    def write_events(self, event_list):
        if self.enabled and event_list:
            for m in self.monitors:
                m.write_events(event_list)

    def flush(self):
        for m in self.monitors:
            m.flush()
