"""Always-on pod telemetry: MFU/goodput step analytics, cluster
aggregation with straggler detection, and on-demand XLA profiling.

This is the layer that turns the repo's one-shot debugging tools into
production observability (ISSUE 9 tentpole):

  * **Step analytics** — per-step wall times ring-buffered on the host
    (no device sync: the tput-timer lesson from round 2 — in steady
    state dispatch-queue backpressure makes the host wall time track
    the device step time); every ``interval_steps`` the collector
    computes p50/p99 step time, tokens/s/chip, MFU (step FLOPs from
    ``Compiled.cost_analysis()`` — the engine captures them once,
    lazily, from the program that actually runs), and the
    compute-vs-exposed-comm split (the PR-3 ``overlap_report`` HLO
    parse: collectives with no async start/done pair are comm the
    schedule left exposed), and writes the lot into the MonitorMaster
    fan-out under the ``Train/Telemetry/*`` tags of
    ``monitor/tag_schema.py``.
  * **Cluster aggregation** — per-host metric dicts exchanged over one
    of two transports (the hot-tier discipline, checkpoint_engine/
    hot_tier.py): ``allgather`` rides the one-device-per-process mesh
    (comm.allgather_bytes — in-caller, because collectives must never
    interleave across threads) and ``fs`` exchanges JSON files under a
    shared dir (the virtual-mesh/bench transport — safe on the pool).
    Rank 0 reports pod-wide p50/p99 step time and the straggler delta
    (slowest host's mean minus the pod median, with the host id).
  * **Goodput** — productive wall time vs the overhead the engine
    reports (checkpoint save/restore latency, reshape, restarts), one
    ``goodput_pct`` number the elastic chaos suite can assert on.
  * **On-demand profiling** — a ``jax.profiler`` server on
    ``profile_port`` (attach xprof/tensorboard to a live pod), plus
    step-ranged trace capture armed by ``DSTPU_PROFILE_STEPS=a:b`` or
    by dropping a ``PROFILE`` trigger file into the flight-recorder
    dir mid-run — a live incident is debuggable without a relaunch.

Everything that is not a deque-append runs off the step critical path:
flushes do fixed small-array math, costs are captured once, fs gathers
and opportunistic flight dumps run on a single background worker (the
async-checkpoint pool pattern).
"""

import json
import os
import threading
import time
from collections import deque

import numpy as np

from ..utils.logging import logger
from .flight_recorder import FlightRecorder
from .tag_schema import TAG_SCHEMA

# --------------------------------------------------------------- peak flops
# bf16 peak per chip by device_kind substring (first match wins; order
# matters: 'v5p' before the bare 'v5'/'v5 lite' family). Unknown chips
# (CPU dev containers, future TPUs) fall back to the v5e figure with
# ``assumed=True`` so an MFU number is never silently built on a wrong
# denominator without saying so.
_PEAK_BF16 = (
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12), ("v5", 197e12),
    ("v4", 275e12), ("v3", 123e12),
)
_FALLBACK_PEAK = 197e12


def peak_flops_per_chip(device_kind):
    """-> (peak_flops, assumed). ``DSTPU_PEAK_FLOPS`` overrides (exact
    hardware the operator knows better than the table)."""
    env = os.environ.get("DSTPU_PEAK_FLOPS")
    if env:
        try:
            return float(env), False
        except ValueError:
            logger.warning(f"DSTPU_PEAK_FLOPS={env!r} is not a float; "
                           f"using the device-kind table")
    kind = str(device_kind or "").lower()
    for key, peak in _PEAK_BF16:
        if key in kind:
            return peak, False
    return _FALLBACK_PEAK, True


def percentile(samples, p):
    """Guarded percentile: None on an empty window (serve_bench _pct
    discipline — never a NaN in an artifact)."""
    if not samples:
        return None
    return float(np.percentile(np.asarray(samples, np.float64), p))


def collective_breakdown(n_collectives, async_pairs):
    """(logical_collectives, exposed_comm_pct) from an
    ``overlap_report``'s entry counts. ``n_collectives`` counts HLO
    entries and an async collective is TWO entries (-start + -done) but
    ONE logical collective — so logical = n - pairs, and the exposed
    share divides the unpaired (synchronous) ops by the LOGICAL count
    (dividing by the entry count would underreport exposure: 1 sync +
    1 async must read 50%, not 33%)."""
    n = int(n_collectives)
    pairs = int(async_pairs)
    logical = n - pairs
    exposed = (100.0 * max(0, n - 2 * pairs) / logical
               if logical > 0 else 0.0)
    return logical, exposed


# ---------------------------------------------------------- cluster math
def aggregate_cluster(by_host, order=None):
    """Pod-wide stats from per-host metric dicts (each carrying
    ``mean_step_ms``): p50/p99 across hosts, and the straggler delta —
    the slowest host's mean step time minus the pod median, with the
    host's id and ring index. Pure math so the 2-host virtual-mesh
    bench and the unit tests exercise exactly what a pod runs.

    ``order`` is the ring order the ``straggler_host`` index is
    reported in (pass the aggregator's ``peers``); without it hosts
    sort lexically — fine for named hosts, WRONG for string process
    ids on pods >= 10 hosts ('10' sorts before '2'), which is why the
    production caller always passes the ring."""
    if order is not None:
        hosts = [h for h in order
                 if by_host.get(h)
                 and by_host[h].get("mean_step_ms") is not None]
    else:
        hosts = sorted(h for h, m in by_host.items()
                       if m and m.get("mean_step_ms") is not None)
    if not hosts:
        return None
    means = [float(by_host[h]["mean_step_ms"]) for h in hosts]
    med = float(np.median(means))
    worst = int(np.argmax(means))
    node = hosts[worst]
    # straggler_host is documented as the RING index — index into the
    # full order, not into the filtered list, which diverges from the
    # ring as soon as any host's metrics are missing for a round
    return {
        "hosts": len(hosts),
        "cluster_step_ms_p50": round(percentile(means, 50), 3),
        "cluster_step_ms_p99": round(percentile(means, 99), 3),
        "straggler_delta_ms": round(means[worst] - med, 3),
        "straggler_host": (order.index(node) if order is not None
                           else worst),
        "straggler_node": node,
    }


class ClusterAggregator:
    """Per-host metric exchange. Transport resolution:

      * ``fs``        — a shared dir + explicit peer ring
                        (``DSTPU_TELEM_DIR`` + ``DSTPU_TELEM_NODE`` /
                        ``DSTPU_TELEM_PEERS``, falling back to the hot
                        tier's ``DSTPU_HOT_NODE``/``DSTPU_HOT_PEERS``
                        ring): each node atomically publishes
                        ``telem-{node}.json`` and reads its peers'.
                        Pure file IO — safe on a background thread.
      * ``allgather`` — a real multi-process jax world: one
                        length-padded byte allgather over the process
                        mesh (comm.allgather_bytes). COLLECTIVE: must
                        run in-caller at a point every process reaches
                        (the flush boundary), never on a side thread.
      * ``None``      — single process, no ring: local-only telemetry.
    """

    def __init__(self, node=None, peers=None, root=None):
        import jax
        env = os.environ
        self.root = root or env.get("DSTPU_TELEM_DIR") or None
        node = node or env.get("DSTPU_TELEM_NODE") \
            or env.get("DSTPU_HOT_NODE")
        peers_s = (",".join(peers) if peers
                   else env.get("DSTPU_TELEM_PEERS")
                   or env.get("DSTPU_HOT_PEERS"))
        self.nprocs = jax.process_count()
        if self.root and peers_s:
            self.transport = "fs"
            self.peers = [p for p in peers_s.split(",") if p]
            self.node = node or str(jax.process_index())
        elif self.nprocs > 1:
            self.transport = "allgather"
            self.peers = [str(i) for i in range(self.nprocs)]
            self.node = str(jax.process_index())
        else:
            self.transport = None
            self.peers = [node or "0"]
            self.node = node or "0"

    @property
    def is_root(self):
        """Whether this node reports the pod-wide aggregates (rank 0 /
        first ring member)."""
        return not self.peers or self.node == self.peers[0]

    # ----------------------------------------------------------- exchange
    def _fs_path(self, node):
        return os.path.join(self.root, f"telem-{node}.json")

    def _fs_publish(self, metrics):
        os.makedirs(self.root, exist_ok=True)
        path = self._fs_path(self.node)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(metrics, f)
        os.replace(tmp, path)

    def _fs_read(self):
        out = {}
        for p in self.peers:
            try:
                with open(self._fs_path(p), encoding="utf-8") as f:
                    out[p] = json.load(f)
            except (OSError, ValueError):
                pass
        return out

    def gather(self, metrics, wait_s=0.0):
        """Publish this host's ``metrics`` and return ``{node: metrics}``
        across the ring (stale peer entries included — a straggling
        publisher is itself signal). ``wait_s`` > 0 (fs transport only)
        polls until every peer has published this round's step."""
        if self.transport is None:
            return {self.node: metrics}
        if self.transport == "allgather":
            from ..comm import comm
            blobs = comm.allgather_bytes(json.dumps(metrics).encode())
            if blobs is None:
                return {self.node: metrics}
            out = {}
            for i, b in enumerate(blobs):
                try:
                    out[self.peers[i]] = json.loads(b.decode())
                except (ValueError, IndexError):
                    pass
            return out
        self._fs_publish(metrics)
        step = metrics.get("step", 0)
        deadline = time.monotonic() + max(0.0, wait_s)
        while True:
            got = self._fs_read()
            fresh = [p for p in self.peers
                     if got.get(p, {}).get("step", -1) >= step]
            if len(fresh) == len(self.peers) \
                    or time.monotonic() >= deadline:
                return got
            time.sleep(0.05)


# ---------------------------------------------------------- xla profiling
_PROFILE_SERVERS = set()


def _maybe_start_server(port):
    """Start the jax profiler server once per process; attach xprof /
    tensorboard to ``localhost:{port}`` on a live pod."""
    try:
        port = int(port or 0)
    except (TypeError, ValueError):  # e.g. DSTPU_PROFILE_PORT=xprof
        logger.warning(
            f"telemetry: ignoring non-numeric profiler port {port!r}")
        return False
    if port <= 0:
        return False
    if port in _PROFILE_SERVERS:
        return True
    try:
        import jax
        jax.profiler.start_server(port)
        _PROFILE_SERVERS.add(port)
        logger.info(f"telemetry: jax profiler server on :{port}")
        return True
    except Exception as e:  # noqa: BLE001 - observability never fatal
        logger.warning(f"telemetry: profiler server on :{port} "
                       f"unavailable: {e}")
        return False


class ProfilerControl:
    """Step-ranged trace capture for live incidents.

    Armed two ways: ``DSTPU_PROFILE_STEPS=a:b`` at launch (capture
    steps [a, b)), or a ``PROFILE`` trigger file dropped into the
    flight-recorder dir mid-run (content = step count, default 5;
    checked only at flush boundaries so the step path never stats a
    file). Traces land under ``{logdir}/xprof`` for
    ``tensorboard --logdir`` / xprof.

    ``on_trace(logdir, steps, step)`` fires after a capture stops —
    the step-anatomy hook: the collector hands the finished trace to
    ``profiling.step_trace`` + the planner reconciler. Advisory: a
    callback failure warns and never re-raises into the step path."""

    def __init__(self, port=0, logdir=None, flight=None, on_trace=None):
        self.server = _maybe_start_server(
            port or os.environ.get("DSTPU_PROFILE_PORT", 0))
        self.logdir = logdir
        self.flight = flight
        self.on_trace = on_trace
        self.range = self._parse(os.environ.get("DSTPU_PROFILE_STEPS"))
        self.active = False
        self._trace_meta = None        # (logdir, start_step) while active

    @staticmethod
    def _parse(spec):
        if not spec:
            return None
        try:
            a, b = (int(v) for v in spec.split(":"))
        except ValueError:
            logger.warning(f"DSTPU_PROFILE_STEPS={spec!r} is not 'a:b'; "
                           f"ignored")
            return None
        if not 0 <= a < b:
            logger.warning(f"DSTPU_PROFILE_STEPS needs 0 <= a < b, got "
                           f"{(a, b)}; ignored")
            return None
        return (a, b)

    def _record(self, kind, **data):
        if self.flight is not None:
            self.flight.record(kind, **data)

    def on_step(self, step):
        """Hot-path hook: two int compares when disarmed."""
        r = self.range
        if r is None:
            return
        try:
            import jax
            if not self.active and r[0] <= step < r[1]:
                # resolve at start time: the flight-recorder root may
                # only be known after the first save_checkpoint
                base = self.logdir or (
                    self.flight._resolved_root()
                    if self.flight is not None else ".")
                logdir = os.path.join(base, "xprof")
                jax.profiler.start_trace(logdir)
                self.active = True
                self._trace_meta = (logdir, step)
                self._record("profile_start", step=step, logdir=logdir)
            elif self.active and step >= r[1]:
                jax.profiler.stop_trace()
                self.active = False
                self.range = None
                self._record("profile_stop", step=step)
                meta, self._trace_meta = self._trace_meta, None
                if self.on_trace is not None and meta is not None:
                    try:
                        self.on_trace(meta[0], max(1, step - meta[1]),
                                      step)
                    except Exception as e:  # noqa: BLE001 - advisory
                        logger.warning(
                            f"telemetry: trace callback failed "
                            f"({type(e).__name__}: {e})")
        except Exception as e:  # noqa: BLE001 - never break the step
            logger.warning(f"telemetry: profiler capture failed: {e}")
            self.active = False
            self.range = None
            self._trace_meta = None

    def check_trigger(self, root, step):
        """Flush-boundary check for the ``PROFILE`` trigger file."""
        if not root or self.range is not None:
            return
        path = os.path.join(root, "PROFILE")
        try:
            if not os.path.exists(path):
                return
            with open(path, encoding="utf-8") as f:
                text = f.read().strip()
            os.remove(path)
            n = int(text) if text else 5
            self.range = (step + 1, step + 1 + max(1, n))
            self._record("profile_armed", start=self.range[0],
                         stop=self.range[1])
        except (OSError, ValueError):
            pass


# ------------------------------------------------------------- training side
class TelemetryCollector:
    """The engine-facing collector. Hot path = :meth:`on_step` (deque
    appends + one modulo); everything heavier happens at
    ``interval_steps`` boundaries, with file IO on the background
    worker. ``monitor`` is the MonitorMaster fan-out (may be disabled —
    the collector still computes, so ``snapshot()`` serves benches and
    tests without any writer configured)."""

    def __init__(self, cfg, monitor=None, n_devices=1, device_kind="",
                 costs_fn=None, node=None):
        self.cfg = cfg
        self.monitor = monitor
        self.n_devices = max(1, int(n_devices))
        self.interval = max(1, int(cfg.interval_steps))
        self.flight = FlightRecorder(size=cfg.flight_recorder_size,
                                     node=node)
        self.flight.set_root(cfg.flightrec_dir
                             or os.environ.get("DSTPU_FLIGHTREC_DIR"))
        self.peak_flops, self.peak_assumed = \
            peak_flops_per_chip(device_kind)
        self.cluster = (ClusterAggregator()
                        if cfg.resolve_cluster_agg() else None)
        self.profiler = ProfilerControl(port=cfg.profile_port,
                                        flight=self.flight,
                                        on_trace=self._on_trace_ready)
        self._reconcile_fn = None
        self._reconcile_warned = False
        self._pending_reconcile_events = None
        self._costs_fn = costs_fn
        self._costs = None
        self._costs_tried = False
        # interval window (host wall times, ms) + cumulative goodput
        self._step_ms = deque(maxlen=4096)
        self._tokens = 0
        self._t0 = time.perf_counter()
        self._overhead_s = {}
        self._warned_tags = set()
        self._pending_cluster_events = None
        self._pipeline = None
        self.last = {}
        # single background worker (created lazily at the first flush
        # that needs it): fs gathers + opportunistic flight dumps ride
        # here (the async-checkpoint-pool pattern); real collectives
        # never do
        self._pool = None
        self._futs = []
        self._closed = False
        # fired fault-injection points land in the flight ring. The
        # registration is WEAK: the injector is process-global, so a
        # bound-method listener would pin every telemetry-enabled
        # engine (collector -> costs_fn -> engine) for the life of the
        # process; a dead collector's hook unregisters itself instead.
        import weakref
        from ..utils import fault_injection
        wself = weakref.ref(self)

        def _fault_hook(point, injected):
            s = wself()
            if s is None:
                fault_injection.remove_listener(_fault_hook)
                return
            s._on_fault(point, injected)

        self._fault_listener = _fault_hook
        fault_injection.add_listener(_fault_hook)

    # ------------------------------------------------------------ hot path
    def on_step(self, step, wall_s, tokens=0):
        """Called once per train_batch with the host wall time. No
        device sync, no IO."""
        self._step_ms.append(wall_s * 1e3)
        self._tokens += int(tokens)
        self.flight.record("step", step=int(step),
                           ms=round(wall_s * 1e3, 3))
        self.profiler.on_step(step)
        if step % self.interval == 0 and len(self._step_ms) > 0:
            self._flush(step)

    def reset_window(self):
        """Restart the measurement window (samples AND their token
        count — clearing one without the other would bias
        tokens_per_sec_chip). Benches call this after warmup so compile
        time never poses as a slow step."""
        self._step_ms.clear()
        self._tokens = 0

    def set_pipeline(self, info):
        """Arm the per-flush pipeline metrics (engine.pipeline_report():
        stages/microbatches/ticks, analytic bubble fraction, host
        staging payload). None disarms."""
        self._pipeline = info

    def set_reconcile(self, fn):
        """Arm modeled-vs-measured reconciliation: ``fn(trace_dir,
        steps)`` -> a ``DriftReport.summary()`` dict (or None) whenever
        ``ProfilerControl`` finishes a step-ranged capture. The engine
        wires its ``_telemetry_reconcile`` here; None disarms."""
        self._reconcile_fn = fn

    def _on_trace_ready(self, trace_dir, steps, step):
        """ProfilerControl's stop hook. Trace parsing reads gzipped
        JSON off disk — background-pool work, never the step path."""
        if self._reconcile_fn is None:
            return
        self._submit(self._reconcile_round, trace_dir, steps, step)

    def _reconcile_round(self, trace_dir, steps, step):
        """Parse + reconcile one finished capture (pool side). Emits
        nothing directly: events park for the next main-thread flush
        (monitor writers are not thread-safe) and the summary lands in
        ``self.last`` + the flight recorder's crash context."""
        try:
            summary = self._reconcile_fn(trace_dir, steps)
        except Exception as e:  # noqa: BLE001 - reconcile is advisory
            if not self._reconcile_warned:
                self._reconcile_warned = True
                logger.warning(f"telemetry: reconcile failed "
                               f"({type(e).__name__}: {e})")
            return
        if summary is None:
            if not self._reconcile_warned:
                self._reconcile_warned = True
                logger.warning(
                    "telemetry: trace produced no step decomposition; "
                    "reconcile skipped (platform may not emit XLA op "
                    "tracks)")
            return
        self.last = dict(self.last, reconcile=summary)
        self.flight.record("reconcile", step=int(step),
                           top_term=summary.get("top_term", ""),
                           top_drift_ms=summary.get("top_drift_ms", 0),
                           wall_err_pct=summary.get("wall_err_pct", 0))
        self.flight.set_context("reconcile", summary)
        self._pending_reconcile_events = [
            ("Train/Reconcile/wall_err_pct",
             summary.get("wall_err_pct", 0.0), step),
            ("Train/Reconcile/top_drift_ms",
             summary.get("top_drift_ms", 0.0), step),
            ("Train/Reconcile/top_drift_term",
             summary.get("top_term_index", -1), step),
            ("Train/Reconcile/coverage_pct",
             summary.get("coverage_pct", 0.0), step),
        ]

    # ------------------------------------------------------------ feedback
    def note_overhead(self, kind, seconds):
        """Non-productive wall time (checkpoint_save /
        checkpoint_restore / reshape / restart) for goodput
        accounting."""
        self._overhead_s[kind] = self._overhead_s.get(kind, 0.0) \
            + float(seconds)
        self.flight.record(kind, s=round(float(seconds), 4))

    def on_restore(self, tier, tag, seconds):
        """A checkpoint load completed: which tier served it is the
        fact the flight recorder must carry into the next crash."""
        self.note_overhead("checkpoint_restore", seconds)
        self.flight.record("restore", tier=str(tier), tag=str(tag))

    def record_event(self, kind, **data):
        self.flight.record(kind, **data)

    def on_crash(self, exc):
        # SystemExit is a DELIBERATE exit, not a crash: the preempt
        # drain raises it after recording 'preempted' and dumping with
        # that reason — a crash-dump here would overwrite the orderly
        # tail the elastic agent reads to classify the death
        if isinstance(exc, SystemExit):
            return
        self.flight.crash(exc)

    def _on_fault(self, point, injected):
        self.flight.record("fault_point", point=point,
                           injected=bool(injected))

    # -------------------------------------------------------------- flush
    def _emit(self, events):
        if self.monitor is None or not getattr(self.monitor, "enabled",
                                               False):
            return
        for tag, _, _ in events:
            if tag not in TAG_SCHEMA and tag not in self._warned_tags:
                self._warned_tags.add(tag)
                logger.warning(
                    f"telemetry: emitting tag {tag!r} that is missing "
                    f"from monitor/tag_schema.py TAG_SCHEMA — register "
                    f"it (the schema lint will fail until you do)")
        self.monitor.write_events(events)

    def _capture_costs(self):
        """One-time step-cost capture (flops + collective schedule) from
        the engine's compiled program. In-caller at the first flush: a
        single extra XLA compile amortized over the whole run (and the
        compile cache makes it cheap when warm)."""
        if self._costs_tried or self._costs_fn is None:
            return
        self._costs_tried = True
        try:
            self._costs = self._costs_fn()
        except Exception as e:  # noqa: BLE001 - telemetry never fatal
            logger.warning(f"telemetry: step-cost capture failed "
                           f"({type(e).__name__}: {e}); MFU/comm "
                           f"breakdown unavailable")
            self._costs = None

    def goodput_pct(self):
        elapsed = max(1e-9, time.perf_counter() - self._t0)
        overhead = sum(self._overhead_s.values())
        return max(0.0, min(100.0, 100.0 * (1.0 - overhead / elapsed)))

    def _flush(self, step):
        # cluster aggregates a background fs gather finished since the
        # last flush: emitted HERE, on the main thread — the monitor
        # writers (csv file map, wandb, TB) are not thread-safe, so
        # write_events never runs on the pool (single-slot handoff,
        # latest wins; attribute swap is atomic under the GIL)
        pending, self._pending_cluster_events = \
            self._pending_cluster_events, None
        if pending:
            self._emit(pending)
        pending, self._pending_reconcile_events = \
            self._pending_reconcile_events, None
        if pending:
            self._emit(pending)
        samples = list(self._step_ms)
        self._step_ms.clear()
        tokens, self._tokens = self._tokens, 0
        window_s = sum(samples) / 1e3
        mean_ms = window_s * 1e3 / len(samples)
        self._capture_costs()

        snap = {
            "step": int(step),
            "steps_in_window": len(samples),
            "mean_step_ms": round(mean_ms, 3),
            "step_time_ms_p50": round(percentile(samples, 50), 3),
            "step_time_ms_p99": round(percentile(samples, 99), 3),
            "goodput_pct": round(self.goodput_pct(), 3),
            "overhead_s": {k: round(v, 4)
                           for k, v in self._overhead_s.items()},
            "elastic_generation": int(
                os.environ.get("ELASTIC_GENERATION", 0) or 0),
            "peak_flops_per_chip": self.peak_flops,
            "peak_assumed": self.peak_assumed,
        }
        if tokens and window_s > 0:
            snap["tokens_per_sec_chip"] = round(
                tokens / window_s / self.n_devices, 1)
        c = self._costs or {}
        if c.get("flops_per_chip"):
            snap["mfu_pct"] = round(
                100.0 * c["flops_per_chip"]
                / (mean_ms / 1e3) / self.peak_flops, 3)
            snap["flops_source"] = c.get("source", "hlo")
        if c.get("collectives") is not None:
            snap["collectives"] = int(c["collectives"])
            snap["exposed_comm_pct"] = round(
                float(c.get("exposed_comm_pct", 0.0)), 3)

        events = [
            ("Train/Telemetry/step_time_ms_p50",
             snap["step_time_ms_p50"], step),
            ("Train/Telemetry/step_time_ms_p99",
             snap["step_time_ms_p99"], step),
            ("Train/Telemetry/goodput_pct", snap["goodput_pct"], step),
        ]
        if "tokens_per_sec_chip" in snap:
            events.append(("Train/Telemetry/tokens_per_sec_chip",
                           snap["tokens_per_sec_chip"], step))
        if "mfu_pct" in snap:
            events.append(("Train/Telemetry/mfu_pct", snap["mfu_pct"],
                           step))
        if "collectives" in snap:
            events.append(("Train/Telemetry/collectives",
                           snap["collectives"], step))
            events.append(("Train/Telemetry/exposed_comm_pct",
                           snap["exposed_comm_pct"], step))
        if self._pipeline is not None:
            p = self._pipeline
            snap["pipeline"] = dict(
                p, steady_tick_ms=round(
                    mean_ms / max(1, p.get("ticks", 1)), 4))
            events.append(("Train/Pipeline/bubble_pct",
                           p["bubble_pct"], step))
            events.append(("Train/Pipeline/steady_tick_ms",
                           snap["pipeline"]["steady_tick_ms"], step))
            events.append(("Train/Pipeline/offload_bytes_per_step",
                           p.get("offload_bytes_per_step", 0), step))
        self._emit(events)

        if self.cluster is not None:
            metrics = {"node": self.cluster.node, "step": int(step),
                       "mean_step_ms": snap["mean_step_ms"],
                       "p99_step_ms": snap["step_time_ms_p99"],
                       "goodput_pct": snap["goodput_pct"]}
            if self.cluster.transport == "allgather":
                # collective transport: in-caller (every process flushes
                # at the same step boundary; a side thread could
                # interleave with the training collectives)
                self._cluster_round(metrics, step, emit_now=True)
            elif self.cluster.transport == "fs":
                self._submit(self._cluster_round, metrics, step, False)
        # opportunistic black-box dump: a SIGKILL'd/hung worker still
        # leaves a record at most one interval old
        if self.flight.root:
            self._submit(self.flight.dump, "interval")
            self.profiler.check_trigger(self.flight.root, step)
        # carry the most recent cluster aggregate across flushes (a
        # pool-side round attaches it asynchronously; a fresh window
        # must not blank it from snapshot())
        if "cluster" in self.last:
            snap.setdefault("cluster", self.last["cluster"])
        # ...and the latest reconcile drift summary, same discipline
        if "reconcile" in self.last:
            snap.setdefault("reconcile", self.last["reconcile"])
        self.last = snap

    def _cluster_round(self, metrics, step, emit_now):
        """Gather + aggregate one round. ``emit_now`` only when running
        in-caller (allgather transport); a pool-side round parks its
        events for the next main-thread flush instead — monitor
        writers are not thread-safe."""
        try:
            got = self.cluster.gather(metrics)
            # ring order, not lexical sort: string process ids ('10'
            # before '2') would misnumber the straggler on >=10 hosts
            agg = aggregate_cluster(got, order=self.cluster.peers)
            if agg is None:
                return
            self.last = dict(self.last, cluster=agg)
            if not self.cluster.is_root:
                return
            events = [
                ("Train/Telemetry/cluster_step_ms_p50",
                 agg["cluster_step_ms_p50"], step),
                ("Train/Telemetry/cluster_step_ms_p99",
                 agg["cluster_step_ms_p99"], step),
                ("Train/Telemetry/straggler_delta_ms",
                 agg["straggler_delta_ms"], step),
                ("Train/Telemetry/straggler_host",
                 agg["straggler_host"], step),
                ("Train/Telemetry/cluster_hosts", agg["hosts"], step),
            ]
            if emit_now:
                self._emit(events)
            else:
                self._pending_cluster_events = events
        except Exception as e:  # noqa: BLE001 - aggregation advisory
            logger.warning(f"telemetry: cluster aggregation failed: {e}")

    # ------------------------------------------------------------ plumbing
    def _submit(self, fn, *args):
        if self._closed:
            return
        if self._pool is None:
            import concurrent.futures as futures
            self._pool = futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dstpu-telemetry")
        self._futs = [f for f in self._futs if not f.done()]
        try:
            self._futs.append(self._pool.submit(fn, *args))
        except RuntimeError:   # pool shut down under our feet
            pass

    def drain(self):
        """Block until queued background work (fs gathers, dumps) is
        done — tests and benches read ``snapshot()`` after this."""
        for f in list(self._futs):
            try:
                f.result(timeout=30)
            except Exception:  # noqa: BLE001 - advisory work
                pass
        self._futs = []

    def snapshot(self):
        """The most recent flush's metrics (plus live goodput)."""
        out = dict(self.last)
        out["goodput_pct_live"] = round(self.goodput_pct(), 3)
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        from ..utils import fault_injection
        fault_injection.remove_listener(self._fault_listener)
        self.drain()
        if self._pool is not None:
            self._pool.shutdown(wait=False)


# -------------------------------------------------------------- serving side
class _ReqTimes:
    __slots__ = ("t_put", "t_first", "t_last", "pending")

    def __init__(self, t_put):
        self.t_put = t_put
        self.t_first = None
        self.t_last = None
        self.pending = 0


class ServingTelemetry:
    """Per-request TTFT/TPOT accounting for the v2 serving engine.

    TPOT is dispatch-amortized: the engine produces tokens in multi-step
    dispatches, so per-token deltas inside one dispatch are meaningless
    — tokens accumulate as ``pending`` and the wall time since the
    previous dispatch is split across them at :meth:`on_dispatch` (one
    call per ``engine.step()``). Sample windows are bounded deques;
    percentiles come from the window (the histogram the fan-out
    exports). With a ``monitor``, ``Serve/Telemetry/*`` events are
    written every ``interval`` completed requests, stepped by the
    completion count."""

    def __init__(self, monitor=None, interval=32, max_samples=4096):
        self.monitor = monitor
        self.interval = max(1, int(interval))
        self._live = {}
        # requests past their first token — the only ones on_dispatch
        # must visit; iterating _live would make every dispatch O(queued)
        # under an admission backlog
        self._started = {}
        self._ttft_ms = deque(maxlen=max_samples)
        self._tpot_ms = deque(maxlen=max_samples)
        self.completed = 0
        self.rejected = 0
        self.active = 0
        self._emitted_at = 0
        # engine-attached PrefixCache (inference/v2/prefix_cache.py);
        # when set, its hit/eviction/CoW counters ride percentiles()
        # and the Serve/Telemetry fan-out
        self._prefix_cache = None
        # speculative decoding: per-round counters plus acceptance-rate
        # EMAs keyed by request class (the router's priority klass) —
        # all zero/empty and absent from percentiles() until the first
        # on_spec_round, so spec-off snapshots stay byte-identical
        self._klass = {}                 # uid -> request class
        self.spec_rounds = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_committed = 0
        self._spec_ema = None            # global acceptance EMA
        self._spec_class_ema = {}        # klass -> acceptance EMA
        # disaggregated serving: requests that left via a prefill->
        # decode handoff (out) or arrived through one (in). Zero and
        # absent from percentiles() on colocated engines, so
        # disagg-off snapshots stay byte-identical.
        self.handoffs_in = 0
        self.handoffs_out = 0
        self._t0 = time.perf_counter()

    def attach_prefix_cache(self, cache):
        self._prefix_cache = cache

    def on_submit(self, uid, klass=0):
        self._live[uid] = _ReqTimes(time.perf_counter())
        self._klass[uid] = int(klass)

    def on_token(self, uid):
        """First token => TTFT sample; later tokens accumulate for the
        dispatch-boundary TPOT split."""
        st = self._live.get(uid)
        if st is None:
            return
        now = time.perf_counter()
        if st.t_first is None:
            st.t_first = st.t_last = now
            self._started[uid] = st
            self._ttft_ms.append((now - st.t_put) * 1e3)
        else:
            st.pending += 1

    def _flush_pending(self, st, now):
        if st.pending and st.t_last is not None:
            per_ms = (now - st.t_last) * 1e3 / st.pending
            # one sample per token, capped so a giant dispatch cannot
            # flood the window
            self._tpot_ms.extend([per_ms] * min(st.pending, 64))
        st.t_last = now
        st.pending = 0

    def on_dispatch(self, active=None):
        now = time.perf_counter()
        for st in self._started.values():
            self._flush_pending(st, now)
        if active is not None:
            self.active = int(active)

    def on_spec_round(self, uid, accepted, proposed, committed):
        """One speculative verify round for ``uid``: ``accepted`` of
        ``proposed`` draft tokens survived greedy verification and
        ``committed`` tokens (accepted + bonus) entered the stream.
        Updates the global and per-request-class acceptance EMAs the
        scheduler/router read for fallback and placement."""
        self.spec_rounds += 1
        self.spec_proposed += int(proposed)
        self.spec_accepted += int(accepted)
        self.spec_committed += int(committed)
        frac = accepted / max(1, proposed)
        a = 0.25                          # matches SPEC_EMA_ALPHA
        self._spec_ema = frac if self._spec_ema is None \
            else (1 - a) * self._spec_ema + a * frac
        k = self._klass.get(uid, 0)
        prev = self._spec_class_ema.get(k)
        self._spec_class_ema[k] = frac if prev is None \
            else (1 - a) * prev + a * frac

    def spec_acceptance_ema(self, klass=None):
        """Acceptance-rate EMA in [0, 1] — per request class when
        ``klass`` is given, global otherwise; None before the first
        verify round (spec off, or nothing speculated yet)."""
        if klass is None:
            return self._spec_ema
        return self._spec_class_ema.get(int(klass))

    def on_finish(self, uid):
        st = self._live.pop(uid, None)
        self._started.pop(uid, None)
        self._klass.pop(uid, None)
        if st is not None and st.t_first is not None:
            self._flush_pending(st, time.perf_counter())
        self.completed += 1

    def on_reject(self, uid):
        """A shed/expired/cancelled request leaves the accounting
        entirely: it has no dispatch boundary to amortize against, so
        leaving it in the maps would poison the TTFT/TPOT windows
        (zero/None samples at the next dispatch) and ``completed``
        would count requests that were never served. Percentile windows
        therefore hold ONLY requests that actually produced tokens to
        completion."""
        st = self._live.pop(uid, None)
        self._started.pop(uid, None)
        self._klass.pop(uid, None)
        if st is not None:
            self.rejected += 1

    # --------------------------- disaggregated prefill/decode handoff
    def submit_stamp(self, uid):
        """Original submit time (``time.perf_counter`` domain) of a
        live request — exported with the KV handoff payload so the
        decode side anchors its windows on the ORIGINAL submit, not
        its own admit time. Peek only; the request stays live here
        until :meth:`on_handoff_out`."""
        st = self._live.get(uid)
        return None if st is None else st.t_put

    def klass_of(self, uid):
        """Request class of a live request (0 when unknown) — carried
        across the handoff so per-class windows stay coherent."""
        return self._klass.get(uid, 0)

    def on_handoff_out(self, uid):
        """The request left THIS engine via a prefill->decode handoff:
        forget it WITHOUT counting a rejection — its TTFT sample (the
        first token was produced here) stays in the window, and the
        decode side owns the rest of its accounting."""
        self._live.pop(uid, None)
        self._started.pop(uid, None)
        self._klass.pop(uid, None)
        self.handoffs_out += 1

    def on_handoff_in(self, uid, klass=0, submit_ts=None):
        """Register a handed-off request on the DECODE side, anchored
        at the ORIGINAL submit stamp carried over the wire (decode-side
        admit time would hide the whole prefill+stream latency). The
        request arrives already STARTED — its first token was produced
        by the prefill replica, so no second TTFT sample is recorded
        here; subsequent tokens amortize TPOT from this boundary.

        Clock-domain caveat: the stamp is exact for the in-process
        transport (same ``perf_counter`` domain). Over the DCN
        transport the stamp comes from another host's clock — counters
        stay exact, latency windows are advisory there."""
        now = time.perf_counter()
        st = _ReqTimes(now if submit_ts is None else float(submit_ts))
        st.t_first = st.t_last = now
        self._live[uid] = st
        self._started[uid] = st
        self._klass[uid] = int(klass)
        self.handoffs_in += 1

    def percentiles(self):
        out = {
            "ttft_ms_p50": percentile(self._ttft_ms, 50),
            "ttft_ms_p99": percentile(self._ttft_ms, 99),
            "tpot_ms_p50": percentile(self._tpot_ms, 50),
            "tpot_ms_p99": percentile(self._tpot_ms, 99),
            "completed": self.completed,
            "active": self.active,
        }
        if self.rejected:
            # only present once a cancel/shed happened: router-off
            # engine snapshots stay byte-identical to pre-router runs
            out["rejected"] = self.rejected
        if self.handoffs_in or self.handoffs_out:
            # only present once a handoff touched this engine:
            # colocated snapshots stay byte-identical
            out["handoffs_in"] = self.handoffs_in
            out["handoffs_out"] = self.handoffs_out
        if self._prefix_cache is not None:
            s = self._prefix_cache.stats()
            elapsed = max(1e-9, time.perf_counter() - self._t0)
            out["prefix_hit_rate_pct"] = s["hit_rate_pct"]
            out["cached_tokens_per_sec"] = round(
                s["cached_tokens"] / elapsed, 1)
            out["prefix_evictions"] = s["evicted_blocks"]
            out["cow_copies"] = s["cow_copies"]
        if self.spec_rounds:
            # only present once a verify round ran: the zero-verify-step
            # guard — spec-off (and spec-on-but-idle) windows carry no
            # spec keys at all rather than NaN/zero-division rows
            out["spec_rounds"] = self.spec_rounds
            out["spec_acceptance_pct"] = round(
                100.0 * self.spec_accepted / max(1, self.spec_proposed),
                1)
            out["spec_tokens_per_verify_step"] = round(
                self.spec_committed / self.spec_rounds, 2)
            out["spec_class_acceptance_ema"] = {
                k: round(v, 3)
                for k, v in sorted(self._spec_class_ema.items())}
        return out

    def maybe_emit(self):
        if self.monitor is None \
                or not getattr(self.monitor, "enabled", False) \
                or self.completed - self._emitted_at < self.interval:
            return
        self._emitted_at = self.completed
        p = self.percentiles()
        step = self.completed
        events = [("Serve/Telemetry/completed", p["completed"], step),
                  ("Serve/Telemetry/active", p["active"], step)]
        for tag, key in (
                ("Serve/Telemetry/ttft_ms_p50", "ttft_ms_p50"),
                ("Serve/Telemetry/ttft_ms_p99", "ttft_ms_p99"),
                ("Serve/Telemetry/tpot_ms_p50", "tpot_ms_p50"),
                ("Serve/Telemetry/tpot_ms_p99", "tpot_ms_p99"),
                # prefix-cache effectiveness (only present with an
                # attached PrefixCache — see attach_prefix_cache)
                ("Serve/Telemetry/prefix_hit_rate_pct",
                 "prefix_hit_rate_pct"),
                ("Serve/Telemetry/cached_tokens_per_sec",
                 "cached_tokens_per_sec"),
                ("Serve/Telemetry/prefix_evictions", "prefix_evictions"),
                ("Serve/Telemetry/cow_copies", "cow_copies"),
                # speculative decoding (only present once a verify
                # round ran; spec_class_acceptance_ema is a dict and
                # rides percentiles()/snapshots only, not the scalar
                # event fan-out)
                ("Serve/Telemetry/spec_rounds", "spec_rounds"),
                ("Serve/Telemetry/spec_acceptance_pct",
                 "spec_acceptance_pct"),
                ("Serve/Telemetry/spec_tokens_per_verify_step",
                 "spec_tokens_per_verify_step")):
            if p.get(key) is not None:
                events.append((tag, p[key], step))
        self.monitor.write_events(events)
