"""Monitor configuration (reference monitor/config.py)."""

from dataclasses import dataclass, field


@dataclass
class TensorBoardConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


@dataclass
class WandbConfig:
    enabled: bool = False
    group: str = ""
    team: str = ""
    project: str = "deepspeed_tpu"


@dataclass
class CSVConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"


@dataclass
class DeepSpeedMonitorConfig:
    """Aggregates the three writer configs (reference
    monitor/config.py:DeepSpeedMonitorConfig)."""
    tensorboard: TensorBoardConfig = field(default_factory=TensorBoardConfig)
    wandb: WandbConfig = field(default_factory=WandbConfig)
    csv_monitor: CSVConfig = field(default_factory=CSVConfig)

    @property
    def enabled(self):
        return (self.tensorboard.enabled or self.wandb.enabled
                or self.csv_monitor.enabled)

    @classmethod
    def from_dict(cls, d):
        d = d or {}

        def take(cls_, key):
            sub = d.get(key, {})
            if isinstance(sub, cls_):
                return sub
            known = set(cls_.__dataclass_fields__)
            unknown = set(sub) - known
            if unknown:
                from ..utils.logging import logger
                logger.warning(f"monitor block '{key}': ignoring unknown "
                               f"keys {sorted(unknown)}")
            return cls_(**{k: v for k, v in sub.items() if k in known})

        return cls(tensorboard=take(TensorBoardConfig, "tensorboard"),
                   wandb=take(WandbConfig, "wandb"),
                   csv_monitor=take(CSVConfig, "csv_monitor"))
