"""Crash flight recorder: a bounded ring of recent structured events,
dumped to ``{ckpt_root}/flightrec/host{n}.json`` when the process dies.

A pod incident leaves almost nothing behind: the dead worker's logs end
mid-step and the elastic agent only sees an exit code. This module keeps
the last N structured events — step completions, fired fault-injection
points, checkpoint saves/restores (with the tier that served them),
reshape decisions, heartbeats, profiler actions — in memory, and writes
them out when it matters:

  * **crash** — the engine wraps its step/save/load paths and calls
    :meth:`FlightRecorder.crash` on any ``BaseException`` (including the
    chaos suite's ``SimulatedKill``) before re-raising;
  * **SIGTERM** — :meth:`install_sigterm` chains a dump in front of the
    previous handler (the elastic agent tears surviving workers down
    with ``terminate()``, so every teardown leaves a record);
  * **hang-detection / SIGKILL** — nothing can run in the victim, so the
    telemetry layer also dumps *opportunistically* at every flush
    interval (off the step path, on the telemetry pool): a worker killed
    cold still leaves a dump at most ``interval_steps`` old.

The elastic agent reads the dumps of failed hosts
(:func:`read_dump`) and attaches the event tail to its failure
classification, so "why did host 3 die" starts from data instead of
archaeology.

Dumps are plain JSON (one object, ``events`` newest-last) written
atomically (tmp + rename) — a dump torn by the dying process never
shadows an older complete one.
"""

import collections
import itertools
import json
import os
import signal
import threading
import time

# unique per-dump tmp-name sequence (next() is atomic under the GIL)
_DUMP_SEQ = itertools.count()


def node_name():
    """This process's node id for dump naming: the elastic agent exports
    ``DSTPU_FLIGHTREC_NODE`` (its host name for the worker); otherwise
    the jax process index."""
    node = os.environ.get("DSTPU_FLIGHTREC_NODE")
    if node:
        return str(node)
    try:
        import jax
        return str(jax.process_index())
    except Exception:  # noqa: BLE001 - pre-backend-init callers
        return "0"


def dump_path(root, node):
    """Dump file for ``node`` under ``root`` — shared by the writer
    (worker) and the reader (elastic agent)."""
    return os.path.join(root, f"host{node}.json")


def read_dump(root, node):
    """The agent-side reader: parsed dump dict for ``node``, or None
    when no (complete) dump exists."""
    try:
        with open(dump_path(root, node), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class FlightRecorder:
    """Thread-safe bounded event ring. ``record`` is the hot-path entry
    (one deque append under an uncontended lock); everything else runs
    off the step path."""

    def __init__(self, size=256, node=None):
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=max(8, int(size)))
        self.node = node_name() if node is None else str(node)
        self.root = None          # set via set_root; None = tmp fallback
        self._prev_sigterm = None
        self._dumped_reason = None
        self._context = {}        # sticky facts carried into every dump

    # ------------------------------------------------------------ events
    def record(self, kind, **data):
        ev = {"t": round(time.time(), 6), "kind": kind}
        ev.update(data)
        with self._lock:
            self._events.append(ev)

    def set_context(self, key, value):
        """Attach a sticky fact to every future dump (latest wins per
        key) — unlike ring events these survive however many steps pass
        before the crash. Telemetry parks the newest reconcile drift
        summary here so a post-mortem shows whether the pod was running
        off-model."""
        with self._lock:
            self._context[key] = value

    def context(self):
        with self._lock:
            return dict(self._context)

    def events(self):
        with self._lock:
            return list(self._events)

    # ------------------------------------------------------------- dumps
    def set_root(self, root):
        """First-wins dump directory: config/env beats the
        save_checkpoint-derived ``{ckpt_root}/flightrec`` default."""
        if root and self.root is None:
            self.root = root

    def _resolved_root(self):
        if self.root:
            return self.root
        import tempfile
        return os.path.join(tempfile.gettempdir(), "dstpu_flightrec")

    def dump(self, reason="manual"):
        """Write the ring to ``{root}/host{node}.json`` (atomic).
        Returns the path, or None when the write itself failed — a
        dying process must never die *harder* because its black box
        could not be written."""
        root = self._resolved_root()
        path = dump_path(root, self.node)
        payload = {
            "node": self.node,
            "pid": os.getpid(),
            "reason": reason,
            "dumped_at": round(time.time(), 6),
            "events": self.events(),
        }
        # only when something was parked: dumps stay byte-identical to
        # the pre-context schema on runs that never reconcile
        ctx = self.context()
        if ctx:
            payload["context"] = ctx
        try:
            os.makedirs(root, exist_ok=True)
            # per-call unique tmp: a main-thread crash dump can race a
            # pool-thread interval dump in the SAME process, and a
            # shared pid-only tmp would tear the JSON both are writing
            tmp = (f"{path}.tmp.{os.getpid()}."
                   f"{threading.get_ident()}.{next(_DUMP_SEQ)}")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._dumped_reason = reason
            return path
        except OSError:
            return None

    def crash(self, exc):
        """Record the terminal exception and dump. Called from
        ``except BaseException`` wrappers — must never raise."""
        try:
            self.record("crash", error=f"{type(exc).__name__}: {exc}"[:300])
            self.dump(reason="crash")
        except Exception:  # noqa: BLE001 - never mask the real failure
            pass

    # ------------------------------------------------------------ signals
    def install_sigterm(self):
        """Chain a dump in front of the current SIGTERM disposition.
        Main-thread only (signal module restriction); a non-main-thread
        caller is a silent no-op."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _handler(signum, frame):
            self.record("sigterm")
            self.dump(reason="sigterm")
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                # restore the default and re-deliver so the exit status
                # still says "terminated by SIGTERM"
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)
            return True
        except (ValueError, OSError):  # non-main thread / exotic host
            return False
