from .monitor import MonitorMaster
from .config import DeepSpeedMonitorConfig, TensorBoardConfig, WandbConfig, CSVConfig
