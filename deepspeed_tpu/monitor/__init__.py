from .monitor import MonitorMaster
from .config import DeepSpeedMonitorConfig, TensorBoardConfig, WandbConfig, CSVConfig
from .tag_schema import TAG_SCHEMA
from .telemetry import TelemetryCollector, ServingTelemetry
from .flight_recorder import FlightRecorder
