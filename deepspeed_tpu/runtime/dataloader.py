"""Data loading helpers.

Counterpart of reference ``runtime/dataloader.py`` (DeepSpeedDataLoader) and
``engine.py:1715 deepspeed_io``. Torch-free: a dataset is any sequence or
iterable of (dict of) numpy arrays; batches are stacked host-side and the
engine shards them onto the mesh.
"""

import numpy as np


class RepeatingLoader:
    """reference runtime/dataloader.py RepeatingLoader: wraps an iterator,
    restarting it when exhausted."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedDataLoader:
    """Batches an indexable dataset of pytrees of arrays.

    Each item: dict of numpy arrays (or a single array). drop_last always
    (static shapes keep XLA happy — the reference pads instead)."""

    def __init__(self, dataset, batch_size, shuffle=False, seed=0,
                 collate_fn=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.collate_fn = collate_fn or _default_collate
        self.epoch = 0

    def __len__(self):
        return len(self.dataset) // self.batch_size

    def __iter__(self):
        order = np.arange(len(self.dataset))
        if self.shuffle:
            np.random.RandomState(self.seed + self.epoch).shuffle(order)
        self.epoch += 1
        for i in range(len(self)):
            idx = order[i * self.batch_size:(i + 1) * self.batch_size]
            yield self.collate_fn([self.dataset[int(j)] for j in idx])


def _default_collate(items):
    first = items[0]
    if isinstance(first, dict):
        return {k: np.stack([it[k] for it in items]) for k in first}
    return np.stack(items)


class SamplerDataLoader:
    """Loader driven by a DeepSpeedDataSampler (curriculum-aware,
    resumable): each iteration draws the sampler's next global index
    batch and collates the items (reference DeepSpeedDataLoader with
    data_sampler, deepspeed_io:1715)."""

    def __init__(self, dataset, sampler, collate_fn=None):
        self.dataset = dataset
        self.sampler = sampler
        self.collate_fn = collate_fn or _default_collate
        self._stream = iter(sampler)

    def __len__(self):
        return len(self.sampler)

    def __iter__(self):
        # the sampler is an endless resumable stream; one __iter__ call
        # is ONE EPOCH (len(self) batches), so the normal
        # `for batch in loader:` loop terminates like the plain loader —
        # sampler state persists across epochs (consumed_samples)
        for _ in range(len(self)):
            idx = next(self._stream)
            yield self.collate_fn([self.dataset[int(j)] for j in idx])
