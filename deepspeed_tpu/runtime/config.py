"""JSON config -> typed config objects.

Counterpart of the reference's ``runtime/config.py:706 DeepSpeedConfig``
(pydantic there; plain dataclasses here — no extra deps, static and
hashable so configs can feed jit). Implements the same batch-size triad
resolution (train_batch = micro_batch * grad_accum * dp_world) with the
reference's error semantics, precision blocks, ZeRO block, and the fork's
checkpoint-engine selection keys (reference runtime/config.py:909-926).
"""

import json
from dataclasses import dataclass, field, fields, asdict

from . import constants as C
from ..utils.logging import logger


class DeepSpeedConfigError(Exception):
    pass


@dataclass
class FP16Config:
    enabled: bool = False
    loss_scale: float = 0.0          # 0 = dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    min_loss_scale: float = 1.0


@dataclass
class BF16Config:
    enabled: bool = False


@dataclass
class OffloadConfig:
    """Reference zero/offload_config.py DeepSpeedZeroOffloadOptimizerConfig /
    ...ParamConfig: where the offloaded state lives."""
    device: str = "none"              # none | cpu | nvme
    nvme_path: str = "/tmp/dstpu_swap"
    pin_memory: bool = True           # accepted for compatibility
    buffer_count: int = 4             # accepted for compatibility

    @classmethod
    def normalize(cls, val):
        """Accept bool (true -> cpu), reference-style dict, or None."""
        if isinstance(val, cls):
            return val
        if val is None or val is False:
            return cls()
        if val is True:
            return cls(device="cpu")
        if isinstance(val, dict):
            known = {f.name for f in fields(cls)}
            out = cls(**{k: v for k, v in val.items() if k in known})
            out.device = str(out.device).lower()
            if out.device not in ("none", "cpu", "nvme"):
                raise DeepSpeedConfigError(
                    f"offload device must be none|cpu|nvme, got "
                    f"{out.device!r}")
            return out
        raise DeepSpeedConfigError(f"bad offload config: {val!r}")

    @property
    def enabled(self):
        return self.device != "none"


@dataclass
class ZeroConfig:
    """Mirrors reference zero/config.py:82 DeepSpeedZeroConfig knobs that are
    meaningful under XLA. Bucket sizes/overlap are accepted for config
    compatibility; XLA's scheduler handles what streams+buckets did."""
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = int(5e8)
    allgather_bucket_size: int = int(5e8)
    overlap_comm: bool = True
    round_robin_gradients: bool = False
    sub_group_size: int = int(1e9)
    prefetch_bucket_size: int = int(5e7)
    param_persistence_threshold: int = int(1e5)
    model_persistence_threshold: int = int(1e10)
    max_live_parameters: int = int(1e9)
    offload_optimizer: object = False   # bool | dict -> OffloadConfig
    offload_param: object = False       # bool | dict -> OffloadConfig
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    hpz_partition_size: int = 1
    mics_shard_size: int = -1

    def __post_init__(self):
        self.offload_optimizer = OffloadConfig.normalize(
            self.offload_optimizer)
        self.offload_param = OffloadConfig.normalize(self.offload_param)
        if self.stage not in (0, 1, 2, 3):
            raise DeepSpeedConfigError(f"invalid ZeRO stage {self.stage}")
        mics = self.mics_shard_size not in (-1, 0)
        hpz = self.hpz_partition_size > 1
        if mics and hpz and self.mics_shard_size != self.hpz_partition_size:
            raise DeepSpeedConfigError(
                f"mics_shard_size={self.mics_shard_size} and "
                f"hpz_partition_size={self.hpz_partition_size} disagree; "
                "both subdivide the same inner data axis — set one (or "
                "equal values)")


@dataclass
class TensorParallelConfig:
    size: int = 1


@dataclass
class PipelineConfig:
    """Pipeline-parallelism block (runtime/pipe/ — the engine resolves
    it and installs ``model._pipe_cfg``; GPT2Pipe consults it per loss):

      stages              pipe mesh axis size (the topology builder
                          reads this when no explicit topology is given).
      micro_batches       microbatches in flight. 0 = auto: the
                          'pipe_microbatch' autotune op's winner for
                          this (stages, batch, seq, d_model) bucket
                          when the winner cache has one, else 2*stages
                          (amortizes the fill/drain bubble).
      schedule            'auto' (defer to the model's own
                          pipe_schedule knob — back-compat; the bench/
                          probe paths set 'zb' explicitly) | 'gpipe'
                          (fill-drain + autodiff backward) | '1f1b'
                          (interleaved, O(stages) live activations) |
                          'zb' (zero-bubble: 1F1B with the backward
                          W/B split filling the drain ticks —
                          runtime/pipe/spmd.py pipeline_zb_grads).
      offload_activations host placement of the steady-state
                          executors' activation rings (and the GPipe
                          path's saved residuals via the offload remat
                          policy): 'auto' = on iff the backend has a
                          distinct host memory kind AND the estimated
                          train state does not fit HBM (the 13B-on-
                          small-pods case); true forces (identity on
                          single-memory-space backends, with a
                          warning); false off.
      offload_moments     optimizer-moment placement on host memory
                          via sharding-with-memory-kind: 'auto' = off
                          (moments offload changes the optimizer
                          update's memory traffic every step — opt in
                          explicitly or let the HBM-fit heuristic of a
                          13B recipe set it); true requires the
                          backend kind (degrades with a warning).
      offload_double_buffer
                          prefetch the next tick's ring read one tick
                          early so the H2D copy hides under compute
                          (the comm-overlap discipline applied to host
                          copies); false fetches at use (A/B lever).
    """
    stages: int = 1
    micro_batches: int = 0            # 0 = auto (winner cache, else 2S)
    partition_method: str = "uniform"
    activation_checkpoint_interval: int = 0
    schedule: str = "auto"            # auto | gpipe | 1f1b | zb
    offload_activations: object = "auto"   # "auto" | bool
    offload_moments: object = "auto"       # "auto" | bool
    offload_double_buffer: bool = True

    def __post_init__(self):
        if self.schedule not in ("auto", "gpipe", "1f1b", "zb"):
            raise DeepSpeedConfigError(
                f"pipeline.schedule must be auto|gpipe|1f1b|zb, got "
                f"{self.schedule!r}")
        for name in ("offload_activations", "offload_moments"):
            if getattr(self, name) not in (True, False, "auto"):
                raise DeepSpeedConfigError(
                    f"pipeline.{name} must be true|false|'auto', got "
                    f"{getattr(self, name)!r}")
        if not isinstance(self.micro_batches, int) \
                or self.micro_batches < 0:
            raise DeepSpeedConfigError(
                f"pipeline.micro_batches must be an int >= 0 (0 = "
                f"auto), got {self.micro_batches!r}")
        if not isinstance(self.stages, int) or self.stages < 1:
            raise DeepSpeedConfigError(
                f"pipeline.stages must be an int >= 1, got "
                f"{self.stages!r}")

    def resolve_schedule(self, model_schedule=None):
        """'auto' defers to the model's own pipe_schedule knob (so the
        existing model-config surface keeps its meaning); an explicit
        block schedule wins over the model."""
        if self.schedule != "auto":
            return self.schedule
        return model_schedule or "gpipe"

    @staticmethod
    def hbm_fits(est_state_bytes, hbm_bytes, margin=0.8):
        """The HBM-fit heuristic behind offload 'auto': does the
        estimated per-chip train state fit in ``margin`` of HBM?
        Unknown sizes (None/0) count as fitting — 'auto' must never
        turn offload on blind."""
        if not est_state_bytes or not hbm_bytes:
            return True
        return est_state_bytes <= margin * hbm_bytes

    def resolve_offload_activations(self, available, pipe_world=1,
                                    est_state_bytes=None, hbm_bytes=None):
        """'auto': on iff the backend can stage to host, a pipe axis is
        actually present, and the HBM-fit heuristic says the state does
        NOT fit — the reference only swaps when memory forces it."""
        if self.offload_activations != "auto":
            return bool(self.offload_activations)
        return bool(available and pipe_world > 1
                    and not self.hbm_fits(est_state_bytes, hbm_bytes))

    def resolve_offload_moments(self, available):
        """'auto' = off (see the field doc); True degrades to off with
        the host_stage warning when the backend has one memory space."""
        if self.offload_moments == "auto":
            return False
        return bool(self.offload_moments) and bool(available)


@dataclass
class OptimizerConfig:
    type: str = "AdamW"
    params: dict = field(default_factory=dict)


@dataclass
class SchedulerConfig:
    type: str = None
    params: dict = field(default_factory=dict)


@dataclass
class CheckpointEngineConfig:
    """Fork parity: reference runtime/config.py:909-926 registers
    datastates/async/none/torch_sn_async engine configs; we expose one
    block with a type switch, plus the crash-consistency knobs
    (retry/degrade policy and retention)."""
    type: str = "sync"                # sync | async | native | none
    host_cache_bytes: int = 1 << 30   # pinned-host staging budget (async/native)
    writer_threads: int = 2
    max_inflight: int = 2
    # retry/degrade policy: each shard write gets save_retries retries
    # with capped exponential backoff, then the engine's degraded writer
    # (native -> python; async pool dead -> in-caller sync write)
    save_retries: int = 2
    retry_backoff_s: float = 0.05
    retry_backoff_cap_s: float = 2.0
    # retention: keep the newest keep_last durable tags, GC older ones
    # only after the newest verifies (CRC + chunk coverage). 0 = keep all.
    keep_last: int = 0
    # hot tier (checkpoint_engine/hot_tier.py): peer-replicated
    # in-memory generations so the common single-host loss restores with
    # zero persistent-storage reads.
    #   hot_tier      "auto" (on iff an elastic launcher exported the
    #                 ring env — DSTPU_HOT_PEERS/DSTPU_HOT_TIER_ROOT/
    #                 DSTPU_HOT_TRANSPORT) | true | false. 'auto' is
    #                 deliberately NOT on for a bare multi-process
    #                 world: the default fs transport writes into
    #                 node-local tmpfs, which only survives a host loss
    #                 when the launcher wired the ring (or the dcn
    #                 transport moves bytes between hosts) — pushing
    #                 replicas nobody could ever restore from would be
    #                 pure per-save overhead
    #   hot_replicas  K: ring neighbors receiving each shard replica
    #   hot_root      store root ("" = DSTPU_HOT_TIER_ROOT env, else
    #                 tmpfs /dev/shm — host RAM, the point of the tier)
    #   hot_keep_last hot-tier retention (a bounded RAM cache, not an
    #                 archive)
    hot_tier: object = "auto"
    hot_replicas: object = 1          # int >= 0 | "auto" (winner cache)
    hot_root: str = ""
    hot_keep_last: int = 2
    # async-push backlog bound (hot_tier.push_async): at most this many
    # pending pushes; the oldest queued one is dropped (counted as an
    # advisory hot_push_errors) and a newer push of the same tag
    # supersedes a still-queued one
    hot_max_inflight_pushes: int = 4
    # preemption-graceful drain: on SIGTERM (TPU maintenance notice /
    # elastic-agent forward) finish the in-flight step, force one
    # hot+replica push and a flight-recorder dump, then exit with
    # PREEMPTED_EXIT_CODE so the agent classifies 'preempted' (no
    # backoff). "auto" = on iff supervised (ELASTIC_GENERATION in env
    # or DSTPU_PREEMPT_DRAIN exported) | true | false.
    preempt_drain: object = "auto"

    def __post_init__(self):
        if self.save_retries < 0:
            raise DeepSpeedConfigError(
                f"checkpoint_engine.save_retries must be >= 0, got "
                f"{self.save_retries}")
        if self.keep_last < 0:
            raise DeepSpeedConfigError(
                f"checkpoint_engine.keep_last must be >= 0 (0 disables "
                f"retention GC), got {self.keep_last}")
        if self.hot_tier not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"checkpoint_engine.hot_tier must be true|false|'auto', "
                f"got {self.hot_tier!r}")
        if self.hot_replicas != "auto" and (
                not isinstance(self.hot_replicas, int)
                or isinstance(self.hot_replicas, bool)
                or self.hot_replicas < 0):
            raise DeepSpeedConfigError(
                f"checkpoint_engine.hot_replicas must be an int >= 0 or "
                f"'auto', got {self.hot_replicas!r}")
        if self.hot_keep_last < 1:
            raise DeepSpeedConfigError(
                f"checkpoint_engine.hot_keep_last must be >= 1 (the "
                f"tier must hold at least the newest generation), got "
                f"{self.hot_keep_last}")
        if not isinstance(self.hot_max_inflight_pushes, int) \
                or isinstance(self.hot_max_inflight_pushes, bool) \
                or self.hot_max_inflight_pushes < 1:
            raise DeepSpeedConfigError(
                f"checkpoint_engine.hot_max_inflight_pushes must be an "
                f"int >= 1 (the bound must admit at least one pending "
                f"push), got {self.hot_max_inflight_pushes!r}")
        if self.preempt_drain not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"checkpoint_engine.preempt_drain must be "
                f"true|false|'auto', got {self.preempt_drain!r}")

    def resolve_preempt_drain(self):
        """'auto' arms the SIGTERM drain iff something supervises us —
        an elastic agent (ELASTIC_GENERATION) or an operator export
        (DSTPU_PREEMPT_DRAIN). Unsupervised runs keep the default
        SIGTERM disposition: nothing would classify the distinct exit
        code, and hijacking the signal would only delay teardown."""
        import os
        if self.preempt_drain != "auto":
            return bool(self.preempt_drain)
        return bool(os.environ.get("ELASTIC_GENERATION") is not None
                    or os.environ.get("DSTPU_PREEMPT_DRAIN"))

    def resolve_hot_tier(self, nprocs=1):
        """'auto' turns the tier on iff an elastic launcher (or the
        operator) exported the ring env. ``nprocs`` is accepted for
        call-site symmetry but deliberately unused — see the hot_tier
        field comment."""
        import os
        if self.hot_tier != "auto":
            return bool(self.hot_tier)
        return bool(os.environ.get("DSTPU_HOT_PEERS")
                    or os.environ.get("DSTPU_HOT_TIER_ROOT")
                    or os.environ.get("DSTPU_HOT_TRANSPORT"))


@dataclass
class CommOverlapConfig:
    """Communication-overlap block (the reference's ``overlap_comm`` +
    ZeRO++ hierarchical collectives, expressed TPU-natively — see
    runtime/zero/overlap.py for what each knob turns into):

      enabled       "auto" (on iff dp_world > 1) | true | false. Turns on
                    XLA's latency-hiding scheduler / async-collective
                    flags and the per-layer grad-reduction annotations.
      bucket_mb     layer-granular reduce gate: a scan layer whose grad
                    bytes are below this emits no in-scan collective (its
                    reduction coalesces into the post-backward one, the
                    reference's bucketing of small grads); also feeds the
                    GPU combine-threshold flags. 0 = annotate everything;
                    "auto" = the 'comm_bucket' autotune winner for this
                    (device, topology, layer-payload) bucket, 32 on a
                    cold cache (byte-identical to the hand-set default).
      prefetch      ZeRO-3: explicit per-layer param gather at the top of
                    the scan body + unroll hint + backward all-gather
                    pipelining flag, so layer i+1's gather flies under
                    layer i's matmuls (PartitionedParameterCoordinator
                    prefetch, declaratively).
      hierarchical  "auto" (on iff the mesh has data_outer > 1) | bool.
                    Two-stage grad reduction: reduce-scatter over the
                    inner ('data','expert') ICI axes, then the cross-
                    slice 'data_outer' (DCN) hop on the already-scattered
                    shard (ZeRO++/MiCS hierarchical partitioning).
      dcn_quantize  int8 block-quantize round trip on the inner-reduced
                    gradient shard feeding the DCN hop (ZeRO++ qgZ
                    numerics). Requires a hierarchical data_outer stage
                    — ignored (with a warning) otherwise; wire-level
                    int8 for explicit pipelines lives in
                    comm/quantized.py. "auto" = the 'dcn_quantize'
                    autotune winner (off on a cold cache — quantization
                    changes numerics, never turned on blind by default).
      scan_unroll   unroll factor of the layer scan when comm overlap is
                    on (gives XLA unrolled iterations to slide gathers /
                    reductions across): int >= 1 | "auto" (the
                    'scan_unroll' winner; 2 on a cold cache — the
                    hand-set value overlap has shipped with).
      set_xla_flags whether the engine may append overlap flags to
                    XLA_FLAGS (only effective before backend init; the
                    DSTPU_COMM_OVERLAP=1 env does it at import time).
    """
    enabled: object = "auto"          # "auto" | bool
    bucket_mb: object = 32            # int >= 0 | "auto" (winner cache)
    prefetch: bool = True
    hierarchical: object = "auto"     # "auto" | bool
    dcn_quantize: object = False      # bool | "auto" (winner cache)
    scan_unroll: object = "auto"      # int >= 1 | "auto" (winner cache)
    set_xla_flags: bool = True

    def __post_init__(self):
        if self.enabled not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"comm_overlap.enabled must be true|false|'auto', got "
                f"{self.enabled!r}")
        if self.hierarchical not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"comm_overlap.hierarchical must be true|false|'auto', "
                f"got {self.hierarchical!r}")
        if self.bucket_mb != "auto" and (
                not isinstance(self.bucket_mb, int)
                or isinstance(self.bucket_mb, bool)
                or self.bucket_mb < 0):
            raise DeepSpeedConfigError(
                f"comm_overlap.bucket_mb must be an int >= 0 or 'auto', "
                f"got {self.bucket_mb!r}")
        if self.dcn_quantize not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"comm_overlap.dcn_quantize must be true|false|'auto', "
                f"got {self.dcn_quantize!r}")
        if self.scan_unroll != "auto" and (
                not isinstance(self.scan_unroll, int)
                or isinstance(self.scan_unroll, bool)
                or self.scan_unroll < 1):
            raise DeepSpeedConfigError(
                f"comm_overlap.scan_unroll must be an int >= 1 or "
                f"'auto', got {self.scan_unroll!r}")

    def resolve_enabled(self, dp_world_size):
        if self.enabled == "auto":
            return dp_world_size > 1
        return bool(self.enabled)

    def resolve_hierarchical(self, data_outer_size):
        if self.hierarchical == "auto":
            return data_outer_size > 1
        return bool(self.hierarchical)


@dataclass
class SequenceConfig:
    """Sequence/context-parallelism block (sequence/ring.py — consumed by
    models whose ``attention_backend='ring'`` when the mesh has seq > 1):

      layout        'zigzag' (default): each rank holds one early + one
                    mirrored late sequence chunk, so causal work is
                    identical across ranks and fully-masked chunk pairs
                    are statically skipped (~2x causal FLOPs saved vs
                    computing-then-masking). 'contiguous': the naive
                    layout (every pair computed, positionally masked) —
                    the A/B fallback.
      block_kernel  'auto' (default): ring steps run the carry-state
                    blockwise Pallas flash kernel with tiles resolved
                    from the autotune winner cache (op 'ring_block';
                    r05 defaults on a miss) | true (kernel, r05 tiles) |
                    false (dense einsum block steps — reference path).
      double_buffer issue each step's KV ppermute BEFORE the step's
                    kernels so the rotation hides under compute (the
                    comm-overlap discipline); false serializes
                    rotate-then-compute (A/B lever).
      rotate_chunks split each KV rotation into this many head-dim
                    ppermutes so the first chunk lands early: int >= 1 |
                    "auto" (the 'ring_rotate' autotune winner; 1 — the
                    fused single-ppermute program — on a cold cache).
    """
    layout: str = "zigzag"
    block_kernel: object = "auto"
    double_buffer: bool = True
    rotate_chunks: object = "auto"    # int >= 1 | "auto" (winner cache)

    def __post_init__(self):
        if self.layout not in ("zigzag", "contiguous"):
            raise DeepSpeedConfigError(
                f"sequence.layout must be 'zigzag'|'contiguous', got "
                f"{self.layout!r}")
        if self.block_kernel not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"sequence.block_kernel must be true|false|'auto', got "
                f"{self.block_kernel!r}")
        if self.rotate_chunks != "auto" and (
                not isinstance(self.rotate_chunks, int)
                or isinstance(self.rotate_chunks, bool)
                or self.rotate_chunks < 1):
            raise DeepSpeedConfigError(
                f"sequence.rotate_chunks must be an int >= 1 or 'auto', "
                f"got {self.rotate_chunks!r}")


@dataclass
class MoEConfig:
    """Dropless-MoE block (moe/sharded_moe.py + ops/pallas/
    grouped_matmul.py — the engine installs it on the model as
    ``model._moe_cfg``; mixtral consults it per dispatch, and for
    MoE-layer models (GPT2MoE) an explicit non-"auto"
    ``grouped_kernel`` here overrides the model-config knob):

      grouped_kernel   expert-FFN engine for the ragged (dropless)
                       paths: "auto" (default — resolve kernel-vs-
                       ragged_dot and tile sizes per shape bucket from
                       the 'moe_grouped_mm' autotune winner cache; a
                       cold cache keeps the lax.ragged_dot program
                       byte-identical) | true (Pallas grouped-GEMM
                       kernel, default tiles) | false (ragged_dot).
      hierarchical_a2a "auto" (default — the EP all_to_all stages
                       ICI -> DCN iff the mesh has a data_outer axis
                       > 1 and the experts divide the combined
                       (outer, expert) shard grid) | true (require the
                       staging; loud error if experts don't divide) |
                       false (always the flat single-hop exchange).
      dcn_quantize     qgZ int8 block round trip on the token payload
                       of the DCN legs ONLY (both directions of the
                       data_outer hop; the ICI hop stays exact) —
                       requires a hierarchical stage, ignored without
                       one (same discipline as comm_overlap).
    """
    grouped_kernel: object = "auto"    # "auto" | bool
    hierarchical_a2a: object = "auto"  # "auto" | bool
    dcn_quantize: object = False       # bool | "auto" (winner cache)

    def __post_init__(self):
        if self.grouped_kernel not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"moe.grouped_kernel must be true|false|'auto', got "
                f"{self.grouped_kernel!r}")
        if self.hierarchical_a2a not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"moe.hierarchical_a2a must be true|false|'auto', got "
                f"{self.hierarchical_a2a!r}")
        if self.dcn_quantize not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"moe.dcn_quantize must be true|false|'auto', got "
                f"{self.dcn_quantize!r}")


@dataclass
class QuantizeConfig:
    """One roof for the training engine's low-precision levers
    (runtime/engine.py consumes it at build). Every field is a planner
    knob — "auto" spellings resolve from the autotune winner cache with
    cold-cache defaults equal to the hand-set values, so a config that
    only adds ``{"quantize": {}}`` compiles byte-identical programs.

      grad_dcn         int8 block-quantize round trip on the DCN
                       (data_outer) leg of the staged ZeRO grad
                       reduction. None (default) defers to
                       comm_overlap.dcn_quantize; true|false|"auto"
                       OVERRIDE it (one quantize block can steer a
                       config whose comm_overlap block is shared).
      moe_dcn          same, for the MoE hierarchical all_to_all's DCN
                       legs; None defers to moe.dcn_quantize.
      int8_matmul      W8A8 dense-MLP compute (ops/pallas/quantization
                       .int8_matmul — dynamic rowwise activation codes x
                       channelwise weight codes, int32 accumulate,
                       straight-through fp grads). false (default) |
                       true | "auto" (the 'mlp_int8' winner cache per
                       shape bucket; winners must pass the registry
                       parity gate before caching, cold cache = off).
      moe_int8_matmul  W8A8 expert-FFN compute (grouped_int8_matmul
                       over lax.ragged_dot): false | true | "auto"
                       (the 'moe_grouped_int8' winner cache).
    """
    grad_dcn: object = None          # None | bool | "auto"
    moe_dcn: object = None           # None | bool | "auto"
    int8_matmul: object = False      # bool | "auto"
    moe_int8_matmul: object = False  # bool | "auto"

    def __post_init__(self):
        if self.grad_dcn not in (None, True, False, "auto"):
            raise DeepSpeedConfigError(
                f"quantize.grad_dcn must be null|true|false|'auto', got "
                f"{self.grad_dcn!r}")
        if self.moe_dcn not in (None, True, False, "auto"):
            raise DeepSpeedConfigError(
                f"quantize.moe_dcn must be null|true|false|'auto', got "
                f"{self.moe_dcn!r}")
        if self.int8_matmul not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"quantize.int8_matmul must be true|false|'auto', got "
                f"{self.int8_matmul!r}")
        if self.moe_int8_matmul not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"quantize.moe_int8_matmul must be true|false|'auto', "
                f"got {self.moe_int8_matmul!r}")


@dataclass
class AutotuneConfig:
    """Measured kernel dispatch (autotuning/kernel_dispatch.py): kernel
    tunables set to "auto" (flash blocks / mlp_kernel / fused_layernorm
    / fused-CE tiles) resolve against a persistent winner cache keyed by
    (device_kind, op, shape-bucket, dtype).

      mode         "" = inherit the DSTPU_AUTOTUNE env (default
                   cache_only) | off | cache_only | on_first_use |
                   search. cache_only never measures — a cold key falls
                   back to the r05-proven defaults; on_first_use runs a
                   measured search per missing key at first trace and
                   persists the winner; search re-measures every key
                   once per process (cache pre-warming/re-validation).
      cache_path   winner cache file ("" = DSTPU_AUTOTUNE_CACHE env or
                   ~/.cache/deepspeed_tpu/kernel_autotune.json). Entries
                   record the chip they were measured on; a cache from
                   another device_kind (e.g. interpret-mode CPU) is
                   refused, not applied.
      chain_lengths / reps
                   search timing knobs: candidates are timed as the
                   slope between two lax.scan chain lengths inside one
                   jit (dispatch-latency cancellation), best-of-reps.
    """
    mode: str = ""
    cache_path: str = ""
    chain_lengths: object = (8, 24)
    reps: int = 3

    def __post_init__(self):
        if self.mode not in ("", "off", "cache_only", "on_first_use",
                             "search"):
            raise DeepSpeedConfigError(
                f"autotune.mode must be ''|off|cache_only|on_first_use|"
                f"search, got {self.mode!r}")
        try:
            k1, k2 = (int(v) for v in self.chain_lengths)
        except (TypeError, ValueError):
            raise DeepSpeedConfigError(
                f"autotune.chain_lengths must be two ints, got "
                f"{self.chain_lengths!r}")
        if not 0 < k1 < k2:
            raise DeepSpeedConfigError(
                f"autotune.chain_lengths needs 0 < k1 < k2, got "
                f"{(k1, k2)}")
        self.chain_lengths = (k1, k2)
        if not isinstance(self.reps, int) or self.reps < 1:
            raise DeepSpeedConfigError(
                f"autotune.reps must be an int >= 1, got {self.reps!r}")


@dataclass
class TelemetryConfig:
    """Pod telemetry block (monitor/telemetry.py + flight_recorder.py —
    the always-on observability layer the engine wires through
    MonitorMaster):

      enabled          "auto" (default: on iff a monitor backend is
                       configured, DSTPU_TELEMETRY=1, a flight-recorder
                       dir is exported (DSTPU_FLIGHTREC_DIR), or the
                       process runs under an elastic agent
                       (ELASTIC_GENERATION)) | true | false.
      interval_steps   steps between telemetry flushes (percentiles,
                       MFU, goodput, cluster aggregation, opportunistic
                       flight dumps). The step path itself only appends
                       to a ring.
      cluster_agg      "auto" (on iff the jax world is multi-process or
                       a fs-transport ring is exported via
                       DSTPU_TELEM_DIR + DSTPU_TELEM_PEERS /
                       DSTPU_HOT_PEERS) | true | false — the pod-wide
                       p50/p99 + straggler-delta aggregation.
      flight_recorder_size
                       bounded in-memory event ring (steps, fault
                       points, restores + tier, reshapes, profiler
                       actions) dumped to
                       ``{ckpt_root}/flightrec/host{n}.json`` on
                       crash/SIGTERM and opportunistically each flush.
      profile_port     jax.profiler server port for live xprof attach
                       (0 = DSTPU_PROFILE_PORT env or off). Step-ranged
                       captures arm via DSTPU_PROFILE_STEPS=a:b or a
                       PROFILE trigger file in the flight-recorder dir.
      flightrec_dir    explicit dump dir ("" = DSTPU_FLIGHTREC_DIR env,
                       else derived from the first save_checkpoint's
                       save_dir).
    """
    enabled: object = "auto"          # "auto" | bool
    interval_steps: int = 20
    cluster_agg: object = "auto"      # "auto" | bool
    flight_recorder_size: int = 256
    profile_port: int = 0
    flightrec_dir: str = ""

    def __post_init__(self):
        if self.enabled not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"telemetry.enabled must be true|false|'auto', got "
                f"{self.enabled!r}")
        if self.cluster_agg not in (True, False, "auto"):
            raise DeepSpeedConfigError(
                f"telemetry.cluster_agg must be true|false|'auto', got "
                f"{self.cluster_agg!r}")
        if not isinstance(self.interval_steps, int) \
                or self.interval_steps < 1:
            raise DeepSpeedConfigError(
                f"telemetry.interval_steps must be an int >= 1, got "
                f"{self.interval_steps!r}")
        if not isinstance(self.flight_recorder_size, int) \
                or self.flight_recorder_size < 8:
            raise DeepSpeedConfigError(
                f"telemetry.flight_recorder_size must be an int >= 8, "
                f"got {self.flight_recorder_size!r}")
        if not isinstance(self.profile_port, int) or self.profile_port < 0:
            raise DeepSpeedConfigError(
                f"telemetry.profile_port must be an int >= 0, got "
                f"{self.profile_port!r}")

    def resolve_enabled(self, monitor_enabled=False):
        """'auto' turns telemetry on when someone can see it (a monitor
        backend) or someone supervises it (elastic agent / exported
        flight-recorder dir)."""
        if self.enabled != "auto":
            return bool(self.enabled)
        import os
        return bool(monitor_enabled
                    or os.environ.get("DSTPU_TELEMETRY") == "1"
                    or os.environ.get("DSTPU_FLIGHTREC_DIR")
                    or os.environ.get("ELASTIC_GENERATION") is not None)

    def resolve_cluster_agg(self):
        if self.cluster_agg != "auto":
            return bool(self.cluster_agg)
        import os
        import jax
        if jax.process_count() > 1:
            return True
        return bool(os.environ.get("DSTPU_TELEM_DIR")
                    and (os.environ.get("DSTPU_TELEM_PEERS")
                         or os.environ.get("DSTPU_HOT_PEERS")))


@dataclass
class ActivationCheckpointingConfig:
    partition_activations: bool = False   # accepted for parity; XLA shards
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: int = 0
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native knob: remat policy name for jax.checkpoint
    policy: str = "nothing_saveable"


@dataclass
class CommsLoggerConfig:
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False


def _take(d, cls, key):
    sub = d.get(key, {})
    if isinstance(sub, cls):
        return sub
    if not isinstance(sub, dict):
        raise DeepSpeedConfigError(f"'{key}' must be a dict, got {type(sub)}")
    known = {f for f in cls.__dataclass_fields__}
    unknown = set(sub) - known
    if unknown:
        logger.warning(f"config block '{key}': ignoring unknown keys {sorted(unknown)}")
    return cls(**{k: v for k, v in sub.items() if k in known})


class DeepSpeedConfig:
    """Resolved, validated run config.

    Batch triad resolution follows reference runtime/config.py: given any two
    of (train_batch_size, train_micro_batch_size_per_gpu,
    gradient_accumulation_steps) the third is derived; given one, the others
    default to fill; all three must satisfy
    train_batch == micro_batch * grad_accum * dp_world.
    """

    def __init__(self, config, dp_world_size=1):
        if isinstance(config, str):
            with open(config) as f:
                config = json.load(f)
        if not isinstance(config, dict):
            raise DeepSpeedConfigError(
                f"expected dict or json path, got {type(config)}")
        self._raw = dict(config)
        self.dp_world_size = dp_world_size

        self.train_batch_size = config.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = config.get(
            C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = config.get(
            C.GRADIENT_ACCUMULATION_STEPS)
        self._resolve_batch_size()

        self.steps_per_print = config.get(C.STEPS_PER_PRINT,
                                          C.STEPS_PER_PRINT_DEFAULT)
        self.gradient_clipping = config.get(C.GRADIENT_CLIPPING,
                                            C.GRADIENT_CLIPPING_DEFAULT)
        self.prescale_gradients = config.get(C.PRESCALE_GRADIENTS, False)
        self.gradient_predivide_factor = config.get(
            C.GRADIENT_PREDIVIDE_FACTOR, 1.0)
        self.wall_clock_breakdown = config.get(C.WALL_CLOCK_BREAKDOWN, False)

        self.fp16 = _take(config, FP16Config, C.FP16)
        self.bf16 = _take(config, BF16Config, C.BF16)
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        self.zero = _take(config, ZeroConfig, C.ZERO_OPTIMIZATION)
        self.tensor_parallel = _take(config, TensorParallelConfig,
                                     C.TENSOR_PARALLEL)
        self.pipeline = _take(config, PipelineConfig, C.PIPELINE)
        self.seq_parallel_size = config.get(C.SEQUENCE_PARALLEL_SIZE, 1)
        self.expert_parallel_size = config.get(C.EXPERT_PARALLEL_SIZE, 1)
        # "auto": when no explicit topology is given, run the
        # auto-parallelism planner (autotuning/planner.py) over the model
        # + visible pod and adopt its rank-1 mesh/schedule; "" keeps the
        # hand-set axis sizes above (the historical behavior).
        self.parallelism = config.get("parallelism", "")
        if self.parallelism not in ("", "auto"):
            raise DeepSpeedConfigError(
                f"parallelism must be ''|'auto', got "
                f"{self.parallelism!r}")

        opt = config.get(C.OPTIMIZER)
        self.optimizer = None if opt is None else _take(
            {"o": opt}, OptimizerConfig, "o")
        sched = config.get(C.SCHEDULER)
        self.scheduler = None if sched is None else _take(
            {"s": sched}, SchedulerConfig, "s")

        self.checkpoint_engine = _take(config, CheckpointEngineConfig,
                                       C.CHECKPOINT_ENGINE)
        self.comm_overlap = _take(config, CommOverlapConfig, "comm_overlap")
        self.sequence = _take(config, SequenceConfig, "sequence")
        self.moe = _take(config, MoEConfig, "moe")
        self.quantize = _take(config, QuantizeConfig, "quantize")
        self.autotune = _take(config, AutotuneConfig, "autotune")
        self.telemetry = _take(config, TelemetryConfig, "telemetry")
        self.activation_checkpointing = _take(
            config, ActivationCheckpointingConfig, C.ACTIVATION_CHECKPOINTING)
        self.comms_logger = _take(config, CommsLoggerConfig, C.COMMS_LOGGER)
        from ..monitor.config import DeepSpeedMonitorConfig
        self.monitor_config = DeepSpeedMonitorConfig.from_dict(config)
        self.monitor_csv = self.monitor_config.csv_monitor  # back-compat

        dtypes = config.get(C.DATA_TYPES, {})
        self.grad_accum_dtype = dtypes.get(C.GRAD_ACCUM_DTYPE)
        self.seq_parallel_comm_dtype = config.get(C.SEQ_PARALLEL_COMM_DTYPE,
                                                  "float32")

        # data efficiency (reference runtime/data_pipeline/config.py
        # schema, condensed; consumed by the engine — curriculum changes
        # the batches the jitted step sees, random-ltd the kept-token
        # count — reference engine.py:336-367 + deepspeed_io:1715):
        #   data_efficiency: {enabled, seed,
        #     data_sampling: {enabled, curriculum_learning: {enabled,
        #         curriculum_type, min_difficulty, max_difficulty,
        #         schedule_type, schedule_config}},
        #     data_routing: {enabled, random_ltd: {enabled,
        #         random_ltd_min_value, random_ltd_max_value,
        #         random_ltd_schedule}}}
        # Legacy top-level curriculum_learning (v1 API) also accepted.
        de = config.get("data_efficiency", {}) or {}
        self.data_efficiency_enabled = bool(de.get("enabled", False))
        self.data_efficiency_seed = int(de.get("seed", 1234))
        sampling = de.get("data_sampling", {}) or {}
        cl = sampling.get("curriculum_learning", {}) or {}
        legacy_cl = config.get("curriculum_learning", {}) or {}
        self.curriculum_config = None
        if self.data_efficiency_enabled and sampling.get(
                "enabled", True) and cl.get("enabled", False):
            self.curriculum_config = {
                k: v for k, v in cl.items() if k != "enabled"}
        elif legacy_cl.get("enabled", False):
            self.curriculum_config = {
                k: v for k, v in legacy_cl.items() if k != "enabled"}
        routing = de.get("data_routing", {}) or {}
        ltd = routing.get("random_ltd", {}) or {}
        self.random_ltd_config = None
        if self.data_efficiency_enabled and routing.get(
                "enabled", True) and ltd.get("enabled", False):
            self.random_ltd_config = {
                k: v for k, v in ltd.items() if k != "enabled"}

    # reference runtime/config.py batch resolution logic, same error text style
    def _resolve_batch_size(self):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        dp = self.dp_world_size
        for name, v in ((C.TRAIN_BATCH_SIZE, train),
                        (C.TRAIN_MICRO_BATCH_SIZE_PER_GPU, micro),
                        (C.GRADIENT_ACCUMULATION_STEPS, gas)):
            if v is not None and (not isinstance(v, int) or v <= 0):
                raise DeepSpeedConfigError(
                    f"{name} must be a positive integer, got {v!r}")

        if all(v is not None for v in (train, micro, gas)):
            if train != micro * gas * dp:
                raise DeepSpeedConfigError(
                    f"Check batch related parameters. train_batch_size is not equal "
                    f"to micro_batch_per_gpu * gradient_acc_step * world_size "
                    f"{train} != {micro} * {gas} * {dp}")
        elif train is not None and micro is not None:
            gas = train // (micro * dp)
            if gas * micro * dp != train:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} not divisible by "
                    f"micro_batch {micro} * dp world size {dp}")
        elif train is not None and gas is not None:
            micro = train // (gas * dp)
            if micro * gas * dp != train:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} not divisible by "
                    f"gradient_accumulation_steps {gas} * dp world size {dp}")
        elif micro is not None:
            gas = 1 if gas is None else gas
            train = micro * gas * dp
        elif train is not None:
            micro = train // dp
            gas = 1
            if micro * dp != train:
                raise DeepSpeedConfigError(
                    f"train_batch_size {train} not divisible by dp world size {dp}")
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu "
                "must be provided")
        self.train_batch_size = train
        self.train_micro_batch_size_per_gpu = micro
        self.gradient_accumulation_steps = gas

    @property
    def precision_dtype(self):
        import jax.numpy as jnp
        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def to_dict(self):
        out = dict(self._raw)
        out[C.TRAIN_BATCH_SIZE] = self.train_batch_size
        out[C.TRAIN_MICRO_BATCH_SIZE_PER_GPU] = self.train_micro_batch_size_per_gpu
        out[C.GRADIENT_ACCUMULATION_STEPS] = self.gradient_accumulation_steps
        return out

    def print_config(self):
        logger.info("DeepSpeedConfig:")
        for k, v in sorted(self.__dict__.items()):
            if k.startswith("_"):
                continue
            logger.info(f"  {k} = {v}")
