from .module import LayerSpec, TiedLayerSpec, PipelineModule
from .topology import (ProcessTopology, PipeDataParallelTopology,
                       PipeModelDataParallelTopology, PipelineParallelGrid)
from .schedule import (TrainSchedule, InferenceSchedule, PipeSchedule,
                       ZeroBubbleSchedule, ForwardPass, BackwardPass,
                       BackwardActGrad, BackwardWeightGrad,
                       SendActivation, RecvActivation, SendGrad, RecvGrad,
                       LoadMicroBatch, ReduceGrads, OptimizerStep,
                       executor_bubble_fraction, executor_tick_units)
from .spmd import (spmd_pipeline, pipeline_1f1b_grads, pipeline_zb_grads,
                   pipeline_loss, PipeOffload)

__all__ = [
    "LayerSpec", "TiedLayerSpec", "PipelineModule",
    "ProcessTopology", "PipeDataParallelTopology",
    "PipeModelDataParallelTopology", "PipelineParallelGrid",
    "TrainSchedule", "InferenceSchedule", "PipeSchedule",
    "ZeroBubbleSchedule",
    "ForwardPass", "BackwardPass", "BackwardActGrad",
    "BackwardWeightGrad", "SendActivation", "RecvActivation",
    "SendGrad", "RecvGrad", "LoadMicroBatch", "ReduceGrads",
    "OptimizerStep", "executor_bubble_fraction", "executor_tick_units",
    "spmd_pipeline", "pipeline_1f1b_grads", "pipeline_zb_grads",
    "pipeline_loss", "PipeOffload",
]
