from .module import LayerSpec, TiedLayerSpec, PipelineModule
from .topology import (ProcessTopology, PipeDataParallelTopology,
                       PipeModelDataParallelTopology, PipelineParallelGrid)
from .schedule import (TrainSchedule, InferenceSchedule, PipeSchedule,
                       ForwardPass, BackwardPass, SendActivation,
                       RecvActivation, SendGrad, RecvGrad, LoadMicroBatch,
                       ReduceGrads, OptimizerStep)
from .spmd import spmd_pipeline

__all__ = [
    "LayerSpec", "TiedLayerSpec", "PipelineModule",
    "ProcessTopology", "PipeDataParallelTopology",
    "PipeModelDataParallelTopology", "PipelineParallelGrid",
    "TrainSchedule", "InferenceSchedule", "PipeSchedule",
    "ForwardPass", "BackwardPass", "SendActivation", "RecvActivation",
    "SendGrad", "RecvGrad", "LoadMicroBatch", "ReduceGrads", "OptimizerStep",
    "spmd_pipeline",
]
