"""SPMD pipeline executor: collective-permute over the 'pipe' mesh axis.

The reference's pipeline engine (runtime/pipe/engine.py:56) is an imperative
instruction interpreter: per-rank processes walk a 1F1B instruction stream
(runtime/pipe/schedule.py:189) exchanging activations over NCCL p2p
(runtime/pipe/p2p.py:50,71). On TPU the same dataflow is ONE jitted SPMD
program:

  * the stacked layer dim of the model params is sharded over the 'pipe'
    mesh axis — each pipe shard owns L/S contiguous layers (the
    PipelineModule partitioning, reference runtime/pipe/module.py:372);
  * a ``shard_map`` manual only over 'pipe' (data/tensor/seq stay
    GSPMD-automatic, so the block's internal sharding constraints keep
    working) runs the rotation loop: at tick t, stage s computes microbatch
    t-s and ``ppermute``s its activation to stage s+1 — the p2p send/recv
    of the reference, but expressed as a collective XLA can schedule;
  * reverse-mode AD through the scan yields the backward pipeline (reverse
    ppermutes) automatically — the schedule the reference hand-codes.

The forward fills the pipe GPipe-style (all M microbatches in flight);
memory is bounded by rematerializing each block (``jax.checkpoint``), the
same trade the reference makes with activation checkpointing. The 1F1B
instruction stream in schedule.py documents/verifies the logical order for
parity tests; this executor is the compute path.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# The steady-state executors compute every gradient EXPLICITLY inside
# the manual region (jax.vjp over per-shard closures; nothing
# differentiates through the shard_map itself) and reduce stage-local
# results with explicit psums, so legacy jax's check_rep machinery —
# whose cond-branch replication unification predates the vma typing the
# executors' pcast annotations target — adds no safety, only spurious
# mismatches (e.g. on the head-loss cond). vma-era jax keeps full
# checking; the GPipe path (spmd_pipeline), which IS differentiated
# through, always keeps it (its transpose relies on the rewrite pass).
_STEADY_STATE_KW = {} if hasattr(jax.lax, "pvary") else \
    {"check_vma": False}


def spmd_pipeline(block_fn, layers, x_mb, *, pipe_axis="pipe",
                  unroll_local=False):
    """Run ``x`` through all L layers, pipelined over the pipe axis.

    Args:
      block_fn: ``(x, layer_slice) -> x`` — one layer's forward. ``x`` is a
        single microbatch activation; ``layer_slice`` is the layers pytree
        with the leading layer dim removed (bundle rngs etc. into it).
      layers: pytree whose leaves have leading dim L (== S * layers_per_
        stage); sharded P(pipe_axis) on that dim by the caller's param specs.
      x_mb: microbatch-stacked input, leaves (M, ...) — replicated over the
        pipe axis, sharded however the caller likes on auto axes.
      pipe_axis: manual mesh axis name.
      unroll_local: unroll the per-stage layer scan (faster for tiny depth).

    Returns outputs with the same (M, ...) structure as ``x_mb``, replicated
    over the pipe axis.

    Must be called under an active mesh (``jax.set_mesh``) that has
    ``pipe_axis``. Total ticks = M + S - 1; per-stage bubble fraction
    (S-1)/(M+S-1) — choose M >= S (reference guidance for 1F1B too).
    """
    mesh = jax.sharding.get_abstract_mesh()
    if mesh.empty or pipe_axis not in mesh.shape:
        raise ValueError(f"spmd_pipeline needs an active mesh with a "
                         f"'{pipe_axis}' axis; got {mesh}")
    S = mesh.shape[pipe_axis]
    if S == 1:
        # degenerate: plain scan over layers, no collectives
        def body(c, layer):
            return block_fn(c, layer), None

        def run(x):
            y, _ = lax.scan(body, x, layers, unroll=unroll_local)
            return y
        return jax.vmap(run)(x_mb) if _leading(x_mb) else run(x_mb)

    M = _leading(x_mb)
    if M is None:
        raise ValueError("x_mb must have a leading microbatch dim")

    # XLA-CPU (the virtual test mesh) check-fails promoting partial-manual
    # sub-f32 all-reduces, so THERE activations cross the shard_map
    # boundary in f32. On TPU bf16 ppermute/psum are legal and halve the
    # boundary bytes — the workaround is scoped to the CPU interpreter.
    f32_boundary = jax.default_backend() == "cpu"

    def _is_lowp(x):
        return (jnp.issubdtype(x.dtype, jnp.floating)
                and jnp.finfo(x.dtype).bits < 32)
    in_dtypes = jax.tree.map(lambda x: x.dtype, x_mb)
    if f32_boundary:
        x_mb = jax.tree.map(
            lambda x: x.astype(jnp.float32) if _is_lowp(x) else x, x_mb)

    def stage_fn(layers_local, x_local):
        sid = lax.axis_index(pipe_axis)

        def run_local(state):
            def body(c, layer):
                return block_fn(c, layer), None
            y, _ = lax.scan(body, state, layers_local, unroll=unroll_local)
            return y

        def varying_zeros(x):
            # CPU: pcast in f32, cast after — the transpose of
            # pcast(to='varying') is a psum over 'pipe', and XLA-CPU
            # check-fails promoting a sub-f32 partial-manual all-reduce.
            # TPU: pcast in the native dtype (bf16 collectives are legal).
            if not f32_boundary:
                return lax.pcast(jnp.zeros(x.shape, x.dtype), (pipe_axis,),
                                 to="varying")
            z = lax.pcast(jnp.zeros(x.shape, jnp.float32), (pipe_axis,),
                          to="varying")
            return z.astype(x.dtype)

        state = jax.tree.map(lambda x: varying_zeros(x[0]), x_local)
        outputs = jax.tree.map(varying_zeros, x_local)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (clamped index; garbage ticks at
            # t >= M never reach the output buffer). The pipe-invariant
            # slice is promoted to pipe-varying EXPLICITLY, in f32, before
            # the dtype cast — otherwise shard_map's vma machinery inserts
            # the promotion inside the where in the compute dtype, and that
            # lowers to a sub-f32 all-reduce XLA-CPU cannot promote.
            inject = jax.tree.map(
                lambda x, dt: lax.pcast(
                    x[jnp.minimum(t, M - 1)], (pipe_axis,),
                    to="varying").astype(dt),
                x_local, in_dtypes)
            state = jax.tree.map(
                lambda i, s: jnp.where(sid == 0, i, s), inject, state)
            out = run_local(state)
            # last stage owns microbatch t-(S-1) at tick t
            idx = t - (S - 1)
            safe = jnp.clip(idx, 0, M - 1)
            valid = (sid == S - 1) & (idx >= 0)

            def write(buf, o):
                cur = lax.dynamic_index_in_dim(buf, safe, 0, keepdims=False)
                return lax.dynamic_update_index_in_dim(
                    buf, jnp.where(valid, o, cur), safe, 0)
            outputs = jax.tree.map(write, outputs, out)
            nxt = jax.tree.map(lambda o: lax.ppermute(o, pipe_axis, perm),
                               out)
            return (nxt, outputs), None

        (_, outputs), _ = lax.scan(tick, (state, outputs),
                                   jnp.arange(M + S - 1))

        # non-last stages hold zeros: psum broadcasts the result pipe-wide.
        # On the CPU test mesh sub-f32 floats go through f32 (XLA-CPU
        # check-fails promoting a partial-manual bf16 all-reduce); on TPU
        # the psum runs in the native dtype — half the boundary bytes.
        def bcast(o):
            if f32_boundary and jnp.issubdtype(o.dtype, jnp.floating) \
                    and jnp.finfo(o.dtype).bits < 32:
                return lax.psum(o.astype(jnp.float32),
                                pipe_axis).astype(o.dtype)
            return lax.psum(o, pipe_axis)
        return jax.tree.map(bcast, outputs)

    return jax.shard_map(
        stage_fn,
        in_specs=(P(pipe_axis), P()),
        out_specs=P(),
        axis_names={pipe_axis},
    )(layers, x_mb)


def _leading(tree):
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return None
    n = leaves[0].shape[0] if leaves[0].ndim else None
    return n


def split_microbatches(x, num_microbatches, batch_dim=0):
    """(B, ...) -> (M, B//M, ...) with stride-M row sampling so each
    microbatch draws evenly from every data-parallel shard of the batch dim
    (a contiguous split would put whole microbatches on single DP shards).
    Inverse: merge_microbatches."""
    M = num_microbatches
    B = x.shape[batch_dim]
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    x = jnp.moveaxis(x, batch_dim, 0)
    x = x.reshape((B // M, M) + x.shape[1:])
    x = jnp.swapaxes(x, 0, 1)           # (M, B//M, ...)
    return x


def merge_microbatches(x, batch_dim=0):
    """Inverse of split_microbatches: (M, B//M, ...) -> (B, ...)."""
    x = jnp.swapaxes(x, 0, 1)
    x = x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
    return jnp.moveaxis(x, 0, batch_dim) if batch_dim else x


# ------------------------------------------------------------- 1F1B executor
def _ring_capacity(S):
    """Saved-input slots per stage under interleaved 1F1B: stage s holds a
    microbatch's input from its forward (tick m + s) until its backward
    (tick m + 2(S-1) - s) — at most 2(S-1) in flight, capacity 2S with
    slack. Independent of the microbatch count M: the memory property the
    whole schedule exists for."""
    return 2 * S


def pipeline_1f1b_grads(block_fn, head_loss_fn, layers_params, layers_aux,
                        head_params, x_mb, tgt_mb, *, pipe_axis="pipe"):
    """Interleaved-1F1B pipelined training pass: mean loss over M
    microbatches AND all gradients, in ONE jitted SPMD program.

    The reference executes 1F1B imperatively (_exec_schedule,
    runtime/pipe/engine.py:1382 walking schedule.py:189's TrainSchedule);
    here the same interleave is a lax.scan over ticks inside a shard_map
    manual on the pipe axis. Per tick every stage does one FORWARD step
    (microbatch t - s) and one BACKWARD step (microbatch t - 2(S-1) + s):
    the backward wave chases the forward wave S-1 ticks behind, so saved
    block inputs live in a fixed-size ring (``_ring_capacity``) rather
    than growing with M — unlike autodiff-of-the-GPipe-scan, which keeps
    every tick's residuals.

    Per-block backward recomputes the forward under ``jax.vjp`` from the
    ring-saved input (activation checkpointing, the reference's trade).
    The last stage seeds each microbatch's cotangent from
    ``head_loss_fn(head_params, y, tgt)`` the same tick it computes y.

    Args:
      block_fn: ``(x, layer_params_slice, layer_aux_slice) -> x``.
      head_loss_fn: ``(head_params, y_mb, tgt_mb) -> scalar`` per-mb loss.
      layers_params: differentiable stacked layers, leading dim L,
        sharded P(pipe_axis).
      layers_aux: non-differentiable per-layer inputs (rng key DATA,
        uint32 — wrap back with jax.random.wrap_key_data in block_fn),
        leading dim L, sharded P(pipe_axis).
      head_params / x_mb / tgt_mb: replicated over the pipe axis
        (x/tgt leaves lead with M).

    Returns (loss, (dlayers_params, dhead_params, dx_mb)).
    """
    mesh = jax.sharding.get_abstract_mesh()
    S = mesh.shape[pipe_axis]
    M = _leading(x_mb)
    R = _ring_capacity(S)
    n_ticks = M + 2 * (S - 1)
    f32_boundary = jax.default_backend() == "cpu"

    def _b(x):
        """Boundary-safe collective dtype (see spmd_pipeline)."""
        if f32_boundary and jnp.issubdtype(x.dtype, jnp.floating) \
                and jnp.finfo(x.dtype).bits < 32:
            return jnp.float32
        return x.dtype

    def stage_fn(lp, la, hp, x_mb, tgt_mb):
        sid = lax.axis_index(pipe_axis)
        # Promote head params to pipe-varying BEFORE any vjp against
        # them: differentiating w.r.t. a pipe-INVARIANT value inside
        # shard_map makes the transpose insert an implicit cross-stage
        # psum (the adjoint of the invariant->varying promotion), which
        # would multiply the masked-accumulate-then-psum pattern by S.
        hp = jax.tree.map(
            lambda p: lax.pcast(p, (pipe_axis,), to="varying"), hp)
        perm_f = [(i, (i + 1) % S) for i in range(S)]
        perm_b = [(i, (i - 1) % S) for i in range(S)]

        def fwd_local(x, lp):
            def body(c, sl):
                p, a = sl
                return block_fn(c, p, a), None
            y, _ = lax.scan(body, x, (lp, la))
            return y

        def vz(x, dt=None):
            z = lax.pcast(
                jnp.zeros(x.shape, _b(x)), (pipe_axis,), to="varying")
            return z.astype(dt or x.dtype)

        x0 = jax.tree.map(lambda x: x[0], x_mb)
        act0 = jax.tree.map(vz, x0)
        dy0 = jax.tree.map(vz, x0)
        ring0 = jax.tree.map(
            lambda x: jnp.tile(vz(x)[None], (R,) + (1,) * x.ndim), x0)
        gacc0 = jax.tree.map(lambda p: vz(p, jnp.float32), lp)
        hacc0 = jax.tree.map(
            lambda p: lax.pcast(jnp.zeros(p.shape, jnp.float32),
                                (pipe_axis,), to="varying"), hp)
        dx0 = jax.tree.map(
            lambda x: jnp.zeros((M,) + x.shape[1:], _b(x)), x_mb)
        dx0 = jax.tree.map(
            lambda x: lax.pcast(x, (pipe_axis,), to="varying"), dx0)
        loss0 = lax.pcast(jnp.zeros((), jnp.float32), (pipe_axis,),
                          to="varying")

        def tick(carry, t):
            act_in, dy_in, ring, gacc, hacc, dx_out, loss_acc = carry
            # ---------- forward half: stage s runs microbatch t - s
            f_idx = t - sid
            f_valid = (f_idx >= 0) & (f_idx < M)
            f_safe = jnp.clip(f_idx, 0, M - 1)
            # f_safe is pipe-varying (depends on sid), so indexing the
            # replicated x_mb already yields a varying value — no pcast
            inject = jax.tree.map(
                lambda x, a: x[f_safe].astype(a.dtype), x_mb, act_in)
            x_in = jax.tree.map(
                lambda i, a: jnp.where(sid == 0, i, a), inject, act_in)
            y = fwd_local(x_in, lp)
            slot = f_safe % R
            ring = jax.tree.map(
                lambda r, x: r.at[slot].set(
                    jnp.where(f_valid, x, r[slot])), ring, x_in)

            # last stage: per-microbatch loss + cotangent seed (cotangent
            # of the MEAN over M, hence the 1/M seed). Guarded by
            # lax.cond on the pipe-varying stage id — legal inside the
            # fully-manual shard_map (per-shard control flow, no
            # collectives in either branch) — so non-last stages skip
            # the d_model x vocab unembed fwd+vjp at runtime instead of
            # computing and masking it (S-fold redundant MXU work that
            # grows with vocab size).
            tgt = jax.tree.map(lambda x: x[f_safe], tgt_mb)
            seed = lax.pcast(jnp.float32(1.0 / M), (pipe_axis,),
                             to="varying")

            def head_branch(hp_, y_, tgt_, seed_):
                l_mb_, vjp_h = jax.vjp(
                    lambda h, yy: head_loss_fn(h, yy, tgt_), hp_, y_)
                dhp_, dy_ = vjp_h(seed_)
                return l_mb_, dhp_, dy_

            def skip_branch(hp_, y_, tgt_, seed_):
                # zeros must carry the same varying-over-pipe type as the
                # head branch's vjp outputs or cond rejects the branches
                zv = lambda a: lax.pcast(jnp.zeros(a.shape, a.dtype),
                                         (pipe_axis,), to="varying")
                return (zv(jnp.zeros((), jnp.float32)),
                        jax.tree.map(zv, hp_), jax.tree.map(zv, y_))

            l_mb, dhp, dy_seed = lax.cond(sid == S - 1, head_branch,
                                          skip_branch, hp, y, tgt, seed)
            seed_valid = f_valid & (sid == S - 1)
            loss_acc = loss_acc + jnp.where(seed_valid, l_mb, 0.0)
            hacc = jax.tree.map(
                lambda a, g: a + jnp.where(seed_valid,
                                           g.astype(jnp.float32), 0.0),
                hacc, dhp)

            # ---------- backward half: stage s runs microbatch
            # t - 2(S-1) + s; the last stage consumes its own seed
            b_idx = t - 2 * (S - 1) + sid
            b_valid = (b_idx >= 0) & (b_idx < M)
            b_safe = jnp.clip(b_idx, 0, M - 1)
            dy = jax.tree.map(
                lambda s_, d: jnp.where(sid == S - 1,
                                        s_.astype(d.dtype), d),
                dy_seed, dy_in)
            x_saved = jax.tree.map(lambda r: r[b_safe % R], ring)
            _, vjp_blk = jax.vjp(fwd_local, x_saved, lp)
            dx, dlp = vjp_blk(dy)
            gacc = jax.tree.map(
                lambda a, g: a + jnp.where(b_valid,
                                           g.astype(jnp.float32), 0.0),
                gacc, dlp)
            write_dx = (sid == 0) & b_valid
            dx_out = jax.tree.map(
                lambda buf, d: buf.at[b_safe].set(
                    jnp.where(write_dx, d.astype(buf.dtype),
                              buf[b_safe])),
                dx_out, dx)

            # rotations: activations forward, cotangents backward
            act_nxt = jax.tree.map(
                lambda o: lax.ppermute(
                    o.astype(_b(o)), pipe_axis, perm_f).astype(o.dtype), y)
            dy_nxt = jax.tree.map(
                lambda o: lax.ppermute(
                    o.astype(_b(o)), pipe_axis, perm_b).astype(o.dtype),
                dx)
            return (act_nxt, dy_nxt, ring, gacc, hacc, dx_out,
                    loss_acc), None

        carry = (act0, dy0, ring0, gacc0, hacc0, dx0, loss0)
        (act, dy, ring, gacc, hacc, dx_out, loss_acc), _ = lax.scan(
            tick, carry, jnp.arange(n_ticks))

        loss = lax.psum(loss_acc, pipe_axis) / M
        # layer grads stay stage-local (P(pipe) like the params); head/dx
        # live only on their owning stage -> psum broadcasts
        hgrads = jax.tree.map(lambda a: lax.psum(a, pipe_axis), hacc)
        dx_mb = jax.tree.map(lambda a: lax.psum(a, pipe_axis), dx_out)
        return loss, gacc, hgrads, dx_mb

    loss, gacc, hgrads, dx_mb = jax.shard_map(
        stage_fn,
        in_specs=(P(pipe_axis), P(pipe_axis), P(), P(), P()),
        out_specs=(P(), P(pipe_axis), P(), P()),
        axis_names={pipe_axis},
        **_STEADY_STATE_KW,
    )(layers_params, layers_aux, head_params, x_mb, tgt_mb)
    dlayers = jax.tree.map(lambda g, p: g.astype(p.dtype),
                           gacc, layers_params)
    dhead = jax.tree.map(lambda g, p: g.astype(p.dtype),
                         hgrads, head_params)
    dx_mb = jax.tree.map(lambda g, x: g.astype(x.dtype), dx_mb, x_mb)
    return loss, (dlayers, dhead, dx_mb)


# --------------------------------------------------- zero-bubble executor
#
# ZB-H1 (the W/B backward split) on top of the 1F1B rotation loop. Each
# block's backward splits into the activation-grad pass B (dx from dy —
# the only piece the previous stage is waiting on) and the weight-grad
# pass W (dW from the ring-saved input and dy — nothing downstream
# consumes it until the optimizer). 1F1B runs B and W fused on the
# backward wave, so every drain tick costs B+W while the forward slot
# idles; here each stage DEFERS its trailing ``zb_deferred_window``
# microbatches' W passes into exactly those forward-drain ticks. The
# index maps (shared with schedule.py's ZeroBubbleSchedule — the
# tick-parity test pins the two together):
#
#     F(m) on stage s  at tick m + s                       (fill wave)
#     B(m) on stage s  at tick m + 2(S-1) - s              (drain wave)
#     W(m) fused with B(m)          for m <  M - K_s
#     W(m) deferred    at tick m + 2(S-1)  (all stages!)   for m >= M - K_s
#
# with K_s = min(2(S-1) - s, M): stage s has exactly 2(S-1) - s ticks
# after its last F and the deferred W(m) wave lands s ticks after B(m) —
# always causally after its own B. Invalid slots are lax.cond no-ops
# (the 1F1B executor computes garbage forwards during the drain instead),
# so the lock-step wall — every tick costs the busiest stage, the
# ppermute is the barrier — drops below the GPipe figure:
# ``schedule.executor_bubble_fraction`` is the model, asserted by tests.
#
# Memory: the 1F1B input ring plus a dy ring of ``S`` slots (a deferred
# microbatch's cotangent lives the s ticks between its B and W) — still
# O(stages), never O(M). Cost of the split: B and W each rematerialize
# the block forward (two recomputes per microbatch instead of the fused
# pass's one) — the standard ZB trade under full activation
# checkpointing, bought back by the drain ticks it fills.
#
# Host offload (``offload=``): the input/dy rings are the activation
# carries the reference's ``swap_tensor`` + ``activation_checkpointing``
# layers spill; with offload on they are INITIALIZED in host memory
# (swap_tensor/host_stage.py) so the in-scan dynamic-update-slice
# writes stage D2H and the reads stage H2D (copy-start/copy-done pairs
# under the latency-hiding scheduler; overlap_report counts them). The
# next tick's B input is prefetched one tick early (``x_pref`` carry, a
# real double buffer); the last stage consumes its own same-tick
# forward input from registers, never through the host.


def zb_deferred_window(stage_id, micro_batches, stages):
    """K_s: how many trailing microbatches' W passes stage s defers into
    its forward-drain ticks. Polymorphic over python ints and traced
    values (the executor and the schedule spec share it)."""
    lo = 2 * (stages - 1) - stage_id
    if isinstance(stage_id, int):
        return min(lo, micro_batches)
    return jnp.minimum(lo, micro_batches)


def zb_f_index(t, stage_id, micro_batches, stages):
    """Microbatch whose FORWARD stage ``stage_id`` runs at tick t
    (valid iff in [0, M))."""
    return t - stage_id


def zb_b_index(t, stage_id, micro_batches, stages):
    """Microbatch whose activation-grad (B) pass runs at tick t."""
    return t - 2 * (stages - 1) + stage_id


def zb_w_deferred_index(t, stage_id, micro_batches, stages):
    """Microbatch whose DEFERRED weight-grad (W) pass runs at tick t —
    a uniform wave (independent of the stage: the per-stage deferral
    window exactly cancels the backward skew). Valid iff in
    [max(M - K_s, 0), M)."""
    return t - 2 * (stages - 1)


def zb_num_ticks(micro_batches, stages):
    """Same tick count as 1F1B: the last deferred W (microbatch M-1)
    lands on tick M - 1 + 2(S-1), the final tick."""
    return micro_batches + 2 * (stages - 1)


def pipeline_zb_grads(block_fn, head_loss_fn, layers_params, layers_aux,
                      head_params, x_mb, tgt_mb, *, pipe_axis="pipe",
                      offload=None):
    """Zero-bubble (ZB-H1) pipelined training pass: mean loss over M
    microbatches AND all gradients in ONE jitted SPMD program, with the
    backward W/B split filling the drain bubble (see the module-level
    schedule notes above). Signature and return match
    :func:`pipeline_1f1b_grads`; ``offload`` is an optional
    ``PipeOffload`` (host placement of the activation rings)."""
    mesh = jax.sharding.get_abstract_mesh()
    S = mesh.shape[pipe_axis]
    M = _leading(x_mb)
    R = _ring_capacity(S)
    n_ticks = zb_num_ticks(M, S)
    f32_boundary = jax.default_backend() == "cpu"

    off = offload if offload is not None else PipeOffload()
    if off.activations:
        from ..swap_tensor import host_stage
        to_host = host_stage.to_host
        to_device = host_stage.to_device
    else:
        to_host = to_device = lambda x: x

    def _b(x):
        if f32_boundary and jnp.issubdtype(x.dtype, jnp.floating) \
                and jnp.finfo(x.dtype).bits < 32:
            return jnp.float32
        return x.dtype

    def stage_fn(lp, la, hp, x_mb, tgt_mb):
        sid = lax.axis_index(pipe_axis)
        K = zb_deferred_window(sid, M, S)
        # see pipeline_1f1b_grads: differentiate only pipe-varying head
        # params or the transpose inserts a cross-stage psum per tick
        hp = jax.tree.map(
            lambda p: lax.pcast(p, (pipe_axis,), to="varying"), hp)
        perm_f = [(i, (i + 1) % S) for i in range(S)]
        perm_b = [(i, (i - 1) % S) for i in range(S)]

        def fwd_local(x, lp):
            def body(c, sl):
                p, a = sl
                return block_fn(c, p, a), None
            y, _ = lax.scan(body, x, (lp, la))
            return y

        def vz(x, dt=None):
            z = lax.pcast(
                jnp.zeros(x.shape, _b(x)), (pipe_axis,), to="varying")
            return z.astype(dt or x.dtype)

        x0 = jax.tree.map(lambda x: x[0], x_mb)
        act0 = jax.tree.map(vz, x0)
        dy0 = jax.tree.map(vz, x0)
        ring0 = jax.tree.map(
            lambda x: to_host(
                jnp.tile(vz(x)[None], (R,) + (1,) * x.ndim)), x0)
        # deferred cotangents live the s ticks between B(m) and W(m):
        # an S-slot ring (slot m % S) bounds them by stages, not M
        dyring0 = jax.tree.map(
            lambda x: to_host(
                jnp.tile(vz(x)[None], (S,) + (1,) * x.ndim)), x0)
        # prefetch buffer lives WITH the ring (host when offloading) so
        # the scan carry keeps one consistent memory space
        xpref0 = jax.tree.map(lambda x: to_host(vz(x)), x0)
        gacc0 = jax.tree.map(lambda p: vz(p, jnp.float32), lp)
        hacc0 = jax.tree.map(
            lambda p: lax.pcast(jnp.zeros(p.shape, jnp.float32),
                                (pipe_axis,), to="varying"), hp)
        dx0 = jax.tree.map(
            lambda x: jnp.zeros((M,) + x.shape[1:], _b(x)), x_mb)
        dx0 = jax.tree.map(
            lambda x: lax.pcast(x, (pipe_axis,), to="varying"), dx0)
        loss0 = lax.pcast(jnp.zeros((), jnp.float32), (pipe_axis,),
                          to="varying")

        def tick(carry, t):
            (act_in, dy_in, ring, dy_ring, x_pref, gacc, hacc, dx_out,
             loss_acc) = carry
            # ---------- F phase: stage s runs microbatch t - s; invalid
            # slots are cond no-ops (the drain tick's forward lane is
            # freed for the deferred W below, not burned on garbage)
            f_idx = zb_f_index(t, sid, M, S)
            f_valid = (f_idx >= 0) & (f_idx < M)
            f_safe = jnp.clip(f_idx, 0, M - 1)
            inject = jax.tree.map(
                lambda x, a: x[f_safe].astype(a.dtype), x_mb, act_in)
            x_in = jax.tree.map(
                lambda i, a: jnp.where(sid == 0, i, a), inject, act_in)

            def f_branch(x_, lp_):
                return fwd_local(x_, lp_)

            def f_skip(x_, lp_):
                return jax.tree.map(vz, x_)

            y = lax.cond(f_valid, f_branch, f_skip, x_in, lp)
            slot = f_safe % R
            ring = jax.tree.map(
                lambda r, x: r.at[slot].set(
                    jnp.where(f_valid, to_host(x), r[slot])), ring, x_in)

            # head: per-microbatch loss + 1/M cotangent seed, last stage
            # only AND only while it still has forwards (its B wave ends
            # with its F wave, so drain ticks skip the unembed entirely)
            tgt = jax.tree.map(lambda x: x[f_safe], tgt_mb)
            seed = lax.pcast(jnp.float32(1.0 / M), (pipe_axis,),
                             to="varying")

            def head_branch(hp_, y_, tgt_, seed_):
                l_mb_, vjp_h = jax.vjp(
                    lambda h, yy: head_loss_fn(h, yy, tgt_), hp_, y_)
                dhp_, dy_ = vjp_h(seed_)
                return l_mb_, dhp_, dy_

            def skip_branch(hp_, y_, tgt_, seed_):
                zv = lambda a: lax.pcast(jnp.zeros(a.shape, a.dtype),
                                         (pipe_axis,), to="varying")
                return (zv(jnp.zeros((), jnp.float32)),
                        jax.tree.map(zv, hp_), jax.tree.map(zv, y_))

            head_on = (sid == S - 1) & f_valid
            l_mb, dhp, dy_seed = lax.cond(head_on, head_branch,
                                          skip_branch, hp, y, tgt, seed)
            seed_valid = head_on
            loss_acc = loss_acc + jnp.where(seed_valid, l_mb, 0.0)
            hacc = jax.tree.map(
                lambda a, g: a + jnp.where(seed_valid,
                                           g.astype(jnp.float32), 0.0),
                hacc, dhp)

            # ---------- B phase: activation-grad only (dx via the
            # x-closure vjp — XLA's cone for dx alone, no dW work on the
            # wave the next stage is waiting on)
            b_idx = zb_b_index(t, sid, M, S)
            b_valid = (b_idx >= 0) & (b_idx < M)
            b_safe = jnp.clip(b_idx, 0, M - 1)
            dy = jax.tree.map(
                lambda s_, d: jnp.where(sid == S - 1,
                                        s_.astype(d.dtype), d),
                dy_seed, dy_in)
            # last stage: B(m) == F(m) same tick — its input is still in
            # registers; other stages use the one-tick-early prefetch
            # (double_buffer, the default) or fetch at use (A/B lever)
            if off.double_buffer:
                x_fetch = x_pref
            else:
                x_fetch = jax.tree.map(lambda r: r[b_safe % R], ring)
            x_for_b = jax.tree.map(
                lambda xi, xp: jnp.where(sid == S - 1, xi,
                                         to_device(xp).astype(xi.dtype)),
                x_in, x_fetch)

            def b_branch(x_, lp_, dy_):
                _, vjp_x = jax.vjp(lambda xx: fwd_local(xx, lp_), x_)
                (dx_,) = vjp_x(dy_)
                return dx_

            def b_skip(x_, lp_, dy_):
                return jax.tree.map(vz, x_)

            dx = lax.cond(b_valid, b_branch, b_skip, x_for_b, lp, dy)
            # stash the cotangent a DEFERRED microbatch's W will need
            defer_class = b_valid & (b_idx >= M - K)
            dslot = b_safe % S
            dy_ring = jax.tree.map(
                lambda r, d: r.at[dslot].set(
                    jnp.where(defer_class, to_host(d), r[dslot])),
                dy_ring, dy)
            write_dx = (sid == 0) & b_valid
            dx_out = jax.tree.map(
                lambda buf, d: buf.at[b_safe].set(
                    jnp.where(write_dx, d.astype(buf.dtype),
                              buf[b_safe])),
                dx_out, dx)

            # ---------- W phase: weight-grad pass — fused with B for
            # the early microbatches, the deferred wave for the last K_s
            w_idx = zb_w_deferred_index(t, sid, M, S)
            w_safe = jnp.clip(w_idx, 0, M - 1)
            w_def = (w_idx >= jnp.maximum(M - K, 0)) & (w_idx < M)
            w_fused = b_valid & (b_idx < M - K)
            x_w = jax.tree.map(
                lambda fb, r: jnp.where(
                    w_def, to_device(r[w_safe % R]).astype(fb.dtype), fb),
                x_for_b, ring)
            dy_w = jax.tree.map(
                lambda d, r: jnp.where(
                    w_def, to_device(r[w_safe % S]).astype(d.dtype), d),
                dy, dy_ring)

            def w_branch(x_, lp_, dy_):
                _, vjp_p = jax.vjp(lambda pp: fwd_local(x_, pp), lp_)
                (dlp_,) = vjp_p(dy_)
                return jax.tree.map(
                    lambda g: g.astype(jnp.float32), dlp_)

            def w_skip(x_, lp_, dy_):
                return jax.tree.map(lambda p: vz(p, jnp.float32), lp_)

            dlp = lax.cond(w_def | w_fused, w_branch, w_skip,
                           x_w, lp, dy_w)
            gacc = jax.tree.map(lambda a, g: a + g, gacc, dlp)

            # prefetch NEXT tick's B input out of the (host) ring — the
            # H2D copy gets a full tick of compute to hide under
            nb_safe = jnp.clip(zb_b_index(t + 1, sid, M, S), 0, M - 1)
            x_pref = jax.tree.map(lambda r: r[nb_safe % R], ring)

            act_nxt = jax.tree.map(
                lambda o: lax.ppermute(
                    o.astype(_b(o)), pipe_axis, perm_f).astype(o.dtype), y)
            dy_nxt = jax.tree.map(
                lambda o: lax.ppermute(
                    o.astype(_b(o)), pipe_axis, perm_b).astype(o.dtype),
                dx)
            return (act_nxt, dy_nxt, ring, dy_ring, x_pref, gacc, hacc,
                    dx_out, loss_acc), None

        carry = (act0, dy0, ring0, dyring0, xpref0, gacc0, hacc0, dx0,
                 loss0)
        (_, _, _, _, _, gacc, hacc, dx_out, loss_acc), _ = lax.scan(
            tick, carry, jnp.arange(n_ticks))

        loss = lax.psum(loss_acc, pipe_axis) / M
        hgrads = jax.tree.map(lambda a: lax.psum(a, pipe_axis), hacc)
        dx_mb = jax.tree.map(lambda a: lax.psum(a, pipe_axis), dx_out)
        return loss, gacc, hgrads, dx_mb

    loss, gacc, hgrads, dx_mb = jax.shard_map(
        stage_fn,
        in_specs=(P(pipe_axis), P(pipe_axis), P(), P(), P()),
        out_specs=(P(), P(pipe_axis), P(), P()),
        axis_names={pipe_axis},
        **_STEADY_STATE_KW,
    )(layers_params, layers_aux, head_params, x_mb, tgt_mb)
    dlayers = jax.tree.map(lambda g, p: g.astype(p.dtype),
                           gacc, layers_params)
    dhead = jax.tree.map(lambda g, p: g.astype(p.dtype),
                         hgrads, head_params)
    dx_mb = jax.tree.map(lambda g, x: g.astype(x.dtype), dx_mb, x_mb)
    return loss, (dlayers, dhead, dx_mb)


import functools as _functools
from typing import NamedTuple as _NamedTuple

import numpy as _np


class PipeOffload(_NamedTuple):
    """Host-offload knobs threaded through the custom_vjp wrappers
    (hashable — nondiff custom_vjp args must be). ``activations`` puts
    the executor's input/dy rings in host memory
    (swap_tensor/host_stage.py resolves the platform's memory kind;
    identity when the platform has a single memory space)."""
    activations: bool = False
    double_buffer: bool = True


def _grads_fn(schedule):
    if schedule == "zb":
        return pipeline_zb_grads
    if schedule == "1f1b":
        return pipeline_1f1b_grads
    raise ValueError(f"unknown steady-state pipeline schedule "
                     f"{schedule!r} (want '1f1b' or 'zb')")


@_functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def pipeline_loss(block_fn, head_loss_fn, pipe_axis, schedule, offload,
                  layers_params, layers_aux, head_params, x_mb, tgt_mb):
    """Differentiable wrapper over the steady-state executors: returns
    the mean microbatch loss; ``jax.grad`` through it yields the grads
    the pipelined pass already computed (stored as vjp residuals), so
    the engine's ordinary value_and_grad drives the schedule unchanged.
    ``schedule``: '1f1b' | 'zb'; ``offload``: PipeOffload or None."""
    kw = {"offload": offload} if schedule == "zb" else {}
    loss, _ = _grads_fn(schedule)(
        block_fn, head_loss_fn, layers_params, layers_aux, head_params,
        x_mb, tgt_mb, pipe_axis=pipe_axis, **kw)
    return loss


def _pl_fwd(block_fn, head_loss_fn, pipe_axis, schedule, offload,
            layers_params, layers_aux, head_params, x_mb, tgt_mb):
    kw = {"offload": offload} if schedule == "zb" else {}
    loss, (dl, dh, dx) = _grads_fn(schedule)(
        block_fn, head_loss_fn, layers_params, layers_aux, head_params,
        x_mb, tgt_mb, pipe_axis=pipe_axis, **kw)
    # the int-dtype primals ride along so the bwd rule can shape their
    # float0 cotangents
    return loss, (dl, dh, dx, layers_aux, tgt_mb)


def _pl_bwd(block_fn, head_loss_fn, pipe_axis, schedule, offload, res, g):
    dl, dh, dx, layers_aux, tgt_mb = res
    scale = lambda tr: jax.tree.map(lambda a: (a * g).astype(a.dtype), tr)
    f0 = lambda tr: jax.tree.map(
        lambda a: _np.zeros(a.shape, jax.dtypes.float0), tr)
    return (scale(dl), f0(layers_aux), scale(dh), scale(dx), f0(tgt_mb))


pipeline_loss.defvjp(_pl_fwd, _pl_bwd)


def pipeline_1f1b_loss(block_fn, head_loss_fn, pipe_axis, layers_params,
                       layers_aux, head_params, x_mb, tgt_mb):
    """Back-compat alias: the 1F1B schedule through the generic
    :func:`pipeline_loss` wrapper."""
    return pipeline_loss(block_fn, head_loss_fn, pipe_axis, "1f1b", None,
                         layers_params, layers_aux, head_params, x_mb,
                         tgt_mb)
