"""SPMD pipeline executor: collective-permute over the 'pipe' mesh axis.

The reference's pipeline engine (runtime/pipe/engine.py:56) is an imperative
instruction interpreter: per-rank processes walk a 1F1B instruction stream
(runtime/pipe/schedule.py:189) exchanging activations over NCCL p2p
(runtime/pipe/p2p.py:50,71). On TPU the same dataflow is ONE jitted SPMD
program:

  * the stacked layer dim of the model params is sharded over the 'pipe'
    mesh axis — each pipe shard owns L/S contiguous layers (the
    PipelineModule partitioning, reference runtime/pipe/module.py:372);
  * a ``shard_map`` manual only over 'pipe' (data/tensor/seq stay
    GSPMD-automatic, so the block's internal sharding constraints keep
    working) runs the rotation loop: at tick t, stage s computes microbatch
    t-s and ``ppermute``s its activation to stage s+1 — the p2p send/recv
    of the reference, but expressed as a collective XLA can schedule;
  * reverse-mode AD through the scan yields the backward pipeline (reverse
    ppermutes) automatically — the schedule the reference hand-codes.

The forward fills the pipe GPipe-style (all M microbatches in flight);
memory is bounded by rematerializing each block (``jax.checkpoint``), the
same trade the reference makes with activation checkpointing. The 1F1B
instruction stream in schedule.py documents/verifies the logical order for
parity tests; this executor is the compute path.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def spmd_pipeline(block_fn, layers, x_mb, *, pipe_axis="pipe",
                  unroll_local=False):
    """Run ``x`` through all L layers, pipelined over the pipe axis.

    Args:
      block_fn: ``(x, layer_slice) -> x`` — one layer's forward. ``x`` is a
        single microbatch activation; ``layer_slice`` is the layers pytree
        with the leading layer dim removed (bundle rngs etc. into it).
      layers: pytree whose leaves have leading dim L (== S * layers_per_
        stage); sharded P(pipe_axis) on that dim by the caller's param specs.
      x_mb: microbatch-stacked input, leaves (M, ...) — replicated over the
        pipe axis, sharded however the caller likes on auto axes.
      pipe_axis: manual mesh axis name.
      unroll_local: unroll the per-stage layer scan (faster for tiny depth).

    Returns outputs with the same (M, ...) structure as ``x_mb``, replicated
    over the pipe axis.

    Must be called under an active mesh (``jax.set_mesh``) that has
    ``pipe_axis``. Total ticks = M + S - 1; per-stage bubble fraction
    (S-1)/(M+S-1) — choose M >= S (reference guidance for 1F1B too).
    """
    mesh = jax.sharding.get_abstract_mesh()
    if mesh.empty or pipe_axis not in mesh.shape:
        raise ValueError(f"spmd_pipeline needs an active mesh with a "
                         f"'{pipe_axis}' axis; got {mesh}")
    S = mesh.shape[pipe_axis]
    if S == 1:
        # degenerate: plain scan over layers, no collectives
        def body(c, layer):
            return block_fn(c, layer), None

        def run(x):
            y, _ = lax.scan(body, x, layers, unroll=unroll_local)
            return y
        return jax.vmap(run)(x_mb) if _leading(x_mb) else run(x_mb)

    M = _leading(x_mb)
    if M is None:
        raise ValueError("x_mb must have a leading microbatch dim")

    # XLA-CPU (the virtual test mesh) check-fails promoting partial-manual
    # sub-f32 all-reduces, so THERE activations cross the shard_map
    # boundary in f32. On TPU bf16 ppermute/psum are legal and halve the
    # boundary bytes — the workaround is scoped to the CPU interpreter.
    f32_boundary = jax.default_backend() == "cpu"

    def _is_lowp(x):
        return (jnp.issubdtype(x.dtype, jnp.floating)
                and jnp.finfo(x.dtype).bits < 32)
    in_dtypes = jax.tree.map(lambda x: x.dtype, x_mb)
    if f32_boundary:
        x_mb = jax.tree.map(
            lambda x: x.astype(jnp.float32) if _is_lowp(x) else x, x_mb)

    def stage_fn(layers_local, x_local):
        sid = lax.axis_index(pipe_axis)

        def run_local(state):
            def body(c, layer):
                return block_fn(c, layer), None
            y, _ = lax.scan(body, state, layers_local, unroll=unroll_local)
            return y

        def varying_zeros(x):
            # CPU: pcast in f32, cast after — the transpose of
            # pcast(to='varying') is a psum over 'pipe', and XLA-CPU
            # check-fails promoting a sub-f32 partial-manual all-reduce.
            # TPU: pcast in the native dtype (bf16 collectives are legal).
            if not f32_boundary:
                return lax.pcast(jnp.zeros(x.shape, x.dtype), (pipe_axis,),
                                 to="varying")
            z = lax.pcast(jnp.zeros(x.shape, jnp.float32), (pipe_axis,),
                          to="varying")
            return z.astype(x.dtype)

        state = jax.tree.map(lambda x: varying_zeros(x[0]), x_local)
        outputs = jax.tree.map(varying_zeros, x_local)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (clamped index; garbage ticks at
            # t >= M never reach the output buffer). The pipe-invariant
            # slice is promoted to pipe-varying EXPLICITLY, in f32, before
            # the dtype cast — otherwise shard_map's vma machinery inserts
            # the promotion inside the where in the compute dtype, and that
            # lowers to a sub-f32 all-reduce XLA-CPU cannot promote.
            inject = jax.tree.map(
                lambda x, dt: lax.pcast(
                    x[jnp.minimum(t, M - 1)], (pipe_axis,),
                    to="varying").astype(dt),
                x_local, in_dtypes)
            state = jax.tree.map(
                lambda i, s: jnp.where(sid == 0, i, s), inject, state)
            out = run_local(state)
            # last stage owns microbatch t-(S-1) at tick t
            idx = t - (S - 1)
            safe = jnp.clip(idx, 0, M - 1)
            valid = (sid == S - 1) & (idx >= 0)

            def write(buf, o):
                cur = lax.dynamic_index_in_dim(buf, safe, 0, keepdims=False)
                return lax.dynamic_update_index_in_dim(
                    buf, jnp.where(valid, o, cur), safe, 0)
            outputs = jax.tree.map(write, outputs, out)
            nxt = jax.tree.map(lambda o: lax.ppermute(o, pipe_axis, perm),
                               out)
            return (nxt, outputs), None

        (_, outputs), _ = lax.scan(tick, (state, outputs),
                                   jnp.arange(M + S - 1))

        # non-last stages hold zeros: psum broadcasts the result pipe-wide.
        # On the CPU test mesh sub-f32 floats go through f32 (XLA-CPU
        # check-fails promoting a partial-manual bf16 all-reduce); on TPU
        # the psum runs in the native dtype — half the boundary bytes.
        def bcast(o):
            if f32_boundary and jnp.issubdtype(o.dtype, jnp.floating) \
                    and jnp.finfo(o.dtype).bits < 32:
                return lax.psum(o.astype(jnp.float32),
                                pipe_axis).astype(o.dtype)
            return lax.psum(o, pipe_axis)
        return jax.tree.map(bcast, outputs)

    return jax.shard_map(
        stage_fn,
        in_specs=(P(pipe_axis), P()),
        out_specs=P(),
        axis_names={pipe_axis},
    )(layers, x_mb)


def _leading(tree):
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return None
    n = leaves[0].shape[0] if leaves[0].ndim else None
    return n


def split_microbatches(x, num_microbatches, batch_dim=0):
    """(B, ...) -> (M, B//M, ...) with stride-M row sampling so each
    microbatch draws evenly from every data-parallel shard of the batch dim
    (a contiguous split would put whole microbatches on single DP shards).
    Inverse: merge_microbatches."""
    M = num_microbatches
    B = x.shape[batch_dim]
    assert B % M == 0, f"batch {B} not divisible by microbatches {M}"
    x = jnp.moveaxis(x, batch_dim, 0)
    x = x.reshape((B // M, M) + x.shape[1:])
    x = jnp.swapaxes(x, 0, 1)           # (M, B//M, ...)
    return x


def merge_microbatches(x, batch_dim=0):
    """Inverse of split_microbatches: (M, B//M, ...) -> (B, ...)."""
    x = jnp.swapaxes(x, 0, 1)
    x = x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
    return jnp.moveaxis(x, 0, batch_dim) if batch_dim else x
