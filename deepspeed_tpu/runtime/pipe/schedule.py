"""Pipeline instruction schedules (1F1B and inference).

Counterpart of reference ``runtime/pipe/schedule.py`` (``TrainSchedule:189``
1F1B, ``InferenceSchedule:135``, ``PipeInstruction`` vocabulary). There the
schedule drives an imperative per-rank interpreter (``_exec_schedule``,
engine.py:1382). Here the compute path is one SPMD program (spmd.py) whose
reverse-mode AD produces the backward pipeline — so these instruction
streams serve as the *specification*: they document the logical order,
power the deadlock/dataflow tests, and give schedule-analysis tooling
(bubble fraction, peak in-flight buffers) the same surface the reference
exposes.
"""


class PipeInstruction:
    """One step of work for one pipeline stage."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class ForwardPass(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class BackwardPass(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class SendActivation(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class RecvActivation(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class SendGrad(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class RecvGrad(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class PipeSchedule:
    """Generates the instruction stream for one (stage, config)."""

    def __init__(self, micro_batches, stages, stage_id):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range [0,{stages})")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self):
        raise NotImplementedError

    def steps(self):
        """Yield lists of PipeInstructions (one list = one logical step)."""
        raise NotImplementedError

    def __iter__(self):
        return iter(self.steps())

    def bubble_fraction(self):
        """Idle fraction of the pipeline fill/drain: (S-1)/(M+S-1)."""
        return (self.stages - 1) / (self.micro_batches + self.stages - 1)


class InferenceSchedule(PipeSchedule):
    """Forward-only pipeline: fill, stream, drain."""

    def num_pipe_buffers(self):
        return 2

    def steps(self):
        M, S, s = self.micro_batches, self.stages, self.stage_id
        for t in range(M + S - 1):
            mb = t - s
            step = []
            if 0 <= mb < M:
                buf = mb % self.num_pipe_buffers()
                if self.is_first_stage or self.is_last_stage:
                    step.append(LoadMicroBatch(micro_batch=mb, buffer_id=buf))
                if not self.is_first_stage:
                    step.append(RecvActivation(micro_batch=mb, buffer_id=buf))
                step.append(ForwardPass(micro_batch=mb, buffer_id=buf))
                if not self.is_last_stage:
                    step.append(SendActivation(micro_batch=mb, buffer_id=buf))
            yield step


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady one-forward-one-backward, cooldown
    backwards. Peak in-flight activations on stage s = min(S - s, M) —
    the memory property that motivates 1F1B over GPipe."""

    def num_pipe_buffers(self):
        return min(self.stages - self.stage_id, self.micro_batches)

    def _phases(self):
        """Sequence of ('F'|'B', micro_batch) for this stage."""
        M, S, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(S - s - 1, M)
        seq = [("F", i) for i in range(warmup)]
        f, b = warmup, 0
        while f < M:
            seq.append(("F", f))
            seq.append(("B", b))
            f += 1
            b += 1
        while b < M:
            seq.append(("B", b))
            b += 1
        return seq

    def steps(self):
        nbuf = self.num_pipe_buffers()
        for kind, mb in self._phases():
            buf = mb % nbuf
            step = []
            if kind == "F":
                if self.is_first_stage or self.is_last_stage:
                    step.append(LoadMicroBatch(micro_batch=mb, buffer_id=buf))
                if not self.is_first_stage:
                    step.append(RecvActivation(micro_batch=mb, buffer_id=buf))
                step.append(ForwardPass(micro_batch=mb, buffer_id=buf))
                if not self.is_last_stage:
                    step.append(SendActivation(micro_batch=mb, buffer_id=buf))
            else:
                if not self.is_last_stage:
                    step.append(RecvGrad(micro_batch=mb, buffer_id=buf))
                step.append(BackwardPass(micro_batch=mb, buffer_id=buf))
                if not self.is_first_stage:
                    step.append(SendGrad(micro_batch=mb, buffer_id=buf))
            yield step
        yield [ReduceGrads(), OptimizerStep()]
