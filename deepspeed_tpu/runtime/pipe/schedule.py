"""Pipeline instruction schedules (1F1B and inference).

Counterpart of reference ``runtime/pipe/schedule.py`` (``TrainSchedule:189``
1F1B, ``InferenceSchedule:135``, ``PipeInstruction`` vocabulary). There the
schedule drives an imperative per-rank interpreter (``_exec_schedule``,
engine.py:1382). Here the compute path is one SPMD program (spmd.py) whose
reverse-mode AD produces the backward pipeline — so these instruction
streams serve as the *specification*: they document the logical order,
power the deadlock/dataflow tests, and give schedule-analysis tooling
(bubble fraction, peak in-flight buffers) the same surface the reference
exposes.
"""


class PipeInstruction:
    """One step of work for one pipeline stage."""

    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(self.kwargs.items()))))


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class LoadMicroBatch(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class ForwardPass(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class BackwardPass(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class BackwardActGrad(PipeInstruction):
    """Zero-bubble B pass: activation gradient only (dx from dy) — the
    piece the previous stage is waiting on. kwargs: micro_batch,
    buffer_id."""


class BackwardWeightGrad(PipeInstruction):
    """Zero-bubble W pass: weight gradient only (dW from the saved
    input and dy) — free-floating filler work, scheduled into the
    drain bubble. kwargs: micro_batch, buffer_id."""


class SendActivation(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class RecvActivation(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class SendGrad(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class RecvGrad(PipeInstruction):
    """kwargs: micro_batch, buffer_id."""


class PipeSchedule:
    """Generates the instruction stream for one (stage, config)."""

    def __init__(self, micro_batches, stages, stage_id):
        if not 0 <= stage_id < stages:
            raise ValueError(f"stage_id {stage_id} out of range [0,{stages})")
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1

    def num_pipe_buffers(self):
        raise NotImplementedError

    def steps(self):
        """Yield lists of PipeInstructions (one list = one logical step)."""
        raise NotImplementedError

    def __iter__(self):
        return iter(self.steps())

    def bubble_fraction(self):
        """Idle fraction of the pipeline fill/drain: (S-1)/(M+S-1)."""
        return (self.stages - 1) / (self.micro_batches + self.stages - 1)


class ZeroBubbleSchedule(PipeSchedule):
    """ZB-H1: 1F1B with each backward split into B (activation grad,
    stays on the drain wave) and W (weight grad, deferred into the
    forward-drain ticks). Written in the reference's imperative
    per-stage phase style; the tick-parity test
    (tests/unit/test_pipe_fast.py) pins this stream against the SPMD
    executor's index maps (runtime/pipe/spmd.py zb_*_index — the
    executed order), so neither can drift from the other.

    Per stage s (K_s = min(2(S-1) - s, M) deferred microbatches):
      * F(m) at tick m + s; B(m) at tick m + 2(S-1) - s (1F1B waves);
      * W(m) fused right after B(m) for m < M - K_s (steady state);
      * W(m) for the last K_s microbatches lands on tick m + 2(S-1) —
        s ticks after its own B, occupying a tick whose forward slot
        has drained. Memory: the 1F1B input ring plus K_s <= S saved
        cotangents — still O(stages)."""

    def num_pipe_buffers(self):
        # input ring (2S in the executor) + the deferred-cotangent ring
        return 2 * self.stages + min(self.stages, self.micro_batches)

    def deferred_window(self):
        return min(2 * (self.stages - 1) - self.stage_id,
                   self.micro_batches)

    def tick_ops(self, t):
        """('F'|'B'|'W', micro_batch) ops this stage runs at tick t, in
        executor phase order (F, then B, then W)."""
        M, S, s = self.micro_batches, self.stages, self.stage_id
        K = self.deferred_window()
        ops = []
        f = t - s
        if 0 <= f < M:
            ops.append(("F", f))
        b = t - 2 * (S - 1) + s
        if 0 <= b < M:
            ops.append(("B", b))
            if b < M - K:
                ops.append(("W", b))        # fused: steady state
        w = t - 2 * (S - 1)
        if max(M - K, 0) <= w < M:
            ops.append(("W", w))            # deferred: drain filler
        return ops

    def num_ticks(self):
        return self.micro_batches + 2 * (self.stages - 1)

    def steps(self):
        M, S = self.micro_batches, self.stages
        nbuf = 2 * S
        for t in range(self.num_ticks()):
            step = []
            for kind, mb in self.tick_ops(t):
                buf = mb % nbuf
                if kind == "F":
                    if self.is_first_stage or self.is_last_stage:
                        step.append(LoadMicroBatch(micro_batch=mb,
                                                   buffer_id=buf))
                    if not self.is_first_stage:
                        step.append(RecvActivation(micro_batch=mb,
                                                   buffer_id=buf))
                    step.append(ForwardPass(micro_batch=mb,
                                            buffer_id=buf))
                    if not self.is_last_stage:
                        step.append(SendActivation(micro_batch=mb,
                                                   buffer_id=buf))
                elif kind == "B":
                    if not self.is_last_stage:
                        step.append(RecvGrad(micro_batch=mb,
                                             buffer_id=buf))
                    step.append(BackwardActGrad(micro_batch=mb,
                                                buffer_id=buf))
                    if not self.is_first_stage:
                        step.append(SendGrad(micro_batch=mb,
                                             buffer_id=buf))
                else:
                    step.append(BackwardWeightGrad(micro_batch=mb,
                                                   buffer_id=buf))
            yield step
        yield [ReduceGrads(), OptimizerStep()]

    def bubble_fraction(self):
        return executor_bubble_fraction("zb", self.micro_batches,
                                        self.stages)


# ------------------------------------------------- lock-step wall model
def executor_tick_units(schedule, micro_batches, stages):
    """Per-tick cost of the SPMD rotation-loop executors in compute
    units (F = B = W = 1): every tick ends in a collective ppermute, so
    the tick costs the BUSIEST stage's lane count. Returns the list of
    per-tick max-unit costs.

      'gpipe'  M+S-1 forward ticks (1 unit) then, via autodiff of the
               scan, M+S-1 backward ticks (B+W fused = 2 units).
      '1f1b'   the interleaved executor computes its forward lane
               unconditionally (garbage on invalid ticks, masked
               accumulation) and the fused B+W backward likewise:
               3 units x (M + 2(S-1)) ticks, flat.
      'zb'     invalid lanes are lax.cond no-ops and W defers into the
               forward-drain ticks: the per-tick max drops wherever
               the busiest stage's W has been deferred away.
    """
    M, S = micro_batches, stages
    if schedule == "gpipe":
        return [1] * (M + S - 1) + [2] * (M + S - 1)
    if schedule == "1f1b":
        return [3] * (M + 2 * (S - 1))
    if schedule == "zb":
        walls = []
        scheds = [ZeroBubbleSchedule(M, S, s) for s in range(S)]
        for t in range(M + 2 * (S - 1)):
            walls.append(max(len(sc.tick_ops(t)) for sc in scheds))
        return walls
    raise ValueError(f"unknown schedule {schedule!r}")


def executor_bubble_fraction(schedule, micro_batches, stages):
    """Idle fraction of the lock-step executor wall: 1 - useful/wall,
    useful = 3M units per stage (F + B + W per microbatch). GPipe
    reduces to the classical (S-1)/(M+S-1); the zero-bubble executor
    is strictly below it (the acceptance bar) because the deferred W
    wave fills the drain ticks the others idle (or burn garbage
    forwards) through."""
    wall = sum(executor_tick_units(schedule, micro_batches, stages))
    return max(0.0, 1.0 - (3.0 * micro_batches) / wall)


class InferenceSchedule(PipeSchedule):
    """Forward-only pipeline: fill, stream, drain."""

    def num_pipe_buffers(self):
        return 2

    def steps(self):
        M, S, s = self.micro_batches, self.stages, self.stage_id
        for t in range(M + S - 1):
            mb = t - s
            step = []
            if 0 <= mb < M:
                buf = mb % self.num_pipe_buffers()
                if self.is_first_stage or self.is_last_stage:
                    step.append(LoadMicroBatch(micro_batch=mb, buffer_id=buf))
                if not self.is_first_stage:
                    step.append(RecvActivation(micro_batch=mb, buffer_id=buf))
                step.append(ForwardPass(micro_batch=mb, buffer_id=buf))
                if not self.is_last_stage:
                    step.append(SendActivation(micro_batch=mb, buffer_id=buf))
            yield step


class TrainSchedule(PipeSchedule):
    """1F1B: warmup forwards, steady one-forward-one-backward, cooldown
    backwards. Peak in-flight activations on stage s = min(S - s, M) —
    the memory property that motivates 1F1B over GPipe."""

    def num_pipe_buffers(self):
        return min(self.stages - self.stage_id, self.micro_batches)

    def _phases(self):
        """Sequence of ('F'|'B', micro_batch) for this stage."""
        M, S, s = self.micro_batches, self.stages, self.stage_id
        warmup = min(S - s - 1, M)
        seq = [("F", i) for i in range(warmup)]
        f, b = warmup, 0
        while f < M:
            seq.append(("F", f))
            seq.append(("B", b))
            f += 1
            b += 1
        while b < M:
            seq.append(("B", b))
            b += 1
        return seq

    def steps(self):
        nbuf = self.num_pipe_buffers()
        for kind, mb in self._phases():
            buf = mb % nbuf
            step = []
            if kind == "F":
                if self.is_first_stage or self.is_last_stage:
                    step.append(LoadMicroBatch(micro_batch=mb, buffer_id=buf))
                if not self.is_first_stage:
                    step.append(RecvActivation(micro_batch=mb, buffer_id=buf))
                step.append(ForwardPass(micro_batch=mb, buffer_id=buf))
                if not self.is_last_stage:
                    step.append(SendActivation(micro_batch=mb, buffer_id=buf))
            else:
                if not self.is_last_stage:
                    step.append(RecvGrad(micro_batch=mb, buffer_id=buf))
                step.append(BackwardPass(micro_batch=mb, buffer_id=buf))
                if not self.is_first_stage:
                    step.append(SendGrad(micro_batch=mb, buffer_id=buf))
            yield step
        yield [ReduceGrads(), OptimizerStep()]
