"""Cartesian process topology — coordinate math over named axes.

Counterpart of reference ``runtime/pipe/topology.py`` (``ProcessTopology:12``,
``PipelineParallelGrid:251``). Pure coordinate bookkeeping, so the design
carries over naturally; here it doubles as the bridge between flat "rank"
reasoning (launcher, schedules, tests) and the named-axis world of the
global ``jax.sharding.Mesh`` (utils/groups.py) — a rank is just a position
in the row-major enumeration of mesh devices.
"""

import itertools
from collections import namedtuple


class ProcessTopology:
    """Maps ranks <-> coordinates over named axes, row-major (first axis
    varies slowest), matching Mesh device-array order."""

    def __init__(self, axes, dims):
        if len(axes) != len(dims):
            raise ValueError("axes and dims must have equal length")
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", self.axes)
        self._coord_to_rank = {}
        for rank, coord in enumerate(itertools.product(
                *[range(d) for d in self.dims])):
            self._coord_to_rank[self.ProcessCoord(*coord)] = rank
        self._rank_to_coord = {r: c for c, r in self._coord_to_rank.items()}

    @property
    def world_size(self):
        n = 1
        for d in self.dims:
            n *= d
        return n

    def get_rank(self, **coords):
        if set(coords) != set(self.axes):
            raise ValueError(f"need all axes {self.axes}, got {list(coords)}")
        return self._coord_to_rank[self.ProcessCoord(**coords)]

    def get_coord(self, rank):
        return self._rank_to_coord[rank]

    def get_dim(self, axis):
        return self.dims[self.axes.index(axis)]

    def get_axis_names(self):
        return list(self.axes)

    def filter_match(self, **filters):
        """Ranks whose coordinates match every given axis=value filter."""
        out = []
        for rank in range(self.world_size):
            coord = self._rank_to_coord[rank]
            if all(getattr(coord, ax) == v for ax, v in filters.items()):
                out.append(rank)
        return out

    def get_axis_comm_lists(self, axis):
        """Groups of ranks that differ only along ``axis`` — the reference's
        process-group construction (topology.py: get_axis_comm_lists); here
        these are the device groups a collective over that mesh axis spans."""
        if axis not in self.axes:
            return []
        other = [ax for ax in self.axes if ax != axis]
        lists = []
        for combo in itertools.product(
                *[range(self.get_dim(ax)) for ax in other]):
            fixed = dict(zip(other, combo))
            group = [self.get_rank(**fixed, **{axis: i})
                     for i in range(self.get_dim(axis))]
            lists.append(group)
        return lists

    def get_rank_repr(self, rank, omit_axes=("data", "pipe"), inner_sep="_",
                      outer_sep="-"):
        """String like 'tensor_00' used in checkpoint filenames (reference
        uses it for layer file naming)."""
        coord = self.get_coord(rank)
        parts = [f"{ax}{inner_sep}{getattr(coord, ax):02d}"
                 for ax in self.axes if ax not in omit_axes]
        return outer_sep.join(parts)

    def __str__(self):
        return (f"ProcessTopology(axes={self.axes}, dims={self.dims})")


class PipeDataParallelTopology(ProcessTopology):
    """pipe x data (reference topology.py: PipeDataParallelTopology)."""

    def __init__(self, num_pp, num_dp):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """pipe x data x model — 3D parallelism."""

    def __init__(self, num_pp, num_dp, num_mp):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """Stage/data/model coordinate queries for a rank (reference
    topology.py:251). Answers "which stage am I", "who are my pipeline
    neighbors" — consumed by schedules and checkpoint naming. On TPU the
    p2p neighbors become the ppermute permutation."""

    def __init__(self, topology=None, rank=0):
        self._topo = topology or PipeDataParallelTopology(1, 1)
        self.global_rank = rank
        self.world_size = self._topo.world_size
        coord = self._topo.get_coord(rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0)
        self.pipe_parallel_size = (self._topo.get_dim("pipe")
                                   if "pipe" in self._topo.axes else 1)
        self.data_parallel_size = (self._topo.get_dim("data")
                                   if "data" in self._topo.axes else 1)
        self.model_parallel_size = (self._topo.get_dim("model")
                                    if "model" in self._topo.axes else 1)

    @property
    def topology(self):
        return self._topo

    def get_stage_id(self):
        return self.stage_id

    def get_data_parallel_id(self):
        return self.data_parallel_id

    def get_pipe_parallel_world_size(self):
        return self.pipe_parallel_size

    def get_data_parallel_world_size(self):
        return self.data_parallel_size

    def get_model_parallel_world_size(self):
        return self.model_parallel_size

    def is_first_stage(self):
        return self.stage_id == 0

    def is_last_stage(self):
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id):
        """Rank holding ``stage_id`` with my other coordinates."""
        coord = self._topo.get_coord(self.global_rank)
        kwargs = {ax: getattr(coord, ax) for ax in self._topo.axes}
        kwargs["pipe"] = stage_id
        return self._topo.get_rank(**kwargs)

    @property
    def prev_stage(self):
        return (self.stage_id - 1) % self.pipe_parallel_size

    @property
    def next_stage(self):
        return (self.stage_id + 1) % self.pipe_parallel_size

    def ppermute_perm(self):
        """The cyclic (src, dst) stage permutation the SPMD executor uses in
        place of p2p send/recv (reference runtime/pipe/p2p.py:50,71)."""
        S = self.pipe_parallel_size
        return [(i, (i + 1) % S) for i in range(S)]
