"""PipelineModule: express a model as a layer sequence, partition to stages.

Counterpart of reference ``runtime/pipe/module.py`` (``LayerSpec:31``,
``TiedLayerSpec:78``, ``PipelineModule:87``, ``_partition_layers:372``).
Functional-JAX redesign: a layer is either a plain callable ``x -> x`` or an
object with ``init(rng) -> params`` and ``apply(params, x) -> x``. The module
owns layer construction, stage partitioning (uniform / parameters /
type:regex, same vocabulary as the reference), and two execution paths:

  * ``apply``: sequential composition — the correctness/reference path and
    the single-stage fallback;
  * ``stacked_params`` + the spmd executor (spmd.py): when layers are
    structurally homogeneous their params stack on a leading layer dim that
    shards over the 'pipe' mesh axis; heterogeneous embed/head layers stay
    outside the pipelined region (how the flagship GPT2Pipe is built).
"""

import re

import jax
import numpy as np


class LayerSpec:
    """Lazily-built layer: stores class + ctor args, builds on demand —
    avoids materializing all stages' layers everywhere (the reference's
    motivation too: module.py:31)."""

    def __init__(self, typename, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)

    def __repr__(self):
        return f"LayerSpec({getattr(self.typename, '__name__', self.typename)})"


class TiedLayerSpec(LayerSpec):
    """A layer whose params are shared with every other TiedLayerSpec of the
    same ``key`` (reference module.py:78 — e.g. tied embedding/unembedding).
    In the SPMD engine tied params are simply replicated over 'pipe' and
    GSPMD psums their grads — the declarative form of the reference's
    tied-weight allreduce (pipe/engine.py:260 _exec_reduce_tied_grads)."""

    def __init__(self, key, typename, *module_args, forward_fn=None,
                 **module_kwargs):
        super().__init__(typename, *module_args, **module_kwargs)
        self.key = key
        self.forward_fn = forward_fn


def partition_balanced(weights, num_parts):
    """Split ``weights`` into ``num_parts`` contiguous chunks minimizing the
    max chunk sum. Binary search on the bottleneck + greedy feasibility —
    O(n log sum). Returns part boundary indices, len num_parts+1.
    (Reference uses ds_utils.partition_balanced for method='parameters'.)"""
    weights = list(weights)
    n = len(weights)
    if num_parts > n:
        raise ValueError(f"cannot split {n} layers into {num_parts} stages")

    def feasible(cap):
        parts, acc = 1, 0
        for w in weights:
            if w > cap:
                return False
            if acc + w > cap:
                parts += 1
                acc = w
            else:
                acc += w
        return parts <= num_parts

    lo, hi = max(weights, default=0), sum(weights)
    while lo < hi:
        mid = (lo + hi) // 2
        if feasible(mid):
            hi = mid
        else:
            lo = mid + 1
    # materialize boundaries at bottleneck lo, greedily, but never leave
    # fewer layers than remaining parts
    bounds = [0]
    acc = 0
    for i, w in enumerate(weights):
        remaining_parts = num_parts - (len(bounds) - 1)
        remaining_layers = n - i
        if (acc + w > lo or remaining_layers < remaining_parts + 1) and acc > 0 \
                and len(bounds) < num_parts:
            bounds.append(i)
            acc = 0
        acc += w
    while len(bounds) < num_parts:
        bounds.append(n - (num_parts - len(bounds)))
    bounds.append(n)
    return bounds


def _param_count(layer):
    if not hasattr(layer, "init"):
        return 0
    shapes = jax.eval_shape(layer.init, jax.random.key(0))
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))


class PipelineModule:
    """A sequence of layers partitioned into pipeline stages."""

    def __init__(self, layers, num_stages=1, partition_method="parameters",
                 loss_fn=None):
        self.specs = list(layers)
        self.layers = [s.build() if isinstance(s, LayerSpec) else s
                       for s in self.specs]
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.parts = self._partition_layers(partition_method)
        # tied keys -> layer indices
        self.tied_groups = {}
        for i, s in enumerate(self.specs):
            if isinstance(s, TiedLayerSpec):
                self.tied_groups.setdefault(s.key, []).append(i)

    # ----------------------------------------------------------- partitioning
    def _partition_layers(self, method):
        """Stage boundaries (reference module.py:372 _partition_layers).
        methods: 'uniform' (equal layer counts), 'parameters' (balance param
        counts), 'type:REGEX' (balance count of layers whose class name
        matches REGEX)."""
        n, S = len(self.layers), self.num_stages
        method = method.lower() if isinstance(method, str) else method
        if method == "uniform":
            weights = [1] * n
        elif method == "parameters":
            weights = [max(_param_count(l), 0) + 1 for l in self.layers]
        elif isinstance(method, str) and method.startswith("type:"):
            pat = method.split(":", 1)[1]
            weights = [1 if re.search(pat, type(l).__name__, re.IGNORECASE)
                       else 0 for l in self.layers]
            if sum(weights) == 0:
                raise ValueError(f"no layer class matches {pat!r}")
            # every stage still needs >= 1 layer: give zeros epsilon weight
            weights = [w * 1000 + 1 for w in weights]
        else:
            raise ValueError(f"unknown partition_method {method!r}")
        return partition_balanced(weights, S)

    def stage_layer_indices(self, stage_id):
        return list(range(self.parts[stage_id], self.parts[stage_id + 1]))

    def stage_of_layer(self, layer_idx):
        for s in range(self.num_stages):
            if self.parts[s] <= layer_idx < self.parts[s + 1]:
                return s
        raise IndexError(layer_idx)

    # ------------------------------------------------------------- execution
    def init(self, rng):
        """Per-layer params tuple; tied layers share (first occurrence owns,
        later ones get None and resolve through the tie at apply time)."""
        params = []
        tied_owner = {}
        keys = jax.random.split(rng, max(len(self.layers), 1))
        for i, (layer, spec) in enumerate(zip(self.layers, self.specs)):
            key = spec.key if isinstance(spec, TiedLayerSpec) else None
            if key is not None and key in tied_owner:
                params.append(None)
                continue
            p = layer.init(keys[i]) if hasattr(layer, "init") else None
            params.append(p)
            if key is not None:
                tied_owner[key] = i
        self._tied_owner = tied_owner
        return tuple(params)

    def _resolve_params(self, params, i):
        spec = self.specs[i]
        if isinstance(spec, TiedLayerSpec) and params[i] is None:
            return params[self._tied_owner[spec.key]]
        return params[i]

    def apply(self, params, x, first_layer=0, last_layer=None):
        """Sequential forward over [first_layer, last_layer) — full model by
        default; a single stage's slice when given its bounds."""
        last_layer = len(self.layers) if last_layer is None else last_layer
        for i in range(first_layer, last_layer):
            layer, spec = self.layers[i], self.specs[i]
            p = self._resolve_params(params, i)
            if isinstance(spec, TiedLayerSpec) and spec.forward_fn is not None:
                x = spec.forward_fn(p, x)
            elif hasattr(layer, "apply"):
                x = layer.apply(p, x)
            else:
                x = layer(x)
        return x

    def apply_stage(self, params, x, stage_id):
        return self.apply(params, x, self.parts[stage_id],
                          self.parts[stage_id + 1])

    def loss(self, params, batch):
        out = self.apply(params, batch["input"])
        if self.loss_fn is None:
            raise ValueError("PipelineModule built without loss_fn")
        return self.loss_fn(out, batch)

    # -------------------------------------------------------------- analysis
    def schedule_streams(self, schedule, micro_batches):
        """Per-stage instruction streams for this module's stage count
        — the analysis surface the reference exposes through its
        schedule objects. ``schedule``: 'gpipe' (the forward
        InferenceSchedule view), '1f1b', or 'zb'."""
        from .schedule import (InferenceSchedule, TrainSchedule,
                               ZeroBubbleSchedule)
        cls = {"gpipe": InferenceSchedule, "1f1b": TrainSchedule,
               "zb": ZeroBubbleSchedule}.get(schedule)
        if cls is None:
            raise ValueError(f"unknown schedule {schedule!r}")
        return [cls(micro_batches, self.num_stages, s)
                for s in range(self.num_stages)]

    def bubble_report(self, micro_batches):
        """Analytic executor bubble fraction per schedule at this stage
        count (runtime/pipe/schedule.py lock-step wall model) — the
        M-selection aid the pipe_microbatch autotune op measures for
        real."""
        from .schedule import executor_bubble_fraction
        return {s: round(executor_bubble_fraction(
                    s, micro_batches, self.num_stages), 4)
                for s in ("gpipe", "1f1b", "zb")}

    def stage_param_counts(self):
        counts = []
        for s in range(self.num_stages):
            counts.append(sum(_param_count(self.layers[i])
                              for i in self.stage_layer_indices(s)))
        return counts

    def __repr__(self):
        return (f"PipelineModule(layers={len(self.layers)}, "
                f"stages={self.num_stages}, parts={self.parts})")
