"""Sign-compressed (1-bit) allreduce with error feedback.

Counterpart of reference ``runtime/comm/nccl.py:16 NcclBackend``
(``compressed_allreduce:51``) / ``runtime/comm/mpi.py`` — the transport
under 1-bit Adam/LAMB and 0/1 Adam. Algorithm (NeurIPS'21 1-bit Adam):

  worker:  c = x + worker_error          (error feedback)
           scale_w = mean(|c_chunk|) per destination chunk
           send sign(c_chunk) packed 1 bit/element + fp32 scale
           worker_error = c - decompress(compressed c)
  server:  (per owned chunk) avg = mean_w(scale_w * sign_w)
           sc = avg + server_error
           scale_s = mean(|sc|); server_error = sc - scale_s * sign(sc)
           broadcast sign(sc) packed + scale_s
  all:     result chunk = scale_s * sign(sc)

On TPU the worker->server exchange is an ``all_to_all`` over the DP mesh
axis and the server->all a ``all_gather`` — the same two hops the
reference issues as gather/scatter, riding ICI. Bit-packing uses uint8
lanes (8 signs/byte): 32x less wire traffic than fp32 + one fp32 scale
per chunk. Runs INSIDE shard_map.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


def pack_signs(x):
    """(N,) float -> (ceil(N/8),) uint8 of sign bits (1 = non-negative).
    N must be a multiple of 8 (pad upstream)."""
    assert x.shape[0] % 8 == 0, f"pack_signs needs N % 8 == 0, got {x.shape}"
    bits = (x >= 0).astype(jnp.uint8).reshape(-1, 8)
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(bits * weights, axis=1, dtype=jnp.uint8)


def unpack_signs(packed, n):
    """(ceil(n/8),) uint8 -> (n,) float32 in {-1, +1}."""
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    bits = (packed[:, None] & weights[None, :]) > 0
    return jnp.where(bits.reshape(-1)[:n], 1.0, -1.0).astype(jnp.float32)


class CompressionState(NamedTuple):
    """Per-device error-feedback residuals. ``worker_error`` covers this
    device's full local tensor; ``server_error`` covers the chunk this
    device owns (N // W elements)."""
    worker_error: jax.Array
    server_error: jax.Array

    @classmethod
    def zeros(cls, n, world):
        assert n % world == 0
        return cls(worker_error=jnp.zeros((n,), jnp.float32),
                   server_error=jnp.zeros((n // world,), jnp.float32))


def compressed_allreduce(x, state: CompressionState, axis_name):
    """1-bit averaged allreduce of (N,) ``x`` (N divisible by 8*W).

    Returns (result (N,), new_state). Deterministic, in-trace; both error
    buffers carry the compression residual into the next call (without
    them sign-SGD style compression does not converge)."""
    W = lax.axis_size(axis_name)
    N = x.shape[0]
    assert N % (8 * W) == 0, (
        f"compressed_allreduce needs N divisible by 8*world={8 * W}, "
        f"got {N}")
    M = N // W

    # ---- worker compression (error feedback)
    c = x.astype(jnp.float32) + state.worker_error
    chunks = c.reshape(W, M)
    scale_w = jnp.mean(jnp.abs(chunks), axis=1)              # (W,)
    signs_w = jnp.sign(chunks)
    signs_w = jnp.where(signs_w == 0, 1.0, signs_w)
    worker_error = c - (scale_w[:, None] * signs_w).reshape(N)
    packed = jax.vmap(pack_signs)(chunks)                    # (W, M//8)

    # ---- worker -> server: each device receives every worker's version
    # of its own chunk
    packed_x = lax.all_to_all(packed, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)     # (W, M//8)
    scale_x = lax.all_to_all(scale_w.reshape(W, 1), axis_name,
                             split_axis=0, concat_axis=0,
                             tiled=True).reshape(W)
    signs = jax.vmap(lambda p: unpack_signs(p, M))(packed_x)  # (W, M)
    avg = jnp.mean(scale_x[:, None] * signs, axis=0)          # (M,)

    # ---- server compression (its own error feedback)
    sc = avg + state.server_error
    scale_s = jnp.mean(jnp.abs(sc))
    sign_s = jnp.sign(sc)
    sign_s = jnp.where(sign_s == 0, 1.0, sign_s)
    server_error = sc - scale_s * sign_s

    # ---- server -> all
    packed_s = pack_signs(sign_s)
    gathered = lax.all_gather(packed_s, axis_name, axis=0)    # (W, M//8)
    scales = lax.all_gather(scale_s, axis_name, axis=0)       # (W,)
    out = (scales[:, None]
           * jax.vmap(lambda p: unpack_signs(p, M))(gathered)).reshape(N)
    return out, CompressionState(worker_error=worker_error,
                                 server_error=server_error)
