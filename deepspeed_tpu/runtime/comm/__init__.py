from .compressed import (pack_signs, unpack_signs, compressed_allreduce,
                         CompressionState)
