"""LR schedules, mirroring the reference's ``runtime/lr_schedules.py``
(LRRangeTest:267, OneCycle:370, WarmupLR:634, WarmupDecayLR:723,
WarmupCosineLR:774).

Each schedule is a pure ``step -> lr`` callable (works both host-side and
traced; the engine passes the value into the jitted update so schedule
changes never recompile). ``step()``/``get_lr()``/``state_dict`` mirror the
reference's scheduler object surface for drop-in familiarity.
"""

import math

import jax.numpy as jnp


class _Schedule:
    def __init__(self):
        self.last_batch_iteration = -1

    def __call__(self, step):
        raise NotImplementedError

    # torch-scheduler-like surface (reference lr_schedules.py get_lr/step)
    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [float(self(max(0, self.last_batch_iteration)))]

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(_Schedule):
    """Linear warmup then constant (reference lr_schedules.py:634)."""

    def __init__(self, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type="log", **_):
        super().__init__()
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type

    def _warmup(self, step):
        frac = jnp.clip(step / self.warmup_num_steps, 0.0, 1.0)
        if self.warmup_type == "log":
            # reference uses log warmup by default
            frac = jnp.log1p(frac * (math.e - 1.0))
        return self.min_lr + (self.max_lr - self.min_lr) * frac

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        return jnp.where(step < self.warmup_num_steps, self._warmup(step),
                         self.max_lr)


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 at total_num_steps (reference :723)."""

    def __init__(self, total_num_steps, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000,
                 warmup_type="log", **_):
        super().__init__(warmup_min_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type)
        self.total_num_steps = total_num_steps

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        decay = jnp.clip(
            (self.total_num_steps - step)
            / max(1.0, self.total_num_steps - self.warmup_num_steps),
            0.0, 1.0)
        return jnp.where(step < self.warmup_num_steps, self._warmup(step),
                         self.max_lr * decay)


class WarmupCosineLR(_Schedule):
    """Warmup then cosine decay (reference :774)."""

    def __init__(self, total_num_steps, warmup_min_ratio=0.0,
                 warmup_num_steps=1000, cos_min_ratio=0.0001, lr=0.001, **_):
        super().__init__()
        self.total_num_steps = total_num_steps
        self.warmup_min_ratio = warmup_min_ratio
        self.warmup_num_steps = max(1, warmup_num_steps)
        self.cos_min_ratio = cos_min_ratio
        self.lr = lr

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        warm_ratio = self.warmup_min_ratio + (
            1 - self.warmup_min_ratio) * (step / self.warmup_num_steps)
        frac = jnp.clip(
            (step - self.warmup_num_steps)
            / max(1, self.total_num_steps - self.warmup_num_steps), 0.0, 1.0)
        cos_ratio = self.cos_min_ratio + (1 - self.cos_min_ratio) * 0.5 * (
            1 + jnp.cos(math.pi * frac))
        ratio = jnp.where(step < self.warmup_num_steps, warm_ratio, cos_ratio)
        return self.lr * ratio


class OneCycle(_Schedule):
    """Triangular cycle then decay (reference :370; LR part only — the
    momentum cycle is a per-optimizer concern the engine wires separately)."""

    def __init__(self, cycle_min_lr, cycle_max_lr, cycle_first_step_size=2000,
                 cycle_second_step_size=None, decay_step_size=0,
                 decay_lr_rate=0.0, **_):
        super().__init__()
        self.min_lr = cycle_min_lr
        self.max_lr = cycle_max_lr
        self.first = cycle_first_step_size
        self.second = (cycle_second_step_size
                       if cycle_second_step_size is not None
                       else cycle_first_step_size)
        self.decay_step_size = decay_step_size
        self.decay_lr_rate = decay_lr_rate

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        total = self.first + self.second
        up = self.min_lr + (self.max_lr - self.min_lr) * (step / self.first)
        down = self.max_lr - (self.max_lr - self.min_lr) * (
            (step - self.first) / self.second)
        in_cycle = jnp.where(step < self.first, up, down)
        if self.decay_step_size > 0:
            decay_steps = (step - total) / self.decay_step_size
            decayed = self.min_lr / (1.0 + self.decay_lr_rate * decay_steps)
            return jnp.where(step < total, jnp.maximum(in_cycle, 0.0), decayed)
        return jnp.clip(in_cycle, self.min_lr, self.max_lr)


class LRRangeTest(_Schedule):
    """LR range sweep for tuning (reference :267)."""

    def __init__(self, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000,
                 lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, **_):
        super().__init__()
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        interval = (jnp.floor(step / self.step_size) if self.staircase
                    else step / self.step_size)
        return self.min_lr * (1 + interval * self.step_rate)


SCHEDULES = {
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
    "WarmupCosineLR": WarmupCosineLR,
    "OneCycle": OneCycle,
    "LRRangeTest": LRRangeTest,
}


def build_scheduler(name, params):
    if name not in SCHEDULES:
        raise ValueError(
            f"unknown scheduler '{name}'; available: {sorted(SCHEDULES)}")
    return SCHEDULES[name](**params)
