"""Hessian eigenvalue estimation by power iteration.

Counterpart of reference ``runtime/eigenvalue.py`` (power iteration over
per-layer curvature, feeding the MoQ quantization schedule). The reference
does manual autograd double-backward; with jax the Hessian-vector product
is ``jvp(grad(loss))`` — exact, jitted, no retained graphs.
"""

import numpy as np
import jax
import jax.numpy as jnp


class Eigenvalue:
    def __init__(self, max_iter=100, tol=1e-2, stability=1e-6,
                 gas_boundary_resolution=1, verbose=False):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.verbose = verbose

    def _normalize(self, tree):
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(x))
                            for x in jax.tree.leaves(tree)))
        norm = jnp.maximum(norm, self.stability)
        return jax.tree.map(lambda x: x / norm, tree), norm

    def compute_eigenvalue(self, loss_fn, params, batch, rng=None):
        """Dominant Hessian eigenvalue of ``loss_fn(params, batch)`` wrt
        params. Returns (eigenvalue, final eigenvector tree)."""
        grad_fn = jax.grad(lambda p: loss_fn(p, batch))

        @jax.jit
        def hvp(p, vec):
            return jax.jvp(grad_fn, (p,), (vec,))[1]

        key = rng if rng is not None else jax.random.key(0)
        leaves, treedef = jax.tree.flatten(params)
        ks = jax.random.split(key, len(leaves))
        v = jax.tree.unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.float32)
            for k, l in zip(ks, leaves)])
        v, _ = self._normalize(v)

        eig = 0.0
        for i in range(self.max_iter):
            hv = hvp(params, v)
            v, norm = self._normalize(hv)
            new_eig = float(norm)
            if self.verbose:
                print(f"power iter {i}: eig={new_eig:.5f}")
            if eig and abs(new_eig - eig) / max(abs(eig), 1e-12) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        return eig, v

    def compute_layer_eigenvalues(self, loss_fn, params, batch, layer_keys,
                                  rng=None):
        """Per-layer-group eigenvalues (reference computes per 'block'):
        power iteration restricted to each subtree named in
        ``layer_keys`` (top-level keys of params)."""
        out = {}
        for key in layer_keys:
            def restricted(sub, batch):
                merged = dict(params)
                merged[key] = sub
                return loss_fn(merged, batch)

            eig, _ = Eigenvalue(self.max_iter, self.tol, self.stability) \
                .compute_eigenvalue(restricted, params[key], batch, rng)
            out[key] = eig
        return out
