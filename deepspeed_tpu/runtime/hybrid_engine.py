"""Hybrid engine — RLHF train/generate flip.

Counterpart of reference ``runtime/hybrid_engine.py:32
DeepSpeedHybridEngine``: one engine that trains (ZeRO-partitioned) and
generates (inference-optimized) with the SAME weights — the RLHF actor
loop. The reference re-shards ZeRO-3 params and swaps in inference
kernels per phase; here the flip is a jitted cast/reshard of the current
bf16 params into the inference engine's shardings (device-to-device,
XLA-planned) and the generation path is the v1 KV-cache engine.
"""

import jax

from ..inference.engine import InferenceEngine
from ..utils.logging import log_dist
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """DeepSpeedEngine + ``generate()`` (reference exposes the HF
    generate surface the same way)."""

    def __init__(self, *args, inference_config=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._inference_config = dict(inference_config or {})
        self._inf_engine = None
        self._inf_params_step = -1
        log_dist("hybrid engine: training + in-loop generation", ranks=[0])

    def _refresh_inference_engine(self):
        if self._inf_engine is None:
            cfg = {"dtype": str(self.param_dtype.__name__
                                if hasattr(self.param_dtype, "__name__")
                                else self.param_dtype)}
            cfg.update(self._inference_config)
            self._inf_engine = InferenceEngine(
                self.model, config=cfg, params=self.state["params"],
                topology=self.topology)
            self._inf_params_step = self.global_step
        elif self._inf_params_step != self.global_step:
            # flip: reshard current training params into the inference
            # shardings (no-op placement change when they already match).
            # The caster is jitted ONCE — a fresh lambda per refresh would
            # recompile every RLHF iteration.
            if not hasattr(self, "_cast_jit"):
                self._cast_jit = jax.jit(
                    lambda p: jax.tree.map(
                        lambda x: x.astype(self._inf_engine.dtype), p),
                    out_shardings=self._inf_engine.param_shardings)
            with jax.set_mesh(self.mesh):
                self._inf_engine.params = self._cast_jit(
                    self.state["params"])
            self._inf_params_step = self.global_step

    def generate(self, input_ids, **kwargs):
        """Generate with the CURRENT training weights (the RLHF
        experience-collection phase)."""
        self._refresh_inference_engine()
        return self._inf_engine.generate(input_ids, **kwargs)

    def eval(self):
        return self

    def train(self):
        return self
