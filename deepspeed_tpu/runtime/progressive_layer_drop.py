"""Progressive layer dropping.

Counterpart of reference ``runtime/progressive_layer_drop.py``
(ProgressiveLayerDrop): theta(t) = (1 - theta_min) * gamma-decay + theta_min
keep probability, consumed by models that drop transformer blocks
stochastically during training (the PLD paper's schedule, verbatim math).
"""

import numpy as np


class ProgressiveLayerDrop:
    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_state(self):
        return {"progressive_layer_drop": True,
                "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        def _prob(x, g, t):
            return (1.0 - t) * np.exp(-g * x) + t

        self.current_theta = float(_prob(global_step, self.gamma,
                                         self.theta))
        return self.current_theta
