"""Static and dynamic loss scaling.

Counterpart of reference ``runtime/fp16/loss_scaler.py:91 DynamicLossScaler``.
The scale lives *inside* the jitted train state as an fp32 scalar so the
skip-on-overflow / grow-after-window logic is pure lax arithmetic — no host
round-trip per step (the reference syncs an overflow flag to host every
step; under XLA that would stall the pipeline).
"""

import jax
import jax.numpy as jnp


class LossScaler:
    """Static scale (reference LossScalerBase). scale=1 for bf16/fp32."""

    def __init__(self, scale=1.0):
        self.static_scale = float(scale)
        self.dynamic = False

    def init_state(self):
        return {"scale": jnp.asarray(self.static_scale, jnp.float32),
                "good_steps": jnp.zeros((), jnp.int32)}

    def update(self, state, overflow):
        return state

    def should_skip(self, state, overflow):
        # with static scaling the reference still skips on overflow
        return overflow


class DynamicLossScaler(LossScaler):
    """reference runtime/fp16/loss_scaler.py:91 semantics:
    * on overflow: scale /= 2 (bounded below), reset window, skip step
      (hysteresis consumes before halving)
    * after `scale_window` consecutive good steps: scale *= 2
    """

    def __init__(self, init_scale=2**16, scale_factor=2.0, scale_window=1000,
                 min_scale=1.0, delayed_shift=1, consecutive_hysteresis=False):
        super().__init__(init_scale)
        self.dynamic = True
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis

    def init_state(self):
        return {"scale": jnp.asarray(self.static_scale, jnp.float32),
                "good_steps": jnp.zeros((), jnp.int32),
                "hysteresis": jnp.asarray(self.delayed_shift, jnp.int32)}

    def update(self, state, overflow):
        scale, good, hyst = (state["scale"], state["good_steps"],
                             state["hysteresis"])
        hyst_after = jnp.where(overflow, jnp.maximum(hyst - 1, 0), hyst)
        drop = overflow & (hyst_after == 0)
        new_scale = jnp.where(
            drop, jnp.maximum(scale / self.scale_factor, self.min_scale),
            scale)
        new_good = jnp.where(overflow, 0, good + 1)
        grow = new_good >= self.scale_window
        new_scale = jnp.where(grow, new_scale * self.scale_factor, new_scale)
        new_good = jnp.where(grow, 0, new_good)
        if self.consecutive_hysteresis:
            # refill on good steps: only N *consecutive* overflows drop scale
            new_hyst = jnp.where(overflow, hyst_after,
                                 jnp.asarray(self.delayed_shift, jnp.int32))
        else:
            # hysteresis is a budget: any N overflows (consecutive or not)
            # drop the scale (reference default semantics)
            new_hyst = hyst_after
        return {"scale": new_scale, "good_steps": new_good,
                "hysteresis": new_hyst}

    def should_skip(self, state, overflow):
        return overflow


def grads_finite(grads):
    """Global overflow check (reference CheckOverflow, runtime/utils.py):
    True iff every grad element is finite."""
    leaves = jax.tree.leaves(grads)
    finite = jnp.asarray(True)
    for g in leaves:
        finite = finite & jnp.all(jnp.isfinite(g))
    return finite


def create_loss_scaler(fp16_cfg=None, dtype=None):
    import jax.numpy as jnp_
    if fp16_cfg is None or not fp16_cfg.enabled or dtype != jnp_.float16:
        return LossScaler(1.0)
    if fp16_cfg.loss_scale and fp16_cfg.loss_scale > 0:
        return LossScaler(fp16_cfg.loss_scale)
    return DynamicLossScaler(init_scale=2.0 ** fp16_cfg.initial_scale_power,
                             scale_window=fp16_cfg.loss_scale_window,
                             min_scale=fp16_cfg.min_loss_scale,
                             delayed_shift=fp16_cfg.hysteresis)
