"""0/1 Adam.

Counterpart of reference ``runtime/fp16/onebit/zoadam.py:359 ZeroOneAdam``
(0/1 Adam paper): no dense warmup — made stable by (a) VARIANCE FREEZING:
v updates on an exponentially-thinning schedule until ``var_freeze_step``
then stays fixed, and (b) LOCAL STEPS: after the freeze, devices apply
purely local updates for k steps (k doubling up to
``2**local_step_clipper``), accumulating them in a comm buffer; at each
sync step the local updates are ROLLED BACK and replaced by the
compressed-allreduced average (reference zoadam.py:243-257: p -= buffer;
allreduce(buffer); exp_avg = buffer/lrs; p += buffer/denom), so replicas
re-converge exactly at every sync point.
"""

import jax.numpy as jnp
from jax import lax

from ...comm.compressed import CompressionState, compressed_allreduce


class ZeroOneAdam:
    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, var_freeze_step=50,
                 var_update_scaler=4, local_step_scaler=100,
                 local_step_clipper=8):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.var_freeze_step = var_freeze_step
        self.var_update_scaler = var_update_scaler
        self.local_step_scaler = local_step_scaler
        self.local_step_clipper = local_step_clipper

    def init(self, n, world, with_comp=True):
        state = {"m": jnp.zeros((n,), jnp.float32),
                 "v": jnp.zeros((n,), jnp.float32),
                 # accumulated local updates since last sync (= sum of
                 # -lr * update), and the lr mass behind them
                 "buf": jnp.zeros((n,), jnp.float32),
                 "lrs": jnp.zeros((), jnp.float32),
                 "step": jnp.zeros((), jnp.int32)}
        if with_comp:
            state["comp"] = CompressionState.zeros(n, world)
        return state

    def _sync_due(self, step):
        """After var freeze, sync every k steps; k doubles every
        ``local_step_scaler`` steps, clipped to 2**local_step_clipper."""
        past = jnp.maximum(step - self.var_freeze_step, 0)
        k = jnp.minimum(past // self.local_step_scaler,
                        self.local_step_clipper)
        interval = 2 ** k
        return (past % interval) == 0

    def _var_update_due(self, step):
        """Variance updates thin out exponentially before the freeze
        (reference var_update_scaler policy)."""
        k = step // self.var_update_scaler
        interval = jnp.minimum(2 ** jnp.minimum(k, 16), 1 << 16)
        return (step % interval) == 0

    def update(self, local_grad, state, params, lr=None, axis_name="data"):
        b1, b2 = self.betas
        lr = self.lr if lr is None else lr
        step = state["step"] + 1
        W = lax.axis_size(axis_name)
        frozen = step > self.var_freeze_step

        def pre_freeze(_):
            """Exact sync every step; v on its thinning schedule."""
            g = lax.psum(local_grad, axis_name) / W
            m = b1 * state["m"] + (1 - b1) * g
            v_new = b2 * state["v"] + (1 - b2) * jnp.square(g)
            v = jnp.where(self._var_update_due(step), v_new, state["v"])
            denom = jnp.sqrt(v) + self.eps
            upd = m / denom
            if self.weight_decay:
                upd = upd + self.weight_decay * params
            p = params - lr * upd
            return (p, m, v, state["buf"], state["lrs"], state["comp"])

        def post_freeze(_):
            """Local step + rollback/sync on schedule."""
            m_local = b1 * state["m"] + (1 - b1) * local_grad
            denom = jnp.sqrt(state["v"]) + self.eps
            upd = m_local / denom
            if self.weight_decay:
                upd = upd + self.weight_decay * params
            delta = -lr * upd
            p = params + delta
            buf = state["buf"] + delta
            lrs = state["lrs"] + lr

            def sync(args):
                p, buf, lrs, m = args
                p = p - buf                      # roll local updates back
                # NOTE: buf includes the decoupled weight-decay term, so
                # the rebuilt momentum absorbs wd*p*denom — this matches
                # the reference exactly (zoadam.py:242 accumulates the
                # full update incl. wd; :257 rebuilds exp_avg from it).
                mom_sum, comp = compressed_allreduce(
                    buf * denom, state["comp"], axis_name)
                m_new = -mom_sum / jnp.maximum(lrs, 1e-12)
                p = p + mom_sum / denom          # averaged replacement
                return (p, m_new, jnp.zeros_like(buf),
                        jnp.zeros_like(lrs), comp)

            def local(args):
                p, buf, lrs, m = args
                return (p, m, buf, lrs, state["comp"])

            p, m, buf, lrs, comp = lax.cond(
                self._sync_due(step), sync, local, (p, buf, lrs, m_local))
            return (p, m, state["v"], buf, lrs, comp)

        p, m, v, buf, lrs, comp = lax.cond(frozen, post_freeze, pre_freeze,
                                           None)
        return p, {"m": m, "v": v, "buf": buf, "lrs": lrs, "comp": comp,
                   "step": step}
