from .adam import OneBitAdam
from .zoadam import ZeroOneAdam
from .lamb import OneBitLamb
from .trainer import OneBitTrainer
