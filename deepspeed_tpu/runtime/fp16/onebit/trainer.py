"""OneBitTrainer — a complete data-parallel training program around a
1-bit optimizer.

The engine's GSPMD train step lets XLA insert gradient collectives from
shardings; 1-bit optimizers must REPLACE that collective with their
compressed exchange, so this trainer builds the step explicitly:
``shard_map`` over the DP axis, per-device local gradients, compressed
momentum sync inside the optimizer (the reference reaches the same
structure through torch DDP-bypass + custom allreduce in
runtime/fp16/onebit/*).

ALL optimizer state and the parameters are stored per-device — global
arrays stacked (W, ...) and sharded over the DP axis — because 1-bit
training state is genuinely per-device: error-feedback residuals always
differ, and 0/1 Adam's local steps let params/momentum drift between sync
points (re-converging exactly at each sync). Per-device memory equals the
replicated layout's, and nothing pretends divergent buffers are equal.

Pure-DP by design (tp/pipe/seq = 1), like the reference's 1-bit
optimizers (incompatible with MoE/PP there too).
"""

import numpy as np
import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ....utils import groups
from ...comm.compressed import CompressionState


def _flatten_info(params):
    leaves, treedef = jax.tree.flatten(params)
    shapes = [l.shape for l in leaves]
    sizes = [int(np.prod(s)) for s in shapes]
    offsets = np.cumsum([0] + sizes)
    return treedef, shapes, sizes, offsets


class OneBitTrainer:
    """``t = OneBitTrainer(loss_fn, params, optimizer); t.step(batch)``.

    loss_fn(params, batch) -> scalar (pure jnp). params: pytree. The
    optimizer is OneBitAdam / ZeroOneAdam / OneBitLamb. Batches shard over
    the 'data' axis.
    """

    def __init__(self, loss_fn, params, optimizer, topology=None,
                 axis_name="data"):
        self.topology = topology or groups.get_topology()
        self.mesh = self.topology.mesh
        if (self.topology.get_model_parallel_world_size() != 1
                or self.topology.get_pipe_parallel_world_size() != 1
                or self.topology.get_sequence_parallel_world_size() != 1
                or self.topology.get_expert_parallel_world_size() != 1
                or self.mesh.shape["data_outer"] != 1):
            raise ValueError("1-bit optimizers support pure (flat) data "
                             "parallelism only (like the reference)")
        self.axis = axis_name
        self.world = self.mesh.shape[self.axis]
        self.loss_fn = loss_fn
        self.optimizer = optimizer

        treedef, shapes, sizes, offsets = _flatten_info(params)
        self._treedef, self._shapes = treedef, shapes
        self._sizes, self._offsets = sizes, offsets
        n = int(offsets[-1])
        self._n_pad = -(-n // (8 * self.world)) * (8 * self.world)
        self._n = n

        # give LAMB its per-tensor segments in the flat vector
        segs = getattr(optimizer, "segments", None)
        if segs == []:
            optimizer.segments = [(int(offsets[i]), int(offsets[i + 1]))
                                  for i in range(len(sizes))]
        elif segs and int(segs[-1][1]) != n:
            raise ValueError(
                f"optimizer.segments end at {segs[-1][1]} but this model "
                f"flattens to {n} params — optimizer instances cannot be "
                "reused across models")

        W = self.world
        shard = NamedSharding(self.mesh, P(self.axis))
        with jax.set_mesh(self.mesh):
            flat = self._flatten(params)
            # every device starts from the same values; rows may diverge
            # later (by design, see module docstring)
            self.flat_params = jax.device_put(
                jnp.broadcast_to(flat, (W,) + flat.shape), shard)
            state = optimizer.init(self._n_pad, W, with_comp=False)
            state["comp"] = CompressionState(
                worker_error=jnp.zeros((self._n_pad,), jnp.float32),
                server_error=jnp.zeros((self._n_pad // W,), jnp.float32))
            self.opt_state = jax.tree.map(
                lambda x: jax.device_put(
                    jnp.broadcast_to(x, (W,) + x.shape), shard), state)
        self._step_jit = None

    # ---------------------------------------------------------- flat utils
    def _flatten(self, params):
        leaves = jax.tree.leaves(params)
        flat = jnp.concatenate(
            [l.reshape(-1).astype(jnp.float32) for l in leaves])
        return jnp.pad(flat, (0, self._n_pad - self._n))

    def _unflatten(self, flat):
        leaves = [flat[int(self._offsets[i]):int(self._offsets[i + 1])]
                  .reshape(self._shapes[i]) for i in range(len(self._sizes))]
        return jax.tree.unflatten(self._treedef, leaves)

    @property
    def params(self):
        """Device 0's view (identical across devices at sync points)."""
        return self._unflatten(self.flat_params[0])

    def _build(self):
        opt = self.optimizer
        axis = self.axis
        loss_fn = self.loss_fn
        unflatten = self._unflatten

        def body(flat_params, opt_state, batch, lr):
            # all state arrives stacked (1, ...): this device's row
            fp = flat_params[0]
            state = jax.tree.map(lambda x: x[0], opt_state)

            loss, local_grad = jax.value_and_grad(
                lambda f: loss_fn(unflatten(f), batch))(fp)
            new_fp, new_state = opt.update(local_grad, state, fp, lr=lr,
                                           axis_name=axis)
            loss = jax.lax.pmean(loss, axis)
            return (new_fp[None], jax.tree.map(lambda x: x[None], new_state),
                    loss)

        state_specs = jax.tree.map(lambda _: P(self.axis), self.opt_state)

        def step(flat_params, opt_state, batch, lr):
            return shard_map(
                body, mesh=self.mesh,
                in_specs=(P(self.axis), state_specs,
                          jax.tree.map(lambda _: P(self.axis), batch),
                          P()),
                out_specs=(P(self.axis), state_specs, P()),
                check_vma=False)(flat_params, opt_state, batch, lr)

        return jax.jit(step, donate_argnums=(0, 1))

    def step(self, batch, lr=None):
        """One optimizer step on a global batch (leading dim divisible by
        the DP world size). Returns the scalar loss."""
        if self._step_jit is None:
            self._step_jit = self._build()
        lr = jnp.asarray(self.optimizer.lr if lr is None else lr,
                         jnp.float32)
        batch = jax.tree.map(jnp.asarray, batch)
        with jax.set_mesh(self.mesh):
            self.flat_params, self.opt_state, loss = self._step_jit(
                self.flat_params, self.opt_state, batch, lr)
        return float(loss)
