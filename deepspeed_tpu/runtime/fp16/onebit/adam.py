"""1-bit Adam.

Counterpart of reference ``runtime/fp16/onebit/adam.py:306 OnebitAdam``:
dense Adam with exact allreduce for ``freeze_step`` warmup steps, then the
variance term freezes and only the momentum is synchronized — through the
sign-compressed, error-feedback allreduce (runtime/comm/compressed.py).
Functional flat-vector design: the optimizer owns one (N,) state per
buffer and runs INSIDE shard_map, consuming each device's LOCAL gradient
(the compression replaces the gradient allreduce — handing it an already
averaged gradient would defeat the point).
"""

import jax
import jax.numpy as jnp
from jax import lax

from ...comm.compressed import CompressionState, compressed_allreduce


class OneBitAdam:
    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=100):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step

    def init(self, n, world, with_comp=True):
        """n: flat param count (divisible by 8*world — pad upstream).
        ``with_comp=False`` lets the caller build the (possibly stacked)
        error-feedback buffers itself without a throwaway allocation."""
        state = {"m": jnp.zeros((n,), jnp.float32),
                 "v": jnp.zeros((n,), jnp.float32),
                 "step": jnp.zeros((), jnp.int32)}
        if with_comp:
            state["comp"] = CompressionState.zeros(n, world)
        return state

    def update(self, local_grad, state, params, lr=None, axis_name="data"):
        """local_grad/params: (N,) fp32; returns (new_params, new_state).
        Call inside shard_map over ``axis_name``."""
        b1, b2 = self.betas
        lr = self.lr if lr is None else lr
        step = state["step"] + 1
        W = lax.axis_size(axis_name)

        def warmup(_):
            g = lax.psum(local_grad, axis_name) / W
            m = b1 * state["m"] + (1 - b1) * g
            v = b2 * state["v"] + (1 - b2) * jnp.square(g)
            return m, v, state["comp"]

        def compressed(_):
            m_local = b1 * state["m"] + (1 - b1) * local_grad
            m, comp = compressed_allreduce(m_local, state["comp"],
                                           axis_name)
            return m, state["v"], comp       # v frozen

        m, v, comp = lax.cond(step <= self.freeze_step, warmup, compressed,
                              None)
        update = m / (jnp.sqrt(v) + self.eps)
        if self.weight_decay:
            update = update + self.weight_decay * params
        new_params = params - lr * update
        return new_params, {"m": m, "v": v, "comp": comp, "step": step}
