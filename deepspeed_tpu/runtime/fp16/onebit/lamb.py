"""1-bit LAMB.

Counterpart of reference ``runtime/fp16/onebit/lamb.py:443 OnebitLamb``:
dense LAMB during warmup while recording each layer's trust ratio
(||p|| / ||update||); after ``freeze_step`` the variance AND the per-layer
trust ratios freeze, momentum syncs through the compressed allreduce, and
the frozen ratios scale each layer's update (the reference additionally
smooths the frozen ratio with ``coeff_beta``; we freeze the running
average the same way).

Flat-vector design with static per-layer ``segments`` (start, end) —
layer boundaries in the flattened param vector.
"""

import jax.numpy as jnp
from jax import lax

from ...comm.compressed import CompressionState, compressed_allreduce


class OneBitLamb:
    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, freeze_step=100, coeff_beta=0.9,
                 max_coeff=10.0, min_coeff=0.01, segments=None):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.coeff_beta = coeff_beta
        self.max_coeff = max_coeff
        self.min_coeff = min_coeff
        self.segments = segments or []

    def init(self, n, world, with_comp=True):
        if not self.segments:
            self.segments = [(0, n)]
        state = {"m": jnp.zeros((n,), jnp.float32),
                 "v": jnp.zeros((n,), jnp.float32),
                 # running trust-ratio average per segment (frozen after
                 # warmup)
                 "coeff": jnp.ones((len(self.segments),), jnp.float32),
                 "step": jnp.zeros((), jnp.int32)}
        if with_comp:
            state["comp"] = CompressionState.zeros(n, world)
        return state

    def _segment_scale(self, params, update, coeff_running, warm):
        """Per-segment trust ratio; during warmup also advances the
        running average. Returns (scaled update, new running coeffs)."""
        out = update
        new_coeffs = []
        for i, (s, e) in enumerate(self.segments):
            p_norm = jnp.linalg.norm(params[s:e])
            u_norm = jnp.linalg.norm(update[s:e])
            # either norm zero -> neutral 1.0 (reference OnebitLamb):
            # zero-init tensors must not get pinned at min_coeff
            ratio = jnp.where((p_norm > 0) & (u_norm > 0),
                              jnp.clip(p_norm / (u_norm + self.eps),
                                       self.min_coeff, self.max_coeff),
                              1.0)
            running = (self.coeff_beta * coeff_running[i]
                       + (1 - self.coeff_beta) * ratio)
            coeff = jnp.where(warm, ratio, coeff_running[i])
            new_coeff = jnp.where(warm, running, coeff_running[i])
            out = out.at[s:e].multiply(coeff)
            new_coeffs.append(new_coeff)
        return out, jnp.stack(new_coeffs)

    def update(self, local_grad, state, params, lr=None, axis_name="data"):
        b1, b2 = self.betas
        lr = self.lr if lr is None else lr
        step = state["step"] + 1
        W = lax.axis_size(axis_name)
        warm = step <= self.freeze_step

        def warmup(_):
            g = lax.psum(local_grad, axis_name) / W
            m = b1 * state["m"] + (1 - b1) * g
            v = b2 * state["v"] + (1 - b2) * jnp.square(g)
            return m, v, state["comp"]

        def compressed(_):
            m_local = b1 * state["m"] + (1 - b1) * local_grad
            m, comp = compressed_allreduce(m_local, state["comp"],
                                           axis_name)
            return m, state["v"], comp

        m, v, comp = lax.cond(warm, warmup, compressed, None)
        update = m / (jnp.sqrt(v) + self.eps)
        if self.weight_decay:
            update = update + self.weight_decay * params
        update, coeff = self._segment_scale(params, update, state["coeff"],
                                            warm)
        new_params = params - lr * update
        return new_params, {"m": m, "v": v, "coeff": coeff, "comp": comp,
                            "step": step}
