"""Config key names, mirroring the reference's ``runtime/constants.py``."""

# Batch size triad (reference runtime/constants.py TRAIN_BATCH_SIZE et al.)
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

OPTIMIZER = "optimizer"
OPTIMIZER_TYPE = "type"
OPTIMIZER_PARAMS = "params"
SCHEDULER = "scheduler"
SCHEDULER_TYPE = "type"
SCHEDULER_PARAMS = "params"

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"

# Precision
FP16 = "fp16"
BF16 = "bf16"
ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_HYSTERESIS = "hysteresis"
FP16_MIN_LOSS_SCALE = "min_loss_scale"

# ZeRO
ZERO_OPTIMIZATION = "zero_optimization"

# Parallel topology (TPU-native extension; the reference takes mpu/ep_size
# through function args rather than config)
TENSOR_PARALLEL = "tensor_parallel"
PIPELINE = "pipeline"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
COMMS_LOGGER = "comms_logger"
MONITOR_CSV = "csv_monitor"
MONITOR_TENSORBOARD = "tensorboard"

ACTIVATION_CHECKPOINTING = "activation_checkpointing"

CHECKPOINT_ENGINE = "checkpoint_engine"  # {"type": "sync"|"async"|"native"|"none", ...}
DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
SEQ_PARALLEL_COMM_DTYPE = "seq_parallel_communication_data_type"
