"""Activation checkpointing (rematerialization).

Counterpart of reference ``runtime/activation_checkpointing/
checkpointing.py`` (Megatron-compatible: ``configure():1010-area``,
``checkpoint():1010``, ``CheckpointFunction:485``, ``CudaRNGStatesTracker
:123``). TPU redesign:

  * ``checkpoint(fn, *args)`` = ``jax.checkpoint`` (remat): recompute in
    backward instead of saving — the same FLOPs-for-HBM trade the
    reference implements by hand with torch.autograd.Function.
  * Policies replace the reference's save/offload knob set:
    ``partition_activations`` (reference shards saved activations across
    TP ranks) maps to saving with a sharding constraint — under GSPMD the
    saved residuals are already sharded by the activation specs, so the
    knob is accepted and folded into the policy choice. ``cpu_checkpointing``
    maps to ``save_and_offload_only_these_names``-style host offload
    policies where the jax version provides them.
  * ``CudaRNGStatesTracker`` maps to an explicit named-PRNG tracker: jax
    RNG is functional, so "states" are just named keys; ``fork(name)``
    yields a fresh deterministic key per use — reproducible dropout across
    TP ranks without device RNG-state mutation.
"""

import contextlib

import jax

from ...utils.logging import logger

_config = None


# --------------------------------------------------------------- rng tracker
class RNGStatesTracker:
    """Named deterministic PRNG streams (reference CudaRNGStatesTracker).

    ``add(name, seed)`` registers a stream; ``with tracker.fork(name) as
    key:`` yields a fresh key (folded with a per-fork counter) — the
    functional analogue of swapping device RNG state in and out."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._seeds = {}
        self._counters = {}

    def get_states(self):
        return dict(self._seeds), dict(self._counters)

    def set_states(self, states):
        self._seeds, self._counters = dict(states[0]), dict(states[1])

    def add(self, name, seed):
        if name in self._seeds:
            raise ValueError(f"rng state {name} already present")
        if seed in self._seeds.values():
            raise ValueError(f"seed {seed} already used")
        self._seeds[name] = seed
        self._counters[name] = 0

    @contextlib.contextmanager
    def fork(self, name="model-parallel-rng"):
        if name not in self._seeds:
            raise KeyError(f"rng state {name} not added")
        key = jax.random.fold_in(jax.random.key(self._seeds[name]),
                                 self._counters[name])
        self._counters[name] += 1
        yield key


_RNG_TRACKER = RNGStatesTracker()
_MODEL_PARALLEL_RNG = "model-parallel-rng"


def get_cuda_rng_tracker():
    """Name kept for drop-in compatibility with Megatron-style callers."""
    return _RNG_TRACKER


def model_parallel_rng_seed(seed, tp_rank=0):
    """reference model_parallel_cuda_manual_seed:200 — distinct dropout
    streams per TP rank, one shared default stream."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("default", seed)
    _RNG_TRACKER.add(_MODEL_PARALLEL_RNG, seed + 2718 + tp_rank)


# ------------------------------------------------------------------ policies
_POLICY_ALIASES = {
    "nothing_saveable": "nothing_saveable",
    "everything_saveable": "everything_saveable",
    "dots_saveable": "dots_saveable",
    "checkpoint_dots": "dots_saveable",
    "dots_with_no_batch_dims_saveable": "dots_with_no_batch_dims_saveable",
    "checkpoint_dots_with_no_batch_dims":
        "dots_with_no_batch_dims_saveable",
}


def offload_policy():
    """The host-offload remat policy (save matmul residuals into host
    memory instead of recomputing — the reference's cpu_checkpointing
    copy of saved activations), or None when this jax/backend cannot
    express it (no offload policy maker, or a single memory space —
    the CPU test mesh). Target memory kind resolved per-platform by
    swap_tensor/host_stage.py ('pinned_host' on TPU)."""
    from ..swap_tensor import host_stage
    maker = getattr(jax.checkpoint_policies,
                    "offload_dot_with_no_batch_dims", None)
    kind = host_stage.host_memory_kind()
    if maker is None or kind is None:
        return None
    return maker("device", kind)


def resolve_policy(name_or_none, cpu_checkpointing=False):
    """Map a policy name (+ cpu_checkpointing) to a jax.checkpoint policy."""
    if cpu_checkpointing:
        # offload matmul residuals to host memory instead of
        # recomputing (the reference copies saved activations to CPU)
        policy = offload_policy()
        if policy is not None:
            return policy
        logger.warning("cpu_checkpointing requested but this jax/backend "
                       "cannot offload (no policy maker or single memory "
                       "space); using the remat policy instead")
    if not name_or_none:
        return None
    canonical = _POLICY_ALIASES.get(name_or_none, name_or_none)
    policy = getattr(jax.checkpoint_policies, canonical, None)
    if policy is None:
        raise ValueError(f"unknown remat policy {name_or_none!r}")
    return policy


# ----------------------------------------------------------------- configure
def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None,
              policy=None):
    """reference checkpointing.configure — store the knob set used by
    subsequent ``checkpoint()`` calls."""
    global _config
    cfg = {"partition_activations": False, "contiguous_checkpointing": False,
           "num_checkpoints": 0, "checkpoint_in_cpu": False,
           "synchronize": False, "profile": False,
           "policy": "nothing_saveable"}
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
        if ac is not None:
            cfg.update(partition_activations=ac.partition_activations,
                       contiguous_checkpointing=(
                           ac.contiguous_memory_optimization),
                       num_checkpoints=ac.number_checkpoints,
                       checkpoint_in_cpu=ac.cpu_checkpointing,
                       synchronize=ac.synchronize_checkpoint_boundary,
                       profile=ac.profile, policy=ac.policy)
    for k, v in [("partition_activations", partition_activations),
                 ("contiguous_checkpointing", contiguous_checkpointing),
                 ("num_checkpoints", num_checkpoints),
                 ("checkpoint_in_cpu", checkpoint_in_cpu),
                 ("synchronize", synchronize), ("profile", profile),
                 ("policy", policy)]:
        if v is not None:
            cfg[k] = v
    _config = cfg


def is_configured():
    return _config is not None


def reset():
    global _config
    _config = None


# ---------------------------------------------------------------- checkpoint
def checkpoint(function, *args, policy=None):
    """Remat ``function(*args)`` (reference checkpoint():1010 — there it
    runs fn under no_grad and replays in backward; jax.checkpoint is that
    transform natively). Usable unconfigured (defaults to full remat)."""
    cfg = _config or {}
    pol = resolve_policy(
        policy if policy is not None else cfg.get("policy"),
        cpu_checkpointing=cfg.get("checkpoint_in_cpu", False))
    return jax.checkpoint(function, policy=pol)(*args)


def checkpoint_wrapper(function, policy=None):
    """Return the remat-wrapped callable (for use inside scans)."""
    cfg = _config or {}
    pol = resolve_policy(
        policy if policy is not None else cfg.get("policy"),
        cpu_checkpointing=cfg.get("checkpoint_in_cpu", False))
    return jax.checkpoint(function, policy=pol)
