from . import checkpointing
