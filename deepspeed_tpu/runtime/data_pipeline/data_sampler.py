"""Deterministic distributed data sampler with curriculum support.

Counterpart of reference ``runtime/data_pipeline/data_sampling/
data_sampler.py:349 DeepSpeedDataSampler``: per-step index batches that
are (a) identical across processes given the same seed/step — each DP
rank slices its own shard, (b) resumable from a consumed-samples count,
and (c) curriculum-aware (a CurriculumScheduler can shrink the effective
batch/sequence as configured). Host-side numpy; the engine turns indices
into device batches.
"""

import numpy as np


class DeepSpeedDataSampler:
    def __init__(self, total_samples, micro_batch_size, data_parallel_rank,
                 data_parallel_size, gradient_accumulation_steps=1,
                 shuffle=True, seed=1234, drop_last=True,
                 curriculum_scheduler=None):
        self.total_samples = int(total_samples)
        self.micro_batch_size = int(micro_batch_size)
        self.dp_rank = int(data_parallel_rank)
        self.dp_size = int(data_parallel_size)
        self.gas = int(gradient_accumulation_steps)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.curriculum_scheduler = curriculum_scheduler
        self.consumed_samples = 0
        if self.dp_rank >= self.dp_size:
            raise ValueError("data_parallel_rank >= data_parallel_size")
        self.global_batch_size = (self.micro_batch_size * self.dp_size
                                  * self.gas)
        if self.total_samples < self.global_batch_size:
            raise ValueError(
                f"total_samples={self.total_samples} < global batch "
                f"{self.global_batch_size}; no full batch can be formed")

    def __len__(self):
        n = self.total_samples // self.global_batch_size
        if not self.drop_last and self.total_samples % self.global_batch_size:
            n += 1
        return n

    @property
    def curriculum_difficulty(self):
        """Difficulty for the most recently drawn global batch (1-based
        step = batches consumed so far)."""
        if self.curriculum_scheduler is None:
            return None
        step = self.consumed_samples // self.global_batch_size
        return self.curriculum_scheduler.update_difficulty(step)

    def _epoch_order(self, epoch):
        order = np.arange(self.total_samples)
        if self.shuffle:
            np.random.RandomState(self.seed + epoch).shuffle(order)
        return order

    def set_consumed_samples(self, n):
        """Resume mid-epoch (reference: consumed_samples from ckpt)."""
        self.consumed_samples = int(n)

    def state_dict(self):
        return {"consumed_samples": self.consumed_samples,
                "curriculum": (self.curriculum_scheduler.state_dict()
                               if self.curriculum_scheduler else None)}

    def load_state_dict(self, sd):
        self.consumed_samples = sd["consumed_samples"]
        if sd.get("curriculum") and self.curriculum_scheduler:
            self.curriculum_scheduler.load_state_dict(sd["curriculum"])

    def __iter__(self):
        """Yields this rank's (micro_batch_size * gas,) index array per
        global step, epoch after epoch."""
        while True:
            epoch = self.consumed_samples // self.total_samples
            offset = self.consumed_samples % self.total_samples
            order = self._epoch_order(epoch)
            remaining = self.total_samples - offset
            if remaining < self.global_batch_size:
                if self.drop_last or remaining == 0:
                    # skip the tail into the next epoch
                    self.consumed_samples += remaining
                    continue
            start = offset
            end = min(start + self.global_batch_size, self.total_samples)
            batch = order[start:end]
            if len(batch) < self.global_batch_size:
                # not drop_last: the final partial global batch must still
                # be SPMD-shaped (every DP rank needs an equal slice), so
                # it is padded by TILING — the tail samples appear twice
                # in that step. Metric consumers that must not
                # double-count the tail should set drop_last=True (the
                # reference sampler instead yields a short batch, which an
                # SPMD engine cannot shard).
                batch = np.resize(batch, self.global_batch_size)
            self.consumed_samples += (end - start)
            per_rank = self.global_batch_size // self.dp_size
            mine = batch[self.dp_rank * per_rank:(self.dp_rank + 1)
                         * per_rank]
            yield mine
