"""Curriculum learning difficulty scheduler.

Counterpart of reference ``runtime/data_pipeline/curriculum_scheduler.py``
(CurriculumScheduler): maps global step -> difficulty (e.g. sequence
length) under fixed_linear / fixed_root / fixed_discrete / custom
schedules. Pure python — identical semantics are correct on any backend.
"""

import math

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:
    """config keys (reference constants):
      curriculum_type: metric name (informational, e.g. 'seqlen')
      min_difficulty, max_difficulty: ints
      schedule_type: fixed_linear | fixed_root | fixed_discrete | custom
      schedule_config:
        fixed_linear/fixed_root: {total_curriculum_step, difficulty_step,
                                  root_degree (root only)}
        fixed_discrete: {difficulty: [..], max_step: [..]} (len-1 bounds)
        custom: set via set_custom_get_difficulty(fn(step)->difficulty)
    """

    def __init__(self, config):
        self.state = {}
        for key in ("min_difficulty", "max_difficulty", "schedule_type"):
            if key not in config:
                raise ValueError(f"curriculum config missing '{key}'")
        self.min_difficulty = int(config["min_difficulty"])
        self.max_difficulty = int(config["max_difficulty"])
        self.schedule_type = config["schedule_type"]
        sched = config.get("schedule_config", {})
        self.custom_get_difficulty = None

        if self.schedule_type in (FIXED_LINEAR, FIXED_ROOT):
            for key in ("total_curriculum_step", "difficulty_step"):
                if key not in sched:
                    raise ValueError(
                        f"{self.schedule_type} schedule missing '{key}'")
            self.total_step = int(sched["total_curriculum_step"])
            self.difficulty_step = int(sched["difficulty_step"])
            self.root_degree = int(sched.get("root_degree", 1))
            if self.schedule_type == FIXED_ROOT and "root_degree" not in sched:
                raise ValueError("fixed_root schedule missing 'root_degree'")
        elif self.schedule_type == FIXED_DISCRETE:
            self.difficulties = list(sched["difficulty"])
            self.max_steps = list(sched["max_step"])
            if len(self.max_steps) != len(self.difficulties) - 1:
                raise ValueError("fixed_discrete: len(max_step) must be "
                                 "len(difficulty) - 1")
        elif self.schedule_type == CUSTOM:
            pass
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type}")

        self.current_difficulty = self.min_difficulty

    def set_custom_get_difficulty(self, fn):
        self.custom_get_difficulty = fn

    def get_difficulty(self, global_step):
        s = self.schedule_type
        if s == CUSTOM:
            if self.custom_get_difficulty is None:
                raise RuntimeError("custom schedule: call "
                                   "set_custom_get_difficulty first")
            return self.custom_get_difficulty(global_step)
        if s == FIXED_DISCRETE:
            for d, m in zip(self.difficulties, self.max_steps):
                if global_step <= m:
                    return d
            return self.difficulties[-1]
        # linear / root ramp from min to max over total_step, quantized to
        # difficulty_step multiples (reference semantics)
        frac = min(1.0, max(global_step, 1) / self.total_step)
        if s == FIXED_ROOT:
            frac = frac ** (1.0 / self.root_degree)
        diff = self.min_difficulty + frac * (self.max_difficulty
                                             - self.min_difficulty)
        diff = int(diff // self.difficulty_step) * self.difficulty_step
        return max(self.min_difficulty, min(self.max_difficulty, diff))

    def update_difficulty(self, global_step):
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    def get_current_difficulty(self):
        return self.current_difficulty

    def state_dict(self):
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd):
        self.current_difficulty = sd["current_difficulty"]
