"""Offline data analyzer — corpus difficulty metrics for curriculum
learning.

Counterpart of reference ``runtime/data_pipeline/data_sampling/
data_analyzer.py:444 DataAnalyzer``: walk a dataset once (optionally in
parallel workers), score every sample under one or more difficulty
metrics, and write per-metric index files (sample->score map + the
sample ids sorted by score, bucketed by distinct score) that curriculum
sampling consumes at train time — the CurriculumScheduler's difficulty d
maps to "samples with metric <= d" through these indexes.

Built-in metrics (the reference ships seqlen + vocabularyrarity):
  * ``seqlen``            — non-pad token count.
  * ``vocab_rarity``      — mean negative log unigram probability of the
    sample's tokens under the corpus unigram distribution (two passes:
    count, then score).
  * any callable ``fn(sample) -> number``.

Outputs under ``output_dir``:
  {metric}_sample_to_metric.npy   (float32, one score per sample)
  {metric}_index_to_sample.npy    (int64 sample ids sorted by score)
  {metric}_metric_values.npy      (sorted scores, aligned with the above)
  summary.json                    (per-metric min/max/mean + file map)
"""

import json
import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np


def seqlen_metric(pad_token_id=0):
    def fn(sample):
        arr = np.asarray(sample)
        return int((arr != pad_token_id).sum())
    fn.requires_counts = False
    return fn


class DataAnalyzer:
    """``DataAnalyzer(dataset).run(output_dir)``.

    dataset: indexable of token arrays (e.g. MMapIndexedDataset or a list
    of np arrays). metrics: {name: callable} — defaults to seqlen +
    vocab_rarity. num_workers: thread fan-out for the scoring pass (the
    reference shards across processes; scoring is numpy-light so threads
    suffice here)."""

    def __init__(self, dataset, metrics=None, pad_token_id=0,
                 num_workers=4):
        self.dataset = dataset
        self.pad_token_id = pad_token_id
        self.num_workers = max(1, num_workers)
        self.metrics = metrics or {
            "seqlen": seqlen_metric(pad_token_id),
            "vocab_rarity": "vocab_rarity",     # built-in two-pass
        }

    # ------------------------------------------------------------ passes
    def _unigram_counts(self):
        counts = {}
        for i in range(len(self.dataset)):
            arr = np.asarray(self.dataset[i]).reshape(-1)
            arr = arr[arr != self.pad_token_id]
            ids, c = np.unique(arr, return_counts=True)
            for t, n in zip(ids.tolist(), c.tolist()):
                counts[t] = counts.get(t, 0) + n
        total = max(1, sum(counts.values()))
        return {t: n / total for t, n in counts.items()}

    def _score(self, metric, probs):
        n = len(self.dataset)

        def one(i):
            arr = np.asarray(self.dataset[i]).reshape(-1)
            if metric == "vocab_rarity":
                toks = arr[arr != self.pad_token_id]
                if len(toks) == 0:
                    return 0.0
                return float(np.mean(
                    [-np.log(probs.get(int(t), 1e-12)) for t in toks]))
            return float(metric(arr))

        if self.num_workers == 1:
            return np.asarray([one(i) for i in range(n)], np.float32)
        with ThreadPoolExecutor(self.num_workers) as pool:
            return np.asarray(list(pool.map(one, range(n))), np.float32)

    # --------------------------------------------------------------- run
    def run(self, output_dir):
        os.makedirs(output_dir, exist_ok=True)
        needs_probs = any(m == "vocab_rarity"
                          for m in self.metrics.values())
        probs = self._unigram_counts() if needs_probs else None
        summary = {"num_samples": len(self.dataset), "metrics": {}}
        for name, metric in self.metrics.items():
            scores = self._score(metric, probs)
            order = np.argsort(scores, kind="stable").astype(np.int64)
            base = os.path.join(output_dir, name)
            np.save(base + "_sample_to_metric.npy", scores)
            np.save(base + "_index_to_sample.npy", order)
            np.save(base + "_metric_values.npy", scores[order])
            summary["metrics"][name] = {
                "min": float(scores.min()), "max": float(scores.max()),
                "mean": float(scores.mean()),
                "files": {k: f"{name}_{k}.npy" for k in
                          ("sample_to_metric", "index_to_sample",
                           "metric_values")},
            }
        with open(os.path.join(output_dir, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        return summary


class CurriculumIndex:
    """Train-time consumer: admissible sample ids for a difficulty value
    (reference data_sampler's curriculum path reads the analyzer's index
    the same way)."""

    def __init__(self, output_dir, metric):
        base = os.path.join(output_dir, metric)
        self.sorted_ids = np.load(base + "_index_to_sample.npy")
        self.sorted_values = np.load(base + "_metric_values.npy")

    def samples_up_to(self, difficulty):
        """ids of every sample with metric <= difficulty (sorted easier
        first)."""
        hi = int(np.searchsorted(self.sorted_values, difficulty, "right"))
        return self.sorted_ids[:hi]
