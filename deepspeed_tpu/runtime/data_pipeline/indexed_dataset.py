"""Memory-mapped indexed dataset.

Counterpart of reference ``runtime/data_pipeline/data_sampling/
indexed_dataset.py:619`` (the Megatron MMapIndexedDataset family): token
sequences stored as one flat binary file plus an index of (offset, length)
per document, read zero-copy through numpy memmap — the layout that lets
a multi-TB corpus feed the sampler without loading anything up front.

Format (little-endian):
  data.bin  — concatenated token arrays (one dtype for the whole file)
  data.idx  — json header line (magic, dtype, count) then
              int64 lengths[count]; offsets are derived (cumsum) on load
"""

import json
import os

import numpy as np

_MAGIC = "DSTPU_IDX_V1"


class IndexedDatasetBuilder:
    """Stream documents in, then ``finalize()``:

        b = IndexedDatasetBuilder("corpus", dtype=np.uint16)
        for doc in docs: b.add_item(tokens)
        b.finalize()
    """

    def __init__(self, path_prefix, dtype=np.int32):
        self.prefix = path_prefix
        self.dtype = np.dtype(dtype)
        os.makedirs(os.path.dirname(os.path.abspath(path_prefix)),
                    exist_ok=True)
        self._data = open(path_prefix + ".bin", "wb")
        self._lengths = []

    def add_item(self, tokens):
        arr = np.asarray(tokens, dtype=self.dtype)
        self._data.write(arr.tobytes())
        self._lengths.append(len(arr))

    def finalize(self):
        self._data.close()
        lengths = np.asarray(self._lengths, np.int64)
        with open(self.prefix + ".idx", "wb") as f:
            header = {"magic": _MAGIC, "dtype": self.dtype.name,
                      "count": len(lengths)}
            f.write((json.dumps(header) + "\n").encode())
            f.write(lengths.tobytes())
        return len(lengths)


class MMapIndexedDataset:
    """Zero-copy document access: ``ds[i] -> np array`` (a view into the
    mapped file; copy before mutating)."""

    def __init__(self, path_prefix):
        with open(path_prefix + ".idx", "rb") as f:
            # bounded read + tolerant decode: a foreign/corrupt binary
            # index must fail the MAGIC check, not raise UnicodeDecodeError
            # or slurp a multi-GB file looking for a newline
            first = f.readline(4096).decode("utf-8", errors="replace")
            try:
                header = json.loads(first)
            except json.JSONDecodeError:
                header = {}
            if header.get("magic") != _MAGIC:
                raise ValueError(f"{path_prefix}.idx: bad magic")
            count = header["count"]
            self.dtype = np.dtype(header["dtype"])
            raw = np.frombuffer(f.read(), dtype=np.int64)
        if len(raw) < count:
            raise ValueError(
                f"{path_prefix}.idx truncated: header says {count} "
                f"documents, index holds {len(raw)}")
        self.lengths = raw[:count]
        self.offsets = np.concatenate(
            [[0], np.cumsum(self.lengths)[:-1]]).astype(np.int64) \
            if count else np.zeros((0,), np.int64)
        self._mmap = np.memmap(path_prefix + ".bin", dtype=self.dtype,
                               mode="r")
        total = int(self.lengths.sum())
        if len(self._mmap) != total:
            raise ValueError(
                f"{path_prefix}.bin holds {len(self._mmap)} tokens but the "
                f"index expects {total} (truncated or mismatched corpus)")

    def __len__(self):
        return len(self.lengths)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if not -len(self) <= i < len(self):
            raise IndexError(f"document {i} out of range [0, {len(self)})")
        off, ln = int(self.offsets[i]), int(self.lengths[i])
        return self._mmap[off:off + ln]

    @property
    def sizes(self):
        return self.lengths

    def total_tokens(self):
        return int(self.lengths.sum())


class FixedSeqDataset:
    """View an indexed dataset as fixed-length training samples (packed
    contiguously across document boundaries, the GPT pretraining layout):
    item i = tokens[i*seq_len : (i+1)*seq_len] as an int32 'input_ids'
    dict, directly consumable by DeepSpeedDataLoader / the engine."""

    def __init__(self, indexed: MMapIndexedDataset, seq_len):
        self.ds = indexed
        self.seq_len = seq_len
        self._n = indexed.total_tokens() // seq_len

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        if not -self._n <= i < self._n:
            # real IndexError so the sequence-iteration protocol (and any
            # bounds bug) terminates instead of yielding empty arrays
            raise IndexError(f"sample {i} out of range [0, {self._n})")
        i %= self._n
        s = self.seq_len
        flat = self.ds._mmap[i * s:(i + 1) * s]
        return {"input_ids": np.asarray(flat, np.int32)}
