"""Random layerwise token dropping (random-LTD).

Counterpart of reference ``runtime/data_pipeline/data_routing/`` +
``csrc/random_ltd/`` (token_sort.cu / gather_scatter.cu): during training,
middle layers see a random subset of tokens; the kept-token count ramps up
on a schedule. The CUDA kernels (sort, gather/scatter) are one
``jax.random.permutation`` + ``jnp.take_along_axis`` here — XLA fuses the
gather/scatter fine on TPU.
"""

import jax
import jax.numpy as jnp


def token_drop(x, keep, rng):
    """Keep ``keep`` random tokens of ``x``: (B, T, D) -> (B, keep, D),
    plus the sorted kept indices (B, keep) for ``token_restore``. Indices
    are sorted so relative order (and position information) is preserved —
    the reference sorts for the same reason (token_sort.cu)."""
    B, T = x.shape[0], x.shape[1]
    idx = jax.vmap(lambda k: jax.random.permutation(k, T)[:keep])(
        jax.random.split(rng, B))
    idx = jnp.sort(idx, axis=-1)
    gathered = jnp.take_along_axis(x, idx[..., None], axis=1)
    return gathered, idx


def token_restore(x_small, idx, x_full):
    """Scatter processed kept tokens back over the full sequence: dropped
    positions keep their (skip-connection) values from ``x_full``."""
    return x_full.at[
        jnp.arange(x_full.shape[0])[:, None], idx].set(x_small)


class RandomLTDScheduler:
    """Kept-token schedule (reference data_routing/scheduler.py):
    linear ramp from min_value to max_value (= full seq len) over
    schedule_config total steps, quantized by seq_step."""

    def __init__(self, config):
        sched = config.get("random_ltd_schedule", {})
        self.min_value = int(config["random_ltd_min_value"])
        self.max_value = int(config["random_ltd_max_value"])
        self.seq_step = int(sched.get("seq_step", 16))
        self.total_steps = int(sched.get("require_steps", 1))
        self.current_seq = self.min_value

    def get_current_seq(self):
        return self.current_seq

    def update_seq(self, global_step):
        frac = min(1.0, max(global_step, 0) / self.total_steps)
        seq = self.min_value + frac * (self.max_value - self.min_value)
        seq = int(seq // self.seq_step) * self.seq_step
        self.current_seq = max(self.min_value, min(self.max_value, seq))
        return self.current_seq

    def state_dict(self):
        return {"current_seq": self.current_seq}

    def load_state_dict(self, sd):
        self.current_seq = sd["current_seq"]
