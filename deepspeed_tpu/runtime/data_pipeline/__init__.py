from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import DeepSpeedDataSampler
from .random_ltd import RandomLTDScheduler, token_drop
from .indexed_dataset import (IndexedDatasetBuilder,
                              MMapIndexedDataset, FixedSeqDataset)
