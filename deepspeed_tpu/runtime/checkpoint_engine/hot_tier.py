"""Peer-replicated in-memory checkpoint hot tier.

The reference fork's whole async-checkpointing layer (DataStates/VELOC,
``csrc/veloc/``) exists so the COMMON failure — one host dies — restores
from a fast in-memory tier instead of re-reading persistent storage.
This module is that tier for the TPU runtime:

  * after every save's D2H extraction, each node pushes its local shard
    (the exact ``extract_local_chunks`` payload, CRC manifest included)
    to K ring-neighbor peers;
  * a node's store lives in host RAM (tmpfs — ``/dev/shm`` by default),
    so it survives worker-process restarts but dies with the host,
    exactly like the pinned host cache it models;
  * on resume, ``manager.load_best_tiered`` tries the hot tier first:
    a generation is loadable when the node's own shards plus surviving
    peer replicas cover every writer — the common single-host loss
    restores with ZERO persistent-storage reads, degrading to the
    durable tier when replicas are insufficient or CRC-invalid.

Store layout (one subtree per node under a shared root):

    {root}/{node}/{tag}/own/shard-{p}.npz        this node's own save
    {root}/{node}/{tag}/from-{origin}/shard-{p}.npz   received replicas

Two transports own the peer push:

  * ``fs`` — the pusher writes straight into the peer's subtree. On a
    single machine (the chaos suites' multi-worker simulation, where
    each "host" is a process and the shared tmpfs root stands in for
    peer RAM) this IS the transfer; the elastic agent models the real
    host-RAM loss by purging a dead host's subtree (purge_node).
  * ``dcn`` — bytes ride the accelerator fabric via
    ``comm.ring_exchange_bytes`` (a collective-permute over a
    one-device-per-process mesh; DCN on a multi-slice pod) and the
    RECEIVER writes them into its own subtree. Collective: every
    process must push at the same save boundary — which the engine's
    multi-process save barrier already guarantees.

Fault points (utils/fault_injection): ``replica_push`` fires once per
peer replica write, ``replica_fetch`` once per replica read during
assembly (own-written shards read clean) — arming them makes pushes
fail (advisory: the durable tier still lands) or poisons the replicas
so loads degrade deterministically.
"""

import concurrent.futures as futures
import glob
import io
import os
import re
import shutil
import tempfile

from ...utils import fault_injection
from ...utils.logging import logger
from . import serialization as ser


def _safe(name):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(name))


def default_root():
    """Hot-store root: DSTPU_HOT_TIER_ROOT env, else tmpfs (/dev/shm —
    host RAM, the point of the tier), else the system tempdir (still
    node-local; documented degradation for hosts without tmpfs)."""
    env = os.environ.get("DSTPU_HOT_TIER_ROOT")
    if env:
        return env
    base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    return os.path.join(base, f"dstpu_hot_{os.getuid()}")


def _step_key(name):
    m = re.search(r"(\d+)$", name)
    return int(m.group(1)) if m else -1


def purge_node(root, node):
    """Drop ``node``'s whole store — the host-RAM-loss boundary. The
    elastic agent calls this for every failed host before relaunch, so
    replicas a dead host held can never serve a restore they would not
    survive in production."""
    shutil.rmtree(os.path.join(root, _safe(node)), ignore_errors=True)


class HotTierStore:
    """One node's view of the peer-replicated hot tier.

    Args:
      root: shared hot-store root (see :func:`default_root`).
      node: this node's id (string). Default: ``DSTPU_HOT_NODE`` env,
        else the jax process index. The elastic launcher exports the
        host name here so agent-side purge and store subtrees agree.
      peers: ORDERED ring membership (list of node ids). Default:
        ``DSTPU_HOT_PEERS`` (comma-separated), else one id per jax
        process. Ring neighbors are computed from this order.
      replicas: K — how many ring neighbors receive each shard.
      keep_last: hot-tier retention (tags per node; the tier is a cache,
        not an archive).
      counters: optional engine counters dict (hot_pushes /
        hot_push_errors bumped here).
    """

    def __init__(self, root=None, node=None, peers=None, replicas=1,
                 keep_last=2, counters=None):
        import jax
        self.root = root or default_root()
        if node is None:
            node = os.environ.get("DSTPU_HOT_NODE") or \
                str(jax.process_index())
        self.node = _safe(node)
        if peers is None:
            env = os.environ.get("DSTPU_HOT_PEERS")
            if env:
                peers = [p for p in env.split(",") if p]
            else:
                peers = [str(i) for i in range(jax.process_count())]
        self.peers = [_safe(p) for p in peers]
        if self.node not in self.peers:
            # a node outside the ring still stores locally (replicas
            # have nowhere meaningful to go); keep membership explicit
            self.peers = self.peers + [self.node]
        self.replicas = max(0, int(replicas))
        self.keep_last = int(keep_last)
        self.counters = counters if counters is not None else {}
        self._pool = futures.ThreadPoolExecutor(max_workers=1)
        self._inflight = []

    # ------------------------------------------------------------ topology
    def ring_neighbors(self):
        """The K distinct peers after this node in ring order."""
        if len(self.peers) <= 1:
            return []
        i = self.peers.index(self.node)
        out = []
        for k in range(1, self.replicas + 1):
            p = self.peers[(i + k) % len(self.peers)]
            if p != self.node and p not in out:
                out.append(p)
        return out

    def _node_dir(self, node):
        return os.path.join(self.root, node)

    def _tag_dir(self, node, tag, sub=None):
        d = os.path.join(self._node_dir(node), tag)
        return os.path.join(d, sub) if sub else d

    # ---------------------------------------------------------------- push
    def _serialize(self, chunks, extra):
        bio = io.BytesIO()
        ser.save_file(bio, chunks, extra_meta=extra)
        return bio.getbuffer()

    def _write_bytes(self, target_dir, fname, payload):
        os.makedirs(target_dir, exist_ok=True)
        tmp = os.path.join(target_dir, f".{fname}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(target_dir, fname))

    def push(self, tag, chunks, extra, shard_name=None):
        """Store this node's shard for ``tag`` locally and replicate it
        to the ring neighbors. Replica failures are ADVISORY (counted,
        logged, never raised): the durable tier is still landing through
        the normal save path, and a hot tier that could fail a save
        would be worse than no hot tier. Returns the number of replicas
        that landed."""
        import jax
        if shard_name is None:
            shard_name = f"shard-{jax.process_index()}.npz"
        payload = self._serialize(chunks, extra)
        ok = 0
        try:
            self._write_bytes(self._tag_dir(self.node, tag, "own"),
                              shard_name, payload)
        except OSError as e:
            self.counters["hot_push_errors"] = \
                self.counters.get("hot_push_errors", 0) + 1
            logger.warning(f"hot tier: local store of {tag} failed: {e}")
            return 0
        for peer in self.ring_neighbors():
            try:
                fault_injection.fire("replica_push")
                self._write_bytes(
                    self._tag_dir(peer, tag, f"from-{self.node}"),
                    shard_name, payload)
                ok += 1
            except fault_injection.SimulatedKill:
                raise
            except Exception as e:  # noqa: BLE001 - advisory path
                self.counters["hot_push_errors"] = \
                    self.counters.get("hot_push_errors", 0) + 1
                logger.warning(
                    f"hot tier: replica push {tag} -> {peer} failed: {e}")
        self.counters["hot_pushes"] = \
            self.counters.get("hot_pushes", 0) + 1
        self.gc()
        return ok

    def push_async(self, tag, chunks, extra, shard_name=None):
        """Replicate off the training critical path (the PR-2 async-pool
        discipline). Degrades to an in-caller push when the pool is
        gone (interpreter teardown)."""
        # prune finished futures so a long run that saves every N steps
        # (and never loads) cannot grow the list unboundedly
        self._inflight = [f for f in self._inflight if not f.done()]
        try:
            fut = self._pool.submit(self.push, tag, chunks, extra,
                                    shard_name)
        except RuntimeError:
            self.push(tag, chunks, extra, shard_name)
            return None
        self._inflight.append(fut)
        return fut

    def push_collective(self, tag, chunks, extra, shard_name=None):
        """DCN transport: exchange the serialized shard with each ring
        neighbor over the comm layer (collective — every process in the
        jax world must call this at the same save boundary), then store
        what THIS node received from its upstream peers. Falls back to
        the fs transport outside a multi-process world. Same ADVISORY
        contract as :meth:`push`: a hot-tier failure (injected
        replica_push fault, tmpfs ENOSPC, a wedged exchange) is counted
        and logged, never raised — it must not cost the durable save
        the engine is about to make."""
        import jax
        if jax.process_count() <= 1 or self.replicas < 1:
            return self.push(tag, chunks, extra, shard_name)
        try:
            return self._push_collective_impl(tag, chunks, extra,
                                              shard_name)
        except fault_injection.SimulatedKill:
            raise
        except Exception as e:  # noqa: BLE001 - advisory path
            self.counters["hot_push_errors"] = \
                self.counters.get("hot_push_errors", 0) + 1
            logger.warning(
                f"hot tier: collective replica push of {tag} failed "
                f"({e}); the durable tier is unaffected")
            return 0

    def _push_collective_impl(self, tag, chunks, extra, shard_name):
        import jax
        from ...comm.comm import ring_exchange_bytes
        if shard_name is None:
            shard_name = f"shard-{jax.process_index()}.npz"
        payload = bytes(self._serialize(chunks, extra))
        self._write_bytes(self._tag_dir(self.node, tag, "own"),
                          shard_name, payload)
        ok = 0
        for k in range(1, self.replicas + 1):
            fault_injection.fire("replica_push")
            recv, origin = ring_exchange_bytes(payload, shift=k)
            if recv is None:
                continue
            origin_node = self.peers[origin % len(self.peers)]
            self._write_bytes(
                self._tag_dir(self.node, tag, f"from-{origin_node}"),
                f"shard-{origin}.npz", recv)
            ok += 1
        self.counters["hot_pushes"] = \
            self.counters.get("hot_pushes", 0) + 1
        self.gc()
        return ok

    def wait(self):
        """Drain in-flight async pushes (advisory failures already
        swallowed inside push)."""
        pending, self._inflight = self._inflight, []
        for fut in pending:
            exc = fut.exception()
            if exc is not None and not isinstance(exc, Exception):
                raise exc          # SimulatedKill et al.
        return True

    def shutdown(self):
        self.wait()
        self._pool.shutdown(wait=True)

    # ------------------------------------------------------------ assembly
    def tags(self):
        """Generations visible anywhere in the hot tier, newest first
        (step-suffix order). A survivor may hold a tag only as replicas
        pushed by a now-dead writer, so the scan covers every node
        subtree, not just our own."""
        seen = set()
        try:
            nodes = os.listdir(self.root)
        except OSError:
            return []
        for node in nodes:
            nd = self._node_dir(node)
            try:
                for t in os.listdir(nd):
                    if os.path.isdir(os.path.join(nd, t)):
                        seen.add(t)
            except OSError:
                continue
        return sorted(seen, key=_step_key, reverse=True)

    def _shard_sources(self, tag):
        """-> {shard_name: (path, is_replica)} best source per shard
        file: this node's own save first (a clean local read), then
        replicas (our own received ones, then other nodes' subtrees) —
        every replica read is a ``replica_fetch`` fire."""
        sources = {}
        own = glob.glob(os.path.join(self._tag_dir(self.node, tag, "own"),
                                     "shard-*.npz"))
        for p in own:
            sources.setdefault(os.path.basename(p), (p, False))
        try:
            others = sorted(n for n in os.listdir(self.root)
                            if n != self.node)
        except OSError:
            others = []
        for node in [self.node] + others:
            pattern = os.path.join(self._tag_dir(node, tag), "*",
                                   "shard-*.npz")
            for p in sorted(glob.glob(pattern)):
                sources.setdefault(os.path.basename(p), (p, True))
        return sources

    def load(self, tag):
        """Assemble ``tag`` from the best available sources. Raises
        CheckpointCorruptionError/ValueError/OSError (the manager's
        FALLBACK_ERRORS) when shards are missing, CRC-invalid, or a
        replica fetch fails — callers degrade to the durable tier."""
        sources = self._shard_sources(tag)
        if not sources:
            raise FileNotFoundError(
                f"hot tier: no shards for tag {tag!r} under {self.root}")
        files = []
        for name in sorted(sources):
            path, is_replica = sources[name]
            if is_replica:
                fault_injection.fire("replica_fetch")
            files.append(path)
        return ser.load_shard_files(files, where=f"hot:{tag}")

    def load_best(self, tag=None):
        """Try candidates (an explicit tag, or every visible generation
        newest-first). -> (tag, flat, header) or (None, None, None)."""
        from .manager import FALLBACK_ERRORS
        candidates = [tag] if tag is not None else self.tags()
        for cand in candidates:
            try:
                flat, header = self.load(cand)
            except FALLBACK_ERRORS as e:
                logger.warning(
                    f"hot tier: generation {cand!r} not restorable "
                    f"({e}); trying the next tier/candidate")
                continue
            return cand, flat, header
        return None, None, None

    # ----------------------------------------------------------- retention
    def gc(self):
        """Keep the newest ``keep_last`` tags in OUR subtree (the hot
        tier is a bounded cache over host RAM, not an archive)."""
        if self.keep_last <= 0:
            return []
        nd = self._node_dir(self.node)
        try:
            tags = sorted((t for t in os.listdir(nd)
                           if os.path.isdir(os.path.join(nd, t))),
                          key=_step_key, reverse=True)
        except OSError:
            return []
        removed = []
        for t in tags[self.keep_last:]:
            shutil.rmtree(os.path.join(nd, t), ignore_errors=True)
            removed.append(t)
        return removed
