"""Peer-replicated in-memory checkpoint hot tier.

The reference fork's whole async-checkpointing layer (DataStates/VELOC,
``csrc/veloc/``) exists so the COMMON failure — one host dies — restores
from a fast in-memory tier instead of re-reading persistent storage.
This module is that tier for the TPU runtime:

  * after every save's D2H extraction, each node pushes its local shard
    (the exact ``extract_local_chunks`` payload, CRC manifest included)
    to K ring-neighbor peers;
  * a node's store lives in host RAM (tmpfs — ``/dev/shm`` by default),
    so it survives worker-process restarts but dies with the host,
    exactly like the pinned host cache it models;
  * on resume, ``manager.load_best_tiered`` tries the hot tier first:
    a generation is loadable when the node's own shards plus surviving
    peer replicas cover every writer — the common single-host loss
    restores with ZERO persistent-storage reads, degrading to the
    durable tier when replicas are insufficient or CRC-invalid.

Store layout (one subtree per node under a shared root):

    {root}/{node}/{tag}/own/shard-{p}.npz        this node's own save
    {root}/{node}/{tag}/from-{origin}/shard-{p}.npz   received replicas
                                                 (origin in OUR slice)
    {root}/{node}/{tag}/replica-from-{origin}/shard-{p}.npz
        cross-slice replicas — provenance is burned into the dir name
        so a survivor can tell, after the origin slice is gone and its
        peers purged, which shards belong to the REPLICA restore tier
    {root}/{node}/{tag}/zero-replica-{slice}/shard-r{p}.npz
        the registered MiCS ZeRO replica: under cross-slice replication
        (data_outer>1) every slice holds a full copy of master/opt
        state in HBM; the engine persists this node's replica shards
        here at each save so the surviving slice can restore from its
        OWN memory even when no cross-slice push landed

Slice awareness: when a slice map is configured (``slices`` arg or the
``DSTPU_HOT_SLICES`` env the elastic agent exports), replica placement
targets peers in a DIFFERENT slice first — a whole-slice failure (ICI
outage, maintenance preemption) then still leaves every shard with a
surviving copy, which ``manager.load_best_tiered`` serves as the
``replica`` tier (ordered hot → replica → durable).

Two transports own the peer push:

  * ``fs`` — the pusher writes straight into the peer's subtree. On a
    single machine (the chaos suites' multi-worker simulation, where
    each "host" is a process and the shared tmpfs root stands in for
    peer RAM) this IS the transfer; the elastic agent models the real
    host-RAM loss by purging a dead host's subtree (purge_node).
  * ``dcn`` — bytes ride the accelerator fabric via
    ``comm.ring_exchange_bytes`` (a collective-permute over a
    one-device-per-process mesh; DCN on a multi-slice pod) and the
    RECEIVER writes them into its own subtree. Collective: every
    process must push at the same save boundary — which the engine's
    multi-process save barrier already guarantees.

Fault points (utils/fault_injection): ``replica_push`` fires once per
peer replica write, ``replica_fetch`` once per replica read during
assembly (own-written shards read clean), ``dcn_partition`` before each
collective cross-peer exchange, ``replica_restore`` once per
replica-TIER source read, and ``slice_loss`` at the slice-aware push
boundary (arming it with ``kill`` models a whole slice dying
mid-training) — arming the advisory ones makes pushes fail (the
durable tier still lands) or poisons the replicas so loads degrade
deterministically.
"""

import concurrent.futures as futures
import glob
import io
import os
import re
import shutil
import tempfile

from ...utils import fault_injection
from ...utils.logging import logger
from . import serialization as ser


def _safe(name):
    return re.sub(r"[^A-Za-z0-9_.-]", "_", str(name))


def default_root():
    """Hot-store root: DSTPU_HOT_TIER_ROOT env, else tmpfs (/dev/shm —
    host RAM, the point of the tier), else the system tempdir (still
    node-local; documented degradation for hosts without tmpfs)."""
    env = os.environ.get("DSTPU_HOT_TIER_ROOT")
    if env:
        return env
    base = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    return os.path.join(base, f"dstpu_hot_{os.getuid()}")


def _step_key(name):
    m = re.search(r"(\d+)$", name)
    return int(m.group(1)) if m else -1


def purge_node(root, node):
    """Drop ``node``'s whole store — the host-RAM-loss boundary. The
    elastic agent calls this for every failed host before relaunch, so
    replicas a dead host held can never serve a restore they would not
    survive in production."""
    shutil.rmtree(os.path.join(root, _safe(node)), ignore_errors=True)


# one-time (per process) hot_replicas clamp warning — a config int and
# an autotuned 'hot_replicas' winner both flow through the constructor,
# and a small pod must not log the same clamp on every engine build
_CLAMP_WARNED = [False]


class HotTierStore:
    """One node's view of the peer-replicated hot tier.

    Args:
      root: shared hot-store root (see :func:`default_root`).
      node: this node's id (string). Default: ``DSTPU_HOT_NODE`` env,
        else the jax process index. The elastic launcher exports the
        host name here so agent-side purge and store subtrees agree.
      peers: ORDERED ring membership (list of node ids). Default:
        ``DSTPU_HOT_PEERS`` (comma-separated), else one id per jax
        process. Ring neighbors are computed from this order.
      replicas: K — how many ring neighbors receive each shard. Clamped
        to ``len(peers) - 1`` (one-time warning): pushing more replicas
        than there are distinct peers would re-send duplicate shards to
        the same host, inflating save overhead for zero durability.
      keep_last: hot-tier retention (tags per node; the tier is a cache,
        not an archive).
      counters: optional engine counters dict (hot_pushes /
        hot_push_errors / replica_pushes bumped here).
      slices: slice membership for cross-slice placement — a dict
        ``{peer: slice_id}`` or a list aligned with ``peers``. Default:
        ``DSTPU_HOT_SLICES`` env (comma-separated, aligned with
        ``DSTPU_HOT_PEERS``; the elastic agent exports both). With more
        than one distinct slice the store becomes slice-AWARE: replica
        pushes target other-slice peers first and other-slice/
        ``replica-from-*`` sources are served as the ``replica`` tier.
      max_inflight_pushes: backlog bound for :meth:`push_async` — at
        most this many pushes may be pending at once (oldest queued
        push dropped with a counted ``hot_push_errors``), and a newer
        push of the same tag supersedes a still-queued one.
    """

    def __init__(self, root=None, node=None, peers=None, replicas=1,
                 keep_last=2, counters=None, slices=None,
                 max_inflight_pushes=4):
        import jax
        self.root = root or default_root()
        if node is None:
            node = os.environ.get("DSTPU_HOT_NODE") or \
                str(jax.process_index())
        self.node = _safe(node)
        if peers is None:
            env = os.environ.get("DSTPU_HOT_PEERS")
            if env:
                peers = [p for p in env.split(",") if p]
            else:
                peers = [str(i) for i in range(jax.process_count())]
        self.peers = [_safe(p) for p in peers]
        if slices is None:
            env = os.environ.get("DSTPU_HOT_SLICES")
            if env:
                slices = [s.strip() for s in env.split(",")]
        if isinstance(slices, (list, tuple)):
            slices = {p: slices[i] if i < len(slices) else "0"
                      for i, p in enumerate(self.peers)}
        self.slice_of = {_safe(k): _safe(v)
                         for k, v in (slices or {}).items()}
        if self.node not in self.peers:
            # a node outside the ring still stores locally (replicas
            # have nowhere meaningful to go); keep membership explicit
            self.peers = self.peers + [self.node]
        self.slice = self.slice_of.get(
            self.node, _safe(os.environ.get("DSTPU_HOT_SLICE", "0")))
        self.slice_of.setdefault(self.node, self.slice)
        for p in self.peers:
            self.slice_of.setdefault(p, "0")
        self.slice_aware = len(set(self.slice_of.values())) > 1
        replicas = max(0, int(replicas))
        cap = max(0, len(self.peers) - 1)
        if replicas > cap:
            if not _CLAMP_WARNED[0]:
                _CLAMP_WARNED[0] = True
                logger.warning(
                    f"hot tier: hot_replicas={replicas} exceeds the "
                    f"ring's {len(self.peers)} peer(s) - 1; clamping to "
                    f"{cap} — extra replicas would target the same "
                    f"peers again (duplicate pushes, zero added "
                    f"durability)")
            replicas = cap
        self.replicas = replicas
        self.keep_last = int(keep_last)
        self.counters = counters if counters is not None else {}
        self.max_inflight_pushes = max(1, int(max_inflight_pushes))
        self._pool = futures.ThreadPoolExecutor(max_workers=1)
        self._inflight = []       # [(tag, future)] — see push_async

    # ------------------------------------------------------------ topology
    def ring_neighbors(self):
        """The K distinct peers after this node in ring order. Slice-
        aware stores pick OTHER-slice peers first (still in ring order),
        so a whole-slice loss leaves every shard a surviving copy; same-
        slice peers only fill in when other slices cannot absorb K."""
        if len(self.peers) <= 1 or self.replicas < 1:
            return []
        i = self.peers.index(self.node)
        order = [self.peers[(i + k) % len(self.peers)]
                 for k in range(1, len(self.peers))]
        if self.slice_aware:
            order = ([p for p in order if self.slice_of[p] != self.slice]
                     + [p for p in order
                        if self.slice_of[p] == self.slice])
        out = []
        for p in order:
            if p != self.node and p not in out:
                out.append(p)
            if len(out) >= self.replicas:
                break
        return out

    def _cross_slice(self, a, b):
        return (self.slice_aware
                and self.slice_of.get(a, "0") != self.slice_of.get(b, "0"))

    def _recv_subdir(self, origin, target):
        """Directory name (under the target's tag dir) a replica from
        ``origin`` lands in — cross-slice provenance is burned into the
        name so the replica TIER survives origin-slice purge."""
        if self._cross_slice(origin, target):
            return f"replica-from-{origin}"
        return f"from-{origin}"

    def _node_dir(self, node):
        return os.path.join(self.root, node)

    def _tag_dir(self, node, tag, sub=None):
        d = os.path.join(self._node_dir(node), tag)
        return os.path.join(d, sub) if sub else d

    # ---------------------------------------------------------------- push
    def _serialize(self, chunks, extra):
        bio = io.BytesIO()
        ser.save_file(bio, chunks, extra_meta=extra)
        return bio.getbuffer()

    def _write_bytes(self, target_dir, fname, payload):
        os.makedirs(target_dir, exist_ok=True)
        tmp = os.path.join(target_dir, f".{fname}.tmp")
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(target_dir, fname))

    def _count_push_error(self, msg):
        self.counters["hot_push_errors"] = \
            self.counters.get("hot_push_errors", 0) + 1
        logger.warning(msg)

    def _count(self, key):
        self.counters[key] = self.counters.get(key, 0) + 1

    def _fire_slice_boundary(self):
        # the slice-death injection point: armed with kill=True it
        # models every host of a slice dying at its push boundary
        # (classed 'fatal' in fault_injection.BLAST_RADIUS — a plain
        # FaultError here fails the push call synchronously, which only
        # a harness arms)
        if self.slice_aware:
            fault_injection.fire("slice_loss")

    def push(self, tag, chunks, extra, shard_name=None):
        """Store this node's shard for ``tag`` locally and replicate it
        to the ring neighbors. Replica failures are ADVISORY (counted,
        logged, never raised): the durable tier is still landing through
        the normal save path, and a hot tier that could fail a save
        would be worse than no hot tier. Returns the number of replicas
        that landed."""
        import jax
        if shard_name is None:
            shard_name = f"shard-{jax.process_index()}.npz"
        payload = self._serialize(chunks, extra)
        ok = 0
        try:
            self._write_bytes(self._tag_dir(self.node, tag, "own"),
                              shard_name, payload)
        except OSError as e:
            self._count_push_error(
                f"hot tier: local store of {tag} failed: {e}")
            return 0
        for peer in self.ring_neighbors():
            try:
                fault_injection.fire("replica_push")
                self._write_bytes(
                    self._tag_dir(peer, tag,
                                  self._recv_subdir(self.node, peer)),
                    shard_name, payload)
                ok += 1
                if self._cross_slice(self.node, peer):
                    self._count("replica_pushes")
            except fault_injection.SimulatedKill:
                raise
            except Exception as e:  # noqa: BLE001 - advisory path
                self._count_push_error(
                    f"hot tier: replica push {tag} -> {peer} failed: {e}")
        self._count("hot_pushes")
        self.gc()
        return ok

    def push_async(self, tag, chunks, extra, shard_name=None):
        """Replicate off the training critical path (the PR-2 async-pool
        discipline). Degrades to an in-caller push when the pool is
        gone (interpreter teardown).

        Backlog bound: repeated advisory push failures (or a slow
        tmpfs) must not let queued futures accumulate across tags, so
        (a) a newer push of the SAME tag supersedes one still queued —
        the superseded payload could never serve a restore the newer
        one would not serve better — and (b) total pending pushes are
        capped at ``max_inflight_pushes``, dropping the oldest
        cancellable future. Every drop is a counted advisory
        ``hot_push_errors``."""
        self._fire_slice_boundary()
        # prune finished futures so a long run that saves every N steps
        # (and never loads) cannot grow the list unboundedly
        self._inflight = [(t, f) for t, f in self._inflight
                          if not f.done()]
        pending = []
        for t, f in self._inflight:
            if t == tag and f.cancel():
                self._count_push_error(
                    f"hot tier: superseded queued push of {t!r} with a "
                    f"newer payload")
            else:
                pending.append((t, f))
        i = 0
        while len(pending) >= self.max_inflight_pushes \
                and i < len(pending):
            t, f = pending[i]
            if f.cancel():
                pending.pop(i)
                self._count_push_error(
                    f"hot tier: push backlog over "
                    f"{self.max_inflight_pushes}; dropped oldest queued "
                    f"push of {t!r}")
            else:
                i += 1          # running — cannot be dropped
        self._inflight = pending
        try:
            fut = self._pool.submit(self.push, tag, chunks, extra,
                                    shard_name)
        except RuntimeError:
            self.push(tag, chunks, extra, shard_name)
            return None
        self._inflight.append((tag, fut))
        return fut

    def push_zero_replica(self, tag, chunks, extra):
        """Register the cross-slice ZeRO replica as a restore source.

        Under MiCS the INNER_DP_AXES-partitioned master/opt state is
        REPLICATED over ``data_outer`` — every slice already holds a
        full copy in HBM. The engine hands this process's replica
        shards (``serialization.extract_replica_chunks``) here at each
        save; they land in our OWN subtree keyed by slice, so after the
        canonical-writer slice dies (and its stores are purged) the
        surviving slice restores from its own memory-resident copy with
        zero persistent-storage reads. Advisory, like every hot push."""
        import jax
        self._fire_slice_boundary()
        fname = f"shard-r{jax.process_index()}.npz"
        try:
            payload = self._serialize(chunks, extra)
            self._write_bytes(
                self._tag_dir(self.node, tag,
                              f"zero-replica-{self.slice}"),
                fname, payload)
        except fault_injection.SimulatedKill:
            raise
        except Exception as e:  # noqa: BLE001 - advisory path
            self._count_push_error(
                f"hot tier: zero-replica push of {tag} failed: {e}")
            return False
        self._count("replica_pushes")
        return True

    def push_collective(self, tag, chunks, extra, shard_name=None):
        """DCN transport: exchange the serialized shard with each ring
        neighbor over the comm layer (collective — every process in the
        jax world must call this at the same save boundary), then store
        what THIS node received from its upstream peers. Falls back to
        the fs transport outside a multi-process world. Same ADVISORY
        contract as :meth:`push`: a hot-tier failure (injected
        replica_push fault, tmpfs ENOSPC, a wedged exchange) is counted
        and logged, never raised — it must not cost the durable save
        the engine is about to make."""
        import jax
        self._fire_slice_boundary()
        if jax.process_count() <= 1 or self.replicas < 1:
            return self.push(tag, chunks, extra, shard_name)
        try:
            return self._push_collective_impl(tag, chunks, extra,
                                              shard_name)
        except fault_injection.SimulatedKill:
            raise
        except Exception as e:  # noqa: BLE001 - advisory path
            self.counters["hot_push_errors"] = \
                self.counters.get("hot_push_errors", 0) + 1
            logger.warning(
                f"hot tier: collective replica push of {tag} failed "
                f"({e}); the durable tier is unaffected")
            return 0

    def _push_collective_impl(self, tag, chunks, extra, shard_name):
        import jax
        from ...comm.comm import ring_exchange_bytes
        if shard_name is None:
            shard_name = f"shard-{jax.process_index()}.npz"
        payload = bytes(self._serialize(chunks, extra))
        self._write_bytes(self._tag_dir(self.node, tag, "own"),
                          shard_name, payload)
        ok = 0
        for k in range(1, self.replicas + 1):
            fault_injection.fire("replica_push")
            # the exchange rides DCN between slices; a partition there
            # is advisory (caught by push_collective) — the durable
            # save at this barrier still lands
            fault_injection.fire("dcn_partition")
            recv, origin = ring_exchange_bytes(payload, shift=k)
            if recv is None:
                continue
            origin_node = self.peers[origin % len(self.peers)]
            self._write_bytes(
                self._tag_dir(self.node, tag,
                              self._recv_subdir(origin_node, self.node)),
                f"shard-{origin}.npz", recv)
            ok += 1
            if self._cross_slice(origin_node, self.node):
                self._count("replica_pushes")
        self._count("hot_pushes")
        self.gc()
        return ok

    def wait(self):
        """Drain in-flight async pushes (advisory failures already
        swallowed inside push; backlog-dropped futures were counted at
        cancel time)."""
        pending, self._inflight = self._inflight, []
        for _tag, fut in pending:
            if fut.cancelled():
                continue
            exc = fut.exception()
            if exc is not None and not isinstance(exc, Exception):
                raise exc          # SimulatedKill et al.
        return True

    def shutdown(self):
        self.wait()
        self._pool.shutdown(wait=True)

    # ------------------------------------------------------------ assembly
    def tags(self):
        """Generations visible anywhere in the hot tier, newest first
        (step-suffix order). A survivor may hold a tag only as replicas
        pushed by a now-dead writer, so the scan covers every node
        subtree, not just our own."""
        seen = set()
        try:
            nodes = os.listdir(self.root)
        except OSError:
            return []
        for node in nodes:
            nd = self._node_dir(node)
            try:
                for t in os.listdir(nd):
                    if os.path.isdir(os.path.join(nd, t)):
                        seen.add(t)
            except OSError:
                continue
        return sorted(seen, key=_step_key, reverse=True)

    def tier_tags(self):
        """-> (hot_tags, replica_tags), each newest first. A tag is a
        HOT candidate when at least one hot-class source exists (an
        ``own`` save, or a same-slice peer replica); a REPLICA candidate
        when at least one replica-class source exists (a cross-slice
        ``replica-from-*`` shard, an other-slice subtree, or a
        registered ``zero-replica-*`` set). A tag may be both — the
        manager tries hot first and degrades down-tier."""
        hot, replica = set(), set()
        try:
            nodes = os.listdir(self.root)
        except OSError:
            return [], []
        for node in nodes:
            nd = self._node_dir(node)
            try:
                tags = [t for t in os.listdir(nd)
                        if os.path.isdir(os.path.join(nd, t))]
            except OSError:
                continue
            for t in tags:
                try:
                    subs = os.listdir(os.path.join(nd, t))
                except OSError:
                    continue
                for sub in subs:
                    cls = self._source_class(node, sub)
                    (replica if cls == "replica" else hot).add(t)
        order = lambda s: sorted(s, key=_step_key, reverse=True)  # noqa: E731
        return order(hot), order(replica)

    def _source_class(self, node, sub):
        """'own' | 'hot' | 'replica' for a source subtree: replica =
        anything only the cross-slice replica tier may serve (burned-in
        ``replica-from-*`` provenance, the registered zero-replica set,
        or ANY subtree of an other-slice node). Without a slice map
        every non-own source is 'hot' — the PR-7 single-host-loss
        behavior, unchanged."""
        if sub.startswith("zero-replica") or \
                sub.startswith("replica-from-"):
            return "replica"
        if self.slice_aware and \
                self.slice_of.get(node, self.slice) != self.slice:
            return "replica"
        if node == self.node and sub == "own":
            return "own"
        return "hot"

    _CLASS_PRIO = {"own": 0, "hot": 1, "replica": 2}

    def _shard_sources(self, tag, tier="replica"):
        """-> {shard_name: (path, cls)} best source per shard file:
        this node's own save first (a clean local read), then same-
        slice replicas, then — only when ``tier='replica'`` — cross-
        slice replica-tier sources. Every non-own read fires
        ``replica_fetch``; replica-class reads additionally fire
        ``replica_restore`` (see :meth:`load`)."""
        max_prio = 1 if tier == "hot" else 2
        sources = {}
        try:
            others = sorted(n for n in os.listdir(self.root)
                            if n != self.node)
        except OSError:
            others = []
        for node in [self.node] + others:
            td = self._tag_dir(node, tag)
            try:
                subs = sorted(os.listdir(td))
            except OSError:
                continue
            for sub in subs:
                cls = self._source_class(node, sub)
                if sub.startswith("zero-replica"):
                    continue      # separate all-or-nothing sets
                prio = self._CLASS_PRIO[cls]
                if prio > max_prio:
                    continue
                for p in sorted(glob.glob(
                        os.path.join(td, sub, "shard-*.npz"))):
                    name = os.path.basename(p)
                    cur = sources.get(name)
                    if cur is None or prio < self._CLASS_PRIO[cur[1]]:
                        sources[name] = (p, cls)
        return sources

    def _zero_replica_sets(self, tag):
        """Complete per-slice ZeRO-replica shard sets for ``tag``, our
        own slice's first — each is an all-or-nothing assembly fallback
        (load_shard_files' per-leaf coverage check rejects a set whose
        slice lost members before every replica shard landed)."""
        by_slice = {}
        try:
            nodes = os.listdir(self.root)
        except OSError:
            return []
        for node in nodes:
            td = self._tag_dir(node, tag)
            for d in glob.glob(os.path.join(td, "zero-replica-*")):
                sl = os.path.basename(d)[len("zero-replica-"):]
                for p in glob.glob(os.path.join(d, "shard-*.npz")):
                    by_slice.setdefault(sl, {})[os.path.basename(p)] = p
        order = sorted(by_slice, key=lambda s: (s != self.slice, s))
        return [[by_slice[s][n] for n in sorted(by_slice[s])]
                for s in order]

    def load(self, tag, tier="replica"):
        """Assemble ``tag`` from the best available sources, bounded by
        ``tier``: 'hot' uses only own + same-slice replicas; 'replica'
        (the default, and the pre-slice behavior when no slice map is
        configured) additionally serves cross-slice replica shards and
        falls back to a registered zero-replica set. Raises
        CheckpointCorruptionError/ValueError/OSError (the manager's
        FALLBACK_ERRORS) when shards are missing, CRC-invalid, or a
        replica fetch fails — callers degrade down-tier."""
        from .manager import FALLBACK_ERRORS
        sources = self._shard_sources(tag, tier=tier)
        last_err = None
        if sources:
            files = []
            for name in sorted(sources):
                path, cls = sources[name]
                if cls != "own":
                    fault_injection.fire("replica_fetch")
                if cls == "replica":
                    fault_injection.fire("replica_restore")
                files.append(path)
            try:
                return ser.load_shard_files(files, where=f"hot:{tag}")
            except FALLBACK_ERRORS as e:
                if tier == "hot":
                    raise
                last_err = e
        if tier == "hot":
            raise FileNotFoundError(
                f"hot tier: no shards for tag {tag!r} under {self.root}")
        for files in self._zero_replica_sets(tag):
            for _ in files:
                fault_injection.fire("replica_restore")
            try:
                return ser.load_shard_files(
                    files, where=f"hot-zero-replica:{tag}")
            except FALLBACK_ERRORS as e:
                last_err = e
                continue
        if last_err is not None:
            raise last_err
        raise FileNotFoundError(
            f"hot tier: no shards for tag {tag!r} under {self.root}")

    def load_best(self, tag=None):
        """Try candidates (an explicit tag, or every visible generation
        newest-first). -> (tag, flat, header) or (None, None, None)."""
        from .manager import FALLBACK_ERRORS
        candidates = [tag] if tag is not None else self.tags()
        for cand in candidates:
            try:
                flat, header = self.load(cand)
            except FALLBACK_ERRORS as e:
                logger.warning(
                    f"hot tier: generation {cand!r} not restorable "
                    f"({e}); trying the next tier/candidate")
                continue
            return cand, flat, header
        return None, None, None

    # ----------------------------------------------------------- retention
    def gc(self):
        """Keep the newest ``keep_last`` tags in OUR subtree (the hot
        tier is a bounded cache over host RAM, not an archive)."""
        if self.keep_last <= 0:
            return []
        nd = self._node_dir(self.node)
        try:
            tags = sorted((t for t in os.listdir(nd)
                           if os.path.isdir(os.path.join(nd, t))),
                          key=_step_key, reverse=True)
        except OSError:
            return []
        removed = []
        for t in tags[self.keep_last:]:
            shutil.rmtree(os.path.join(nd, t), ignore_errors=True)
            removed.append(t)
        return removed
