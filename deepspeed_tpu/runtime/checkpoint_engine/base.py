"""Checkpoint engine plugin interface.

Counterpart of reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``
including the fork's additions: the base API is
``create/save/load/commit`` and the fork adds ``wait()/shutdown()`` for
async engines (SURVEY §5.4; engine.save_checkpoint_terminate at
engine.py:3114 does barrier -> shutdown -> barrier).

A "state_dict" here is a pytree of host numpy arrays plus JSON-able
metadata; engines only move bytes. Device->host staging is the engine
caller's job (runtime/engine.py save_checkpoint), mirroring how the
reference's VELOC engine receives tensors and owns the D2H pipeline.

Robustness contract (all engines):
  * a save that raises has NOT called ``on_durable`` — the 'latest'
    pointer can never name a torn generation;
  * transient write failures are retried with capped exponential
    backoff (``save_retries`` / ``retry_backoff_s`` knobs on
    CheckpointEngineConfig), then degrade to the engine's fallback
    writer when it has one (native -> python, async -> in-caller sync);
  * every failed save version surfaces exactly ONE CheckpointSaveError
    from ``wait()``/``commit()`` — failed futures never wedge
    ``_inflight``;
  * ``counters`` records saves/loads/retries/fallbacks/errors so the
    runtime engine can emit them as monitor events.
"""

import time

from ...utils import fault_injection
from ...utils.logging import logger


class CheckpointSaveError(RuntimeError):
    """One save version failed durably (retries + fallback exhausted).
    Carries the version and target path so the operator knows exactly
    which generation is NOT on disk."""

    def __init__(self, version, path, cause):
        super().__init__(
            f"checkpoint save (version {version}) to {path} failed "
            f"after retries/fallback: {cause}")
        self.version = version
        self.path = path
        self.cause = cause


def _new_counters():
    return {
        "saves": 0,            # successful engine-level saves
        "loads": 0,
        "retries": 0,          # write attempts that failed and were retried
        "fallbacks": 0,        # saves completed by the degraded writer
        "save_errors": 0,      # versions that failed even after fallback
        "load_fallbacks": 0,   # loads served by an older durable tag
        "gc_removed": 0,       # tags deleted by retention GC
        # hot tier (checkpoint_engine/hot_tier.py)
        "hot_pushes": 0,       # local+peer replications completed
        "hot_push_errors": 0,  # advisory replica-push failures
        "hot_restores": 0,     # loads served from in-memory replicas
        "hot_fallbacks": 0,    # hot tier present but degraded to durable
        "durable_restores": 0,  # loads that DID read persistent storage
        # cross-slice replica tier (slice-aware hot_tier + MiCS
        # zero-replica registration)
        "replica_pushes": 0,     # cross-slice replica/zero-replica pushes
        "replica_restores": 0,   # loads served by the replica tier
        "replica_fallbacks": 0,  # replica tier present but degraded
    }


class CheckpointEngine:
    def __init__(self, config_params=None):
        self.config = config_params
        self.save_retries = int(getattr(config_params, "save_retries", 2))
        self.retry_backoff_s = float(
            getattr(config_params, "retry_backoff_s", 0.05))
        self.retry_backoff_cap_s = float(
            getattr(config_params, "retry_backoff_cap_s", 2.0))
        self.counters = _new_counters()

    def create(self, tag):
        """Log/prepare for a save under ``tag``."""

    def makedirs(self, path, exist_ok=False):
        import os
        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict, path):
        raise NotImplementedError

    def load(self, path, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        """Mark ``tag`` durable (reference: nebula/veloc commit).
        Surfaces any already-completed failed save (non-blocking)."""
        return True

    def wait(self, version=None):
        """Block until async work for ``version`` (or all) is durable.
        Fork addition (veloc_checkpoint_engine.py wait). Raises
        CheckpointSaveError once per failed version."""
        return True

    def drain(self, version=None):
        """Like wait(), but never raises for failed saves (they stay
        queued for the next wait()/commit()). Load/recovery paths use
        this: a failed save must not block reading durable data."""
        return self.wait(version)

    def shutdown(self):
        """Drain and stop background machinery (fork addition)."""
        return True

    # ------------------------------------------------------- retry/degrade
    def _write_with_retry(self, attempt, fallback, desc):
        """Run ``attempt()`` with capped exponential backoff on failure;
        after ``save_retries`` failed retries, run ``fallback()`` (the
        degraded writer) when provided. SimulatedKill is never retried —
        it models SIGKILL. Raises the last error when everything fails
        (callers wrap it into CheckpointSaveError with version info)."""
        last = None
        for i in range(self.save_retries + 1):
            try:
                return attempt()
            except fault_injection.SimulatedKill:
                raise
            except Exception as e:  # noqa: BLE001 - any IO failure retries
                last = e
                if i < self.save_retries:
                    self.counters["retries"] += 1
                    delay = min(self.retry_backoff_cap_s,
                                self.retry_backoff_s * (2 ** i))
                    logger.warning(
                        f"checkpoint write to {desc} failed "
                        f"(attempt {i + 1}/{self.save_retries + 1}): {e}; "
                        f"retrying in {delay:.2f}s")
                    time.sleep(delay)
        if fallback is not None:
            try:
                result = fallback()
                self.counters["fallbacks"] += 1
                logger.warning(
                    f"checkpoint write to {desc} degraded to the "
                    f"fallback writer after {self.save_retries + 1} "
                    f"failed attempts ({last})")
                return result
            except fault_injection.SimulatedKill:
                raise
            except Exception as e:  # noqa: BLE001
                last = e
        raise last
