"""Checkpoint engine plugin interface.

Counterpart of reference ``runtime/checkpoint_engine/checkpoint_engine.py:9``
including the fork's additions: the base API is
``create/save/load/commit`` and the fork adds ``wait()/shutdown()`` for
async engines (SURVEY §5.4; engine.save_checkpoint_terminate at
engine.py:3114 does barrier -> shutdown -> barrier).

A "state_dict" here is a pytree of host numpy arrays plus JSON-able
metadata; engines only move bytes. Device->host staging is the engine
caller's job (runtime/engine.py save_checkpoint), mirroring how the
reference's VELOC engine receives tensors and owns the D2H pipeline.
"""


class CheckpointEngine:
    def __init__(self, config_params=None):
        self.config = config_params

    def create(self, tag):
        """Log/prepare for a save under ``tag``."""

    def makedirs(self, path, exist_ok=False):
        import os
        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict, path):
        raise NotImplementedError

    def load(self, path, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        """Mark ``tag`` durable (reference: nebula/veloc commit)."""
        return True

    def wait(self, version=None):
        """Block until async work for ``version`` (or all) is durable.
        Fork addition (veloc_checkpoint_engine.py wait)."""
        return True

    def shutdown(self):
        """Drain and stop background machinery (fork addition)."""
        return True
