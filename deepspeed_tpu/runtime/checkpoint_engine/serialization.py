"""Checkpoint serialization: pytree <-> flat npz-style container.

Torch-free replacement for ``torch.save``: a checkpoint file is a zip
(via numpy.savez) of leaf arrays keyed by escaped tree paths, plus a
``__meta__`` JSON entry carrying the treedef and non-array values. The
layout is *sharding-agnostic*: leaves are GLOBAL logical arrays, so a
checkpoint written under one ZeRO stage / mesh loads under any other — the
capability the reference needs offline conversion for
(checkpoint/ds_to_universal.py)."""

import json
import os
import zipfile
import zlib

import numpy as np
import jax

from ...utils import fault_injection


_SEP = "/"


class CheckpointCorruptionError(ValueError):
    """A shard failed integrity verification (truncated zip, CRC
    mismatch, missing manifest entry). Subclasses ValueError so callers
    that already guard reassembly errors catch it too; load paths use
    it to fall back to the previous durable generation."""


def flatten_state(tree):
    """-> (dict path->leaf, meta dict of non-array leaves)."""
    flat = {}
    meta = {}
    for path, leaf in jax.tree.leaves_with_path(tree):
        key = _SEP.join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        if isinstance(leaf, (int, float, str, bool)) or leaf is None:
            meta[key] = leaf
        else:
            flat[key] = leaf
    return flat, meta


def unflatten_into(template, flat, meta=None):
    """Rebuild a pytree shaped like ``template`` from flat path->array."""
    meta = meta or {}

    def pick(path, leaf):
        key = _SEP.join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        if key in flat:
            return flat[key]
        if key in meta:
            return meta[key]
        raise KeyError(f"checkpoint missing key {key}")

    return jax.tree.map_with_path(pick, template)


def _crc(arr):
    a = np.ascontiguousarray(arr)
    return zlib.crc32(a.view(np.uint8).reshape(-1)) & 0xFFFFFFFF


def save_file(path, tree, extra_meta=None):
    """Write one shard. Integrity: the header carries a per-entry CRC32
    manifest (verified by load_file). Durability: file-path writes go to
    ``path + ".tmp"``, fsync, then atomic ``os.replace`` — a crash at
    ANY byte of the write leaves the previously durable shard at
    ``path`` untouched (the CheckFreq/VELOC two-phase rule)."""
    fault_injection.fire("serialize")
    flat, meta = flatten_state(tree)
    arrays = {}
    manifest = {}
    for k, v in flat.items():
        arr = np.asarray(v)
        # np.savez keys cannot contain '/': escape
        key = k.replace("/", "%2F")
        arrays[key] = arr
        manifest[key] = _crc(arr)
    header = {"meta": meta, "extra": extra_meta or {}, "version": 2,
              "crc": manifest}
    arrays["__meta__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    if hasattr(path, "write"):
        # in-memory target (native engine serializes to bytes; the C++
        # pool owns the byte write — and fires the 'write' point — plus
        # its own tmp/rename)
        np.savez(path, **arrays)
        return
    tmp = str(path) + ".tmp"
    # re-create the tag dir: a retrying attempt must heal even if the
    # (then-empty) dir was swept by retention GC in between
    os.makedirs(os.path.dirname(str(path)) or ".", exist_ok=True)
    try:
        with open(tmp, "wb") as f:
            fault_injection.fire("write")
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        fault_injection.fire("rename")
        os.replace(tmp, path)
    except Exception:
        # a failed attempt must not leak a full-size tmp shard; a
        # SimulatedKill/real crash still leaves one, faithfully to
        # SIGKILL (retention GC sweeps the emptied tag dirs)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(str(path)))


def _fsync_dir(dirpath):
    """Make a rename durable: fsync the containing directory (best
    effort — not all filesystems support directory fds)."""
    try:
        fd = os.open(dirpath or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def load_file(path, verify=True):
    """-> (flat dict path->array, header dict). ``verify`` checks every
    entry against the header's CRC32 manifest (files written before the
    manifest existed — header version 1 — load unverified)."""
    try:
        with np.load(path, allow_pickle=False) as z:
            header = json.loads(bytes(z["__meta__"].tobytes()).decode())
            flat_raw = {k: z[k] for k in z.files if k != "__meta__"}
    except (zipfile.BadZipFile, KeyError, EOFError, OSError,
            ValueError) as e:
        if isinstance(e, FileNotFoundError):
            raise
        raise CheckpointCorruptionError(
            f"checkpoint shard {path} is unreadable "
            f"(truncated or torn write?): {e}") from e
    manifest = header.get("crc")
    if verify and manifest is not None:
        for key in manifest:
            if key not in flat_raw:
                raise CheckpointCorruptionError(
                    f"checkpoint shard {path}: chunk entry {key!r} "
                    f"listed in the CRC manifest but absent from the "
                    f"archive — torn shard")
        for key, arr in flat_raw.items():
            want = manifest.get(key)
            if want is None:
                raise CheckpointCorruptionError(
                    f"checkpoint shard {path}: entry {key!r} absent "
                    f"from the CRC manifest — foreign or tampered data")
            got = _crc(arr)
            if got != want:
                raise CheckpointCorruptionError(
                    f"checkpoint shard {path}: CRC mismatch on "
                    f"{key!r} (want {want:#010x}, got {got:#010x}) — "
                    f"shard is corrupt")
    flat = {k.replace("%2F", "/"): v for k, v in flat_raw.items()}
    return flat, header


# --------------------------------------------------------- sharded layout
# Per-host shard files (reference engine.py:3545 _save_zero_checkpoint
# writes per-DP-rank partition files for exactly this reason): each process
# writes ONLY its addressable shards — no process_allgather of the full
# model state over DCN, no single writer. A chunk file 'shard-{p}.npz'
# holds this process's chunks keyed '{leafkey}#{i}' plus an index entry
# per leaf ({global shape, dtype, chunk offsets}); any process count /
# topology reassembles the global logical tensors on load.

def extract_local_chunks(tree):
    """-> (chunks dict, index dict, meta dict) for THIS process.

    Device-array leaves contribute their addressable shards with
    replica_id == 0 (each global shard is written exactly once across the
    job); host/numpy leaves are single chunks owned by process 0."""
    import jax as _jax
    flat, meta = flatten_state(tree)
    chunks, index = {}, {}
    pid = _jax.process_index()
    for key, leaf in flat.items():
        if isinstance(leaf, _jax.Array):
            entry = {"shape": list(leaf.shape), "dtype": str(leaf.dtype),
                     "chunks": []}
            for i, sh in enumerate(leaf.addressable_shards):
                if sh.replica_id != 0:
                    continue
                data = np.asarray(sh.data)
                start = [0 if s.start is None else int(s.start)
                         for s in sh.index]
                # process-unique chunk key: enumerate() restarts at 0 on
                # every process, so '{key}#{i}' alone would collide
                # across shard files in multi-process checkpoints
                ck = f"{key}#{pid}.{i}"
                chunks[ck] = data
                entry["chunks"].append({"key": ck, "start": start})
            index[key] = entry
        else:
            arr = np.asarray(leaf)
            entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                     "chunks": []}
            if pid == 0:
                ck = f"{key}#0.0"
                chunks[ck] = arr
                entry["chunks"].append(
                    {"key": ck, "start": [0] * arr.ndim})
            index[key] = entry
    return chunks, index, meta


def extract_replica_chunks(tree):
    """-> (chunks, index, meta) for THIS process's CROSS-SLICE REPLICA
    copy of ``tree``.

    The mirror image of :func:`extract_local_chunks`: that one writes
    each global shard exactly once (replica_id == 0 — the canonical
    copy); this one collects the SECOND copy (replica_id == 1), which
    under MiCS partitioning (shard over INNER_DP_AXES, replicate over
    ``data_outer``) is the sibling slice's HBM-resident replica of
    master/opt state. The hot tier persists these chunks as the
    ``zero-replica`` restore source, so a slice that loses its sibling
    can reassemble the full state from its own memory.

    Chunk keys are ``{key}#r{pid}.{i}`` — disjoint from the canonical
    ``{key}#{pid}.{i}`` namespace, so a replica shard file can never be
    confused with (or double-fill) a canonical one. Every leaf gets an
    index entry even when this process holds no replica of it: the
    per-leaf coverage check in :func:`load_shard_files` then rejects an
    incomplete replica set instead of resuming from a torn copy."""
    import jax as _jax
    flat, meta = flatten_state(tree)
    chunks, index = {}, {}
    pid = _jax.process_index()
    for key, leaf in flat.items():
        if isinstance(leaf, _jax.Array):
            entry = {"shape": list(leaf.shape), "dtype": str(leaf.dtype),
                     "chunks": []}
            for i, sh in enumerate(leaf.addressable_shards):
                if sh.replica_id != 1:
                    continue
                data = np.asarray(sh.data)
                start = [0 if s.start is None else int(s.start)
                         for s in sh.index]
                ck = f"{key}#r{pid}.{i}"
                chunks[ck] = data
                entry["chunks"].append({"key": ck, "start": start})
            index[key] = entry
        else:
            # host/numpy leaves are replicated on every host by
            # construction; re-owned by process 0 like the canonical
            # extraction
            arr = np.asarray(leaf)
            entry = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                     "chunks": []}
            if pid == 0:
                ck = f"{key}#r0.0"
                chunks[ck] = arr
                entry["chunks"].append(
                    {"key": ck, "start": [0] * arr.ndim})
            index[key] = entry
    return chunks, index, meta


def load_sharded(dirpath):
    """Read every shard-*.npz in ``dirpath`` and reassemble the global
    logical arrays. -> (flat dict path->array, normalized header)."""
    import glob
    files = sorted(glob.glob(os.path.join(dirpath, "shard-*.npz")))
    if not files:
        raise FileNotFoundError(f"no shard-*.npz under {dirpath}")
    return load_shard_files(files, where=dirpath)


def load_shard_files(files, where=None):
    """Reassemble the global logical arrays from an explicit list of
    shard file paths (they need not share a directory — the hot tier
    assembles a generation from shard replicas scattered across peer
    stores). -> (flat dict path->array, normalized header)."""
    where = where or (os.path.dirname(files[0]) if files else "<empty>")
    return _load_shard_files(files, where)


def _load_shard_files(files, dirpath):
    merged = {}
    all_chunks = {}
    header0 = None
    for f in files:
        flat, header = load_file(f)
        for k, e in (header["extra"].get("index") or {}).items():
            cur = merged.setdefault(
                k, {"shape": e["shape"], "dtype": e["dtype"], "chunks": []})
            cur["chunks"].extend(e["chunks"])
        all_chunks.update(flat)
        if os.path.basename(f) == "shard-0.npz":
            header0 = header
    header0 = header0 or header
    # Coverage validation: the reassembly buffer is np.empty, so any gap
    # (missing shard file, partial copy, mismatched process count) would
    # silently resume training from uninitialized memory. Check the shard
    # file count against the writer's recorded world size, then require
    # every leaf's chunks to cover it exactly.
    nprocs = (header0["extra"].get("user_extra") or {}).get("nprocs")
    if nprocs is not None and len(files) != nprocs:
        raise ValueError(
            f"incomplete checkpoint {dirpath}: found {len(files)} shard "
            f"files but the writer recorded nprocs={nprocs}")
    out = {}
    for k, e in merged.items():
        total = int(np.prod(e["shape"], dtype=np.int64))
        filled = 0
        arr = np.empty(e["shape"], np.dtype(e["dtype"]))
        for c in e["chunks"]:
            if c["key"] not in all_chunks:
                raise ValueError(
                    f"checkpoint {dirpath}: leaf {k} chunk {c['key']} "
                    f"indexed but absent from every shard file")
            data = all_chunks[c["key"]]
            sl = tuple(slice(s, s + n) for s, n in zip(c["start"],
                                                       data.shape))
            arr[sl] = data
            filled += data.size
        if filled != total:
            raise ValueError(
                f"checkpoint {dirpath}: leaf {k} covered by "
                f"{filled}/{total} elements — shard files missing or "
                f"written by a torn save")
        out[k] = arr
    extra = dict(header0["extra"])
    meta = extra.pop("__tree_meta__", {})
    extra.pop("index", None)
    return out, {"meta": meta, "extra": extra.get("user_extra", extra)}


def load_state(tag_dir):
    """Load a checkpoint tag directory in either layout: legacy monolithic
    ``state.npz`` (global arrays, one writer) or the sharded per-host
    layout. -> (flat dict, header with 'meta'/'extra')."""
    legacy = os.path.join(tag_dir, "state.npz")
    if os.path.exists(legacy):
        return load_file(legacy)
    return load_sharded(tag_dir)


def verify_tag(tag_dir):
    """Full integrity pass over one tag directory: every shard's zip
    structure + CRC manifest, and (sharded layout) chunk coverage of
    every leaf + the writer's recorded nprocs. Raises
    CheckpointCorruptionError / ValueError / FileNotFoundError on any
    defect; returns True when the generation is known-good. Retention
    GC calls this on the NEWEST tag before deleting older ones, so
    recovery always has a loadable generation.

    Unlike load_sharded this never materializes the reassembled global
    arrays — it holds one shard in memory at a time and checks coverage
    arithmetically (sum of chunk sizes vs leaf size), so GC's per-save
    verification costs a read pass, not a full-model host allocation."""
    import glob
    legacy = os.path.join(tag_dir, "state.npz")
    if os.path.exists(legacy):
        load_file(legacy)
        return True
    files = sorted(glob.glob(os.path.join(tag_dir, "shard-*.npz")))
    if not files:
        raise FileNotFoundError(f"no shard files under {tag_dir}")
    chunk_sizes = {}
    merged = {}
    header0 = None
    for f in files:
        flat, header = load_file(f)   # zip structure + CRC manifest
        for k, arr in flat.items():
            chunk_sizes[k] = int(arr.size)
        for k, e in (header["extra"].get("index") or {}).items():
            cur = merged.setdefault(k, {"shape": e["shape"],
                                        "chunks": []})
            cur["chunks"].extend(c["key"] for c in e["chunks"])
        if os.path.basename(f) == "shard-0.npz":
            header0 = header
        del flat
    header0 = header0 or header
    nprocs = (header0["extra"].get("user_extra") or {}).get("nprocs")
    if nprocs is not None and len(files) != nprocs:
        raise ValueError(
            f"incomplete checkpoint {tag_dir}: found {len(files)} shard "
            f"files but the writer recorded nprocs={nprocs}")
    for k, e in merged.items():
        total = int(np.prod(e["shape"], dtype=np.int64))
        filled = 0
        for ck in e["chunks"]:
            if ck not in chunk_sizes:
                raise ValueError(
                    f"checkpoint {tag_dir}: leaf {k} chunk {ck} indexed "
                    f"but absent from every shard file")
            filled += chunk_sizes[ck]
        if filled != total:
            raise ValueError(
                f"checkpoint {tag_dir}: leaf {k} covered by "
                f"{filled}/{total} elements — shard files missing or "
                f"written by a torn save")
    return True
