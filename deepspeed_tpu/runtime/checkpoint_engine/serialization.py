"""Checkpoint serialization: pytree <-> flat npz-style container.

Torch-free replacement for ``torch.save``: a checkpoint file is a zip
(via numpy.savez) of leaf arrays keyed by escaped tree paths, plus a
``__meta__`` JSON entry carrying the treedef and non-array values. The
layout is *sharding-agnostic*: leaves are GLOBAL logical arrays, so a
checkpoint written under one ZeRO stage / mesh loads under any other — the
capability the reference needs offline conversion for
(checkpoint/ds_to_universal.py)."""

import io
import json

import numpy as np
import jax


_SEP = "/"


def flatten_state(tree):
    """-> (dict path->leaf, meta dict of non-array leaves)."""
    flat = {}
    meta = {}
    for path, leaf in jax.tree.leaves_with_path(tree):
        key = _SEP.join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        if isinstance(leaf, (int, float, str, bool)) or leaf is None:
            meta[key] = leaf
        else:
            flat[key] = leaf
    return flat, meta


def unflatten_into(template, flat, meta=None):
    """Rebuild a pytree shaped like ``template`` from flat path->array."""
    meta = meta or {}

    def pick(path, leaf):
        key = _SEP.join(str(getattr(p, "key", getattr(p, "idx", p)))
                        for p in path)
        if key in flat:
            return flat[key]
        if key in meta:
            return meta[key]
        raise KeyError(f"checkpoint missing key {key}")

    return jax.tree.map_with_path(pick, template)


def save_file(path, tree, extra_meta=None):
    flat, meta = flatten_state(tree)
    arrays = {}
    for k, v in flat.items():
        arr = np.asarray(v)
        # np.savez keys cannot contain '/': escape
        arrays[k.replace("/", "%2F")] = arr
    header = {"meta": meta, "extra": extra_meta or {}, "version": 1}
    arrays["__meta__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    if hasattr(path, "write"):
        np.savez(path, **arrays)
    else:
        with open(path, "wb") as f:
            np.savez(f, **arrays)


def load_file(path):
    """-> (flat dict path->array, header dict)."""
    with np.load(path, allow_pickle=False) as z:
        header = json.loads(bytes(z["__meta__"].tobytes()).decode())
        flat = {k.replace("%2F", "/"): z[k] for k in z.files
                if k != "__meta__"}
    return flat, header
