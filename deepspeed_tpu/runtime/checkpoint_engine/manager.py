"""Durable-tag bookkeeping shared by the runtime engine and the chaos
tests: atomic 'latest' publication, retention GC, and the ordered list
of generations a loader should try.

These are module-level functions (not engine methods) on purpose —
crash-consistency of the *directory* protocol must be testable without
building a model/jit pipeline, and every engine plugin shares one
protocol:

  {save_dir}/{tag}/shard-{p}.npz   durable generations (CRC manifests)
  {save_dir}/latest                atomically-replaced pointer; only
                                   ever names a fully durable tag
"""

import glob
import os
import re
import shutil
import threading
import time

from ...utils import fault_injection
from ...utils.logging import logger
from . import serialization as ser

# Publication and GC run on async-engine writer threads; two saves can
# reach durability concurrently. This lock serializes the in-process
# latest/GC critical sections so (a) GC never double-counts a tag two
# overlapping runs both saw, and (b) the .latest tmp file is never
# written by two threads at once. Cross-process publication is already
# serialized by the rank-0/barrier protocol in engine.save_checkpoint.
_publish_lock = threading.Lock()


def publish_latest(save_dir, tag, seq=None):
    """Atomically point ``latest`` at ``tag``. Callers must only invoke
    this AFTER every shard of ``tag`` is durable (the on_durable /
    barrier protocol in runtime/engine.py save_checkpoint).

    ``seq``: optional monotonic sequence (the engine passes the global
    step the tag was saved at). With async engines two in-flight saves
    can hit durability out of order; the guard keeps 'latest' from
    regressing to the older generation. Returns False when skipped."""
    os.makedirs(save_dir, exist_ok=True)
    fault_injection.fire("commit")
    with _publish_lock:
        if seq is not None:
            cur = _read_seq(save_dir)
            if cur is not None and cur > seq:
                logger.info(
                    f"not publishing 'latest'={tag!r} (seq {seq}): a "
                    f"newer generation (seq {cur}) is already published")
                return False
        tmp = os.path.join(save_dir, ".latest.tmp")
        with open(tmp, "w") as f:
            f.write(tag)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(save_dir, "latest"))
        if seq is not None:
            tmp2 = os.path.join(save_dir, ".latest_seq.tmp")
            with open(tmp2, "w") as f:
                f.write(str(int(seq)))
            os.replace(tmp2, os.path.join(save_dir, ".latest_seq"))
        ser._fsync_dir(save_dir)
    return True


def _read_seq(save_dir):
    try:
        with open(os.path.join(save_dir, ".latest_seq")) as f:
            return int(f.read().strip())
    except (OSError, ValueError):
        return None


def read_latest(save_dir):
    """-> tag named by the 'latest' pointer, or None."""
    p = os.path.join(save_dir, "latest")
    try:
        with open(p) as f:
            tag = f.read().strip()
        return tag or None
    except OSError:
        return None


def list_tags(save_dir):
    """Tag directories that contain checkpoint data, newest first
    (mtime order, name as tiebreak)."""
    try:
        names = os.listdir(save_dir)
    except OSError:
        return []
    out = []
    for name in names:
        p = os.path.join(save_dir, name)
        if not os.path.isdir(p):
            continue
        if not (os.path.exists(os.path.join(p, "state.npz"))
                or glob.glob(os.path.join(p, "shard-*.npz"))):
            continue
        try:
            mtime = os.path.getmtime(p)
        except OSError:
            continue   # GC'd by a writer thread between listdir and stat
        out.append((mtime, _step_key(name), name))
    return [name for _, _, name in sorted(out, reverse=True)]


def _step_key(name):
    """mtime tie-break (coarse-granularity filesystems): the trailing
    integer of the tag name, so global_step10 orders after global_step9
    instead of lexicographically before it."""
    m = re.search(r"(\d+)$", name)
    return int(m.group(1)) if m else -1


def load_candidates(load_dir, tag=None, hot_store=None):
    """Generations to try loading, best first. An explicit ``tag`` is
    the only candidate (the caller asked for THAT generation — silently
    substituting another would be worse than failing). With no tag: the
    'latest' pointer first, then every other tag newest-first, so a
    corrupt newest generation falls back to the previous durable one.

    With ``hot_store`` the candidate list grows a TIER dimension and the
    return shape becomes ``[(tier, tag), ...]`` ordered hot → replica →
    durable — the common single-host loss restores from surviving
    same-slice in-memory replicas, a whole-slice loss from the cross-
    slice REPLICA tier (``replica-from-*`` shards and the registered
    MiCS zero-replica; still zero persistent-storage reads), degrading
    to the durable tier when replicas are insufficient or CRC-invalid.
    Staleness guard (applied to BOTH in-memory tiers): a hot/replica
    generation OLDER than the published durable 'latest' is dropped
    (the advisory replica push can lag or fail without failing the
    save, so the RAM tier may hold only step N-1 after step N durably
    committed — serving it would silently roll a committed generation
    back). A generation NEWER than 'latest' is kept: it is the latest
    trained state even though its durable commit never landed.

    This list is THE tier-order definition — :func:`load_best_tiered`
    consumes it rather than re-deriving its own."""
    if tag is not None:
        durable = [tag]
    else:
        latest = read_latest(load_dir)
        tags = list_tags(load_dir)
        durable = [latest] if latest else []
        durable.extend(t for t in tags if t != latest)
    if hot_store is None:
        return durable
    # stores without tier_tags (older stubs) expose a single hot list
    if hasattr(hot_store, "tier_tags"):
        hot, replica = hot_store.tier_tags()
    else:
        hot, replica = hot_store.tags(), []
    if tag is not None:
        # only a tag the tier actually holds is a hot/replica candidate
        # — a cold RAM tier after a full restart is routine, not a
        # degradation, and must not fire the hot_fallbacks signal
        hot = [tag] if tag in hot else []
        replica = [tag] if tag in replica else []
    else:
        latest = durable[0] if durable else None
        if latest is not None:
            floor = _step_key(latest)
            hot = [t for t in hot if _step_key(t) >= floor]
            replica = [t for t in replica if _step_key(t) >= floor]
    return ([("hot", t) for t in hot]
            + [("replica", t) for t in replica]
            + [("durable", t) for t in durable])


# Errors that mean "this generation is unloadable, try the previous
# durable one" — the ONE definition of the fallback trigger set shared
# by the training engine, the inference engine, and the chaos tests.
FALLBACK_ERRORS = (ser.CheckpointCorruptionError, ValueError, OSError)


def load_best(load_dir, tag=None, loader=None, counters=None):
    """Load the best available generation with fallback: try each
    candidate from :func:`load_candidates` with ``loader(tag_dir)``
    (default :func:`serialization.load_state`); a candidate failing with
    one of FALLBACK_ERRORS falls through to the next, bumping
    ``counters['load_fallbacks']``.

    Returns ``(tag, flat, header)``; ``(None, None, None)`` when no
    checkpoint exists at all. Raises CheckpointCorruptionError when
    generations exist but none is loadable — resuming silently from
    scratch would be worse than failing loudly."""
    loader = loader or ser.load_state
    last_err = None
    tried = 0
    candidates = load_candidates(load_dir, tag)
    for i, cand in enumerate(candidates):
        tag_dir = os.path.join(load_dir, cand)
        if not os.path.isdir(tag_dir):
            continue
        tried += 1
        try:
            flat, header = loader(tag_dir)
        except FALLBACK_ERRORS as e:
            last_err = e
            if i + 1 < len(candidates):
                # only a real fallback (another candidate exists) is
                # counted/logged — an explicit corrupt tag with nothing
                # to fall back to must not report a recovery
                if counters is not None:
                    counters["load_fallbacks"] += 1
                logger.warning(
                    f"checkpoint tag {cand!r} failed verification/load "
                    f"({e}); falling back to the previous durable "
                    f"generation")
            continue
        return cand, flat, header
    if tried == 0:
        return None, None, None
    raise ser.CheckpointCorruptionError(
        f"no loadable checkpoint generation under {load_dir} "
        f"(tried {tried} tag(s))") from last_err


def load_best_tiered(load_dir, tag=None, hot_store=None, loader=None,
                     counters=None):
    """Tier-ordered load over the :func:`load_candidates` order: the
    hot tier's surviving same-slice replicas first, then the cross-
    slice REPLICA tier (both minus stale generations — see the
    staleness guard there), the durable generations last.
    -> (tier, tag, flat, header); tier is 'hot', 'replica' or 'durable'
    (None when nothing exists anywhere). An in-memory candidate failing
    (missing shards, CRC-invalid replica, poisoned ``replica_fetch``/
    ``replica_restore``) degrades DOWN-TIER exactly once per tier —
    bumping ``counters['hot_fallbacks']`` / ``['replica_fallbacks']``
    — rather than failing the resume."""
    if hot_store is not None:
        tiered = load_candidates(load_dir, tag, hot_store=hot_store)
        attempted = {"hot": 0, "replica": 0}
        for tier, cand in tiered:
            if tier == "durable":
                break             # durable phase delegates to load_best
            attempted[tier] += 1
            try:
                if hasattr(hot_store, "tier_tags"):
                    flat, header = hot_store.load(cand, tier=tier)
                else:
                    flat, header = hot_store.load(cand)
            except FALLBACK_ERRORS as e:
                logger.warning(
                    f"{tier} tier: generation {cand!r} not restorable "
                    f"({e}); trying the next tier/candidate")
                continue
            if counters is not None:
                key = ("hot_restores" if tier == "hot"
                       else "replica_restores")
                counters[key] = counters.get(key, 0) + 1
            return tier, cand, flat, header
        for tier, key in (("hot", "hot_fallbacks"),
                          ("replica", "replica_fallbacks")):
            if attempted[tier]:
                if counters is not None:
                    counters[key] = counters.get(key, 0) + 1
                logger.warning(
                    f"{tier} tier: no generation restorable from "
                    f"surviving replicas; degrading down-tier")
    cand, flat, header = load_best(load_dir, tag, loader=loader,
                                   counters=counters)
    if cand is None:
        return None, None, None, None
    if counters is not None:
        counters["durable_restores"] = \
            counters.get("durable_restores", 0) + 1
    return "durable", cand, flat, header


def gc_tags(save_dir, keep_last, counters=None):
    """Retention: delete all but the newest ``keep_last`` durable tags.

    Only runs after the NEWEST tag passes a full integrity verification
    (CRC manifests + chunk coverage) — if the newest generation is torn,
    nothing is deleted, so recovery always has a known-good generation.
    The tag named by 'latest' is never deleted regardless of age.
    Returns the list of removed tags; never raises (GC is advisory —
    a failed cleanup must not fail the save that triggered it)."""
    if not keep_last or keep_last <= 0:
        return []
    try:
        with _publish_lock:
            return _gc_tags_locked(save_dir, keep_last, counters)
    except Exception as e:  # noqa: BLE001 - advisory
        logger.warning(f"checkpoint retention GC failed: {e}")
        return []


def _gc_tags_locked(save_dir, keep_last, counters):
    tags = list_tags(save_dir)
    _sweep_empty_tag_dirs(save_dir, keep=set(tags))
    if len(tags) <= keep_last:
        return []
    try:
        ser.verify_tag(os.path.join(save_dir, tags[0]))
    except Exception as e:  # noqa: BLE001 - verification IS the gate
        logger.warning(
            f"checkpoint retention GC skipped: newest tag "
            f"{tags[0]!r} failed verification ({e}); keeping every "
            f"older generation as recovery candidates")
        return []
    protect = set(tags[:keep_last])
    latest = read_latest(save_dir)
    if latest:
        protect.add(latest)
    removed = []
    for t in tags[keep_last:]:
        if t in protect:
            continue
        shutil.rmtree(os.path.join(save_dir, t), ignore_errors=True)
        removed.append(t)
    if removed:
        logger.info(
            f"checkpoint retention (keep_last={keep_last}): removed "
            f"{len(removed)} old generation(s): {removed}")
        if counters is not None:
            counters["gc_removed"] += len(removed)
    return removed


def _sweep_empty_tag_dirs(save_dir, keep, min_age_s=900):
    """Failed saves leave empty tag directories behind (their tmp shard
    is unlinked on failure); sweep them so intermittent storage failures
    cannot grow an unbounded dir set. Two defenses against racing an
    in-flight save whose tag dir is momentarily empty: os.rmdir refuses
    non-empty dirs (a tag holding a tmp being written survives), and
    only dirs older than ``min_age_s`` are touched (a freshly created
    tag is younger; the write path also re-creates its dir on retry)."""
    try:
        names = os.listdir(save_dir)
    except OSError:
        return
    cutoff = time.time() - min_age_s
    for name in names:
        if name in keep:
            continue
        p = os.path.join(save_dir, name)
        if not os.path.isdir(p):
            continue
        try:
            if os.stat(p).st_mtime > cutoff:
                continue
            os.rmdir(p)
        except OSError:
            pass
