"""Checkpoint engine implementations.

Parity with the reference's plugin set (engine.py:931-963 selection):
  * SyncCheckpointEngine   — torch_checkpoint_engine.py:12 equivalent.
  * AsyncCheckpointEngine  — async_checkpoint_engine.py:17 equivalent:
    device->host staging happens on the caller (fast path), serialization +
    file IO on a thread pool; ``wait()`` drains, ``shutdown()`` joins.
  * NativeCheckpointEngine — veloc_checkpoint_engine.py:42 equivalent:
    same pipeline but the file write goes through the C++ writer pool
    (op_builder 'native_ckpt', csrc/ckpt_writer.cpp) with pwrite'd chunks —
    the VELOC _d2h_trf/_h2f_trf split re-imagined for TPU hosts.
  * NoneCheckpointEngine   — none_checkpoint_engine.py:12: no-op for
    measuring checkpoint overhead.
"""

import concurrent.futures as futures
import os
import threading

from ...utils.logging import logger, log_dist
from .base import CheckpointEngine
from . import serialization as ser


class SyncCheckpointEngine(CheckpointEngine):
    def save(self, state_dict, path, on_durable=None):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tree, extra = state_dict
        ser.save_file(path, tree, extra_meta=extra)
        if on_durable is not None:
            on_durable()

    def load(self, path, map_location=None):
        return ser.load_file(path)


class NoneCheckpointEngine(CheckpointEngine):
    def save(self, state_dict, path, on_durable=None):
        return True

    def load(self, path, map_location=None):
        raise RuntimeError("NoneCheckpointEngine cannot load")


class AsyncCheckpointEngine(CheckpointEngine):
    """Thread-pool writer. The caller stages device arrays to host (the
    cheap, bandwidth-bound part — analogous to VELOC's pinned-cache D2H);
    serialization+IO (the slow part) happens off the training thread."""

    def __init__(self, config_params=None, max_workers=None, max_inflight=2):
        super().__init__(config_params)
        workers = max_workers or getattr(config_params, "writer_threads", 2)
        self.max_inflight = getattr(config_params, "max_inflight",
                                    max_inflight)
        self._pool = futures.ThreadPoolExecutor(max_workers=workers)
        self._inflight = {}
        self._lock = threading.Lock()
        self._version = 0

    def save(self, state_dict, path, on_durable=None):
        with self._lock:
            self._version += 1
            version = self._version
        # backpressure: bound staged-copy memory like VELOC's host cache
        while len([f for f in self._inflight.values() if not f.done()]) \
                >= self.max_inflight:
            self.wait(min(self._inflight))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tree, extra = state_dict

        def task():
            ser.save_file(path, tree, extra_meta=extra)
            # durability callback runs on the writer thread AFTER the bytes
            # land, so e.g. the 'latest' pointer never names a torn file
            if on_durable is not None:
                on_durable()

        fut = self._pool.submit(task)
        self._inflight[version] = fut
        return version

    def load(self, path, map_location=None):
        self.wait()
        return ser.load_file(path)

    def wait(self, version=None):
        items = (list(self._inflight.items()) if version is None
                 else [(version, self._inflight[version])]
                 if version in self._inflight else [])
        for v, fut in items:
            fut.result()
            self._inflight.pop(v, None)
        return True

    def commit(self, tag):
        return True

    def shutdown(self):
        self.wait()
        self._pool.shutdown(wait=True)
        return True


class NativeCheckpointEngine(AsyncCheckpointEngine):
    """Async engine whose byte-writing goes through the C++ writer pool
    when available (falls back to the pure-python path)."""

    def __init__(self, config_params=None, **kw):
        super().__init__(config_params, **kw)
        try:
            from ...ops.native import ckpt_writer
            self._writer = ckpt_writer.Writer(
                threads=getattr(config_params, "writer_threads", 2))
        except Exception as e:  # noqa: BLE001 - optional native ext
            logger.warning(f"native ckpt writer unavailable ({e}); "
                           "using python writer")
            self._writer = None

    def save(self, state_dict, path, on_durable=None):
        if self._writer is None:
            return super().save(state_dict, path, on_durable=on_durable)
        with self._lock:
            self._version += 1
            version = self._version
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tree, extra = state_dict
        fut = self._pool.submit(self._native_save, path, tree, extra,
                                on_durable)
        self._inflight[version] = fut
        return version

    def _native_save(self, path, tree, extra, on_durable=None):
        # serialize to bytes in-thread, write via the native pwrite pool
        import io
        bio = io.BytesIO()
        ser.save_file(bio, tree, extra_meta=extra)
        self._writer.write(path, bio.getbuffer())
        if on_durable is not None:
            on_durable()


ENGINES = {
    "sync": SyncCheckpointEngine,
    "async": AsyncCheckpointEngine,
    "native": NativeCheckpointEngine,
    "none": NoneCheckpointEngine,
    # reference-fork config names (engine.py:931-963 selection) map onto
    # the equivalent TPU engines: torch -> sync; veloc/datastates (C++
    # pinned-cache writer pipelines) -> native; torch_sn_async -> async
    "torch": SyncCheckpointEngine,
    "veloc": NativeCheckpointEngine,
    "datastates": NativeCheckpointEngine,
    "torch_sn_async": AsyncCheckpointEngine,
    "nebula": AsyncCheckpointEngine,   # Azure tiered async -> async
}


def create_checkpoint_engine(cfg):
    """cfg: CheckpointEngineConfig (runtime/config.py)."""
    typ = getattr(cfg, "type", "sync")
    if typ not in ENGINES:
        raise ValueError(f"unknown checkpoint engine '{typ}'; "
                         f"available: {sorted(ENGINES)}")
    return ENGINES[typ](cfg)
