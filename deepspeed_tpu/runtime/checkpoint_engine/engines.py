"""Checkpoint engine implementations.

Parity with the reference's plugin set (engine.py:931-963 selection):
  * SyncCheckpointEngine   — torch_checkpoint_engine.py:12 equivalent.
  * AsyncCheckpointEngine  — async_checkpoint_engine.py:17 equivalent:
    device->host staging happens on the caller (fast path), serialization +
    file IO on a thread pool; ``wait()`` drains, ``shutdown()`` joins.
  * NativeCheckpointEngine — veloc_checkpoint_engine.py:42 equivalent:
    same pipeline but the file write goes through the C++ writer pool
    (op_builder 'native_ckpt', csrc/ckpt_writer.cpp) with pwrite'd chunks —
    the VELOC _d2h_trf/_h2f_trf split re-imagined for TPU hosts.
  * NoneCheckpointEngine   — none_checkpoint_engine.py:12: no-op for
    measuring checkpoint overhead.

Failure semantics (the crash-consistency layer):
  * every write is retried with capped exponential backoff
    (base.CheckpointEngine._write_with_retry), then degrades — native
    falls back to the pure-python writer, async falls back to an
    in-caller synchronous write when its pool is dead;
  * ``on_durable`` only ever fires after the bytes are durable, so a
    failed save can never publish 'latest';
  * a version whose save failed is popped from ``_inflight`` and its
    error raised exactly once, from ``wait()`` or ``commit()`` —
    ``drain()`` (used by load/recovery paths) collects failures without
    raising so durable data stays readable after a failed save.
"""

import concurrent.futures as futures
import io
import os
import threading

from ...utils import fault_injection
from ...utils.logging import logger
from .base import CheckpointEngine, CheckpointSaveError
from . import serialization as ser


class SyncCheckpointEngine(CheckpointEngine):
    def save(self, state_dict, path, on_durable=None):
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tree, extra = state_dict
        try:
            self._write_with_retry(
                lambda: ser.save_file(path, tree, extra_meta=extra),
                None, path)
        except fault_injection.SimulatedKill:
            raise
        except Exception as e:  # noqa: BLE001
            self.counters["save_errors"] += 1
            raise CheckpointSaveError(0, path, e) from e
        self.counters["saves"] += 1
        if on_durable is not None:
            on_durable()

    def load(self, path, map_location=None):
        self.counters["loads"] += 1
        return ser.load_file(path)


class NoneCheckpointEngine(CheckpointEngine):
    def save(self, state_dict, path, on_durable=None):
        return True

    def load(self, path, map_location=None):
        raise RuntimeError("NoneCheckpointEngine cannot load")


class AsyncCheckpointEngine(CheckpointEngine):
    """Thread-pool writer. The caller stages device arrays to host (the
    cheap, bandwidth-bound part — analogous to VELOC's pinned-cache D2H);
    serialization+IO (the slow part) happens off the training thread."""

    def __init__(self, config_params=None, max_workers=None, max_inflight=2):
        super().__init__(config_params)
        workers = max_workers or getattr(config_params, "writer_threads", 2)
        self.max_inflight = getattr(config_params, "max_inflight",
                                    max_inflight)
        self._pool = futures.ThreadPoolExecutor(max_workers=workers)
        self._inflight = {}
        self._failures = {}      # version -> exception, each raised ONCE
        self._lock = threading.Lock()
        self._version = 0

    # --------------------------------------------------------------- write
    def _write_payload(self, path, tree, extra):
        """One write attempt (overridden by the native engine)."""
        ser.save_file(path, tree, extra_meta=extra)

    def _fallback_writer(self, path, tree, extra):
        """-> zero-arg callable performing the degraded write, or None
        when no lower tier exists (the python writer IS the last tier
        for the plain async engine)."""
        return None

    def _run_save(self, version, path, tree, extra, on_durable):
        try:
            self._write_with_retry(
                lambda: self._write_payload(path, tree, extra),
                self._fallback_writer(path, tree, extra), path)
        except fault_injection.SimulatedKill:
            raise
        except Exception as e:  # noqa: BLE001
            self.counters["save_errors"] += 1
            raise CheckpointSaveError(version, path, e) from e
        self.counters["saves"] += 1
        # durability callback runs on the writer thread AFTER the bytes
        # land, so e.g. the 'latest' pointer never names a torn file
        if on_durable is not None:
            on_durable()

    def save(self, state_dict, path, on_durable=None):
        with self._lock:
            self._version += 1
            version = self._version
        self._reap()
        # backpressure: bound staged-copy memory like VELOC's host cache.
        # A failed old save surfaces here (once) rather than wedging the
        # window shut forever.
        while len(self._inflight) >= self.max_inflight:
            self.wait(min(self._inflight))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tree, extra = state_dict
        try:
            fut = self._pool.submit(self._run_save, version, path, tree,
                                    extra, on_durable)
        except RuntimeError as e:
            # writer pool dead (shutdown/interpreter teardown): degrade
            # this save to a synchronous in-caller write instead of
            # losing the generation
            logger.warning(
                f"async checkpoint pool unavailable ({e}); degrading "
                f"save v{version} to a synchronous write")
            self.counters["fallbacks"] += 1
            self._run_save(version, path, tree, extra, on_durable)
            return version
        self._inflight[version] = fut
        return version

    # ------------------------------------------------------------ wait/err
    def _collect(self, version, fut):
        """Record the outcome of a finished future. The version is
        ALREADY popped from _inflight — a failure is queued in _failures
        to be raised exactly once."""
        exc = fut.exception()
        if exc is None:
            return
        if not isinstance(exc, Exception):   # SimulatedKill et al.
            raise exc
        self._failures[version] = exc

    def _reap(self):
        """Non-blocking: fold any finished futures into _failures."""
        for v, fut in list(self._inflight.items()):
            if fut.done():
                self._inflight.pop(v, None)
                self._collect(v, fut)

    def _raise_one_failure(self):
        if not self._failures:
            return
        v = min(self._failures)
        exc = self._failures.pop(v)
        if isinstance(exc, CheckpointSaveError):
            raise exc
        raise CheckpointSaveError(v, "<unknown>", exc) from exc

    def _drain_targets(self, version):
        if version is None:
            return sorted(self._inflight)
        return [version] if version in self._inflight else []

    def wait(self, version=None):
        # pop BEFORE result: one failed save must not raise from every
        # later wait()/load() forever
        for v in self._drain_targets(version):
            fut = self._inflight.pop(v, None)
            if fut is not None:
                fut.exception()   # block until done
                self._collect(v, fut)
        self._raise_one_failure()
        return True

    def drain(self, version=None):
        """wait() without raising: failures stay queued for the next
        wait()/commit(). Recovery paths use this so a failed save can't
        block loading the previous durable generation."""
        for v in self._drain_targets(version):
            fut = self._inflight.pop(v, None)
            if fut is not None:
                fut.exception()
                self._collect(v, fut)
        return True

    def load(self, path, map_location=None):
        self.drain()
        self.counters["loads"] += 1
        return ser.load_file(path)

    def commit(self, tag):
        self._reap()
        self._raise_one_failure()
        return True

    def shutdown(self):
        self.wait()
        self._pool.shutdown(wait=True)
        return True


class NativeCheckpointEngine(AsyncCheckpointEngine):
    """Async engine whose byte-writing goes through the C++ writer pool
    when available; degrades to the pure-python writer per save when the
    native path fails."""

    def __init__(self, config_params=None, **kw):
        super().__init__(config_params, **kw)
        try:
            from ...ops.native import ckpt_writer
            # fsync=True: the tmp's bytes must be durable BEFORE the
            # rename publishes them — otherwise on_durable fires (and
            # retention GC deletes older generations) while the shard
            # is still page cache, and a power loss strands 'latest' on
            # a torn file with the known-good tags already gone
            self._writer = ckpt_writer.Writer(
                threads=getattr(config_params, "writer_threads", 2),
                fsync=True)
        except Exception as e:  # noqa: BLE001 - optional native ext
            logger.warning(f"native ckpt writer unavailable ({e}); "
                           "using python writer")
            self._writer = None

    def _write_payload(self, path, tree, extra):
        if self._writer is None:
            return super()._write_payload(path, tree, extra)
        # serialize to bytes in-thread (CRC manifest included), write via
        # the native pwrite pool to a tmp name, then atomic rename — the
        # C++ path gets the same two-phase durability as the python one
        bio = io.BytesIO()
        ser.save_file(bio, tree, extra_meta=extra)
        tmp = str(path) + ".tmp"
        os.makedirs(os.path.dirname(str(path)) or ".", exist_ok=True)
        try:
            self._writer.write(tmp, bio.getbuffer())
            fault_injection.fire("rename")
            os.replace(tmp, path)
        except Exception:
            # failed attempts must not leak full-size tmp shards (a
            # SimulatedKill/real crash still leaves one, like SIGKILL)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        ser._fsync_dir(os.path.dirname(str(path)))

    def _fallback_writer(self, path, tree, extra):
        if self._writer is None:
            return None     # already on the python writer
        # degrade: the plain python writer (its own tmp+fsync+rename)
        return lambda: ser.save_file(path, tree, extra_meta=extra)


ENGINES = {
    "sync": SyncCheckpointEngine,
    "async": AsyncCheckpointEngine,
    "native": NativeCheckpointEngine,
    "none": NoneCheckpointEngine,
    # reference-fork config names (engine.py:931-963 selection) map onto
    # the equivalent TPU engines: torch -> sync; veloc/datastates (C++
    # pinned-cache writer pipelines) -> native; torch_sn_async -> async
    "torch": SyncCheckpointEngine,
    "veloc": NativeCheckpointEngine,
    "datastates": NativeCheckpointEngine,
    "torch_sn_async": AsyncCheckpointEngine,
    "nebula": AsyncCheckpointEngine,   # Azure tiered async -> async
}


def create_checkpoint_engine(cfg):
    """cfg: CheckpointEngineConfig (runtime/config.py)."""
    typ = getattr(cfg, "type", "sync")
    if typ not in ENGINES:
        raise ValueError(f"unknown checkpoint engine '{typ}'; "
                         f"available: {sorted(ENGINES)}")
    return ENGINES[typ](cfg)
