from .swapper import AsyncTensorSwapper, OptimizerStateSwapper
from . import host_stage
