from .swapper import AsyncTensorSwapper, OptimizerStateSwapper
