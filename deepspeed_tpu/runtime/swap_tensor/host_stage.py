"""In-program host staging: the reference's ``swap_tensor`` tier for
values that live INSIDE a jitted program.

The reference's swap layer moves tensors out of device memory
imperatively (AsyncTensorSwapper -> AIO -> NVMe, swapper.py in this
package); under XLA the same capability for in-program values is a
memory-space annotation: ``jax.device_put`` onto the host memory kind
inside jit stages the value out of HBM, and XLA's host-offload pass
legalizes the dynamic-update-slice / gather traffic into async
``copy-start``/``copy-done`` pairs the latency-hiding scheduler can
overlap (the reference overlaps its D2H with compute through CUDA
streams; here the compiler owns the schedule). The pipeline executors
(runtime/pipe/spmd.py) use this to keep their activation rings — the
``activation_checkpointing`` CPU-checkpoint trade — in host RAM, and the
engine uses the same memory kind for optimizer-moment placement.

Platform reality: TPU exposes ``pinned_host`` next to ``device``; the
CPU backend has a SINGLE memory space (``unpinned_host`` is the default
memory), so there the transfer is an identity and ``available()`` is
False — callers gate structural assertions on it and 'auto' knobs
resolve off.
"""

import functools

import jax

from ...utils.logging import logger

try:                                    # jax >= 0.6 exports it publicly
    from jax.sharding import TransferToMemoryKind as _TransferToMemoryKind
except ImportError:                     # legacy jax (0.4.x dev container)
    try:
        from jax._src.sharding_impls import TransferToMemoryKind \
            as _TransferToMemoryKind
    except ImportError:                 # no memory-kind support at all
        _TransferToMemoryKind = None


@functools.lru_cache(maxsize=None)
def memory_kinds():
    """(default_kind, host_kind): the default device memory kind and the
    best host-side kind, or (None, None) when the backend predates
    memory spaces. Cached — backend memories are fixed per process."""
    try:
        dev = jax.devices()[0]
        default = dev.default_memory().kind
        kinds = {m.kind for m in dev.addressable_memories()}
    except Exception:  # noqa: BLE001 - legacy backends lack the API
        return None, None
    for host in ("pinned_host", "unpinned_host"):
        if host in kinds and host != default:
            return default, host
    return default, None


def host_memory_kind():
    """The host memory kind offload targets, or None when the platform
    has a single memory space (offload degenerates to identity)."""
    return memory_kinds()[1]


def available():
    """True iff host staging actually moves bytes on this backend."""
    return _TransferToMemoryKind is not None \
        and host_memory_kind() is not None


def to_host(x):
    """Stage ``x`` into host memory (identity when the platform has no
    distinct host space — the CPU test mesh). Usable inside jit and
    inside shard_map manual regions (memory kinds are orthogonal to
    sharding)."""
    kind = host_memory_kind()
    if kind is None or _TransferToMemoryKind is None:
        return x
    return jax.device_put(x, _TransferToMemoryKind(kind))


def to_device(x):
    """Bring a host-staged value back to device memory (identity when
    staging is unavailable)."""
    default, host = memory_kinds()
    if host is None or _TransferToMemoryKind is None:
        return x
    return jax.device_put(x, _TransferToMemoryKind(default))


def with_host_memory_kind(sharding):
    """``sharding`` re-targeted at the host memory kind (for optimizer
    moments and other engine-owned state); the original sharding when
    staging is unavailable (with a one-time note, not an error — the
    knob is advisory on single-memory-space platforms)."""
    kind = host_memory_kind()
    if kind is None:
        _warn_unavailable()
        return sharding
    return sharding.with_memory_kind(kind)


_warned = False


def _warn_unavailable():
    global _warned
    if not _warned:
        _warned = True
        logger.warning(
            "host offload requested but this backend exposes a single "
            "memory space (no distinct host memory kind); offload "
            "annotations degrade to identity")
