"""Host<->disk tensor swapping over the async-IO pool.

Counterpart of reference ``runtime/swap_tensor/`` (AsyncTensorSwapper in
async_swapper.py, partitioned_optimizer_swapper.py /
partitioned_param_swapper.py backed by the AIO op): spill tensors that
don't fit to NVMe and bring them back on demand, overlapping the file IO
with compute. On TPU the swap targets HOST staging (device arrays are
fetched with ``jax.device_get`` first — the VELOC-style D2H hop), so this
layer serves optimizer-state offload, parameter banks for serving, and
checkpoint staging.
"""

import json
import os

import numpy as np
import jax


class AsyncTensorSwapper:
    """swap_out(key, array) -> async file write; swap_in(key) -> array.
    ``wait()`` drains writes; reads are synchronous (the caller needs the
    data) unless ``async_=True`` (then ``wait_in(key)`` finalizes)."""

    def __init__(self, swap_dir, num_threads=4, block_size=1 << 20,
                 fsync=False):
        from ...ops.native.aio import AsyncIOHandle
        self.dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.aio = AsyncIOHandle(block_size=block_size,
                                 num_threads=num_threads)
        self.fsync = fsync
        self._meta = {}      # key -> (shape, dtype str)
        self._out_reqs = {}  # key -> req id
        self._in_reqs = {}   # key -> (req id, buffer)

    def _path(self, key):
        safe = str(key).replace("/", "%2F")
        return os.path.join(self.dir, f"{safe}.bin")

    # ---------------------------------------------------------------- out
    def swap_out(self, key, array, blocking=False):
        """array: numpy or jax array (device arrays are fetched to host
        first). The host buffer is pinned by the aio handle until wait.
        A still-inflight write to the same key is drained first (two
        O_TRUNC writers on one path would interleave)."""
        self.wait(key)
        arr = np.ascontiguousarray(jax.device_get(array))
        self._meta[key] = (arr.shape, str(arr.dtype))
        if blocking:
            self.aio.sync_pwrite(arr, self._path(key), fsync=self.fsync)
        else:
            self._out_reqs[key] = self.aio.async_pwrite(
                arr, self._path(key), fsync=self.fsync)
        return key

    def wait(self, key=None):
        """Drain pending swap-outs (one key or all)."""
        keys = [key] if key is not None else list(self._out_reqs)
        for k in keys:
            req = self._out_reqs.pop(k, None)
            if req is not None:
                self.aio.wait(req)
        return True

    # ----------------------------------------------------------------- in
    def swap_in(self, key, async_=False):
        shape, dtype = self._meta[key]
        buf = np.empty(shape, np.dtype(dtype))
        self.wait(key)  # a pending write to the same key must land first
        if async_:
            self._in_reqs[key] = (self.aio.async_pread(
                buf, self._path(key)), buf)
            return None
        self.aio.sync_pread(buf, self._path(key))
        return buf

    def wait_in(self, key):
        req, buf = self._in_reqs.pop(key)
        self.aio.wait(req)
        return buf

    def keys(self):
        return list(self._meta)

    def remove(self, key):
        self.wait(key)
        self._meta.pop(key, None)
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def close(self):
        self.wait()
        self.aio.close()


def _skeleton(tree, metas):
    """Pytree (dict/list/tuple of arrays) -> JSON-able skeleton whose
    leaves are {"__leaf__": i}; metas collects (shape, dtype) per leaf in
    traversal order. Supports the containers json can round-trip."""
    if isinstance(tree, dict):
        return {k: _skeleton(tree[k], metas) for k in sorted(tree)}
    if isinstance(tree, (list, tuple)):
        return [_skeleton(v, metas) for v in tree]
    arr = np.asarray(tree)
    metas.append((list(arr.shape), str(arr.dtype)))
    return {"__leaf__": len(metas) - 1}


def _from_skeleton(skel, leaves):
    if isinstance(skel, dict):
        if "__leaf__" in skel:
            return leaves[skel["__leaf__"]]
        return {k: _from_skeleton(v, leaves) for k, v in skel.items()}
    return [_from_skeleton(v, leaves) for v in skel]


class OptimizerStateSwapper:
    """Swap whole optimizer-state pytrees (reference
    partitioned_optimizer_swapper.py role): ``swap_out_tree(key, tree)``
    writes every leaf (async) + a json manifest carrying the tree
    skeleton and per-leaf shape/dtype, so ``swap_in_tree`` restores in a
    FRESH process (crash/restart is the point of offload). Trees must be
    dict/list/tuple containers (json-representable); tuples come back as
    lists."""

    def __init__(self, swap_dir, **kw):
        # the durable manifest certifies leaf data: leaves must reach the
        # platter, so fsync defaults ON here (unlike the raw swapper)
        kw.setdefault("fsync", True)
        self.swapper = AsyncTensorSwapper(swap_dir, **kw)
        self.dir = swap_dir

    def _manifest(self, key):
        return os.path.join(self.dir, f"{key}.manifest.json")

    def swap_out_tree(self, key, tree, blocking=False):
        """blocking=False overlaps the NVMe writes with caller compute;
        the durable manifest is deferred until ``wait()`` (or the next
        swap_in of the key), so it always lands AFTER its leaf data —
        a crash before wait() leaves the previous manifest intact."""
        tree = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), tree)
        metas = []
        skel = _skeleton(tree, metas)
        leaves = []
        _collect_leaves(tree, leaves)
        names = [f"{key}.{i}" for i in range(len(leaves))]
        for name, leaf in zip(names, leaves):
            self.swapper.swap_out(name, leaf, blocking=blocking)
        self._pending = getattr(self, "_pending", {})
        self._pending[key] = {"names": names, "skeleton": skel,
                              "metas": metas}
        if blocking:
            self._finalize(key)
        return key

    def _finalize(self, key):
        m = self._pending.pop(key, None)
        if m is None:
            return
        for name in m["names"]:
            self.swapper.wait(name)
        tmp = self._manifest(key) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(m, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest(key))

    def swap_in_tree(self, key):
        if key in getattr(self, "_pending", {}):
            self._finalize(key)
        with open(self._manifest(key)) as f:
            m = json.load(f)
        leaves = []
        for name, (shape, dtype) in zip(m["names"], m["metas"]):
            # restore swapper metadata for fresh processes
            self.swapper._meta[name] = (tuple(shape), dtype)
            leaves.append(self.swapper.swap_in(name))
        return _from_skeleton(m["skeleton"], leaves)

    def wait(self):
        for key in list(getattr(self, "_pending", {})):
            self._finalize(key)
        return self.swapper.wait()

    def close(self):
        self.wait()
        self.swapper.close()


def _collect_leaves(tree, out):
    """Leaf order matching _skeleton (sorted dict keys)."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            _collect_leaves(tree[k], out)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            _collect_leaves(v, out)
    else:
        out.append(np.asarray(tree))
