"""MoQ (Mixture of Quantization) schedule.

Counterpart of reference ``runtime/quantize.py`` (Quantizer driving
quantize-aware training with a decreasing bit width, optionally modulated
by Hessian eigenvalues): tracks the current target bits from
``start_bits`` down to ``target_bits`` every ``quantize_period`` steps
(doubling periods, reference semantics), and exposes ``quantize(tree)``
applying symmetric fake quantization at the current precision via the
compression ops.
"""

import jax

from ..compression import ops as cops


class Quantizer:
    def __init__(self, q_target_bits=8, q_start_bits=16, q_period=100,
                 q_rounding="nearest", use_quantizer_kernel=False,
                 eigenvalue_enabled=False, layer_keys=None):
        self.target_bits = q_target_bits
        self.start_bits = q_start_bits
        self.period = q_period
        self.rounding = q_rounding
        self.eigenvalue_enabled = eigenvalue_enabled
        self.layer_keys = layer_keys or []
        self.current_bits = q_start_bits
        self._next_change = q_period

    def update(self, global_step, eigenvalues=None):
        """Advance the schedule; with eigenvalues (dict from
        runtime/eigenvalue.py) sharp (high-curvature) layers keep high
        precision LONGER — the reference stretches the period by
        ``1 + floor(eigenvalue * 4)`` (quantize.py:70)."""
        period = self.period
        if self.eigenvalue_enabled and eigenvalues:
            mean_eig = sum(eigenvalues.values()) / len(eigenvalues)
            if mean_eig > 0:
                period = int(self.period * (1 + int(mean_eig * 4)))
        if (global_step >= self._next_change
                and self.current_bits > self.target_bits):
            self.current_bits -= 1
            self._next_change = global_step + period * 2 ** (
                self.start_bits - self.current_bits)
        return self.current_bits

    def quantize(self, tree, bits=None):
        bits = bits or self.current_bits
        if bits >= 16:
            return tree
        return jax.tree.map(
            lambda x: cops.quantize_weight(x, bits=bits)
            if getattr(x, "ndim", 0) >= 2 else x, tree)

    def state_dict(self):
        return {"current_bits": self.current_bits,
                "next_change": self._next_change}

    def load_state_dict(self, sd):
        self.current_bits = sd["current_bits"]
        self._next_change = sd["next_change"]
