"""ZeRO stages as sharding plans.

The heart of the reference is 10k+ lines of hook-driven bucketing
(zero/stage_1_and_2.py:98, zero/stage3.py:75, partition_parameters.py:780).
On TPU the same *memory states* are expressed as sharding specs and the
collectives fall out of GSPMD:

  stage 0: params/master/opt replicated over DP; grads allreduced.
  stage 1: optimizer state + fp32 master partitioned over the DP axes
           (reference: bit16_groups_flat partitions, stage_1_and_2.py:1575).
           Grads allreduce, each shard updates its partition, params
           re-materialize replicated (the step-end allgather,
           stage_1_and_2.py:1815).
  stage 2: + gradients partitioned: the grad->master path is constrained
           to the partitioned spec so XLA lowers the backward reduction to
           reduce_scatter instead of all_reduce (reference
           reduce_independent_p_g_buckets_and_remove_grads:926).
  stage 3: + bf16 params partitioned; forward/backward all_gathers emerge
           where GSPMD needs full weights, freed after use — the
           declarative form of PartitionedParameterCoordinator
           fetch/release (partitioned_param_coordinator.py:261,395).

A "partition" here = sharding a leaf along its first dimension divisible by
the partition count and not already sharded (the reference flattens to 1-D
and pads instead: runtime/utils.py partition helpers; dimension-sharding
keeps XLA layouts natural and avoids materializing a flat copy).

MiCS / ZeRO++ hpZ (zero/mics.py:64, utils/groups.py:505) map to partitioning
over the INNER data axes so params replicate across 'data_outer' (slice
boundaries): the engine passes ``partition_axes=INNER_DP_AXES``
(('data','expert')) for MiCS, or ``param_partition_axes=INNER_DP_AXES``
for hpZ's stage-3 secondary param shard while master/opt stay on the full
DP_AXES (('data_outer','data','expert')).
"""

from jax.sharding import PartitionSpec as P

from ...utils.groups import DP_AXES


def _axes_size(mesh, axes):
    n = 1
    for a in (axes if isinstance(axes, tuple) else (axes,)):
        n *= mesh.shape[a]
    return n


def _used_axes(spec):
    used = set()
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            used.add(a)
    return used


def add_partition_axis(shape, base_spec, axes, mesh):
    """Return base_spec with ``axes`` added on the LAST eligible dim:
    divisible by the partition count, not already sharded. Last (not first)
    because models stack layers on dim 0 and ``lax.scan`` slices that dim
    each iteration — partitioning an inner dim makes stage-3 materialize one
    layer per scan step (the fetch/release pattern) instead of re-gathering
    the whole stack. Axes already present in the spec are dropped from the
    partition group (e.g. expert weights TP/EP-sharded on 'expert' partition
    over 'data' only — the reference's expert-DP group,
    utils/groups.py:331). Falls back to the unmodified spec (replicated) —
    the reference similarly keeps small tensors whole below
    param_persistence_threshold."""
    spec = list(base_spec) + [None] * (len(shape) - len(base_spec))
    used = _used_axes(spec)
    ax_tuple = tuple(a for a in
                     (axes if isinstance(axes, tuple) else (axes,))
                     if a not in used)
    count = _axes_size(mesh, ax_tuple) if ax_tuple else 1
    if count == 1:
        return P(*spec) if spec else base_spec
    for dim in reversed(range(len(shape))):
        if spec[dim] is None and shape[dim] % count == 0 and shape[dim] >= count:
            spec[dim] = ax_tuple if len(ax_tuple) > 1 else ax_tuple[0]
            return P(*spec)
    return P(*spec)


class ZeroShardingPlan:
    """Computes param/master/grad sharding specs for a model + mesh."""

    def __init__(self, stage, mesh, tp_specs, shapes,
                 partition_axes=DP_AXES, param_partition_axes=None):
        """tp_specs/shapes: pytrees (same structure) of PartitionSpec and
        shape tuples. partition_axes: mesh axes forming the ZeRO partition
        group for master/optimizer/grads (full DP group by default; the
        inner INNER_DP_AXES for MiCS plans — replicating over 'data_outer'
        like MiCS replicates across sub-groups). param_partition_axes:
        override for the stage-3 bf16 param shard (hpZ/ZeRO++ secondary
        partition: params shard intra-slice so forward allgathers ride ICI
        while optimizer state stays partitioned over all of DP)."""
        import jax
        self.stage = stage
        self.mesh = mesh
        self.partition_axes = partition_axes
        self.param_partition_axes = param_partition_axes or partition_axes

        def partitioned(axes):
            def f(spec, shape):
                return add_partition_axis(shape, spec, axes, mesh)
            return f

        is_spec = lambda x: isinstance(x, P)
        # bf16 params: partitioned only at stage 3
        self.param_specs = (
            jax.tree.map(partitioned(self.param_partition_axes), tp_specs,
                         shapes, is_leaf=is_spec)
            if stage >= 3 else tp_specs)
        # fp32 master + optimizer state: partitioned from stage 1
        self.master_specs = (
            jax.tree.map(partitioned(partition_axes), tp_specs, shapes,
                         is_leaf=is_spec)
            if stage >= 1 else tp_specs)
        # gradients: partitioned (reduce-scatter) from stage 2
        self.grad_specs = self.master_specs if stage >= 2 else tp_specs

    def shardings(self, which):
        import jax
        from jax.sharding import NamedSharding
        specs = {"param": self.param_specs, "master": self.master_specs,
                 "grad": self.grad_specs}[which]
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs,
                            is_leaf=lambda x: isinstance(x, P))

    def cross_slice_replica(self):
        """True when this plan's master/opt partition REPLICATES over a
        non-trivial ``data_outer`` axis (the MiCS shape: shard over
        INNER_DP_AXES, replicate across slices). That replica is the
        robustness half of ROADMAP item 2 — a full copy of master/opt
        state resident in every slice's HBM, which the checkpoint hot
        tier registers as the ``zero-replica`` restore source so a
        surviving slice can restore without its dead sibling."""
        if "data_outer" not in self.mesh.axis_names:
            return False
        return (self.stage >= 1
                and "data_outer" not in self.partition_axes
                and int(self.mesh.shape["data_outer"]) > 1)

    def describe(self):
        """JSON-able summary of the plan: stage, partition group sizes,
        and the master-partition spec per leaf path. Saved into every
        checkpoint's metadata — NOT consumed on load (specs are always
        re-derived from the model + current mesh, the
        ``match_partition_rules`` discipline: resume must be
        topology-independent end to end) — but it lets
        :func:`reshape_diff` report exactly which leaves re-partition
        when a checkpoint lands on a different mesh."""
        import jax
        leaves = {}
        for path, spec in jax.tree.leaves_with_path(
                self.master_specs, is_leaf=lambda x: isinstance(x, P)):
            key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                           for p in path)
            leaves[key] = [list(e) if isinstance(e, tuple) else e
                           for e in spec]
        return {
            "stage": self.stage,
            "partition_axes": list(self.partition_axes),
            "partition_group": _axes_size(self.mesh, self.partition_axes),
            "mesh_shape": {a: int(self.mesh.shape[a])
                           for a in self.mesh.axis_names},
            "master_specs": leaves,
        }


def reshape_diff(saved_desc, plan):
    """Compare a checkpoint's recorded plan description against the plan
    the CURRENT topology derived. -> dict with the leaves whose
    partitioning changed ('resharded'), the leaves the new mesh cannot
    partition and replicates instead ('replicated'), and the old/new
    partition-group sizes. Purely diagnostic: the load path re-shards
    from global logical tensors regardless; this tells the operator what
    the reshape actually did (and a test what it MUST do)."""
    new_desc = plan.describe()
    old_specs = (saved_desc or {}).get("master_specs", {})
    resharded, replicated = [], []
    for key, new_spec in new_desc["master_specs"].items():
        old_spec = old_specs.get(key)
        if old_spec is not None and old_spec != new_spec:
            resharded.append(key)
        if plan.stage >= 1 and all(e is None for e in new_spec):
            replicated.append(key)
    return {
        "resharded": sorted(resharded),
        "replicated": sorted(replicated),
        "old_partition_group": (saved_desc or {}).get("partition_group"),
        "new_partition_group": new_desc["partition_group"],
        "old_stage": (saved_desc or {}).get("stage"),
        "new_stage": new_desc["stage"],
    }
