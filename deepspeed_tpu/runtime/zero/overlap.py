"""Communication overlap for ZeRO — collectives hidden under compute.

Counterpart of the reference's ``overlap_comm`` machinery (stage_1_and_2.py
reduce_independent_p_g_buckets_and_remove_grads:926 — per-bucket async
reduce during backward; partitioned_param_coordinator.py:261 __all_gather
prefetch) and the ZeRO++ hierarchical collectives (utils/groups.py:505).
Where the reference owns CUDA streams and fires NCCL ops from grad hooks,
here the SAME schedule is obtained declaratively, in three layers:

1. **XLA flags** (`xla_overlap_flags`): the latency-hiding scheduler and
   async-collective-fusion flags make XLA split every collective into
   ``*-start``/``*-done`` pairs and slide compute between them; the
   backward all-gather pipelining pass double-buffers in-loop gathers
   across scan iterations (the ZeRO-3 prefetch engine, in the compiler).
   Flags must land *before* backend init — the engine applies them when
   it can, and ``DSTPU_COMM_OVERLAP=1`` applies them at
   ``import deepspeed_tpu`` time for launcher/bench paths. Channel and
   gating are platform-dependent (`overlap_env_var`): ``--xla_tpu_*``
   flags live only in libtpu's own flag registry — host-side
   ``XLA_FLAGS`` parsing FATALs on them (and on any name outside the
   DebugOptions proto) — so the TPU set rides ``LIBTPU_INIT_ARGS`` (the
   channel bench.py already uses for ``xla_tpu_scoped_vmem_limit_kib``)
   while the GPU set, whose names are proto-resident, rides
   ``XLA_FLAGS``. Off TPU/GPU no flags are emitted at all.

2. **Per-layer gradient reduction** (`make_layer_comm_hook`): a
   ``custom_vjp`` identity wrapped around each scanned layer's params.
   Its backward constrains the layer's cotangent to the per-layer ZeRO
   grad sharding, which forces GSPMD to emit that layer's reduce-scatter
   INSIDE the backward scan body — grad comm for layer i overlaps
   backward compute of layer i-1 — instead of one monolithic reduction
   of the stacked (L, ...) tree after the loop. ``bucket_bytes`` gates
   which layers get an in-scan collective (small layers coalesce into
   the post-loop reduction, the reference's bucket semantics). With
   ``hierarchical``, the constraint is staged: inner ('data','expert')
   axes first (ICI reduce-scatter of the full payload), then the full
   spec including 'data_outer' (the DCN hop moves only the 1/W_inner
   scattered shard — MiCS/ZeRO++ two-stage). The forward optionally
   constrains the layer to its gathered (TP-only) spec — one explicit
   all-gather at the top of the scan body for ZeRO-3, the op the
   pipelining pass prefetches.

3. **HLO verification** (`overlap_report`): the schedule above is a
   *request*; this parses ``compiled.as_text()`` and reports what XLA
   actually emitted — collectives, ``*-start/*-done`` async pairs,
   which sit inside while (scan) bodies, and which mesh axes each
   collective's replica groups correspond to. CPU lowers collectives
   synchronously (no start/done in HLO), so async-pair assertions are
   only meaningful on TPU/GPU; placement and axis checks work anywhere.
"""

import os
import re

import numpy as np

from jax.sharding import PartitionSpec as P

from ...utils.logging import logger

# ---------------------------------------------------------------- XLA flags

# The v5e/v4 overlap set (latency-hiding scheduler + async collective
# fusion + data-parallel all-reduce optimization). Every flag is
# boolean-valued and safe at dp=1.
TPU_OVERLAP_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_tpu_enable_data_parallel_all_reduce_opt=true",
    "--xla_tpu_data_parallel_opt_different_sized_ops=true",
)
# ZeRO-3: rotate in-loop all-gathers across backward scan iterations
# (the compiler-level double buffer the prefetch hook's explicit gather
# feeds).
TPU_PREFETCH_FLAGS = (
    "--xla_tpu_enable_ag_backward_pipelining=true",
)
GPU_OVERLAP_FLAGS = (
    "--xla_gpu_enable_latency_hiding_scheduler=true",
)


def platform_guess():
    """Best-effort platform WITHOUT initializing the backend (reading
    jax.default_backend() would lock in the current XLA_FLAGS)."""
    plats = os.environ.get("JAX_PLATFORMS", "")
    if plats:
        return plats.split(",")[0].strip() or None
    import importlib.util
    if importlib.util.find_spec("libtpu") is not None:
        return "tpu"
    return None


def xla_overlap_flags(platform, prefetch=True, bucket_mb=0):
    """The flag list for ``platform`` (None/cpu -> no flags: names
    outside the host DebugOptions proto are fatal in XLA_FLAGS, and
    there is no scheduler to tune on CPU anyway)."""
    if platform == "tpu":
        flags = list(TPU_OVERLAP_FLAGS)
        if prefetch:
            flags += list(TPU_PREFETCH_FLAGS)
        return flags
    if platform in ("gpu", "cuda", "rocm"):
        flags = list(GPU_OVERLAP_FLAGS)
        if bucket_mb:
            nbytes = int(bucket_mb) * (1 << 20)
            flags += [
                f"--xla_gpu_all_reduce_combine_threshold_bytes={nbytes}",
                f"--xla_gpu_all_gather_combine_threshold_bytes={nbytes}",
                f"--xla_gpu_reduce_scatter_combine_threshold_bytes={nbytes}",
            ]
        return flags
    return []


def overlap_env_var(platform):
    """Which env var carries the overlap flags: ``--xla_tpu_*`` names
    exist only in libtpu's flag registry (host XLA_FLAGS parsing FATALs
    on them), so TPU rides LIBTPU_INIT_ARGS; GPU names are DebugOptions-
    proto-resident and ride XLA_FLAGS."""
    return "LIBTPU_INIT_ARGS" if platform == "tpu" else "XLA_FLAGS"


def backend_initialized():
    try:
        from jax._src import xla_bridge as xb
        return bool(getattr(xb, "_backends", None))
    except Exception:  # noqa: BLE001 - conservative: assume live
        return True


def apply_xla_flags(flags, env_var="XLA_FLAGS"):
    """Append ``flags`` to ``env_var`` (LIBTPU_INIT_ARGS for the TPU
    set, see ``overlap_env_var``) if the backend has not initialized
    yet. Returns (applied, reason) — never raises; flags that are
    already present count as applied."""
    if not flags:
        return True, "no flags for this platform"
    current = os.environ.get(env_var, "")
    have = {f.split("=")[0] for f in current.split()}
    missing = [f for f in flags if f.split("=")[0] not in have]
    if not missing:
        return True, f"already set in {env_var}"
    if backend_initialized():
        return False, ("backend already initialized; set "
                       "DSTPU_COMM_OVERLAP=1 before first device use")
    os.environ[env_var] = (current + " " + " ".join(missing)).strip()
    return True, f"appended {len(missing)} flags to {env_var}"


def apply_env_overlap_flags():
    """Import-time hook (deepspeed_tpu/__init__.py): DSTPU_COMM_OVERLAP=1
    applies the overlap flag set before anything touches the backend —
    the only reliable path for bench/launcher subprocesses."""
    if os.environ.get("DSTPU_COMM_OVERLAP") != "1":
        return False
    platform = platform_guess()
    flags = xla_overlap_flags(
        platform,
        prefetch=os.environ.get("DSTPU_COMM_PREFETCH", "1") == "1",
        bucket_mb=int(os.environ.get("DSTPU_COMM_BUCKET_MB", "0") or 0))
    applied, reason = apply_xla_flags(flags, overlap_env_var(platform))
    if flags and not applied:
        logger.warning(f"comm_overlap env flags not applied: {reason}")
    return applied


# ------------------------------------------------------ per-layer specs

SKIP = "skip"  # sentinel leaf: annotator leaves this one to XLA


def drop_layer_dim(spec):
    """Per-layer spec from a stacked (L, ...) leaf spec. The scan slices
    dim 0; a spec that shards dim 0 cannot be expressed per-layer ->
    SKIP."""
    entries = list(spec)
    if entries and entries[0] is not None:
        return SKIP
    return P(*entries[1:])


def split_inner(spec, outer_axis="data_outer"):
    """Spec with ``outer_axis`` removed from every entry — stage 1 of the
    hierarchical reduction (constrain here first: GSPMD reduce-scatters
    over the remaining inner axes on ICI; the later full-spec constraint
    adds only the small cross-slice hop). Returns SKIP if the spec never
    mentions outer_axis (nothing to stage)."""
    if spec == SKIP:
        return SKIP
    out, changed = [], False
    for e in spec:
        if isinstance(e, tuple) and outer_axis in e:
            rest = tuple(a for a in e if a != outer_axis)
            out.append(rest if len(rest) > 1 else
                       (rest[0] if rest else None))
            changed = True
        elif e == outer_axis:
            out.append(None)
            changed = True
        else:
            out.append(e)
    return P(*out) if changed else SKIP


def _is_spec_leaf(x):
    return isinstance(x, P) or x == SKIP


def layer_grad_bytes(layer_tree, gdtype=None):
    """Static per-layer gradient payload (bytes) — the bucket gate."""
    import jax
    import jax.numpy as jnp
    itemsize = (jnp.dtype(gdtype).itemsize if gdtype is not None else None)
    total = 0
    for leaf in jax.tree.leaves(layer_tree):
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        total += n * (itemsize if itemsize is not None
                      else leaf.dtype.itemsize)
    return total


def make_layer_comm_hook(grad_specs, *, gather_specs=None,
                         hierarchical=False, outer_axis="data_outer",
                         dcn_quantize=False, bucket_bytes=0, gdtype=None):
    """Build the per-layer annotation hook the engine installs on the
    model (``model._layer_comm_hook``); the model calls it on each
    scanned layer's param slice (gpt2.block_forward).

    grad_specs / gather_specs: pytrees of PER-LAYER PartitionSpec (or
    SKIP), structurally matching one layer's param tree. Forward:
    constrain to gather_specs (the explicit ZeRO-3 all-gather). Backward:
    constrain the cotangent to grad_specs — staged via ``split_inner``
    when hierarchical — forcing the per-scan-iteration reduce-scatter.
    Specs are plain PartitionSpecs resolved against the ambient mesh
    (the engine traces under ``jax.set_mesh``).
    """
    import jax

    inner_specs = (jax.tree.map(
        lambda s: split_inner(s, outer_axis), grad_specs,
        is_leaf=_is_spec_leaf) if hierarchical else None)
    if dcn_quantize and inner_specs is None:
        # no hierarchical stage -> no DCN hop to compress: clamping the
        # full local cotangent would be silent precision loss for zero
        # bandwidth benefit
        logger.warning("comm_overlap.dcn_quantize ignored: no "
                       "hierarchical data_outer stage on this mesh")
        dcn_quantize = False

    def _constrain(tree, specs):
        def leaf(s, x):
            if s == SKIP:
                return x
            return jax.lax.with_sharding_constraint(x, s)
        return jax.tree.map(leaf, specs, tree, is_leaf=_is_spec_leaf)

    def should_annotate(layer_tree):
        """Static bucket gate: small layers skip the in-scan collective
        (they coalesce into the post-backward reduction instead — the
        reference never fires a reduce below its bucket size either)."""
        return (not bucket_bytes
                or layer_grad_bytes(layer_tree, gdtype) >= bucket_bytes)

    @jax.custom_vjp
    def annotate(layer):
        return (_constrain(layer, gather_specs)
                if gather_specs is not None else layer)

    def fwd(layer):
        return annotate(layer), None

    def bwd(_, g):
        if inner_specs is not None:
            # stage 1: ICI reduce-scatter of the full payload
            g = _constrain(g, inner_specs)
            if dcn_quantize:
                # qgZ placement: clamp the inner-reduced shard feeding
                # the DCN hop — only leaves that actually HAVE a
                # data_outer stage (inner spec != SKIP); without a
                # hierarchical stage there is no DCN wire and the clamp
                # would be pure precision loss (the factory drops it,
                # see below)
                from ...comm.quantized import dcn_precision_clamp

                def clamp(s, x):
                    return x if s == SKIP else dcn_precision_clamp(x)
                g = jax.tree.map(clamp, inner_specs, g,
                                 is_leaf=_is_spec_leaf)
        # final (or only) stage: the full ZeRO grad partition; under
        # hierarchical this adds just the cross-DCN hop of the shard
        g = _constrain(g, grad_specs)
        return (g,)

    annotate.defvjp(fwd, bwd)

    def hook(layer):
        if not should_annotate(layer):
            return (_constrain(layer, gather_specs)
                    if gather_specs is not None else layer)
        return annotate(layer)

    hook.should_annotate = should_annotate  # exposed for tests
    return hook


# ------------------------------------------------------- HLO inspection

_COLL_OPS = ("all-reduce", "reduce-scatter", "all-gather", "all-to-all",
             "collective-permute")
# '%name = TYPE opcode(' — opcode may carry -start/-done and .N
# suffixes; TYPE may be a tuple (async start shapes) so anything between
# '=' and the first 'opcode(' is skipped lazily
_COLL_LINE_RE = re.compile(
    r"%[\w.\-]+\s*=\s*.*?\s"
    r"(all-reduce|reduce-scatter|all-gather|all-to-all|collective-permute)"
    r"(-start|-done)?(?:\.\d+)?\(")
# computation header: '%name (params...) -> ret {' (params nest parens,
# so only the leading '%name (' — instruction lines have '= ' after the
# name and never match)
_COMP_RE = re.compile(r"^\s*(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_WHILE_BODY_RE = re.compile(r"\bbody=%([\w.\-]+)")
_RG_EXPLICIT_RE = re.compile(r"replica_groups=\{(\{[\d,{} ]*\})\}")
_RG_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")


def parse_replica_groups(line):
    """Replica groups from one HLO line -> list of tuples of device ids,
    handling both the explicit ``{{0,1},{2,3}}`` and the iota
    ``[G,S]<=[dims]T(perm)`` forms. None if the line carries none."""
    m = _RG_IOTA_RE.search(line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims))).reshape(dims)
        if m.group(4):
            perm = [int(x) for x in m.group(4).split(",")]
            ids = ids.transpose(perm)
        return [tuple(int(x) for x in row)
                for row in ids.reshape(g, s)]
    m = _RG_EXPLICIT_RE.search(line)
    if m:
        return [tuple(int(x) for x in grp.split(",") if x.strip())
                for grp in re.findall(r"\{([\d, ]*)\}", m.group(1))]
    return None


def parse_collectives(hlo_text):
    """All collective ops in an HLO module text. Returns a list of dicts:
    {op, phase ('start'|'done'|None), computation, groups, line}."""
    out = []
    bodies = set()
    cur = None
    for line in hlo_text.splitlines():
        mc = _COMP_RE.match(line)
        if mc and line.rstrip().endswith("{"):
            cur = mc.group(1)
        for mb in _WHILE_BODY_RE.finditer(line):
            bodies.add(mb.group(1))
        m = _COLL_LINE_RE.search(line)
        if m:
            out.append({
                "op": m.group(1),
                "phase": (m.group(2) or "").lstrip("-") or None,
                "computation": cur,
                "groups": parse_replica_groups(line),
                "line": line.strip(),
            })
    for c in out:
        c["in_loop"] = c["computation"] in bodies
    return out


# host-staging copies: the XLA host-offload pass legalizes memory-kind
# transfers (pipeline activation rings, moment placement) into
# copy-start/copy-done pairs whose shapes carry the host memory space
# marker S(5). CPU has a single memory space, so these only appear on
# TPU/GPU programs.
_COPY_LINE_RE = re.compile(
    r"%[\w.\-]+\s*=\s*.*?\scopy(-start|-done)?(?:\.\d+)?\(")
_HOST_SPACE_RE = re.compile(r"S\(5\)")


def parse_host_copies(hlo_text):
    """Copy ops whose shapes carry the host memory space (S(5)) — the
    staging traffic host offload generates. Returns dicts
    {phase, computation, in_loop, line} like parse_collectives."""
    out = []
    bodies = set()
    cur = None
    for line in hlo_text.splitlines():
        mc = _COMP_RE.match(line)
        if mc and line.rstrip().endswith("{"):
            cur = mc.group(1)
        for mb in _WHILE_BODY_RE.finditer(line):
            bodies.add(mb.group(1))
        m = _COPY_LINE_RE.search(line)
        if m and _HOST_SPACE_RE.search(line):
            out.append({
                "phase": (m.group(1) or "").lstrip("-") or None,
                "computation": cur,
                "line": line.strip(),
            })
    for c in out:
        c["in_loop"] = c["computation"] in bodies
    return out


def count_async_pairs(collectives):
    """Matched ``*-start``/``*-done`` pairs per collective op kind."""
    pairs = 0
    for op in _COLL_OPS:
        starts = sum(1 for c in collectives
                     if c["op"] == op and c["phase"] == "start")
        dones = sum(1 for c in collectives
                    if c["op"] == op and c["phase"] == "done")
        pairs += min(starts, dones)
    return pairs


def expected_axis_groups(mesh, axes):
    """The replica-group partition a collective over mesh ``axes`` uses:
    a set of frozensets of device ids."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    names = list(mesh.axis_names)
    ids = np.asarray(
        [d.id for d in mesh.devices.flat]).reshape(mesh.devices.shape)
    perm = ([names.index(a) for a in names if a not in axes]
            + [names.index(a) for a in axes])
    size = int(np.prod([mesh.shape[a] for a in axes]))
    rows = ids.transpose(perm).reshape(-1, size)
    return {frozenset(int(x) for x in row) for row in rows}


def match_axes(groups, mesh):
    """Which mesh axes a collective's replica groups correspond to.
    Tries each single axis plus the canonical DP combinations; returns
    the first (smallest) matching axis tuple or None."""
    if not groups:
        return None
    got = {frozenset(g) for g in groups}
    from ...utils.groups import (DP_AXES, INNER_DP_AXES, EXPERT_DP_AXES,
                                 GRAD_REDUCE_AXES)
    candidates = ([(a,) for a in mesh.axis_names]
                  + [INNER_DP_AXES, EXPERT_DP_AXES, DP_AXES,
                     GRAD_REDUCE_AXES, tuple(mesh.axis_names)])
    for axes in candidates:
        try:
            if expected_axis_groups(mesh, axes) == got:
                return axes
        except KeyError:
            continue
    return None


def overlap_report(hlo_text, mesh=None):
    """Summarize a compiled module's collective schedule: counts, async
    start/done pairs, in-(scan)-loop placement, and per-collective mesh
    axes (when ``mesh`` is given). The dict the engine's
    ``verify_comm_overlap`` returns and the tier-1 HLO tests assert on."""
    colls = parse_collectives(hlo_text)
    axes = []
    for c in colls:
        c["axes"] = (match_axes(c["groups"], mesh)
                     if mesh is not None else None)
        if c["axes"]:
            axes.append(c["axes"])
    in_loop_by_op = {}
    for c in colls:
        if c["in_loop"]:
            in_loop_by_op[c["op"]] = in_loop_by_op.get(c["op"], 0) + 1
    # host staging traffic (pipeline ring offload / moment placement):
    # S(5)-space copies, async pairs counted like the collectives
    copies = parse_host_copies(hlo_text)
    copy_starts = sum(1 for c in copies if c["phase"] == "start")
    copy_dones = sum(1 for c in copies if c["phase"] == "done")
    return {
        "n_collectives": len(colls),
        "async_pairs": count_async_pairs(colls),
        "in_loop": sum(1 for c in colls if c["in_loop"]),
        # per-op in-(scan)-loop counts: a ring-attention step reports its
        # KV rotation here as 'collective-permute' (engine
        # verify_comm_overlap's acceptance signal for the overlap); a
        # pipelined step its stage rotation
        "in_loop_by_op": in_loop_by_op,
        "ops": sorted({c["op"] for c in colls}),
        "axes": sorted({tuple(a) for a in axes}),
        "host_copies": len(copies),
        "host_copy_async_pairs": min(copy_starts, copy_dones),
        "in_loop_host_copies": sum(1 for c in copies if c["in_loop"]),
        "collectives": colls,
    }
