"""ZeRO-Offload: optimizer state in host RAM (optionally tiered to NVMe),
stepped by the native C++ CPU Adam.

Counterpart of the reference CPU-offload paths:
  * ``runtime/zero/stage_1_and_2.py:1181`` (async_accumulate_grad_in_cpu_
    via_gpu) + ``ops/adam/cpu_adam.py:13 DeepSpeedCPUAdam`` — device
    computes grads, host owns fp32 master + Adam moments and steps them.
  * ``runtime/zero/stage3.py:584`` (_configure_tensor_swapping) — optimizer
    and param state tiered to NVMe through the AIO pool
    (partitioned/pipelined_optimizer_swapper, partitioned_param_swapper).

TPU-first shape of the same capability: the jitted device program computes
loss + clipped, unscaled fp32 grads and an overflow flag; grads land on the
host (the D2H hop the reference does with cudaMemcpyAsync), the C++ worker
pool (csrc/cpu_adam.cpp) steps each leaf in place, and the refreshed bf16
params are pushed back to the device sharding leaf-by-leaf. Device memory
holds ONLY bf16 params (+ transient grads): the 12 bytes/param of
master+m+v move to host RAM. With ``offload_optimizer.device='nvme'`` the
m/v moments stream from disk (leaf i+1 prefetching under leaf i's CPU
step — the reference's pipelined_optimizer_swapper); with
``offload_param.device='nvme'`` the fp32 master streams too, so host RAM
holds one leaf's state at a time. The bf16 working params stay
device-resident: under XLA the per-layer gather the reference does for
NVMe params IS the ZeRO-3 scan-dim sharding, not a host round trip.
"""

import numpy as np
import jax

from ...utils.logging import log_dist


def _leaf_paths(tree, prefix=()):
    """Yield (path_tuple, leaf) pairs in deterministic (sorted-key) order
    (matches jax.tree.map's dict ordering)."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _leaf_paths(tree[k], prefix + (str(k),))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _leaf_paths(v, prefix + (str(i),))
    else:
        yield prefix, tree


def _get_path(tree, path):
    for p in path:
        tree = tree[int(p)] if isinstance(tree, (list, tuple)) else tree[p]
    return tree


class HostOffloadOptimizer:
    """Owns the fp32 master params + Adam moments off-device and applies
    the update with the native CPU Adam worker pool.

    step(host_grads, lr, on_leaf) walks the leaves; ``on_leaf(path,
    master_flat, shape)`` fires after each leaf's update so the caller
    can push the refreshed (bf16) leaf back to the device while the
    next leaf's NVMe reads are in flight."""

    def __init__(self, master_tree, opt_config, offload_opt_cfg,
                 offload_param_cfg=None, num_threads=8):
        from ...ops.native.cpu_adam import DeepSpeedCPUAdam
        params = dict(opt_config.params) if opt_config is not None else {}
        betas = tuple(params.get("betas", (0.9, 0.999)))
        typ = (opt_config.type if opt_config is not None else "AdamW").lower()
        if typ not in ("adam", "adamw", "fusedadam"):
            raise ValueError(
                f"offload_optimizer supports Adam/AdamW (got '{typ}') — the "
                "native CPU kernel is Adam-family (reference "
                "DeepSpeedCPUAdam)")
        adamw = typ == "adamw" or bool(params.get("adam_w_mode", True))
        self.adam = DeepSpeedCPUAdam(
            lr=float(params.get("lr", 1e-3)), betas=betas,
            eps=float(params.get("eps", 1e-8)),
            weight_decay=float(params.get("weight_decay", 0.0)),
            adamw_mode=adamw,
            bias_correction=bool(params.get("bias_correction", True)),
            num_threads=num_threads)
        self.state_nvme = offload_opt_cfg.device == "nvme"
        self.master_nvme = (offload_param_cfg is not None
                            and offload_param_cfg.device == "nvme")
        self._swapper = None
        if self.state_nvme or self.master_nvme:
            from ..swap_tensor.swapper import AsyncTensorSwapper
            path = (offload_opt_cfg.nvme_path if self.state_nvme
                    else offload_param_cfg.nvme_path)
            self._swapper = AsyncTensorSwapper(path)

        # copy=True: device_get hands back non-writeable views, and the
        # CPU Adam updates in place
        host = jax.tree.map(
            lambda x: np.array(x, np.float32, copy=True, order="C"),
            master_tree)
        self._shapes = {p: l.shape for p, l in _leaf_paths(host)}
        self._paths = list(self._shapes)
        n_total = sum(int(np.prod(s)) for s in self._shapes.values())

        if self.master_nvme:
            for path, leaf in _leaf_paths(host):
                self._swapper.swap_out(self._key(path, "w"), leaf.reshape(-1))
            self._swapper.wait()
            self.master = None
        else:
            self.master = host

        if self.state_nvme:
            # moments start as zeros on disk; streamed every step after
            for path, shape in self._shapes.items():
                z = np.zeros(int(np.prod(shape)), np.float32)
                self._swapper.swap_out(self._key(path, "m"), z)
                self._swapper.swap_out(self._key(path, "v"), z)
            self._swapper.wait()
            self.state = None
        else:
            self.state = {
                path: {"m": np.zeros(int(np.prod(shape)), np.float32),
                       "v": np.zeros(int(np.prod(shape)), np.float32)}
                for path, shape in self._shapes.items()}
        log_dist(
            f"offload_optimizer: host CPU Adam over {n_total / 1e6:.1f}M "
            f"params (moments: {'nvme' if self.state_nvme else 'host RAM'}, "
            f"master: {'nvme' if self.master_nvme else 'host RAM'})",
            ranks=[0])

    @staticmethod
    def _key(path, which):
        return "/".join(path) + "." + which

    # ------------------------------------------------------------- stepping
    def _prefetch(self, path):
        if self.state_nvme:
            self._swapper.swap_in(self._key(path, "m"), async_=True)
            self._swapper.swap_in(self._key(path, "v"), async_=True)
        if self.master_nvme:
            self._swapper.swap_in(self._key(path, "w"), async_=True)

    def step(self, host_grads, lr, on_leaf=None):
        """host_grads: pytree of np arrays (fp32 or bf16) matching the
        master structure. Applies Adam in place; calls ``on_leaf(path,
        master_flat, shape)`` after each leaf. Returns the master tree
        (None when the master is NVMe-tiered — consume leaves via
        on_leaf)."""
        self.adam.set_lr(float(lr))
        sw = self._swapper
        if sw is not None:
            self._prefetch(self._paths[0])
        for i, path in enumerate(self._paths):
            shape = self._shapes[path]
            if self.state_nvme:
                st = {"m": sw.wait_in(self._key(path, "m")),
                      "v": sw.wait_in(self._key(path, "v"))}
            else:
                st = self.state[path]
            if self.master_nvme:
                w = sw.wait_in(self._key(path, "w"))
            else:
                w = _get_path(self.master, path).reshape(-1)
            if sw is not None and i + 1 < len(self._paths):
                self._prefetch(self._paths[i + 1])
            g = np.asarray(_get_path(host_grads, path)).reshape(-1)
            self.adam.step(w, g, st, increment_step=(i == 0))
            if on_leaf is not None:
                on_leaf(path, w, shape)
            if self.state_nvme:
                sw.swap_out(self._key(path, "m"), st["m"])
                sw.swap_out(self._key(path, "v"), st["v"])
            if self.master_nvme:
                sw.swap_out(self._key(path, "w"), w)
        if sw is not None:
            sw.wait()
        return self.master

    # --------------------------------------------------------- checkpointing
    def master_tree(self):
        """Full fp32 master as a nested tree (reads from NVMe if tiered)."""
        def take(path):
            if self.master_nvme:
                flat = self._swapper.swap_in(self._key(path, "w"))
            else:
                flat = _get_path(self.master, path).reshape(-1)
            return flat.reshape(self._shapes[path]).copy()
        return self._map_structure(take)

    def state_tree(self):
        """{'step', 'm': tree, 'v': tree} mirroring the master structure —
        the checkpointable optimizer state (reads back from NVMe when
        tiered)."""
        def fetch(which):
            def take(path):
                if self.state_nvme:
                    flat = self._swapper.swap_in(self._key(path, which))
                else:
                    flat = self.state[path][which]
                return flat.reshape(self._shapes[path]).copy()
            return self._map_structure(take)
        return {"step": np.int32(self.adam.get_step()),
                "m": fetch("m"), "v": fetch("v")}

    def load_master_tree(self, tree):
        for path in self._paths:
            flat = np.ascontiguousarray(
                np.asarray(_get_path(tree, path), np.float32).reshape(-1))
            if self.master_nvme:
                self._swapper.swap_out(self._key(path, "w"), flat)
            else:
                _get_path(self.master, path).reshape(-1)[:] = flat
        if self.master_nvme:
            self._swapper.wait()

    def load_state_tree(self, tree):
        """Inverse of state_tree (call after load_master_tree)."""
        self.adam.set_step(int(tree.get("step", 0)))
        for which in ("m", "v"):
            for path in self._paths:
                flat = np.ascontiguousarray(np.asarray(
                    _get_path(tree[which], path), np.float32).reshape(-1))
                if self.state_nvme:
                    self._swapper.swap_out(self._key(path, which), flat)
                else:
                    self.state[path][which][:] = flat
        if self.state_nvme:
            self._swapper.wait()

    def _map_structure(self, take):
        """Rebuild the nested master structure: ``take(path)`` is
        called with each _leaf_paths path (the callbacks above resolve
        their own storage from it — no stateful parallel iteration)."""
        def build(paths, depth):
            heads = {}
            for p in paths:
                heads.setdefault(p[depth], []).append(p)
            if len(paths) == 1 and len(paths[0]) == depth:
                return take(paths[0])
            out = {}
            for k in sorted(heads):
                sub = heads[k]
                if all(len(p) == depth + 1 for p in sub):
                    out[k] = take(sub[0])
                else:
                    out[k] = build(sub, depth + 1)
            return out
        return build(self._paths, 0)

    def close(self):
        self.adam.close()
        if self._swapper is not None:
            self._swapper.close()
