"""DeepSpeedEngine — the training engine.

Counterpart of reference ``runtime/engine.py:181 DeepSpeedEngine`` (init
pipeline SURVEY §3.1, fwd/bwd/step §3.2). TPU-first redesign:

  * The train state (bf16 params, fp32 master, optimizer state, loss-scale
    state, step) is ONE pytree whose leaves carry NamedShardings computed by
    the ZeRO plan (runtime/zero/partitioning.py). What the reference does
    with hooks + buckets + streams, XLA does from the sharding annotations:
    stage-1 partitioned update + step-end allgather, stage-2 reduce_scatter,
    stage-3 per-layer gather, all overlapped by XLA's latency-hiding
    scheduler (the `overlap_comm` analogue).
  * `train_batch()` is one jitted program: `lax.scan` over gradient
    accumulation micro-steps, grad clip, overflow-safe optimizer update with
    in-state dynamic loss scaling (no host sync per step, unlike the
    reference's CheckOverflow).
  * The staged `forward()/backward()/step()` API is kept for parity: forward
    computes loss+grads in one jitted call (autodiff is a transform, not a
    tape), backward accumulates into a sharded grad buffer, step applies the
    update at the accumulation boundary (reference
    is_gradient_accumulation_boundary semantics).
"""

import functools
import os
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..ops.optimizers import build_optimizer
from ..utils import groups
from ..utils.groups import TopologyConfig, BATCH_AXES
from ..utils.logging import logger, log_dist
from ..utils.timer import (SynchronizedWallClockTimer, ThroughputTimer,
                           TRAIN_BATCH_TIMER)
from .config import DeepSpeedConfig, _take, CommOverlapConfig
from .fp16.loss_scaler import create_loss_scaler, grads_finite
from .lr_schedules import build_scheduler
from .zero.partitioning import ZeroShardingPlan
from .zero import overlap as comm_overlap


def _tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype), tree)


def _is_spec(x):
    return isinstance(x, P)


class DeepSpeedEngine:
    def __init__(self, model, config, optimizer=None, lr_scheduler=None,
                 topology=None, seed=0):
        # --- topology & config (reference engine.py:1112
        #     _configure_distributed_model) ---
        if isinstance(config, dict):
            raw = config
        elif isinstance(config, DeepSpeedConfig):
            raw = config._raw
        else:
            # str path: read the json directly — batch-triad validation
            # belongs to the dp-aware DeepSpeedConfig built below
            import json as _json
            with open(config) as _f:
                raw = _json.load(_f)
        # comm-overlap XLA flags (zero/overlap.py) must land before the
        # backend initializes — which happens at the first jax.devices()
        # call when this engine builds its own topology below — so the
        # block is parsed ahead of the full config. "auto" only applies
        # flags when a multi-process/overlap env hint exists: flags at
        # dp=1 would perturb the measured single-chip headline.
        co_early = _take(raw, CommOverlapConfig, "comm_overlap")
        want_flags = co_early.set_xla_flags and (
            co_early.enabled is True
            or (co_early.enabled == "auto"
                and (os.environ.get("COORDINATOR_ADDRESS")
                     or os.environ.get("DSTPU_COMM_OVERLAP") == "1")))
        if want_flags:
            platform = comm_overlap.platform_guess()
            # bucket_mb="auto" resolves from the winner cache LATER (at
            # _install_comm_overlap, after the backend is up — dispatch
            # needs device_kind); the pre-backend flags take the cold-
            # cache default, which is what "auto" resolves to anyway
            flag_mb = (co_early.bucket_mb
                       if isinstance(co_early.bucket_mb, int) else 32)
            self._overlap_flags = comm_overlap.apply_xla_flags(
                comm_overlap.xla_overlap_flags(
                    platform, prefetch=co_early.prefetch,
                    bucket_mb=flag_mb),
                comm_overlap.overlap_env_var(platform))
        else:
            self._overlap_flags = (False, "not requested")
        # auto-parallelism: ``parallelism: "auto"`` hands the mesh choice
        # to the planner (autotuning/planner.py) when no explicit
        # topology was constructed — an explicit ``topology=`` argument
        # always wins. The adopted plan is stashed so _resolve_pipeline
        # can consume its schedule/microbatch/offload picks wherever the
        # pipeline knobs were themselves left on 'auto'.
        self._auto_plan = None
        self.plan_report = None
        if topology is None and raw.get("parallelism", "") == "auto":
            from ..autotuning import planner as _planner
            report = _planner.plan_for_engine(model, raw)
            best = report.top() if report is not None else None
            if best is not None:
                self._auto_plan = best
                self.plan_report = report
                topology = groups.initialize(TopologyConfig(
                    **best.topology_kwargs()))
                m = best.mesh
                log_dist(
                    "parallelism=auto: planned mesh "
                    + "x".join(f"{a}={m[a]}" for a in
                               ("pipe", "data_outer", "data", "expert",
                                "seq", "tensor"))
                    + f" schedule={best.schedule} M={best.micro_batches}"
                    + f" offload={best.offload}"
                    + f" (modeled {best.wall_ms:.3g} ms/step,"
                    + f" {report.considered} considered,"
                    + f" {report.pruned_hbm} HBM-pruned)", ranks=[0])
            else:
                log_dist(
                    "parallelism=auto: planner produced no feasible "
                    "plan; falling back to the explicit config axes",
                    ranks=[0])
        if topology is None:
            zero_raw = raw.get("zero_optimization", {})
            shard = int(zero_raw.get("mics_shard_size", -1))
            if shard in (-1, 0):
                shard = int(zero_raw.get("hpz_partition_size", 1))
                shard = shard if shard > 1 else -1
            topology = groups.initialize(TopologyConfig(
                tensor_parallel_size=raw.get("tensor_parallel", {}).get("size", 1),
                pipe_parallel_size=raw.get("pipeline", {}).get("stages", 1),
                seq_parallel_size=raw.get("sequence_parallel_size", 1),
                expert_parallel_size=raw.get("expert_parallel_size", 1),
                zero_shard_size=shard,
            ))
        self.topology = topology
        self.mesh = topology.mesh
        dp_world = topology.get_data_parallel_world_size()
        # `raw` is the parsed dict in every non-DeepSpeedConfig branch —
        # no second read of a json path
        self.config = (config if isinstance(config, DeepSpeedConfig)
                       else DeepSpeedConfig(raw, dp_world_size=dp_world))
        dist.configure(self.config)

        # measured kernel dispatch: the autotune mode/cache is process-
        # global (kernel choice must agree across every trace), so the
        # engine pushes its config block down BEFORE any program traces;
        # empty fields inherit the DSTPU_AUTOTUNE* env defaults
        from ..autotuning import kernel_dispatch
        kernel_dispatch.configure_from_config(self.config.autotune)

        # comm-overlap resolution (the XLA flags were handled above,
        # pre-backend; this decides the program-level annotations).
        # hierarchical 'auto' consults the 'grad_staging' collective op's
        # winner cache with the do>1 heuristic as the cold-cache default
        # — same answer as before until a measured winner disagrees
        co = self.config.comm_overlap
        self._overlap_on = co.resolve_enabled(dp_world)
        self._overlap_hier = self._overlap_on and \
            self._resolve_grad_staging(co, topology, model)
        self.comm_overlap_report = None

        self.model = model
        # sequence/context-parallel knobs (config 'sequence' block):
        # models with attention_backend='ring' read this when seq-sharded
        # (gpt2.block_attn -> sequence/ring.py layout/kernel/overlap)
        try:
            self.model._sequence_cfg = self.config.sequence
        except (AttributeError, TypeError):   # frozen/slotted models
            log_dist(
                "sequence config block could not be installed on the "
                "model (attribute assignment rejected); ring attention "
                "will use the module defaults", ranks=[0])
        # dropless-MoE knobs (config 'moe' block): grouped-GEMM kernel
        # dispatch + hierarchical ICI->DCN expert all_to_all staging
        # (moe/sharded_moe.py; mixtral._mlp and the MoE layers consult
        # model._moe_cfg per dispatch)
        moe_cfg = self.config.moe
        qz = self.config.quantize
        if qz.moe_dcn is not None:
            # 'quantize' block override: moe_dcn=None defers to
            # moe.dcn_quantize, anything else steers the MoE DCN legs
            import dataclasses as _dc
            moe_cfg = _dc.replace(moe_cfg, dcn_quantize=qz.moe_dcn)
        try:
            self.model._moe_cfg = moe_cfg
        except (AttributeError, TypeError):   # frozen/slotted models
            log_dist(
                "moe config block could not be installed on the model "
                "(attribute assignment rejected); MoE layers will use "
                "the module defaults", ranks=[0])
        # W8A8 compute levers (quantize block): models consult these at
        # trace time (gpt2._mlp / mixtral._moe_knobs); False defaults
        # keep the compiled programs byte-identical
        try:
            self.model._int8_matmul = qz.int8_matmul
            self.model._moe_int8 = qz.moe_int8_matmul
        except (AttributeError, TypeError):   # frozen/slotted models
            log_dist(
                "quantize config block could not be installed on the "
                "model (attribute assignment rejected); int8 matmul "
                "levers will use the module defaults", ranks=[0])
        self.zero_stage = self.config.zero.stage
        self.param_dtype = self.config.precision_dtype
        # pipeline block (config 'pipeline'): schedule / microbatch /
        # host-offload resolution happens ONCE here (pre-state: the
        # moments placement changes the optimizer-state shardings) and
        # is installed on the model as _pipe_cfg for GPT2Pipe to
        # consult at trace time
        self._pipe = self._resolve_pipeline()
        try:
            self.model._pipe_cfg = self._pipe
        except (AttributeError, TypeError):   # frozen/slotted models
            log_dist(
                "pipeline config block could not be installed on the "
                "model (attribute assignment rejected); pipelined "
                "models will use their module defaults", ranks=[0])
        model_dtype = getattr(getattr(model, "config", None), "dtype",
                              None)
        if model_dtype is not None and \
                jnp.dtype(model_dtype) != jnp.dtype(self.param_dtype):
            # the engine computes in param_dtype (fp32 master handled
            # internally); a model whose own dtype knob disagrees mixes
            # activation dtypes mid-scan and fails with an opaque carry
            # type error — tell the user which knob to change
            raise ValueError(
                f"model config dtype {jnp.dtype(model_dtype).name!r} != "
                f"engine precision {jnp.dtype(self.param_dtype).name!r} "
                f"(from the bf16/fp16 config blocks); set the model's "
                f"dtype to match, or enable/disable bf16 accordingly")
        self.global_step = 0
        self.micro_steps = 0

        # --- optimizer / scheduler (reference engine.py:1246,:915) ---
        if optimizer is None:
            if self.config.optimizer is None:
                raise ValueError("no optimizer: pass one or set config['optimizer']")
            optimizer = build_optimizer(self.config.optimizer.type,
                                        self.config.optimizer.params)
        self.optimizer = optimizer
        if lr_scheduler is None and self.config.scheduler is not None:
            lr_scheduler = build_scheduler(self.config.scheduler.type,
                                           self.config.scheduler.params)
        self.lr_scheduler = lr_scheduler

        self.loss_scaler = create_loss_scaler(self.config.fp16,
                                              self.param_dtype)

        # --- sharding plan + state materialization (reference zero.Init +
        #     _configure_zero_optimizer) ---
        self._build_state(seed)
        self._build_programs()

        from .checkpoint_engine.engines import create_checkpoint_engine
        self.checkpoint_engine = create_checkpoint_engine(
            self.config.checkpoint_engine)

        # peer-replicated in-memory hot tier (checkpoint_engine/
        # hot_tier.py): 'auto' is on iff an elastic launcher exported
        # the ring env (DSTPU_HOT_PEERS/DSTPU_HOT_TIER_ROOT/
        # DSTPU_HOT_TRANSPORT — deliberately NOT bare multi-process;
        # see the config field comment); restores try it before any
        # persistent-storage read
        self.hot_store = None
        ce_cfg = self.config.checkpoint_engine
        if ce_cfg.resolve_hot_tier():
            from .checkpoint_engine.hot_tier import HotTierStore
            replicas = ce_cfg.hot_replicas
            if replicas == "auto":
                # measured replication degree for this per-host shard
                # payload (op 'hot_replicas'; K=1 — the hand-set ring
                # default — on a cold cache)
                from ..ops.pallas._common import (dispatch, dtype_name,
                                                  hot_replicas_bucket)
                shard_mb = self._layer_grad_mb(
                    self.model, self.param_dtype)
                mcfg = getattr(self.model, "config", None)
                shard_mb *= max(1, int(getattr(mcfg, "n_layer", 1)))
                shard_mb = max(1, shard_mb // max(1, jax.process_count()))
                replicas = int(dispatch(
                    "hot_replicas", hot_replicas_bucket(shard_mb,
                                                        self.mesh),
                    dtype_name(self.param_dtype), {"k": 1})["k"])
            # the store clamps replicas (config ints AND the autotuned
            # winner above both flow through here) to ring size - 1 with
            # a one-time warning, and reads slice membership from
            # DSTPU_HOT_SLICES (the elastic agent exports it) for
            # cross-slice replica placement
            self.hot_store = HotTierStore(
                root=ce_cfg.hot_root or None,
                replicas=int(replicas),
                keep_last=ce_cfg.hot_keep_last,
                counters=self.checkpoint_engine.counters,
                max_inflight_pushes=ce_cfg.hot_max_inflight_pushes)
        # which tier served the most recent load_checkpoint (None before
        # any load / when nothing was found): 'hot' | 'replica' |
        # 'durable'
        self.last_restore_tier = None
        # preemption-graceful drain (tentpole of the slice-survivability
        # work): a SIGTERM — TPU maintenance notice, or the elastic
        # agent forwarding one — only SETS this flag; the in-flight
        # train_batch finishes, then _preempt_drain forces one
        # hot+replica push and a flight dump and exits with the
        # distinct PREEMPTED_EXIT_CODE the agent maps to 'preempted'
        # (healthy host kept, no backoff penalty)
        self._preempt_requested = False
        self._last_ckpt_save_dir = None
        if ce_cfg.resolve_preempt_drain():
            self._install_preempt_drain()

        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.config.train_batch_size,
            steps_per_output=self.config.steps_per_print)

        # monitoring fan-out (reference engine.py:253 MonitorMaster; events
        # written at step boundaries like engine.py:1993-2001)
        from ..monitor.monitor import MonitorMaster
        self.monitor = MonitorMaster(self.config.monitor_config)

        # pod telemetry (monitor/telemetry.py): step analytics (MFU /
        # tokens-per-chip / p50-p99 from host wall times, no device
        # sync), goodput accounting fed by the checkpoint paths below,
        # cluster aggregation, and the crash flight recorder + on-demand
        # profiler. 'auto' arms it when a monitor backend, the elastic
        # agent, or an explicit env hint is present.
        self.telemetry = None
        tcfg = self.config.telemetry
        # 'auto' must resolve from the rank-symmetric CONFIG flag, not
        # MonitorMaster.enabled (rank-0-gated): the cluster allgather
        # transport is collective, so arming telemetry on rank 0 only
        # would hang the pod at the first flush
        if tcfg.resolve_enabled(self.config.monitor_config.enabled):
            from ..monitor.telemetry import TelemetryCollector
            self.telemetry = TelemetryCollector(
                tcfg, monitor=self.monitor,
                n_devices=int(self.mesh.size),
                device_kind=jax.devices()[0].device_kind,
                costs_fn=self._telemetry_step_costs)
            # SIGTERM black-box dump: only chained when something will
            # actually read it (an elastic agent supervises us, or a
            # dump dir was exported) — unconditional installs would
            # chain a handler per engine built in one process
            if (os.environ.get("ELASTIC_GENERATION") is not None
                    or os.environ.get("DSTPU_FLIGHTREC_DIR")
                    or tcfg.flightrec_dir):
                self.telemetry.flight.install_sigterm()
            self._telemetry_lower_args = None
            # pipelined runs: arm the per-flush pipeline metrics
            # (bubble fraction, steady-tick wall, offload payload)
            pinfo = self.pipeline_report()
            if pinfo is not None:
                self.telemetry.set_pipeline(pinfo)
            # step-anatomy reconciliation: when ProfilerControl stops a
            # step-ranged capture, hand the trace to the parser + the
            # planner reconciler (pool-side; advisory)
            self.telemetry.set_reconcile(self._telemetry_reconcile)

        # data efficiency (reference engine.py:336-367): the curriculum
        # scheduler changes the SEQUENCE LENGTH the jitted step sees
        # (shape buckets — difficulty_step bounds distinct programs) and
        # random-LTD the kept-token count of middle layers
        self.curriculum_scheduler = None
        self._curriculum_difficulty = None
        if self.config.curriculum_config is not None:
            from .data_pipeline.curriculum_scheduler import (
                CurriculumScheduler)
            self.curriculum_scheduler = CurriculumScheduler(
                self.config.curriculum_config)
        self.random_ltd_scheduler = None
        if self.config.random_ltd_config is not None:
            from .data_pipeline.random_ltd import RandomLTDScheduler
            self.random_ltd_scheduler = RandomLTDScheduler(
                self.config.random_ltd_config)
            if not self._loss_accepts_ltd():
                raise ValueError(
                    "random_ltd is enabled but the model's loss() takes "
                    "no ltd_keep argument (models/gpt2.py implements it)")
        log_dist(
            f"engine ready: zero_stage={self.zero_stage} dtype={self.param_dtype} "
            f"dp={dp_world} tp={topology.get_model_parallel_world_size()} "
            f"sp={topology.get_sequence_parallel_world_size()} "
            f"ep={topology.get_expert_parallel_world_size()} "
            f"micro_bs={self.config.train_micro_batch_size_per_gpu} "
            f"gas={self.config.gradient_accumulation_steps} "
            f"overlap={self._overlap_on}", ranks=[0])

    # ------------------------------------------------------------------ state
    def _build_state(self, seed):
        rng = jax.random.key(seed)
        abstract = jax.eval_shape(self.model.init, rng)
        shapes = jax.tree.map(lambda l: l.shape, abstract)
        tp_specs = self.model.partition_specs(self.topology)
        self._tp_specs = tp_specs
        # MiCS: everything shards over the inner group, replicates over
        # data_outer (zero/mics.py:64). hpZ/ZeRO++: only the stage-3 bf16
        # param shard is intra-slice; optimizer state stays global-DP
        # (utils/groups.py:505 secondary group).
        from ..utils.groups import DP_AXES, INNER_DP_AXES
        zc = self.config.zero
        mics = zc.mics_shard_size not in (-1, 0)
        hpz = zc.hpz_partition_size > 1
        want = max(zc.mics_shard_size, zc.hpz_partition_size)
        if (mics or hpz) and self.topology.axis_size("data_outer") == 1 \
                and self.topology.axis_size("data") > want:
            log_dist(
                f"mics/hpz shard size {want} configured but the topology "
                "was built without zero_shard_size; sharding over the full "
                "DP group instead", ranks=[0])
        self.plan = ZeroShardingPlan(
            self.zero_stage, self.mesh, tp_specs, shapes,
            partition_axes=INNER_DP_AXES if mics else DP_AXES,
            param_partition_axes=INNER_DP_AXES if hpz else None)
        param_sh = self.plan.shardings("param")
        master_sh = self.plan.shardings("master")
        self.param_shardings = param_sh
        self.master_shardings = master_sh
        self.grad_shardings = self.plan.shardings("grad")

        self.use_master = self.param_dtype != jnp.float32

        # ZeRO-Offload (reference stage_1_and_2.py:1181 CPU-offload grads +
        # cpu_adam, stage3.py:584 NVMe tensor swapping): master + Adam
        # moments leave the device entirely — the host optimizer owns them
        # and the device state holds ONLY bf16 params.
        self.offload_opt_cfg = self.config.zero.offload_optimizer
        self.offload_param_cfg = self.config.zero.offload_param
        self.offload_enabled = (self.offload_opt_cfg.enabled
                                or self.offload_param_cfg.enabled)
        self.host_optimizer = None
        # multi-process offload: each process device_gets and host-steps
        # ONLY its addressable master shards (reference
        # stage_1_and_2.py:1181 — every DP rank cpu-steps its partition)
        self._offload_multi = self.offload_enabled and \
            jax.process_count() > 1

        with jax.set_mesh(self.mesh):
            if self.offload_enabled:
                # fp32 init materialized once, fetched to host, then freed:
                # the device never holds master/opt state after init
                master_dev = jax.jit(
                    lambda r: _tree_cast(self.model.init(r), jnp.float32),
                    out_shardings=master_sh)(rng)
                params = jax.jit(
                    lambda m: _tree_cast(m, self.param_dtype),
                    out_shardings=param_sh)(master_dev)
                if self._offload_multi:
                    host_master = self._collect_local_shards(
                        master_dev, record_meta=True)
                else:
                    host_master = jax.device_get(master_dev)
                del master_dev
                from .zero.offload import HostOffloadOptimizer
                self.host_optimizer = HostOffloadOptimizer(
                    host_master, self.config.optimizer,
                    self.offload_opt_cfg, self.offload_param_cfg)
                del host_master
                master = None
                opt_state = None
                opt_sh = None
            else:
                params = jax.jit(
                    lambda r: _tree_cast(self.model.init(r),
                                         self.param_dtype),
                    out_shardings=param_sh)(rng)
                if self.use_master:
                    master = jax.jit(lambda p: _tree_cast(p, jnp.float32),
                                     out_shardings=master_sh)(params)
                else:
                    # fp32 training: master IS params (sharded per master
                    # plan from stage>=1; the update allgathers into param
                    # specs)
                    master = jax.jit(lambda p: p,
                                     out_shardings=master_sh)(params)
                opt_sh = self._opt_state_shardings(master)
                opt_state = jax.jit(self.optimizer.init,
                                    out_shardings=opt_sh)(master)
        self.opt_shardings = opt_sh

        # replicated scalars are CREATED by a jitted program rather than
        # device_put from host: device_put cannot target non-addressable
        # shardings on a multi-process mesh, a same-value computation can
        def _scalars():
            return (self.loss_scaler.init_state(),
                    jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
                    jax.random.key(seed + 1))

        rep = jax.tree.map(lambda _: NamedSharding(self.mesh, P()),
                           jax.eval_shape(_scalars))
        scale_state, step0, skipped0, rng0 = jax.jit(
            _scalars, out_shardings=rep)()
        self.state = {
            "params": params,
            "master": master,
            "opt": opt_state,
            "scale": scale_state,
            "step": step0,
            # overflow-skip counter lives on device so counting it never
            # forces a host sync (reference syncs CheckOverflow every step)
            "skipped": skipped0,
            "rng": rng0,
        }
        self.state_shardings = {
            "params": param_sh,
            "master": None if self.offload_enabled else master_sh,
            "opt": opt_sh,
            "scale": jax.tree.map(
                lambda _: NamedSharding(self.mesh, P()), scale_state),
            "step": NamedSharding(self.mesh, P()),
            "skipped": NamedSharding(self.mesh, P()),
            "rng": NamedSharding(self.mesh, P()),
        }
        # grad accumulation buffer for the staged API (lazy)
        self._acc_grads = None
        self._pending_loss = None

    def _opt_state_shardings(self, master):
        """Optimizer state sharding: subtrees structurally matching the
        param tree inherit master shardings (m/v/etc.); scalars
        replicate. With ``pipeline.offload_moments`` resolved on, the
        moment subtrees are re-targeted at the host memory kind
        (sharding-with-memory-kind — the reference's swap_tensor
        optimizer tier expressed as placement; XLA streams them through
        the update)."""
        master_def = jax.tree.structure(master)
        state_shape = jax.eval_shape(self.optimizer.init, master)
        repl = NamedSharding(self.mesh, P())
        moment_sh = self.master_shardings
        if getattr(self._pipe, "offload_moments", False):
            from .swap_tensor import host_stage
            moment_sh = jax.tree.map(host_stage.with_host_memory_kind,
                                     self.master_shardings)
        out = {}
        for key, sub in state_shape.items():
            if jax.tree.structure(sub) == master_def:
                out[key] = moment_sh
            else:
                out[key] = jax.tree.map(lambda _: repl, sub)
        return out

    # -------------------------------------------------------------- programs
    def _loss_accepts_step(self):
        import inspect
        try:
            return "step" in inspect.signature(self.model.loss).parameters
        except (TypeError, ValueError):
            return False

    def _loss_accepts_ltd(self):
        import inspect
        try:
            return "ltd_keep" in inspect.signature(
                self.model.loss).parameters
        except (TypeError, ValueError):
            return False

    def _model_loss(self, params, batch, rng, step=None, ltd_keep=None):
        kwargs = {}
        if self.topology.get_sequence_parallel_world_size() > 1:
            kwargs["seq_sharded"] = True
        # schedule-aware models (e.g. compression wrappers) take the
        # traced global step for schedule_offset gating
        if step is not None and self._loss_accepts_step():
            kwargs["step"] = step
        if ltd_keep is not None:
            kwargs["ltd_keep"] = ltd_keep
        return self.model.loss(params, batch, rng=rng, train=True, **kwargs)

    def _build_programs(self):
        gas = self.config.gradient_accumulation_steps
        clip = self.config.gradient_clipping
        opt = self.optimizer
        scaler = self.loss_scaler
        grad_specs = self.plan.grad_specs
        param_specs = self.plan.param_specs
        pdtype = self.param_dtype
        use_master = self.use_master
        constrain = jax.lax.with_sharding_constraint
        # accumulate/reduce dtype: fp32 default (the reference
        # grad_accum_dtype default); data_types.grad_accum_dtype "bf16"
        # halves the full-model transient grad tree — the knob the 1.3B
        # ZeRO-3 single-chip point needs to fit 16 GB HBM (the optimizer
        # still computes its update in fp32)
        gdtype = jnp.dtype({"fp32": "float32", "bf16": "bfloat16",
                            "fp16": "float16", None: "float32"}.get(
            self.config.grad_accum_dtype, self.config.grad_accum_dtype))

        # per-layer comm annotations consumed by the model's block scan
        # (must precede tracing, which happens at the first jitted call)
        self._install_comm_overlap(gdtype)

        def micro_loss_and_grads(params, micro_batch, rng, scale,
                                 step=None, ltd_keep=None):
            def scaled(p):
                return self._model_loss(p, micro_batch, rng,
                                        step=step, ltd_keep=ltd_keep) \
                    * scale
            loss_scaled, grads = jax.value_and_grad(scaled)(params)
            grads = _tree_cast(grads, gdtype)
            return loss_scaled / scale, grads

        def unscale_clip_grads(grads, scale):
            """Shared unscale + overflow check + global-norm clip — ONE
            definition so the fused, offload, and staged paths cannot
            drift. Returns (grads, finite, gnorm); the global norm's
            cross-shard psum falls out of GSPMD."""
            # keep each leaf's own dtype through the unscale (the fp32
            # scalar would silently promote a bf16 grad tree to fp32 —
            # exactly the materialization grad_accum_dtype=bf16 avoids)
            grads = jax.tree.map(
                lambda g, s: constrain((g / scale).astype(g.dtype), s),
                grads, grad_specs)
            finite = grads_finite(grads)
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                     for g in jax.tree.leaves(grads))
            gnorm = jnp.sqrt(sq)
            if clip and clip > 0:
                coef = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(
                    lambda g: (g * coef).astype(g.dtype), grads)
            return grads, finite, gnorm

        def apply_update(state, grads, lr):
            """grads: fp32 tree, already averaged over GAS; scale included."""
            scale = state["scale"]["scale"]
            grads, finite, gnorm = unscale_clip_grads(grads, scale)
            new_master, new_opt = opt.update(grads, state["opt"],
                                             state["master"], lr=lr)
            # skip-on-overflow: keep old state where not finite
            sel = lambda new, old: jax.tree.map(
                lambda n, o: jnp.where(finite, n, o), new, old)
            new_master = sel(new_master, state["master"])
            new_opt = sel(new_opt, state["opt"])
            new_params = jax.tree.map(
                lambda m, s: constrain(m.astype(pdtype), s),
                new_master, param_specs) if use_master else jax.tree.map(
                lambda m, s: constrain(m, s), new_master, param_specs)
            new_scale = scaler.update(state["scale"], ~finite)
            new_state = dict(state)
            new_state.update(params=new_params, master=new_master,
                             opt=new_opt, scale=new_scale,
                             step=state["step"] + 1,
                             skipped=state["skipped"]
                             + jnp.where(finite, 0, 1).astype(jnp.int32),
                             rng=jax.random.fold_in(state["rng"], 0))
            metrics = {"grad_norm": gnorm, "overflow": ~finite,
                       "loss_scale": scale}
            return new_state, metrics

        def train_step(state, batch, lr, ltd_keep=None):
            """batch leaves: (gas, per_step_batch, ...); ltd_keep is a
            STATIC kept-token count (random-LTD) — distinct values are
            distinct programs, bounded by the schedule's seq_step"""
            scale = state["scale"]["scale"]

            if gas == 1:
                # no accumulation buffer: skip the zeros-init + add round
                # trip through HBM (O(model size) fp32 traffic per step)
                micro = jax.tree.map(lambda x: x[0], batch)
                loss, grads = micro_loss_and_grads(
                    state["params"], micro,
                    jax.random.fold_in(state["rng"], 0), scale,
                    step=state["step"], ltd_keep=ltd_keep)
                grads = jax.tree.map(lambda g, s: constrain(g, s),
                                     grads, grad_specs)
                new_state, metrics = apply_update(state, grads, lr)
                metrics["loss"] = loss
                return new_state, metrics

            def body(carry, micro):
                acc, rng, i = carry
                loss, grads = micro_loss_and_grads(
                    state["params"], micro, jax.random.fold_in(rng, i),
                    scale, step=state["step"], ltd_keep=ltd_keep)
                grads = jax.tree.map(lambda g, s: constrain(g, s),
                                     grads, grad_specs)
                acc = jax.tree.map(lambda a, g: a + g / gas, acc, grads)
                return (acc, rng, i + 1), loss

            zero_grads = jax.tree.map(
                lambda s: jnp.zeros(s.shape, gdtype),
                jax.eval_shape(lambda p: _tree_cast(p, gdtype),
                               state["params"]))
            zero_grads = jax.tree.map(lambda g, s: constrain(g, s),
                                      zero_grads, grad_specs)
            (grads, _, _), losses = jax.lax.scan(
                body, (zero_grads, state["rng"], 0), batch)
            # accumulated grads carry the loss scale; apply_update divides
            # it out once.
            new_state, metrics = apply_update(state, grads, lr)
            metrics["loss"] = jnp.mean(losses)
            return new_state, metrics

        def micro_step(state, batch, micro_idx):
            scale = state["scale"]["scale"]
            rng = jax.random.fold_in(state["rng"], micro_idx)
            loss, grads = micro_loss_and_grads(state["params"], batch, rng,
                                               scale, step=state["step"])
            grads = jax.tree.map(lambda g, s: constrain(g, s), grads,
                                 grad_specs)
            return loss, grads

        def acc_add(acc, grads):
            return jax.tree.map(lambda a, g: a + g / gas, acc, grads)

        def grad_step(state, batch, ltd_keep=None):
            """ZeRO-Offload device half: loss + clipped, UNSCALED fp32
            grads + overflow flag. The update happens on the host
            (zero/offload.py HostOffloadOptimizer)."""
            scale = state["scale"]["scale"]

            def micro(carry, micro_batch):
                acc, rng, i = carry
                loss, grads = micro_loss_and_grads(
                    state["params"], micro_batch,
                    jax.random.fold_in(rng, i), scale, step=state["step"],
                    ltd_keep=ltd_keep)
                grads = jax.tree.map(lambda g, s: constrain(g, s),
                                     grads, grad_specs)
                acc = jax.tree.map(lambda a, g: a + g / gas, acc, grads)
                return (acc, rng, i + 1), loss

            if gas == 1:
                first = jax.tree.map(lambda x: x[0], batch)
                loss, grads = micro_loss_and_grads(
                    state["params"], first,
                    jax.random.fold_in(state["rng"], 0), scale,
                    step=state["step"], ltd_keep=ltd_keep)
                losses = loss
            else:
                zeros = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, jnp.float32),
                    jax.eval_shape(lambda p: _tree_cast(p, jnp.float32),
                                   state["params"]))
                zeros = jax.tree.map(lambda g, s: constrain(g, s),
                                     zeros, grad_specs)
                (grads, _, _), losses = jax.lax.scan(
                    micro, (zeros, state["rng"], 0), batch)
            grads, finite, gnorm = unscale_clip_grads(grads, scale)
            metrics = {"loss": jnp.mean(losses), "grad_norm": gnorm,
                       "overflow": ~finite, "loss_scale": scale}
            return grads, metrics

        def offload_finalize(state, overflow):
            """Counter/scale half of the step (device-side, tiny)."""
            new_state = dict(state)
            new_state.update(
                scale=scaler.update(state["scale"], overflow),
                step=state["step"] + 1,
                skipped=state["skipped"]
                + jnp.where(overflow, 1, 0).astype(jnp.int32),
                rng=jax.random.fold_in(state["rng"], 0))
            return new_state

        def finish_grads(grads, scale):
            """Staged-API ZeRO-Offload: unscale/clip the accumulated grads
            on device before the host update."""
            grads, finite, gnorm = unscale_clip_grads(grads, scale)
            return grads, {"grad_norm": gnorm, "overflow": ~finite,
                           "loss_scale": scale}

        st_sh = lambda: self.state_shardings
        with jax.set_mesh(self.mesh):
            if self.offload_enabled:
                self._grad_step_jit = jax.jit(
                    grad_step, static_argnums=(2,),
                    in_shardings=(st_sh(), None),
                    out_shardings=(self.grad_shardings, None))
                self._offload_finalize_jit = jax.jit(
                    offload_finalize, donate_argnums=(0,),
                    in_shardings=(st_sh(), None),
                    out_shardings=st_sh())
                self._finish_grads_jit = jax.jit(
                    finish_grads, donate_argnums=(0,),
                    in_shardings=(self.grad_shardings, None),
                    out_shardings=(self.grad_shardings, None))
                # multi-process push-back: updated fp32 master shards ->
                # replicated/resharded bf16 params (GSPMD emits the
                # all-gather); the fp32 input is transient and donated
                self._offload_push_jit = jax.jit(
                    lambda m: _tree_cast(m, self.param_dtype),
                    donate_argnums=(0,),
                    in_shardings=(self.master_shardings,),
                    out_shardings=self.param_shardings)
            self._train_step_jit = None if self.offload_enabled else jax.jit(
                train_step, donate_argnums=(0,), static_argnums=(3,),
                in_shardings=(st_sh(), None, None),
                out_shardings=(st_sh(), None))
            self._micro_step_jit = jax.jit(
                micro_step, in_shardings=(st_sh(), None, None),
                out_shardings=(None, self.grad_shardings))
            eval_kwargs = {}
            if self.topology.get_sequence_parallel_world_size() > 1:
                eval_kwargs["seq_sharded"] = True
            self._eval_loss_jit = jax.jit(functools.partial(
                self.model.loss, train=False, **eval_kwargs))
            self._acc_add_jit = jax.jit(
                acc_add, donate_argnums=(0,),
                in_shardings=(self.grad_shardings, self.grad_shardings),
                out_shardings=self.grad_shardings)
            self._apply_update_jit = jax.jit(
                apply_update, donate_argnums=(0, 1),
                in_shardings=(st_sh(), self.grad_shardings, None),
                out_shardings=(st_sh(), None))

    # ---------------------------------------------------------- pipeline
    def _resolve_pipeline(self):
        """Resolve the ``pipeline`` config block against this topology
        and backend (runtime/config.py PipelineConfig docs the knobs):
        schedule, microbatch count (winner cache via the
        'pipe_microbatch' autotune op when 0/auto), and the host-offload
        placements — activations need a distinct host memory kind
        (swap_tensor/host_stage.py) and 'auto' additionally needs the
        HBM-fit heuristic to say the state does NOT fit."""
        from types import SimpleNamespace
        from .swap_tensor import host_stage
        pcfg = self.config.pipeline
        S = self.topology.get_pipe_parallel_world_size()
        mcfg = getattr(self.model, "config", None)
        model_sched = getattr(mcfg, "pipe_schedule", None)
        schedule = pcfg.resolve_schedule(model_sched)
        # parallelism=auto: the adopted plan's picks fill the knobs
        # still on block-level 'auto' — an explicit pipeline.schedule
        # wins, but the model-config default does not (opting into the
        # planner makes it the authority for the schedule choice)
        ap = getattr(self, "_auto_plan", None)
        if ap is not None and pcfg.schedule == "auto" \
                and ap.schedule != "none":
            schedule = ap.schedule
        avail = host_stage.available()
        est = self._estimate_pipe_state_bytes()
        hbm = self._device_hbm_bytes()
        acts = pcfg.resolve_offload_activations(
            avail, pipe_world=S, est_state_bytes=est, hbm_bytes=hbm)
        moments = pcfg.resolve_offload_moments(avail)
        if ap is not None and avail:
            if pcfg.offload_activations == "auto" and ap.offload:
                acts = True
            if pcfg.offload_moments == "auto" and ap.offload:
                moments = True
        if pcfg.offload_moments is True and not avail:
            log_dist(
                "pipeline.offload_moments=true but this backend has a "
                "single memory space; moments stay device-resident",
                ranks=[0])
        if pcfg.offload_activations is True and not avail:
            log_dist(
                "pipeline.offload_activations=true but this backend "
                "has a single memory space; staging degrades to "
                "identity (no bytes move)", ranks=[0])
        micro = pcfg.micro_batches or getattr(
            mcfg, "pipe_microbatches", 0)
        if not micro and S > 1 and ap is not None:
            # the plan's M already priced the bubble/efficiency knee;
            # degrade to a dividing count like the dispatch path does
            micro = int(ap.micro_batches)
            B = max(1, self.config.train_batch_size
                    // self.config.gradient_accumulation_steps)
            if B % micro:
                micro = next((m for m in (2 * S, S, 1) if B % m == 0),
                             1)
                log_dist(
                    f"pipeline: planned micro_batches "
                    f"{ap.micro_batches} does not divide the global "
                    f"batch {B}; using {micro}", ranks=[0])
        if not micro and S > 1 and mcfg is not None \
                and hasattr(mcfg, "d_model"):
            # 'auto' M: the measured knee between bubble amortization
            # (more microbatches) and per-tick MXU efficiency (fewer) —
            # cold cache = the 2S guidance, same program as before
            from ..ops.pallas._common import (dispatch, dtype_name,
                                              pipe_bucket)
            # the pipelined loss sees ONE accumulation micro-step's
            # rows, not the global batch — bucket and divisibility
            # must use what the model will actually split
            B = max(1, self.config.train_batch_size
                    // self.config.gradient_accumulation_steps)
            bucket = pipe_bucket(S, B, mcfg.max_seq_len, mcfg.d_model)
            winner = dispatch("pipe_microbatch", bucket,
                              dtype_name(self.param_dtype),
                              {"micro": 2 * S, "offload": int(acts)})
            micro = int(winner["micro"])
            if B % micro:
                # the bucket pow2-rounds B, so a cached winner can fail
                # the REAL batch's divisibility — 'auto' must degrade
                # to a dividing count, never crash the trace
                micro = next((m for m in (2 * S, S, 1) if B % m == 0),
                             1)
                log_dist(
                    f"pipeline: tuned micro_batches "
                    f"{winner['micro']} does not divide the global "
                    f"batch {B}; using {micro}", ranks=[0])
        if S > 1:
            log_dist(
                f"pipeline: stages={S} schedule={schedule} "
                f"micro_batches={micro or 2 * S} offload_acts={acts} "
                f"offload_moments={moments} "
                f"(host_kind={host_stage.host_memory_kind()})",
                ranks=[0])
        return SimpleNamespace(
            stages=S, schedule=schedule, micro_batches=int(micro),
            offload_activations=bool(acts),
            offload_moments=bool(moments),
            offload_double_buffer=bool(pcfg.offload_double_buffer))

    def _estimate_pipe_state_bytes(self):
        """Rough per-chip train-state bytes for the HBM-fit heuristic:
        working params + grads (divided over pipe x tensor) plus the
        fp32 master + Adam moments (divided over the ZeRO partition
        group from stage >= 1). A heuristic for the offload 'auto'
        knob, not an allocator."""
        import jax.numpy as _jnp
        mcfg = getattr(self.model, "config", None)
        count = getattr(mcfg, "num_params", None)
        if not callable(count):
            return None
        n = count()
        pp = max(1, self.topology.get_pipe_parallel_world_size())
        tp = max(1, self.topology.get_model_parallel_world_size())
        dp = max(1, self.topology.get_data_parallel_world_size())
        shard = pp * tp
        pbytes = _jnp.dtype(self.param_dtype).itemsize
        gname = self.config.grad_accum_dtype
        gbytes = {"bf16": 2, "fp16": 2}.get(gname, 4)
        opt_shard = shard * (dp if self.zero_stage >= 1 else 1)
        return int(n * (pbytes + gbytes) / shard + n * 12 / opt_shard)

    def _device_hbm_bytes(self):
        """Per-chip device memory budget: DSTPU_HBM_BYTES override,
        else the backend's own bytes_limit, else None (the heuristic
        then counts everything as fitting)."""
        env = os.environ.get("DSTPU_HBM_BYTES")
        if env:
            try:
                return int(float(env))
            except ValueError:
                logger.warning(
                    f"ignoring non-numeric DSTPU_HBM_BYTES={env!r}")
        try:
            stats = jax.devices()[0].memory_stats()
            return int(stats["bytes_limit"]) if stats else None
        except Exception:  # noqa: BLE001 - CPU/older backends
            return None

    def pipeline_report(self):
        """Schedule/offload analytics for the active pipeline (None at
        pipe=1): the analytic executor bubble fractions
        (runtime/pipe/schedule.py lock-step wall model — the number
        telemetry emits as Train/Pipeline/bubble_pct) and the host
        staging payload the offload moves per step."""
        pr = self._pipe
        S = pr.stages
        if S <= 1:
            return None
        from .pipe.schedule import executor_bubble_fraction
        sched = pr.schedule if pr.schedule in ("gpipe", "1f1b", "zb") \
            else "gpipe"
        M = pr.micro_batches or 2 * S
        gas = max(1, self.config.gradient_accumulation_steps)
        # ticks per OPTIMIZER step: each accumulation micro-step runs
        # one full schedule pass (telemetry's step wall covers all gas)
        ticks = gas * (M + 2 * (S - 1) if sched in ("1f1b", "zb")
                       else 2 * (M + S - 1))
        info = {
            "stages": S, "micro_batches": M, "schedule": sched,
            "ticks": ticks,
            "bubble_pct": round(
                100 * executor_bubble_fraction(sched, M, S), 3),
            "gpipe_bubble_pct": round(
                100 * executor_bubble_fraction("gpipe", M, S), 3),
            "offload_activations": pr.offload_activations,
            "offload_moments": pr.offload_moments,
            "offload_bytes_per_step": 0,
        }
        mcfg = getattr(self.model, "config", None)
        from .swap_tensor import host_stage
        if pr.offload_activations and host_stage.available() \
                and mcfg is not None and hasattr(mcfg, "d_model"):
            # the ring traffic: each tick stages one microbatch's
            # activation D2H (ring write) and reads one back H2D —
            # the copy-overhead budget the offload must hide, PER CHIP
            # (the batch dim shards over dp, so a chip's ring only
            # stages its own slice). Zero on single-memory-space
            # backends: there staging is identity and reporting
            # phantom bytes would poison the A/B
            dp = max(1, self.topology.get_data_parallel_world_size())
            rows = max(1, self.config.train_batch_size
                       // (gas * dp * M))
            act = rows * mcfg.max_seq_len * mcfg.d_model * \
                jnp.dtype(self.param_dtype).itemsize
            info["offload_bytes_per_step"] = int(2 * ticks * act)
        return info

    # ------------------------------------------------------- comm overlap
    @staticmethod
    def _layer_grad_mb(model, dtype):
        """Per-layer gradient payload in MB — the shape-bucket key the
        grad-collective autotune ops (comm_bucket / grad_staging /
        dcn_quantize) are cached under. 1 when the model can't say."""
        mcfg = getattr(model, "config", None)
        count = getattr(mcfg, "num_params", None)
        if not callable(count):
            return 1
        n_layer = max(1, int(getattr(mcfg, "n_layer", 1)))
        per = count() * jnp.dtype(dtype).itemsize / n_layer
        return max(1, int(per) >> 20)

    def _resolve_grad_staging(self, co, topology, model):
        """comm_overlap.hierarchical: explicit bool wins; 'auto' is the
        'grad_staging' winner for this (device, topology, layer-payload)
        bucket — the do>1 heuristic on a cold cache (byte-identical to
        the pre-planner resolution)."""
        do = topology.axis_size("data_outer")
        if co.hierarchical != "auto":
            return bool(co.hierarchical)
        from ..ops.pallas._common import (dispatch, dtype_name,
                                          grad_comm_bucket)
        dt = self.config.precision_dtype
        win = dispatch(
            "grad_staging",
            grad_comm_bucket(self._layer_grad_mb(model, dt),
                             topology.mesh),
            dtype_name(dt), {"hierarchical": int(do > 1)})
        return bool(win["hierarchical"])

    def _install_comm_overlap(self, gdtype):
        """Install the per-layer comm hook on the model (zero/overlap.py):
        forward gathers the ZeRO-3 layer shard explicitly (the prefetch
        target), backward constrains the layer cotangent to its per-layer
        grad sharding so the reduce-scatter lands INSIDE the backward
        scan — grad comm for layer i overlapping compute of layer i-1 —
        optionally staged hierarchically over ('data','expert') then
        'data_outer'."""
        co = self.config.comm_overlap
        if not self._overlap_on:
            return
        blocks_grad = (self.plan.grad_specs.get("blocks")
                       if isinstance(self.plan.grad_specs, dict) else None)
        blocks_tp = (self._tp_specs.get("blocks")
                     if isinstance(self._tp_specs, dict) else None)
        if blocks_grad is None or blocks_tp is None or \
                not hasattr(self.model, "block_forward"):
            log_dist(
                "comm_overlap: model has no scanned 'blocks' params; "
                "per-layer annotations skipped (XLA flags unaffected)",
                ranks=[0])
            return
        is_spec = lambda x: isinstance(x, P)
        grad_layer = jax.tree.map(comm_overlap.drop_layer_dim, blocks_grad,
                                  is_leaf=is_spec)
        # 'auto' knobs resolve against the collective winner cache under
        # the gradient bucket for this model+topology; every cold-cache
        # default equals the hand-set value, so a miss compiles the
        # byte-identical program
        from ..ops.pallas._common import (dispatch, dtype_name,
                                          grad_comm_bucket,
                                          scan_unroll_bucket)
        dt_name = dtype_name(self.param_dtype)
        gbucket = grad_comm_bucket(
            self._layer_grad_mb(self.model, self.param_dtype), self.mesh)
        bucket_mb = co.bucket_mb
        if bucket_mb == "auto":
            bucket_mb = int(dispatch("comm_bucket", gbucket, dt_name,
                                     {"bucket_mb": 32})["bucket_mb"])
        dcn_quantize = co.dcn_quantize
        # 'quantize' block override (one roof for the low-precision
        # levers): grad_dcn=None defers to comm_overlap.dcn_quantize
        qz_grad = self.config.quantize.grad_dcn
        if qz_grad is not None:
            dcn_quantize = qz_grad
        if dcn_quantize == "auto":
            dcn_quantize = bool(dispatch("dcn_quantize", gbucket, dt_name,
                                         {"quantize": 0})["quantize"])
        gather_layer = None
        prefetch_on = (co.prefetch and self.zero_stage >= 3
                       and not self.offload_param_cfg.enabled)
        if prefetch_on:
            gather_layer = jax.tree.map(comm_overlap.drop_layer_dim,
                                        blocks_tp, is_leaf=is_spec)
            # unrolled scan bodies give the i+1 gather layer i's matmuls
            # to hide under; 'auto' = the 'scan_unroll' winner (2 — the
            # hand-set minimum overlap has shipped with — on a miss)
            unroll = co.scan_unroll
            if unroll == "auto":
                mcfg = getattr(self.model, "config", None)
                unroll = int(dispatch(
                    "scan_unroll",
                    scan_unroll_bucket(getattr(mcfg, "n_layer", 1),
                                       getattr(mcfg, "d_model", 0),
                                       self.mesh),
                    dt_name, {"unroll": 2})["unroll"])
            self.model._scan_unroll_min = int(unroll)
        self.model._layer_comm_hook = comm_overlap.make_layer_comm_hook(
            grad_layer, gather_specs=gather_layer,
            hierarchical=self._overlap_hier,
            dcn_quantize=dcn_quantize,
            bucket_bytes=bucket_mb << 20, gdtype=gdtype)
        log_dist(
            f"comm_overlap on: bucket_mb={bucket_mb} "
            f"prefetch={prefetch_on} hierarchical={self._overlap_hier} "
            f"dcn_quantize={dcn_quantize} "
            f"xla_flags={self._overlap_flags[1]}", ranks=[0])

    def verify_comm_overlap(self, batch, require_async=False):
        """Compile the train-step program on ``batch`` and report the
        collective schedule XLA ACTUALLY emitted (``compiled.as_text()``
        through zero/overlap.overlap_report): collective count, async
        start/done pairs, in-scan-loop placement — broken down per op in
        ``in_loop_by_op``, so a seq-parallel ring step shows its KV
        ``collective-permute`` rotation INSIDE the scan body — and the
        mesh axes each collective's replica groups map to.
        ``require_async`` raises if a dp>=2 step carries no async pairs —
        the overlap flags did not take effect (TPU/GPU only: CPU lowers
        collectives synchronously in HLO)."""
        batch = jax.tree.map(self._add_gas_dim, batch)
        batch = self._shard_batch(batch, with_gas_dim=True)
        with jax.set_mesh(self.mesh):
            if self.offload_enabled:
                compiled = self._grad_step_jit.lower(
                    self.state, batch, None).compile()
            else:
                compiled = self._train_step_jit.lower(
                    self.state, batch, self._current_lr(), None).compile()
        report = comm_overlap.overlap_report(compiled.as_text(),
                                             mesh=self.mesh)
        # pipelined step: attach the schedule analytics (bubble
        # fractions, offload payload) next to what the HLO shows — the
        # in-loop collective-permute count is the pipe's steady-state
        # rotation, host_copies its staging traffic
        pinfo = self.pipeline_report()
        if pinfo is not None:
            report["pipeline"] = pinfo
        self.comm_overlap_report = report
        if require_async and report["n_collectives"] \
                and not report["async_pairs"]:
            raise RuntimeError(
                f"comm_overlap: step has {report['n_collectives']} "
                f"collectives but no async start/done pairs — overlap "
                f"flags did not take effect (set DSTPU_COMM_OVERLAP=1 "
                f"in the environment before the backend initializes)")
        return report

    # -------------------------------------------------------------- telemetry
    def _telemetry_step_costs(self):
        """Step FLOPs + collective-schedule breakdown for the telemetry
        layer, from the COMPILED train-step program: flops via
        ``Compiled.cost_analysis()`` (the flops-profiler source — XLA's
        own count for the exact program that runs, per participating
        chip under SPMD), exposed-comm share via the PR-3
        ``overlap_report`` HLO parse (collectives with no async
        start/done pair). Called once, lazily, at the first telemetry
        flush — one extra AOT compile amortized over the run. Falls
        back to the analytic ``model.config.flops_per_token()`` when
        lowering is impossible (e.g. before any step ran)."""
        args = getattr(self, "_telemetry_lower_args", None)
        if args is None:
            return None
        # one-shot: the stash pins a full device-resident global batch
        # in HBM — released the moment the capture runs (the telemetry
        # layer's _costs_tried keeps the step path from re-stashing)
        self._telemetry_lower_args = None
        batch, lr, ltd = args
        with jax.set_mesh(self.mesh):
            if self.offload_enabled:
                compiled = self._grad_step_jit.lower(
                    self.state, batch, ltd).compile()
            else:
                compiled = self._train_step_jit.lower(
                    self.state, batch, lr, ltd).compile()
        from ..profiling.flops_profiler import compiled_costs
        costs = compiled_costs(compiled)
        flops = float(costs.get("flops", 0.0) or 0.0)
        source = "hlo"
        if flops <= 0:
            fpt = getattr(getattr(self.model, "config", None),
                          "flops_per_token", None)
            if callable(fpt):
                tokens = self.config.train_batch_size * \
                    self.model.config.max_seq_len
                flops = fpt() * tokens / max(1, int(self.mesh.size))
                source = "analytic"
        out = {"flops_per_chip": flops or None, "source": source,
               "collectives": None, "exposed_comm_pct": None}
        try:
            report = comm_overlap.overlap_report(compiled.as_text(),
                                                 mesh=self.mesh)
            from ..monitor.telemetry import collective_breakdown
            out["collectives"], out["exposed_comm_pct"] = \
                collective_breakdown(report["n_collectives"],
                                     report["async_pairs"])
        except Exception:  # noqa: BLE001 - breakdown is best-effort
            pass
        return out

    def telemetry_report(self):
        """The most recent telemetry snapshot (None when telemetry is
        off). Benches/tests call ``engine.telemetry.drain()`` first when
        they need queued background work folded in."""
        return None if self.telemetry is None else \
            self.telemetry.snapshot()

    def _telemetry_reconcile(self, trace_dir, steps):
        """TelemetryCollector's reconcile hook: parse the finished
        profiler capture into a StepDecomposition, score this engine's
        actual mesh/schedule with the planner's ``_score``, and stash
        the full drift report for :meth:`reconcile_report`. Returns the
        compact summary the collector emits, or None when the platform
        produced no parseable trace (the collector warns once)."""
        from ..autotuning import reconcile as _rec
        decomp, report = _rec.from_engine(self, trace_dir, steps=steps)
        self._last_reconcile = (decomp, report)
        return None if report is None else report.summary()

    def reconcile_report(self):
        """The most recent modeled-vs-measured drift report as a dict
        (``{"decomposition": ..., "drift": ...}``), or None before any
        profiled capture reconciled. Drain telemetry first — the parse
        runs on the collector's background pool."""
        pair = getattr(self, "_last_reconcile", None)
        if pair is None:
            return None
        decomp, report = pair
        return {
            "decomposition": None if decomp is None else decomp.to_dict(),
            "drift": None if report is None else report.to_dict(),
        }

    # ----------------------------------------------------------------- batch
    def deepspeed_io(self, dataset, batch_size=None, shuffle=True,
                     seed=None):
        """Build the engine's data loader (reference engine.py:1715
        ``deepspeed_io``). With data efficiency enabled, a
        DeepSpeedDataSampler drives it: deterministic across restarts
        (``sampler.state_dict``), curriculum-aware, resumable. The
        single-controller engine feeds GLOBAL batches, so the sampler
        runs at dp_rank 0 / dp_size 1 and train_batch shards them."""
        from .dataloader import DeepSpeedDataLoader, SamplerDataLoader
        batch_size = batch_size or self.config.train_batch_size
        seed = (self.config.data_efficiency_seed if seed is None
                else seed)
        if (self.config.data_efficiency_enabled
                or self.curriculum_scheduler is not None):
            from .data_pipeline.data_sampler import DeepSpeedDataSampler
            sampler = DeepSpeedDataSampler(
                total_samples=len(dataset),
                micro_batch_size=batch_size,
                data_parallel_rank=0, data_parallel_size=1,
                gradient_accumulation_steps=1,
                shuffle=shuffle, seed=seed,
                curriculum_scheduler=self.curriculum_scheduler)
            # a load_checkpoint that ran before the sampler existed
            # stashed the saved position (global consumed samples —
            # topology-independent); install it now
            stash = getattr(self, "_resume_sampler_state", None)
            if stash is not None:
                sampler.load_state_dict(stash)
                self._resume_sampler_state = None
            self.data_sampler = sampler
            return SamplerDataLoader(dataset, sampler)
        return DeepSpeedDataLoader(dataset, batch_size, shuffle=shuffle,
                                   seed=seed)

    @property
    def curriculum_difficulty(self):
        """Difficulty of the most recent train_batch (None before the
        first step / without a curriculum)."""
        return self._curriculum_difficulty

    def _current_lr(self):
        if self.lr_scheduler is not None:
            return jnp.asarray(self.lr_scheduler(self.global_step),
                               jnp.float32)
        # constant lr: reuse one device scalar (a fresh host->device
        # transfer per step adds real latency through remote transports);
        # invalidated if the user mutates optimizer.lr mid-training
        cached = getattr(self, "_lr_cache", None)
        if cached is None or cached[0] != self.optimizer.lr:
            self._lr_cache = (self.optimizer.lr,
                              jnp.asarray(self.optimizer.lr, jnp.float32))
        return self._lr_cache[1]

    def _add_gas_dim(self, x):
        """(train_batch_size, ...) -> (gas, train_batch_size//gas, ...)."""
        gas = self.config.gradient_accumulation_steps
        x = np.asarray(x)
        assert x.shape[0] == self.config.train_batch_size, (
            f"batch dim {x.shape[0]} != train_batch_size "
            f"{self.config.train_batch_size}")
        return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])

    def _shard_batch(self, batch, with_gas_dim):
        """Host batch -> global sharded arrays. Leaves (B_total, ...) or
        (gas, B, ...) when with_gas_dim."""
        seq_sharded = self.topology.get_sequence_parallel_world_size() > 1

        def put(x):
            x = np.asarray(x)
            dims = [None] * x.ndim
            b_dim = 1 if with_gas_dim else 0
            dims[b_dim] = BATCH_AXES
            if seq_sharded and x.ndim > b_dim + 1:
                dims[b_dim + 1] = "seq"
            return jax.device_put(
                x, NamedSharding(self.mesh, P(*dims)))

        return jax.tree.map(put, batch)

    def train_batch(self, batch):
        """One full optimizer step over a global batch.

        batch leaves: (train_batch_size, ...) host arrays; reshaped to
        (gas, train_batch_size // gas, ...) and scanned.

        Telemetry rides this path without touching it: the host wall
        time of the call (async dispatch — in steady state queue
        backpressure makes it track the device step) feeds the step
        ring, and any terminal exception (including the chaos suite's
        SimulatedKill) dumps the flight recorder before re-raising.
        """
        if self.telemetry is None:
            return self._train_batch_inner(batch)
        tokens = 0
        try:
            # shape only — np.asarray here would be a blocking D2H copy
            # of the whole leaf on every step for device-resident batches
            shape = next((getattr(x, "shape", None)
                          for x in jax.tree.leaves(batch)), None)
            if shape:
                tokens = int(shape[0]) * (
                    int(shape[1]) if len(shape) > 1 else 1)
        except Exception:  # noqa: BLE001 - tokens are advisory
            pass
        t0 = time.perf_counter()
        try:
            loss = self._train_batch_inner(batch)
        except BaseException as e:
            self.telemetry.on_crash(e)
            raise
        self.telemetry.on_step(self.global_step,
                               time.perf_counter() - t0, tokens=tokens)
        return loss

    def _train_batch_inner(self, batch):
        gas = self.config.gradient_accumulation_steps
        self.tput_timer.start()
        if self.curriculum_scheduler is not None:
            # curriculum (reference engine curriculum hook): the batch is
            # truncated to the scheduled difficulty BEFORE sharding when
            # the metric IS sequence length, so the jitted step compiles
            # one program per distinct difficulty (difficulty_step bounds
            # the count). Non-seqlen metrics only record the difficulty —
            # samplers/users consume it (truncating e.g. a vocab-rarity
            # percentile as a length would train on garbage).
            diff = self.curriculum_scheduler.update_difficulty(
                self.global_step + 1)
            self._curriculum_difficulty = diff
            if self.config.curriculum_config.get(
                    "curriculum_type", "seqlen") == "seqlen":
                batch = jax.tree.map(
                    lambda x: x[:, :diff] if getattr(x, "ndim", 0) >= 2
                    else x, batch)
        ltd_keep = None
        if self.random_ltd_scheduler is not None:
            ltd_keep = int(self.random_ltd_scheduler.update_seq(
                self.global_step))
        batch = jax.tree.map(self._add_gas_dim, batch)
        batch = self._shard_batch(batch, with_gas_dim=True)
        if self.telemetry is not None \
                and not self.telemetry._costs_tried \
                and getattr(self, "_telemetry_lower_args", None) is None:
            # stashed refs for the one-time lazy step-cost capture
            # (_telemetry_step_costs): same sharded shapes as the
            # program that runs, so lower() hits the compile cache.
            # Never re-stashed once the capture ran — the stash holds
            # a device-resident global batch
            self._telemetry_lower_args = (
                batch,
                None if self.offload_enabled else self._current_lr(),
                ltd_keep)
        with jax.set_mesh(self.mesh):
            if self.offload_enabled:
                grads, metrics = self._grad_step_jit(self.state, batch,
                                                     ltd_keep)
                metrics = self._host_optimizer_step(grads, metrics)
            else:
                self.state, metrics = self._train_step_jit(
                    self.state, batch, self._current_lr(), ltd_keep)
        self.global_step += 1
        self.micro_steps += gas
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self.tput_timer.stop(global_step=True,
                             sync_arrays=metrics["loss"])
        self._maybe_print(metrics)
        # liveness beat for the elastic agent: a worker that stops
        # completing steps (hung collective, wedged host) is killed and
        # restarted from 'latest' just like one that died outright
        from ..utils import touch_heartbeat
        touch_heartbeat()
        if self._preempt_requested:
            # SIGTERM arrived mid-step; the step above completed, so
            # state is at a clean boundary — drain and exit
            self._preempt_drain()
        return metrics["loss"]

    # ----------------------------------------------------- preemption drain
    def _install_preempt_drain(self):
        """Chain a SIGTERM handler that only requests a drain. Installed
        BEFORE the flight recorder's install_sigterm, so on a real
        signal the recorder dumps first and then falls through to us
        (its handler calls the previous disposition). Main-thread only
        — a non-main-thread engine build keeps the prior disposition."""
        import signal as _signal
        import threading as _threading
        if _threading.current_thread() is not _threading.main_thread():
            return False

        def _handler(signum, frame):
            # flag only — no logging/IO in signal context; the message
            # and the drain itself run at the next step boundary
            self._preempt_requested = True
            if callable(prev):
                prev(signum, frame)

        try:
            prev = _signal.signal(_signal.SIGTERM, _handler)
            return True
        except (ValueError, OSError):
            return False

    def _preempt_drain(self):
        """The graceful half of a preemption: force one hot+replica
        push of the CURRENT step (zero persistent-storage reads on the
        other side of the maintenance window), dump the flight
        recorder with the preemption recorded at the tail, and exit
        with the distinct code the elastic agent classifies as
        'preempted' (healthy host, no backoff penalty). The forced
        save is advisory — a failing push must not turn a clean
        preemption into a crash-looking death."""
        from ..elasticity.elastic_agent import PREEMPTED_EXIT_CODE
        self._preempt_requested = False
        logger.warning(
            f"preemption notice (SIGTERM) at step {self.global_step}: "
            f"forcing a hot+replica push, dumping the flight recorder, "
            f"exiting {PREEMPTED_EXIT_CODE} (preempted)")
        try:
            if self._last_ckpt_save_dir is not None:
                self.save_checkpoint(self._last_ckpt_save_dir)
            if self.hot_store is not None:
                self.hot_store.wait()
        except Exception as e:  # noqa: BLE001 - drain is best-effort
            logger.warning(f"preemption drain: forced push failed ({e}); "
                           f"exiting preempted anyway")
        if self.telemetry is not None:
            self.telemetry.flight.record(
                "preempted", step=self.global_step,
                drained=self._last_ckpt_save_dir is not None)
            self.telemetry.flight.dump(reason="preempted")
        raise SystemExit(PREEMPTED_EXIT_CODE)

    def _collect_local_shards(self, tree, record_meta=False):
        """Multi-process offload: per leaf, the 1D concatenation of THIS
        process's addressable shards (fp32). ``record_meta`` stores the
        (device, index, shape, size) piece layout so gradients can be
        validated against it and updated pieces pushed back."""
        metas = []

        def leaf(garr):
            shards = sorted(garr.addressable_shards,
                            key=lambda s: s.device.id)
            metas.append([(s.device, s.index, s.data.shape) for s in shards])
            return np.concatenate(
                [np.ravel(np.asarray(s.data)) for s in shards])

        out = jax.tree.map(leaf, tree)
        if record_meta:
            self._offload_shard_meta = metas
        else:
            for i, (got, want) in enumerate(
                    zip(metas, self._offload_shard_meta)):
                if [(g[1], g[2]) for g in got] != \
                        [(w[1], w[2]) for w in want]:
                    raise AssertionError(
                        f"offload leaf {i}: gradient shard layout "
                        f"{[(g[1], g[2]) for g in got]} does not match "
                        f"the master layout — grad and master shardings "
                        f"must partition identically for the host step")
        return out

    def _push_local_master(self, leaf_idx, w_flat):
        """Rebuild one global fp32 master leaf from this process's updated
        pieces (every process calls this for every leaf — the global
        array assembly is a collective contract, not a transfer)."""
        meta = self._offload_shard_meta[leaf_idx]
        sharding = jax.tree.leaves(self.master_shardings)[leaf_idx]
        shape = jax.tree.leaves(self.state["params"])[leaf_idx].shape
        bufs, off = [], 0
        for dev, index, pshape in meta:
            n = int(np.prod(pshape))
            bufs.append(jax.device_put(
                w_flat[off:off + n].reshape(pshape), dev))
            off += n
        return jax.make_array_from_single_device_arrays(
            shape, sharding, bufs)

    def _host_optimizer_step(self, grads, metrics):
        """ZeRO-Offload host half: pull grads, CPU-Adam the host master,
        push refreshed bf16 params leaf-by-leaf (reference
        stage_1_and_2.py:1745 step with cpu_offload; the leafwise push
        overlaps the next leaf's NVMe reads). Multi-process: each process
        steps only its addressable master shards; the refreshed params
        are re-assembled from per-process pieces and cast/resharded by a
        tiny jitted program (the all-gather the reference does with
        all_gather_dp_groups falls out of GSPMD)."""
        overflow = bool(np.asarray(metrics["overflow"]))
        if not overflow:
            lr = float(np.asarray(self._current_lr()))
            if self._offload_multi:
                host_grads = self._collect_local_shards(grads)
                del grads
                master_leaves = []

                def on_leaf_multi(path, w_flat, shape):
                    master_leaves.append(self._push_local_master(
                        len(master_leaves), w_flat))

                self.host_optimizer.step(host_grads, lr, on_leaf_multi)
                master_global = jax.tree.unflatten(
                    jax.tree.structure(self.state["params"]),
                    master_leaves)
                with jax.set_mesh(self.mesh):
                    self.state["params"] = self._offload_push_jit(
                        master_global)
            else:
                host_grads = jax.device_get(grads)
                del grads
                np_dtype = np.dtype(self.param_dtype)
                shardings_flat = jax.tree.leaves(self.param_shardings)
                leaves_out = []

                def on_leaf(path, w_flat, shape):
                    arr = w_flat.reshape(shape)
                    if arr.dtype != np_dtype:
                        arr = arr.astype(np_dtype)
                    leaves_out.append(jax.device_put(
                        arr, shardings_flat[len(leaves_out)]))

                self.host_optimizer.step(host_grads, lr, on_leaf)
                self.state["params"] = jax.tree.unflatten(
                    jax.tree.structure(self.state["params"]), leaves_out)
        self.state = self._offload_finalize_jit(
            self.state, jnp.asarray(overflow))
        return metrics

    # ------------------------------------------- staged fwd/bwd/step (parity)
    def forward(self, batch):
        """loss = engine(batch): computes loss AND grads (one fused jitted
        call — autodiff is a transform, not a tape) for the current micro
        batch; grads are staged for step()."""
        batch = self._shard_batch(batch, with_gas_dim=False)
        micro_idx = jnp.asarray(
            self.micro_steps % max(1, self.config.gradient_accumulation_steps),
            jnp.int32)
        with jax.set_mesh(self.mesh):
            loss, grads = self._micro_step_jit(self.state, batch, micro_idx)
            if self._acc_grads is None:
                zeros = jax.jit(
                    lambda g: jax.tree.map(jnp.zeros_like, g),
                    out_shardings=self.grad_shardings)(grads)
                self._acc_grads = zeros
            self._acc_grads = self._acc_add_jit(self._acc_grads, grads)
        self._pending_loss = loss
        return loss

    __call__ = forward

    def backward(self, loss=None):
        """Grads were produced in forward(); kept for API parity
        (reference engine.py:1968)."""
        self.micro_steps += 1
        return loss if loss is not None else self._pending_loss

    def is_gradient_accumulation_boundary(self):
        return self.micro_steps % self.config.gradient_accumulation_steps == 0

    def step(self):
        """Apply the optimizer at accumulation boundaries (reference
        engine.py:2170: non-boundary steps are no-ops)."""
        if not self.is_gradient_accumulation_boundary():
            return
        assert self._acc_grads is not None, "step() before forward()"
        with jax.set_mesh(self.mesh):
            if self.offload_enabled:
                metrics = self._staged_offload_step()
            else:
                self.state, metrics = self._apply_update_jit(
                    self.state, self._acc_grads, self._current_lr())
        self._acc_grads = None
        self.global_step += 1
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._maybe_print(metrics)
        return metrics

    def _staged_offload_step(self):
        """Staged-API ZeRO-Offload: unscale/clip the accumulated grads on
        device (prebuilt program), then run the host update."""
        grads, metrics = self._finish_grads_jit(
            self._acc_grads, self.state["scale"]["scale"])
        metrics["loss"] = self._pending_loss
        return self._host_optimizer_step(grads, metrics)

    # ------------------------------------------------------------------ misc
    def _write_monitor_events(self, metrics):
        if not self.monitor.enabled:
            return
        events = [("Train/Samples/lr", float(self._current_lr()),
                   self.global_step)]
        loss = metrics.get("loss")
        if loss is not None:
            events.append(("Train/Samples/train_loss", float(loss),
                           self.global_step))
        if self.loss_scaler.dynamic:
            events.append(("Train/Samples/loss_scale",
                           float(metrics["loss_scale"]), self.global_step))
        self.monitor.write_events(events)

    def _write_ckpt_monitor_events(self, kind, latency_ms):
        """Checkpoint health counters -> monitor fan-out (save/load
        latency plus the cumulative retry/fallback/GC counters the
        chaos acceptance criteria track)."""
        if not self.monitor.enabled:
            return
        c = self.checkpoint_engine.counters
        step = self.global_step
        # full literal tags (no f-string assembly): the metric-schema
        # lint greps production code for every documented tag
        latency_tag = ("Train/Checkpoint/save_latency_ms"
                       if kind == "save"
                       else "Train/Checkpoint/load_latency_ms")
        self.monitor.write_events([
            (latency_tag, latency_ms, step),
            ("Train/Checkpoint/retries", c["retries"], step),
            ("Train/Checkpoint/fallbacks", c["fallbacks"], step),
            ("Train/Checkpoint/save_errors", c["save_errors"], step),
            ("Train/Checkpoint/load_fallbacks", c["load_fallbacks"],
             step),
            ("Train/Checkpoint/gc_removed", c["gc_removed"], step),
            ("Train/Checkpoint/hot_pushes", c["hot_pushes"], step),
            ("Train/Checkpoint/hot_push_errors", c["hot_push_errors"],
             step),
            ("Train/Checkpoint/hot_restores", c["hot_restores"], step),
            ("Train/Checkpoint/hot_fallbacks", c["hot_fallbacks"],
             step),
            ("Train/Checkpoint/durable_restores", c["durable_restores"],
             step),
            ("Train/Checkpoint/replica_pushes", c["replica_pushes"],
             step),
            ("Train/Checkpoint/replica_restores", c["replica_restores"],
             step),
            ("Train/Checkpoint/replica_fallbacks", c["replica_fallbacks"],
             step),
        ])

    def _maybe_print(self, metrics):
        self._write_monitor_events(metrics)
        if (self.config.steps_per_print and
                self.global_step % self.config.steps_per_print == 0):
            loss = metrics.get("loss")
            loss_s = f"loss={float(loss):.4f} " if loss is not None else ""
            log_dist(
                f"step={self.global_step} {loss_s}"
                f"lr={float(self._current_lr()):.3e} "
                f"grad_norm={float(metrics['grad_norm']):.3f} "
                f"scale={float(metrics['loss_scale']):.0f} "
                f"overflow={bool(metrics['overflow'])}", ranks=[0])

    def get_flops_profile(self, batch):
        """Flops/bytes of the compiled train-step program on ``batch``
        (reference engine.py:2240-2252 flops-profiler hook; here the costs
        come from XLA's own cost analysis of the program that runs)."""
        from ..profiling.flops_profiler import FlopsProfiler, \
            compiled_costs
        batch = jax.tree.map(self._add_gas_dim, batch)
        batch = self._shard_batch(batch, with_gas_dim=True)
        prof = FlopsProfiler(self.model)
        prof.start_profile()
        prof.set_params(self.state["params"])
        with jax.set_mesh(self.mesh):
            compiled = self._train_step_jit.lower(
                self.state, batch, self._current_lr()).compile()
        costs = compiled_costs(compiled)
        prof.record("train_step", costs.get("flops", 0.0),
                    costs.get("bytes accessed", 0.0))
        return prof

    def get_lr(self):
        return [float(self._current_lr())]

    def get_global_grad_norm(self):
        return None  # computed in-step; exposed via metrics

    @property
    def params(self):
        return self.state["params"]

    @property
    def skipped_steps(self):
        return int(np.asarray(self.state["skipped"]))

    # ------------------------------------------------------------ checkpoint
    def _ckpt_tree(self):
        """State staged for saving: fp32 master + optimizer + scale +
        counters. bf16 params are re-derived on load (cast of master).
        Under ZeRO-Offload the master/opt live on the host (read back from
        NVMe when tiered)."""
        if self.offload_enabled:
            return {"master": self.host_optimizer.master_tree(),
                    "opt": self.host_optimizer.state_tree(),
                    "scale": self.state["scale"],
                    "step": self.state["step"],
                    "skipped": self.state["skipped"],
                    "rng_data": jax.random.key_data(self.state["rng"])}
        return {"master": self.state["master"], "opt": self.state["opt"],
                "scale": self.state["scale"], "step": self.state["step"],
                "skipped": self.state["skipped"],
                "rng_data": jax.random.key_data(self.state["rng"])}

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True):
        """See :meth:`_save_checkpoint_inner` — this wrapper feeds the
        telemetry layer (goodput overhead accounting, flight-recorder
        dump dir, crash dumps) without touching save semantics."""
        if self.telemetry is None:
            return self._save_checkpoint_inner(save_dir, tag,
                                               client_state, save_latest)
        # the ISSUE-9 dump location: {ckpt_root}/flightrec/host{n}.json
        # (config/env dirs win — set_root is first-wins)
        self.telemetry.flight.set_root(
            os.path.join(save_dir, "flightrec"))
        t0 = time.perf_counter()
        try:
            out = self._save_checkpoint_inner(save_dir, tag,
                                              client_state, save_latest)
        except BaseException as e:
            self.telemetry.note_overhead("checkpoint_save",
                                         time.perf_counter() - t0)
            self.telemetry.on_crash(e)
            raise
        self.telemetry.note_overhead("checkpoint_save",
                                     time.perf_counter() - t0)
        self.telemetry.record_event("checkpoint_saved", tag=str(out))
        return out

    def _save_checkpoint_inner(self, save_dir, tag=None,
                               client_state=None, save_latest=True):
        """reference engine.py:3124. Layout:
        {save_dir}/{tag}/shard-{process}.npz + {save_dir}/latest (shared
        FS, like the reference assumes).

        Each process writes ONLY its addressable shards (the reference's
        per-rank _save_zero_checkpoint, engine.py:3545) — no
        process_allgather of the full model state over DCN, no single
        writer. The shard files carry a chunk index so ANY ZeRO stage /
        mesh / process count reassembles the global logical tensors on
        load — the property the reference needs checkpoint/
        ds_to_universal.py for. Durable-latest: single-process, the
        'latest' pointer is written by the checkpoint engine only after
        the shard's bytes are durable (async overlap preserved);
        multi-process, every process drains its own writes and a
        cross-process barrier runs before rank 0 publishes 'latest', so
        it can never name a checkpoint whose other-rank shards are still
        in flight.
        """
        import os
        from ..utils import fault_injection
        from .checkpoint_engine import serialization as ser
        t_start = time.perf_counter()
        tag = tag or f"global_step{self.global_step}"
        self.checkpoint_engine.create(tag)
        # D2H staging of LOCAL shards only (the VELOC _d2h_trf analogue;
        # synchronous, bandwidth-bound), then the engine writes async if
        # configured.
        fault_injection.fire("d2h")
        chunks, index, meta = ser.extract_local_chunks(self._ckpt_tree())
        sampler = getattr(self, "data_sampler", None)
        extra = {
            "index": index,
            "__tree_meta__": meta,
            "user_extra": {
                "global_step": self.global_step,
                "micro_steps": self.micro_steps,
                "zero_stage": self.zero_stage,
                "nprocs": jax.process_count(),
                "lr_scheduler": (self.lr_scheduler.state_dict()
                                 if self.lr_scheduler is not None else None),
                "client_state": client_state or {},
                # reshape-on-resume metadata: the topology/batch shape
                # this generation was written under (diagnostic + the
                # global-batch preservation rule) and the sampler
                # position (topology-independent: consumed samples are
                # global). Specs are NEVER loaded from here — resume
                # re-derives them from the model + current mesh.
                "topology": self._topology_desc(),
                "batch": {
                    "train_batch_size": self.config.train_batch_size,
                    "micro": self.config.train_micro_batch_size_per_gpu,
                    "gas": self.config.gradient_accumulation_steps,
                },
                "zero_plan": self.plan.describe(),
                "sampler": (sampler.state_dict()
                            if sampler is not None else None),
            },
        }
        path = os.path.join(save_dir, tag,
                            f"shard-{jax.process_index()}.npz")

        # hot tier: replicate this shard to the ring neighbors off the
        # critical path (advisory — a hot-tier failure can never cost
        # the durable save). The dcn transport is collective, so it
        # runs in-caller at this save boundary (every process is here).
        self._last_ckpt_save_dir = save_dir
        if self.hot_store is not None:
            if (os.environ.get("DSTPU_HOT_TRANSPORT") == "dcn"
                    and jax.process_count() > 1):
                self.hot_store.push_collective(tag, chunks, extra)
            else:
                self.hot_store.push_async(tag, chunks, extra)
            if self.plan.cross_slice_replica():
                # MiCS: master/opt replicate over data_outer — register
                # the sibling-slice copy THIS process already holds in
                # HBM as a replica-tier restore source. Its extra omits
                # nprocs: the replica set's completeness is enforced by
                # per-leaf chunk coverage, not by the canonical
                # shard-file count
                rchunks, ridx, rmeta = ser.extract_replica_chunks(
                    self._ckpt_tree())
                rextra = {
                    "index": ridx,
                    "__tree_meta__": rmeta,
                    "user_extra": dict(extra["user_extra"],
                                       nprocs=None,
                                       zero_replica=True),
                }
                self.hot_store.push_zero_replica(tag, rchunks, rextra)

        from .checkpoint_engine import manager as ckpt_manager
        keep_last = getattr(self.config.checkpoint_engine, "keep_last", 0)
        seq = self.global_step   # captured NOW: with async engines two
        # in-flight saves can reach durability out of order; the seq
        # guard keeps 'latest' from regressing to the older one

        def mark_latest():
            ckpt_manager.publish_latest(save_dir, tag, seq=seq)
            # retention GC rides the durability path (the writer thread
            # for async engines), so it can never run before the new
            # generation is durable; gc_tags itself re-verifies the
            # newest tag before deleting anything and never raises
            ckpt_manager.gc_tags(save_dir, keep_last,
                                 counters=self.checkpoint_engine.counters)

        rank0 = jax.process_index() == 0
        if save_latest and jax.process_count() > 1:
            # 'latest' must only ever name a checkpoint whose EVERY shard
            # is durable. on_durable fires when THIS process's shard is
            # down; other ranks may still be writing (especially async) —
            # so drain local writes, then agree cross-process before
            # rank 0 publishes. The agreement is an allgather of per-rank
            # success flags (itself the barrier): a rank whose save
            # failed must still REACH the collective — raising before it
            # would deadlock every surviving rank — and a failure on ANY
            # rank vetoes publication, so 'latest' cannot name a
            # generation with a missing shard.
            err = None
            try:
                self.checkpoint_engine.save((chunks, extra), path)
                self.checkpoint_engine.wait()
            except Exception as e:  # noqa: BLE001 - re-raised after sync
                err = e
            from jax.experimental import multihost_utils
            flags = multihost_utils.process_allgather(
                np.asarray([0.0 if err is not None else 1.0],
                           np.float32))
            all_ok = bool(np.asarray(flags).min() >= 1.0)
            # a no-op engine (checkpoint=none) writes nothing: publishing
            # 'latest' would dangle at an empty tag directory
            if rank0 and all_ok and os.path.exists(path):
                mark_latest()
            elif rank0 and not all_ok:
                log_dist(
                    f"not publishing 'latest' for tag {tag!r}: a rank's "
                    f"shard write failed; the previous durable "
                    f"generation remains the recovery point", ranks=[0])
            if err is not None:
                raise err
        else:
            self.checkpoint_engine.save(
                (chunks, extra), path,
                on_durable=(mark_latest if save_latest and rank0
                            else None))
        self.checkpoint_engine.commit(tag)
        self._write_ckpt_monitor_events(
            "save", (time.perf_counter() - t_start) * 1e3)
        return tag

    def _topology_desc(self):
        t = self.topology
        return {"world": int(self.mesh.size),
                "dp": t.get_data_parallel_world_size(),
                "tp": t.get_model_parallel_world_size(),
                "ep": t.get_expert_parallel_world_size(),
                "seq": t.get_sequence_parallel_world_size(),
                "pipe": t.get_pipe_parallel_world_size()}

    def load_checkpoint(self, load_dir, tag=None,
                        load_optimizer_states=True,
                        load_lr_scheduler_states=True,
                        elastic_reshape=True):
        """See :meth:`_load_checkpoint_inner` — telemetry wrapper:
        restore latency feeds goodput, the serving tier lands in the
        flight recorder (the fact a post-restore crash dump must
        carry), and terminal failures dump before re-raising."""
        if self.telemetry is None:
            return self._load_checkpoint_inner(
                load_dir, tag, load_optimizer_states,
                load_lr_scheduler_states, elastic_reshape)
        self.telemetry.flight.set_root(
            os.path.join(load_dir, "flightrec"))
        t0 = time.perf_counter()
        try:
            out = self._load_checkpoint_inner(
                load_dir, tag, load_optimizer_states,
                load_lr_scheduler_states, elastic_reshape)
        except BaseException as e:
            self.telemetry.note_overhead("checkpoint_restore",
                                         time.perf_counter() - t0)
            self.telemetry.on_crash(e)
            raise
        if out[0] is not None:
            self.telemetry.on_restore(self.last_restore_tier, out[0],
                                      time.perf_counter() - t0)
        return out

    def _load_checkpoint_inner(self, load_dir, tag=None,
                               load_optimizer_states=True,
                               load_lr_scheduler_states=True,
                               elastic_reshape=True):
        """reference engine.py:2750. Returns (path, client_state).

        Recovery semantics: with no explicit ``tag``, the HOT TIER's
        surviving in-memory replicas are tried first (the common
        single-host loss restores with zero persistent-storage reads),
        then the durable candidates: the 'latest'-named generation
        first, then every other durable tag newest-first — a corrupt or
        truncated shard (CRC mismatch, torn zip, missing chunks) makes
        the loader FALL BACK to the previous durable generation instead
        of crashing the restart. Only when a checkpoint exists but NO
        generation is loadable does it raise (resuming silently from
        scratch would be worse). An explicit ``tag`` is never
        substituted. ``self.last_restore_tier`` records which tier
        ('hot'/'replica'/'durable') served the load; with ``'hot'`` or
        ``'replica'`` the returned
        path names the generation but may not exist on persistent
        storage (a hot generation whose durable commit never landed is
        deliberately restorable). Under an elastic agent
        (``ELASTIC_GENERATION`` in the env), a checkpoint that exists
        but has NO loadable generation exits with
        ``CORRUPT_CKPT_EXIT_CODE`` so the agent classifies the failure
        as corrupt-checkpoint (healthy host kept, backoff applied)
        instead of dropping the host as dead.

        Reshape-on-resume (``elastic_reshape``, default on): a
        checkpoint written under a DIFFERENT dp×tp×ep topology or ZeRO
        stage loads anyway — state re-partitions from the global logical
        tensors onto the current plan, gradient-accumulation steps
        rescale so the GLOBAL batch size is preserved, the sampler
        position carries over (consumed samples are global), and the RNG
        key is folded deterministically for the new mesh."""
        import os
        from .checkpoint_engine import serialization as ser
        from .checkpoint_engine import manager as ckpt_manager
        t_start = time.perf_counter()
        # drain, not wait: a previously FAILED async save must not block
        # reading the durable generations that did land
        self.checkpoint_engine.drain()
        if self.hot_store is not None:
            self.hot_store.wait()

        def loader(tag_dir):
            legacy = os.path.join(tag_dir, "state.npz")
            if os.path.exists(legacy):
                return self.checkpoint_engine.load(legacy)
            return ser.load_sharded(tag_dir)

        try:
            tier, cand, flat, header = ckpt_manager.load_best_tiered(
                load_dir, tag, hot_store=self.hot_store, loader=loader,
                counters=self.checkpoint_engine.counters)
        except ser.CheckpointCorruptionError:
            if os.environ.get("ELASTIC_GENERATION") is not None:
                # supervised by an elastic agent: exit with the
                # corrupt-checkpoint code so the agent keeps this
                # (healthy) host and backs off instead of shrinking the
                # world around a storage problem
                from ..elasticity.elastic_agent import (
                    CORRUPT_CKPT_EXIT_CODE)
                logger.error(
                    f"no loadable checkpoint generation under "
                    f"{load_dir}; exiting {CORRUPT_CKPT_EXIT_CODE} for "
                    f"the elastic agent's corrupt-checkpoint handling")
                raise SystemExit(CORRUPT_CKPT_EXIT_CODE)
            raise
        self.last_restore_tier = tier
        if cand is None:
            return None, {}
        path = os.path.join(load_dir, cand)
        # structural template only — no device transfer
        template = jax.eval_shape(self._ckpt_tree)
        tree = ser.unflatten_into(template, flat, header.get("meta"))
        extra = header["extra"]

        master = tree["master"]
        with jax.set_mesh(self.mesh):
            state = dict(self.state)
            if self.offload_enabled:
                self.host_optimizer.load_master_tree(master)
                if load_optimizer_states:
                    self.host_optimizer.load_state_tree(tree["opt"])
                np_dtype = np.dtype(self.param_dtype)
                state["params"] = jax.tree.map(
                    lambda m, s: jax.device_put(
                        np.asarray(m, np.float32).astype(np_dtype), s),
                    master, self.param_shardings)
            else:
                new_master = jax.device_put(master, self.master_shardings)
                new_params = jax.jit(
                    lambda m: _tree_cast(m, self.param_dtype),
                    out_shardings=self.param_shardings)(new_master)
                state["master"] = new_master
                state["params"] = new_params
                if load_optimizer_states:
                    state["opt"] = jax.device_put(tree["opt"],
                                                  self.opt_shardings)
            state["scale"] = jax.device_put(tree["scale"],
                                            self.state_shardings["scale"])
            state["step"] = jax.device_put(
                jnp.asarray(tree["step"], jnp.int32),
                self.state_shardings["step"])
            state["skipped"] = jax.device_put(
                jnp.asarray(tree.get("skipped", 0), jnp.int32),
                self.state_shardings["skipped"])
            state["rng"] = jax.device_put(
                jax.random.wrap_key_data(tree["rng_data"]),
                self.state_shardings["rng"])
        self.state = state
        self.global_step = int(extra.get("global_step", 0))
        self.micro_steps = int(extra.get("micro_steps", 0))
        if (load_lr_scheduler_states and self.lr_scheduler is not None
                and extra.get("lr_scheduler") is not None):
            self.lr_scheduler.load_state_dict(extra["lr_scheduler"])
        # sampler position: consumed samples are GLOBAL, so the position
        # carries across any topology. Applied to a live sampler when
        # one exists; stashed otherwise and installed by deepspeed_io
        # when the sampler is built after the resume.
        sampler_state = extra.get("sampler")
        if sampler_state is not None:
            live = getattr(self, "data_sampler", None)
            if live is not None:
                live.load_state_dict(sampler_state)
            else:
                self._resume_sampler_state = sampler_state
        if elastic_reshape:
            self._reshape_on_resume(extra)
        self._write_ckpt_monitor_events(
            "load", (time.perf_counter() - t_start) * 1e3)
        return path, extra.get("client_state", {})

    def _preserve_saved_global_batch(self, extra):
        """The global-batch preservation rule: the checkpoint's
        train_batch_size wins over a batch DERIVED from a
        micro-batch-only config (an EXPLICIT train_batch_size in the
        user's raw config is their call and is respected, with a
        warning). With the per-host micro batch fixed,
        gradient-accumulation steps rescale to
        ``saved_train_batch / (micro * dp)`` — an indivisible
        combination raises instead of silently training at a different
        effective batch. Returns True when the step programs were
        rebuilt under the new gas."""
        from .constants import TRAIN_BATCH_SIZE
        saved_batch = extra.get("batch") or {}
        target = saved_batch.get("train_batch_size")
        if not target or target == self.config.train_batch_size:
            return False
        if TRAIN_BATCH_SIZE in getattr(self.config, "_raw", {}):
            log_dist(
                f"resume: checkpoint global batch {target} != the "
                f"explicitly configured train_batch_size "
                f"{self.config.train_batch_size}; the explicit config "
                f"wins (drop train_batch_size from the config to "
                f"preserve the checkpoint's batch across topologies)",
                ranks=[0])
            return False
        micro = self.config.train_micro_batch_size_per_gpu
        dp = self.topology.get_data_parallel_world_size()
        new_gas = target // max(1, micro * dp)
        if new_gas < 1 or new_gas * micro * dp != target:
            raise ValueError(
                f"reshape-on-resume: cannot preserve the global "
                f"batch size {target} at dp={dp} with "
                f"micro_batch={micro} (needs gradient_"
                f"accumulation_steps={target}/{micro * dp}); "
                f"pick a micro batch that divides it")
        log_dist(
            f"resume: preserving global batch {target}: "
            f"gradient_accumulation_steps "
            f"{self.config.gradient_accumulation_steps} -> {new_gas} "
            f"at dp={dp}", ranks=[0])
        self.config.gradient_accumulation_steps = new_gas
        self.config.train_batch_size = target
        self.tput_timer.batch_size = target
        # gas is closed over by every jitted step program
        self._build_programs()
        return True

    def _reshape_on_resume(self, extra):
        """Adapt the resumed run to a topology change (runtime/zero/
        partitioning.py reshape_diff documents what re-partitioned; the
        device_put in load_checkpoint already re-sharded the global
        logical tensors onto the current plan). Returns True when the
        checkpoint was written under a different topology.

        The global-batch preservation rule: the checkpoint's
        train_batch_size wins. With the per-host micro batch fixed,
        gradient-accumulation steps rescale to
        ``saved_train_batch / (micro * new_dp)`` — an indivisible
        combination raises instead of silently training at a different
        effective batch. The RNG key folds with the new dp world so the
        resumed world's per-microstep streams are deterministic (a
        same-topology resume keeps the key bitwise)."""
        from ..utils import fault_injection
        from .zero.partitioning import reshape_diff
        saved_topo = extra.get("topology") or {}
        cur_topo = self._topology_desc()
        stage_changed = ("zero_stage" in extra
                        and extra["zero_stage"] != self.zero_stage)
        topo_changed = bool(saved_topo) and saved_topo != cur_topo
        # global-batch preservation runs REGARDLESS of a topology
        # change: a run that was itself reshaped saves gas≠1 under its
        # own topology, and a fresh same-topology engine built from the
        # micro-batch-only config would silently shrink the effective
        # batch on resume
        rescaled = self._preserve_saved_global_batch(extra)
        if rescaled:
            # accumulation boundaries re-align to the new gas
            self.micro_steps = self.global_step * \
                self.config.gradient_accumulation_steps
        if not topo_changed and not stage_changed:
            return rescaled
        fault_injection.fire("reshape")
        diff = reshape_diff(extra.get("zero_plan"), self.plan)
        log_dist(
            f"reshape-on-resume: checkpoint topology {saved_topo} / "
            f"stage {extra.get('zero_stage')} -> {cur_topo} / stage "
            f"{self.zero_stage}; {len(diff['resharded'])} leaves "
            f"re-partitioned (group {diff['old_partition_group']} -> "
            f"{diff['new_partition_group']}), "
            f"{len(diff['replicated'])} replicated on the new mesh",
            ranks=[0])
        if topo_changed:
            self.micro_steps = self.global_step * \
                self.config.gradient_accumulation_steps
            # deterministic RNG fold for the new mesh: every surviving
            # world derives the same key, distinct from the old world's
            fold = int(cur_topo["dp"]) * 1000003 + int(cur_topo["world"])
            rep = self.state_shardings["rng"]
            with jax.set_mesh(self.mesh):
                self.state["rng"] = jax.jit(
                    lambda r: jax.random.fold_in(r, fold),
                    out_shardings=rep)(self.state["rng"])
        if self.monitor.enabled:
            self.monitor.write_events([
                ("Train/Checkpoint/reshape", 1, self.global_step),
            ])
        if self.telemetry is not None:
            self.telemetry.record_event(
                "reshape", saved=saved_topo, current=cur_topo,
                stage=self.zero_stage)
        return True

    def save_checkpoint_terminate(self):
        """Fork parity (engine.py:3114): drain async checkpoint work."""
        dist.barrier()
        self.checkpoint_engine.wait()
        self.checkpoint_engine.shutdown()
        if self.hot_store is not None:
            self.hot_store.shutdown()
        if self.telemetry is not None:
            self.telemetry.close()
        dist.barrier()

    def save_16bit_model(self, save_dir, dtype=None):
        """Consolidated HF export (reference engine.py:3625
        ``save_16bit_model`` + utils/zero_to_fp32.py): write the CURRENT
        model weights — whatever the ZeRO stage or mesh sharding — as a
        standard HuggingFace checkpoint directory that ``transformers``
        loads directly.

        TPU-first: no per-rank partitioned files to stitch. The bf16
        param tree already exists as global jax.Arrays; a single host
        gather (process_allgather across hosts) consolidates it, and
        rank 0 writes model.safetensors + config.json via
        checkpoint/hf_export.py. Returns the save path (all ranks).
        """
        from ..checkpoint.hf_export import export_hf
        params = self.state["params"]
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            params = multihost_utils.process_allgather(params, tiled=True)
        else:
            params = jax.tree.map(lambda a: np.asarray(a), params)
        if jax.process_index() == 0:
            export_hf(self.model, params, save_dir,
                      dtype=dtype or jnp.dtype(self.param_dtype).name)
        dist.barrier()
        return save_dir

    def eval_loss(self, batch):
        batch = self._shard_batch(batch, with_gas_dim=False)
        with jax.set_mesh(self.mesh):
            return self._eval_loss_jit(self.state["params"], batch)
