"""Wall-clock and throughput timers.

Counterpart of the reference's ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` at utils/timer.py:43, ``ThroughputTimer`` at
utils/timer.py:198). On TPU there are no CUDA events; synchronization is a
``jax.block_until_ready`` fence on whatever arrays the caller hands us, or a
plain device barrier via ``jax.effects_barrier`` when none are given.
"""

import time

from .logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _device_sync(arrays=None):
    try:
        import jax
        if arrays is not None:
            jax.block_until_ready(arrays)
        else:
            jax.effects_barrier()
    except Exception:
        pass


class _Timer:
    def __init__(self, name):
        self.name_ = name
        self.started_ = False
        self.elapsed_ = 0.0
        self.start_time = 0.0
        self.records = []

    def start(self):
        assert not self.started_, f"{self.name_} timer has already been started"
        self.start_time = time.time()
        self.started_ = True

    def stop(self, record=False, sync_arrays=None):
        assert self.started_, f"{self.name_} timer is not started"
        _device_sync(sync_arrays)
        elapsed = time.time() - self.start_time
        self.elapsed_ += elapsed
        if record:
            self.records.append(elapsed)
        self.started_ = False

    def reset(self):
        self.started_ = False
        self.elapsed_ = 0.0

    def elapsed(self, reset=True):
        started = self.started_
        if started:
            self.stop()
        elapsed = self.elapsed_
        if reset:
            self.reset()
        if started:
            self.start()
        return elapsed

    def mean(self):
        if not self.records:
            return 0.0
        return sum(self.records) / len(self.records)


class SynchronizedWallClockTimer:
    """Group of named timers; ``log`` prints elapsed ms like the reference."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def has_timer(self, name):
        return name in self.timers

    def log(self, names, normalizer=1.0, reset=True, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += f" | {name}: {elapsed:.2f}"
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0):
        assert normalizer > 0.0
        return {
            name: self.timers[name].mean() * 1000.0 / normalizer
            for name in names if name in self.timers
        }


class ThroughputTimer:
    """Tokens/samples-per-second accounting (reference utils/timer.py:198)."""

    def __init__(self, batch_size, start_step=2, steps_per_output=50, monitor_memory=False):
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.total_timed_steps = 0
        self.steps_per_output = steps_per_output
        # Async-dispatch-honest accounting: a hard device sync every step
        # would serialize host prep with device compute (the overlap IS the
        # TPU performance story), so time is measured over report WINDOWS:
        # one sync when the window opens, one when it closes; everything
        # in between stays pipelined. Per-step times inside a window are
        # not individually observable — only window averages are reported.
        self._window_start = None
        self._window_steps = 0

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        self.started = True
        if (self.global_step_count >= self.start_step
                and self._window_start is None):
            _device_sync()
            self._window_start = time.time()
            self._window_steps = 0

    def _close_window(self, sync_arrays=None):
        """Sync the device and fold the open window into the running
        totals. Returns the window's (duration, steps) or None."""
        if self._window_start is None or self._window_steps == 0:
            return None
        _device_sync(sync_arrays)
        now = time.time()
        window = now - self._window_start
        steps = self._window_steps
        self.total_elapsed_time += window
        self.total_timed_steps += steps
        self._window_start = now
        self._window_steps = 0
        return window, steps

    def stop(self, global_step=False, report_speed=True, sync_arrays=None):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
            if self._window_start is not None:
                self._window_steps += 1
                if (self.steps_per_output and self.global_step_count
                        % self.steps_per_output == 0):
                    closed = self._close_window(sync_arrays)
                    if report_speed and closed and closed[0] > 0:
                        window, steps = closed
                        log_dist(
                            f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                            f"global_step={self.global_step_count}, "
                            f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.6g}, "
                            f"CurrSamplesPerSec={self.batch_size * steps / window:.6g}",
                            ranks=[0])

    def avg_samples_per_sec(self):
        # close any open window first (with steps_per_output=0 nothing
        # else ever folds time in, and the sync makes the answer honest)
        self._close_window()
        if self.total_timed_steps > 0 and self.total_elapsed_time > 0:
            samples = self.batch_size * self.total_timed_steps
            return samples / self.total_elapsed_time
        return float("-inf")
