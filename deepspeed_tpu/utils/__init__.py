from .logging import logger, log_dist, print_rank_0
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from . import groups


def touch_heartbeat():
    """Liveness beat consumed by DSElasticAgent's hang detector. The
    agent (or launcher) sets DSTPU_HEARTBEAT_FILE in the worker env; the
    engine touches it once per completed train_batch. Unset = no-op, so
    standalone runs pay one dict lookup."""
    import os
    path = os.environ.get("DSTPU_HEARTBEAT_FILE")
    if not path:
        return
    try:
        with open(path, "a"):
            pass
        os.utime(path, None)
    except OSError:
        pass
