from .logging import logger, log_dist, print_rank_0
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from . import groups
