"""Deterministic fault injection for the checkpoint/recovery subsystem
and the serving plane (router/replica front-end).

The chaos tests (tests/unit/test_chaos_checkpoint.py) need to prove that
a torn shard, a dying writer thread, or a crash between "bytes written"
and "latest published" never costs a resumable run. Random fault
injection cannot prove that — it proves "we got lucky this run". This
module provides NAMED, COUNTED injection points threaded through the
save pipeline, so a test can say "the 2nd byte-write of this save
fails" and get exactly that, every run.

Injection points currently wired (grep for ``fault_injection.fire``):

  ==============  =====================================================
  point           fires in
  ==============  =====================================================
  d2h             runtime/engine.py save_checkpoint, after the local
                  shard extraction (the VELOC D2H stage)
  serialize       serialization.save_file, before the pytree is encoded
  write           serialization.save_file byte write, and
                  ops/native/ckpt_writer.py Writer.write (C++ path)
  rename          serialization.save_file, before the atomic
                  tmp -> final os.replace
  commit          checkpoint_engine manager publish_latest, before the
                  'latest' pointer is replaced
  replica_push    checkpoint_engine hot_tier, once per peer replica
                  write (the in-memory hot tier's DCN push)
  replica_fetch   checkpoint_engine hot_tier, once per remote-peer
                  shard fetch during hot-tier assembly — arming it
                  poisons the replicas so loads degrade to the durable
                  tier
  host_loss       elasticity/elastic_agent.py membership change, once
                  per failed host (and hot_tier.purge_node) — the
                  host-RAM-loss boundary of the hot tier
  slice_loss      checkpoint_engine hot_tier, once per slice-aware
                  push boundary (arming with ``kill`` models a whole
                  slice dying mid-training), and
                  elasticity/elastic_agent.py, once per fully-lost
                  slice at membership change
  dcn_partition   checkpoint_engine hot_tier collective push, before
                  each cross-slice ``ring_exchange_bytes`` — arming it
                  models a DCN partition during the exchange (advisory:
                  the durable save still lands)
  replica_restore checkpoint_engine hot_tier, once per replica-TIER
                  source read during assembly (cross-slice replicas and
                  the registered ZeRO replica) — arming it poisons the
                  replica tier so loads degrade to durable
  reshape         runtime/engine.py load_checkpoint, before the
                  reshape-on-resume path re-partitions state onto a
                  different topology
  serve_dispatch  inference/v2/replica.py Replica.submit, once per
                  request handed to a replica engine — the router's
                  dispatch boundary (retryable: the request re-queues
                  at the front and re-routes next round)
  serve_step      inference/v2/replica.py Replica.step, once per
                  scheduler iteration (retryable: the replica health
                  machine counts it; ``max_step_failures`` CONSECUTIVE
                  failures = no recent step progress = the heartbeat
                  contract broken, and the replica is declared dead)
  replica_death   inference/v2/replica.py Replica.step, once per
                  iteration — arming it models the replica worker
                  dying mid-decode; the router (the supervising
                  recovery layer, like the elastic agent for
                  host_loss) re-enqueues its in-flight requests and
                  replays them on a survivor
  serve_verify    inference/v2/replica.py Replica.step, once per
                  iteration whose next engine step would run a
                  speculative verify dispatch (``engine.spec_pending``)
                  — arming it models a failure landing mid-speculation
                  (retryable: the replica health machine counts it like
                  serve_step; the engine's rollback must leave no
                  speculative tokens behind and a failover replay must
                  stay byte-identical)
  router_overload inference/v2/router.py overload detection, once per
                  router step — arming it injects a forced overload
                  round (advisory: load is shed as typed Overloaded
                  rejections; it can never kill a replica or fail a
                  request the shed policy would not have picked)
  kv_stream       inference/v2/kv_transfer.py transport ``send``, once
                  per prefill->decode handoff payload (retryable: the
                  prefill replica keeps full ownership until the decode
                  side confirms the import, so the router leaves the
                  sequence parked and retries next round from
                  unchanged state)
  kv_import       inference/v2/kv_transfer.py import_sequence, before
                  the handoff payload is unpacked into the decode
                  replica's allocator/cache (retryable: fires before
                  any decode-side mutation, so a failed import leaves
                  both replicas unchanged and the router retries)
  kill            any of the above via ``kill=True`` — raises
                  SimulatedKill (BaseException) which NO layer retries,
                  modeling SIGKILL mid-save
  ==============  =====================================================

Faults are armed per-point with a countdown (skip the first N fires)
and a failure budget (fail the next M fires, then heal) — enough to
express "fail once then succeed" (retry coverage), "always fail"
(degrade coverage), and "die at the commit boundary" (crash-consistency
coverage) deterministically.

Arming is process-local via :func:`arm` / :func:`reset`, or via the
``DSTPU_FAULT_INJECT`` env var for subprocess tests:
``DSTPU_FAULT_INJECT="write:2,rename:1:skip=1"`` arms two write
failures and one rename failure after one clean rename.
"""

import os
import threading

# Canonical registry of every named injection point wired into
# production code. tests/unit/test_fault_points_lint.py asserts (a)
# each of these is fired somewhere in deepspeed_tpu/ and (b) each is
# armed by at least one chaos test — so injection points cannot
# silently rot when the code around them is refactored. Add the point
# here WHEN you add its fire() call.
KNOWN_POINTS = (
    "d2h",
    "serialize",
    "write",
    "rename",
    "commit",
    "replica_push",
    "replica_fetch",
    "replica_restore",
    "dcn_partition",
    "host_loss",
    "slice_loss",
    "reshape",
    "serve_dispatch",
    "serve_step",
    "serve_verify",
    "replica_death",
    "router_overload",
    "kv_stream",
    "kv_import",
)

# Blast-radius class per injection point — the contract the lint in
# tests/unit/test_fault_points_lint.py enforces mechanically:
#
#   advisory   the failure is counted/logged and MUST NOT propagate to
#              the save/load caller (the PR-7 "a push failure can never
#              cost the durable save" rule; loads degrade down-tier)
#   retryable  the save retry/degrade policy owns the failure — it may
#              surface only as CheckpointSaveError after the budget
#   fatal      the failure propagates (crash-consistency boundaries and
#              process/host/slice-death points; only ``kill`` or a test
#              harness is expected to observe them)
BLAST_RADIUS = {
    "d2h": "fatal",
    "serialize": "retryable",
    "write": "retryable",
    "rename": "retryable",
    "commit": "fatal",
    "replica_push": "advisory",
    "replica_fetch": "advisory",
    "replica_restore": "advisory",
    "dcn_partition": "advisory",
    "host_loss": "fatal",
    "slice_loss": "fatal",
    "reshape": "fatal",
    # serving plane: the router is the recovery layer above the
    # replica, so "retryable" means the ROUTER's re-route/health policy
    # owns the failure (not the checkpoint save policy), and the fatal
    # replica_death propagates out of Replica.step() as ReplicaDead for
    # the router to observe — mirroring how host_loss propagates to the
    # elastic agent. router_overload is advisory: shedding is a typed,
    # counted service decision and must never take a replica down.
    "serve_dispatch": "retryable",
    "serve_step": "retryable",
    "serve_verify": "retryable",
    "replica_death": "fatal",
    "router_overload": "advisory",
    # disaggregated serving handoff (ISSUE 20): both halves fire BEFORE
    # any state moves — the prefill replica owns the sequence until the
    # decode side confirms the import — so the router's retry-next-round
    # policy owns these failures end to end
    "kv_stream": "retryable",
    "kv_import": "retryable",
}


class FaultError(OSError):
    """The injected failure for retryable points (an IO-shaped error,
    so the production retry path treats it like a real EIO)."""

    def __init__(self, point, fire_index):
        super().__init__(5, f"injected fault at '{point}' "
                            f"(fire #{fire_index})")
        self.point = point
        self.fire_index = fire_index


class SimulatedKill(BaseException):
    """Process death mid-save. Deliberately a BaseException: no retry
    loop, ``except Exception`` recovery path, or engine fallback may
    swallow it — exactly like SIGKILL. Tests catch it at top level and
    then assert on-disk state."""

    def __init__(self, point):
        super().__init__(f"simulated process kill at '{point}'")
        self.point = point


class _Arm:
    __slots__ = ("skip", "fails", "kill")

    def __init__(self, fails, skip=0, kill=False):
        self.fails = int(fails)
        self.skip = int(skip)
        self.kill = bool(kill)


class FaultInjector:
    """Registry of armed faults + a fire log. Thread-safe: writer
    threads in the async engines fire points concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        self._arms = {}
        self._fired = {}     # point -> total fire() calls (hit or not)
        self._hits = {}      # point -> injected-failure count
        # observers called as fn(point, injected=True) whenever an
        # armed point actually injects (clean fires on unarmed points
        # stay silent — d2h/write/rename fire on every save and would
        # flood a bounded ring). The telemetry flight recorder rides
        # here so fired points land in the crash dump. Deliberately NOT
        # cleared by reset(): tests reset armed faults constantly;
        # detaching a live engine's recorder mid-run would silently
        # blind its black box.
        self._listeners = []
        self._load_env()

    # ------------------------------------------------------------- arming
    def arm(self, point, fails=1, skip=0, kill=False):
        """Arm ``point``: after ``skip`` clean passes, the next
        ``fails`` fires raise (FaultError, or SimulatedKill when
        ``kill``), then the point heals."""
        with self._lock:
            self._arms[point] = _Arm(fails, skip=skip, kill=kill)

    def reset(self):
        with self._lock:
            self._arms.clear()
            self._fired.clear()
            self._hits.clear()

    def _load_env(self):
        spec = os.environ.get("DSTPU_FAULT_INJECT", "")
        for part in filter(None, (p.strip() for p in spec.split(","))):
            fields = part.split(":")
            point, fails = fields[0], 1
            skip, kill = 0, False
            if len(fields) > 1 and fields[1]:
                fails = int(fields[1])
            for extra in fields[2:]:
                if extra.startswith("skip="):
                    skip = int(extra[5:])
                elif extra == "kill":
                    kill = True
            self._arms[point] = _Arm(fails, skip=skip, kill=kill)

    # ------------------------------------------------------------- firing
    def fire(self, point):
        """Called at an injection point. No-op (beyond counting) unless
        the point is armed."""
        with self._lock:
            n = self._fired.get(point, 0) + 1
            self._fired[point] = n
            arm = self._arms.get(point)
            if arm is None:
                return
            if arm.skip > 0:
                arm.skip -= 1
                return
            if arm.fails <= 0:
                return
            arm.fails -= 1
            self._hits[point] = self._hits.get(point, 0) + 1
            kill = arm.kill
        self._notify(point, True)
        if kill:
            raise SimulatedKill(point)
        raise FaultError(point, n)

    # ----------------------------------------------------------- listeners
    def add_listener(self, fn):
        """Register ``fn(point, injected)`` — called outside the lock
        whenever an armed point injects; listener exceptions are
        swallowed (observability must never alter fault semantics)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)

    def remove_listener(self, fn):
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def _notify(self, point, injected):
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(point, injected)
            except Exception:  # noqa: BLE001 - observers are advisory
                pass

    # ---------------------------------------------------------- inspection
    def fired(self, point):
        """Total fire() calls seen at ``point`` (hit or clean)."""
        with self._lock:
            return self._fired.get(point, 0)

    def hits(self, point):
        """Injected failures actually raised at ``point``."""
        with self._lock:
            return self._hits.get(point, 0)

    def armed(self, point):
        with self._lock:
            arm = self._arms.get(point)
            return arm is not None and arm.fails > 0


# Process-global injector: production code fires against this; tests
# arm/reset it. fire() on an un-armed point is two dict ops under an
# uncontended lock — cheap enough to leave in the hot save path.
injector = FaultInjector()

fire = injector.fire
arm = injector.arm
reset = injector.reset
add_listener = injector.add_listener
remove_listener = injector.remove_listener
