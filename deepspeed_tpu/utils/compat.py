"""Back-compat shims for older jax releases.

The codebase targets current jax (``jax.set_mesh`` as the ambient-mesh
context, ``jax.typeof``, ``jax.sharding.get_abstract_mesh``). CI / dev
containers sometimes carry an older jaxlib where those entry points do
not exist yet; this module installs the closest older-API equivalents so
the same code runs in both places. On a current jax every shim is a
no-op (the real attribute wins).
"""

import jax


def _ambient_mesh():
    """The legacy ambient mesh (set by the Mesh context manager)."""
    from jax._src.mesh import thread_resources
    return thread_resources.env.physical_mesh


def install():
    if not hasattr(jax, "set_mesh"):
        # the legacy Mesh context manager provides the same ambient
        # mesh for with_sharding_constraint / PartitionSpec resolution
        def _set_mesh(mesh):
            return mesh

        jax.set_mesh = _set_mesh

    if not hasattr(jax.sharding, "get_abstract_mesh"):
        jax.sharding.get_abstract_mesh = _ambient_mesh

    if not hasattr(jax, "shard_map"):
        # jax.shard_map(f, mesh=, in_specs=, out_specs=, axis_names=,
        # check_vma=) -> experimental shard_map(f, mesh, in_specs,
        # out_specs, check_rep=, auto=); mesh defaults to the ambient
        # mesh, axis_names maps to its complement ``auto`` set
        def _shard_map(f, *, mesh=None, in_specs=None, out_specs=None,
                       axis_names=None, check_vma=None, **kw):
            from jax.experimental.shard_map import shard_map as _sm
            if mesh is None:
                mesh = _ambient_mesh()
            if check_vma is not None:
                kw["check_rep"] = check_vma
            if axis_names is not None:
                auto = frozenset(mesh.axis_names) - frozenset(axis_names)
                if auto:
                    kw["auto"] = auto
            return _sm(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kw)

        jax.shard_map = _shard_map

    if not hasattr(jax.lax, "axis_size"):
        # static axis size from the legacy axis-env frame
        def _axis_size(axis_name):
            from jax._src.core import axis_frame
            f = axis_frame(axis_name)
            return f if isinstance(f, int) else f.size

        jax.lax.axis_size = _axis_size

    if not hasattr(jax.lax, "pcast"):
        # jax without vma typing (< 0.7) has no lax.pcast; there
        # shard_map's check_rep machinery — the vma system's ancestor —
        # inserts the replicated<->varying conversions pcast makes
        # explicit, including the psum adjoint on the transpose path,
        # so the closest older-API equivalent is an identity. The
        # pipeline grad-parity tests (tests/unit/test_pipe.py, shard_map
        # pipeline vs sequential model, fwd AND grads) gate this shim's
        # numerics; it was the one seed tier-1-era failure the original
        # shim set left unfixed.
        def _pcast(x, axes=None, *, to=None, **kw):  # noqa: ARG001
            return x

        jax.lax.pcast = _pcast

    if not hasattr(jax.tree, "leaves_with_path"):
        from jax import tree_util as _tu
        jax.tree.leaves_with_path = _tu.tree_leaves_with_path
        jax.tree.flatten_with_path = _tu.tree_flatten_with_path
        if not hasattr(jax.tree, "map_with_path"):
            jax.tree.map_with_path = _tu.tree_map_with_path

    if not hasattr(jax.lax, "pvary"):   # vma-era marker: legacy check_rep
        # legacy shard_map's check_rep registry predates a rule for the
        # remat-policy `name` primitive (jax.ad_checkpoint.
        # checkpoint_name — a pure identity tag), so any model using
        # named remat policies failed to trace inside a partial-manual
        # region ("No replication rule for name"). The standard
        # (replication-intersection) rule is exactly right for an
        # identity; registering it makes the pipe-only-mesh pipeline
        # executors traceable on legacy jaxlib.
        try:
            from jax._src.ad_checkpoint import name_p
            from jax.experimental import shard_map as _esm
            if name_p not in _esm._check_rules:
                _esm.register_standard_check(name_p)
                _esm.register_standard_rewrite(name_p)
        except Exception:  # noqa: BLE001 - internals moved; vma-era jax
            pass           # has its own rule anyway


install()
