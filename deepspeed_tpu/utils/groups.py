"""Parallel topology: one global device mesh instead of process groups.

TPU-native counterpart of the reference's ``deepspeed/utils/groups.py``
(``initialize(ep_size, mpu)`` at utils/groups.py:51 and the DP/MP/EP/SP getters
at utils/groups.py:317-560). Where the reference carves ``torch.distributed``
process groups out of a world, we build a single ``jax.sharding.Mesh`` whose
named axes play the group roles:

    pipe    - pipeline-parallel stages (p2p via ppermute)
    data    - expert-data-parallel axis: replicas that also hold ZeRO
              partitions of expert params/optimizer state
    expert  - expert parallelism (MoE all_to_all); expert=1 folds into data
    seq     - Ulysses sequence parallelism (all_to_all head<->seq scatter)
    tensor  - tensor (megatron-style) model parallelism

Group semantics w.r.t. the reference:
    * the reference's "data-parallel group" (utils/groups.py:345) for
      NON-expert params is the combined ('data_outer','data','expert') axes
      (DP_AXES) - every device holding a replica of a non-expert param;
    * the "expert-parallel group" (utils/groups.py:317) is the 'expert' axis;
    * the "expert-data-parallel group" (utils/groups.py:331) is
      ('data_outer','data');
    * the "sequence-parallel group" (utils/groups.py:452) is 'seq';
    * gradients of non-expert params are additionally summed over 'seq'
      (reference stage_1_and_2.py:1070 divides by sp size);
    * ZeRO partitions optimizer state over the data-parallel group
      (DP_AXES), mirroring zero/stage_1_and_2.py; MiCS/hpZ partition over
      the inner ('data','expert') only (INNER_DP_AXES), replicating across
      'data_outer'.

XLA inserts the collectives; these axes just name them. ICI carries any axis
within a slice; 'data_outer' is outermost (slowest-varying) so DCN
(multi-slice) traffic is the infrequent cross-group reduction, as the
reference does with hierarchical ZeRO++ groups (utils/groups.py:505).
"""

from dataclasses import dataclass

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical mesh axis order. Data parallelism is TWO axes — 'data_outer'
# (slowest-varying; DCN across slices) x 'data' (ICI within a slice) — so
# hierarchical ZeRO variants (MiCS zero/mics.py:64, ZeRO++ hpZ
# utils/groups.py:505) are just "partition over 'data', replicate over
# 'data_outer'". data_outer is size 1 unless zero_shard_size subdivides DP.
MESH_AXES = ("pipe", "data_outer", "data", "expert", "seq", "tensor")

# Axis groups (tuples usable directly inside PartitionSpec / lax collectives).
DP_AXES = ("data_outer", "data", "expert")    # non-expert-param DP
INNER_DP_AXES = ("data", "expert")            # intra-slice shard group
EXPERT_DP_AXES = ("data_outer", "data")       # expert-param data parallelism
GRAD_REDUCE_AXES = ("data_outer", "data", "expert", "seq")
BATCH_AXES = ("data_outer", "data", "expert")  # batch dim of the global batch


@dataclass(frozen=True)
class TopologyConfig:
    """Sizes for each mesh axis. -1 for data = fill with remaining devices.
    ``zero_shard_size``: subdivide DP so the inner 'data' axis (the ZeRO
    shard group for MiCS/hpZ) has this size, replicating over 'data_outer';
    -1 = all of DP on the inner axis."""
    data_parallel_size: int = -1
    tensor_parallel_size: int = 1
    pipe_parallel_size: int = 1
    seq_parallel_size: int = 1
    expert_parallel_size: int = 1
    zero_shard_size: int = -1


class ParallelTopology:
    """Owns the global Mesh and answers group-size/rank queries."""

    def __init__(self, config: TopologyConfig = None, devices=None):
        config = config or TopologyConfig()
        if devices is None:
            devices = jax.devices()
        n = len(devices)
        fixed = (config.tensor_parallel_size * config.pipe_parallel_size *
                 config.seq_parallel_size * config.expert_parallel_size)
        dp = config.data_parallel_size
        if dp == -1:
            if n % fixed != 0:
                raise ValueError(
                    f"world size {n} not divisible by tensor*pipe*seq*expert={fixed}")
            dp = n // fixed
        if dp * fixed != n:
            raise ValueError(
                f"data({dp}) * tensor({config.tensor_parallel_size}) * "
                f"pipe({config.pipe_parallel_size}) * seq({config.seq_parallel_size}) * "
                f"expert({config.expert_parallel_size}) = {dp * fixed} != world size {n}")
        shard = config.zero_shard_size
        if shard in (-1, 0):
            shard = dp
        if dp % shard != 0:
            raise ValueError(
                f"zero_shard_size {shard} does not divide data-parallel "
                f"size {dp}")
        self.config = TopologyConfig(
            data_parallel_size=dp,
            tensor_parallel_size=config.tensor_parallel_size,
            pipe_parallel_size=config.pipe_parallel_size,
            seq_parallel_size=config.seq_parallel_size,
            expert_parallel_size=config.expert_parallel_size,
            zero_shard_size=shard,
        )
        shape = (self.config.pipe_parallel_size, dp // shard, shard,
                 self.config.expert_parallel_size,
                 self.config.seq_parallel_size,
                 self.config.tensor_parallel_size)
        device_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(device_array, MESH_AXES)

    # --- size getters (reference utils/groups.py:317-560 parity) ---
    @property
    def world_size(self):
        return self.mesh.size

    def axis_size(self, axis):
        return self.mesh.shape[axis]

    def get_data_parallel_world_size(self):
        """Replicas of a non-expert param: data_outer * data * expert."""
        return (self.axis_size("data_outer") * self.axis_size("data")
                * self.axis_size("expert"))

    def get_expert_parallel_world_size(self):
        return self.axis_size("expert")

    def get_expert_data_parallel_world_size(self):
        return self.axis_size("data_outer") * self.axis_size("data")

    def get_zero_shard_group_size(self):
        """Intra-slice ZeRO shard group (MiCS shard group / hpZ secondary
        partition): data * expert axes."""
        return self.axis_size("data") * self.axis_size("expert")

    def get_model_parallel_world_size(self):
        return self.axis_size("tensor")

    def get_sequence_parallel_world_size(self):
        return self.axis_size("seq")

    def get_pipe_parallel_world_size(self):
        return self.axis_size("pipe")

    # --- sharding helpers ---
    def sharding(self, *spec):
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def batch_sharding(self, seq_dim=None):
        """Global-batch sharding: batch dim over DP axes, optionally the
        sequence dim over 'seq' (Ulysses input layout)."""
        if seq_dim is None or self.get_sequence_parallel_world_size() == 1:
            return self.sharding(BATCH_AXES)
        if seq_dim == 0:
            raise ValueError("seq_dim must differ from the batch dim (0)")
        spec = [BATCH_AXES] + [None] * seq_dim
        spec[seq_dim] = "seq"
        return self.sharding(*spec)


_TOPOLOGY = None


def initialize(config: TopologyConfig = None, devices=None, force=False):
    """Create (or return) the global topology. Mirrors groups.initialize
    (reference utils/groups.py:51) being idempotent: repeat calls with an
    equivalent (post-resolution) config return the same object."""
    global _TOPOLOGY
    if _TOPOLOGY is None or force:
        _TOPOLOGY = ParallelTopology(config, devices)
    elif config is not None:
        candidate = ParallelTopology(config, devices)
        if candidate.config != _TOPOLOGY.config:
            _TOPOLOGY = candidate
    return _TOPOLOGY


def get_topology():
    global _TOPOLOGY
    if _TOPOLOGY is None:
        _TOPOLOGY = ParallelTopology()
    return _TOPOLOGY


def reset():
    global _TOPOLOGY
    _TOPOLOGY = None


def get_mesh():
    return get_topology().mesh
