"""Rank-aware logging.

TPU-native counterpart of the reference's ``deepspeed/utils/logging.py``
(``logger``, ``log_dist``). Under JAX's single-controller-per-host model the
"rank" is ``jax.process_index()``; we avoid importing jax at module import time
so logging works before distributed init.
"""

import logging
import os
import sys
import functools

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


def _create_logger(name="DeepSpeedTPU", level=logging.INFO):
    lg = logging.getLogger(name)
    lg.setLevel(level)
    lg.propagate = False
    if not lg.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s")
        handler.setFormatter(formatter)
        lg.addHandler(handler)
    return lg


logger = _create_logger(
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info").lower(),
                         logging.INFO))


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the listed process ranks (None / [-1] = all)."""
    rank = _process_index()
    if ranks is None or -1 in ranks or rank in ranks:
        logger.log(level, f"[Rank {rank}] {message}")


@functools.lru_cache(None)
def warn_once(message):
    logger.warning(message)


def print_rank_0(message):
    if _process_index() == 0:
        logger.info(message)
