"""Meta-device model materialization.

Counterpart of reference ``utils/init_on_device.py OnDevice`` (construct a
model on the 'meta' device: shapes without storage). The jax-native form:
``abstract_init`` evaluates a model's init under ``jax.eval_shape`` —
zero FLOPs, zero memory — yielding the ShapeDtypeStruct tree that sharding
plans and checkpoint loaders consume; ``materialize`` then creates the
real (optionally sharded) params.
"""

import jax


class OnDevice:
    """``with OnDevice(model, device='meta'): params = model.init(rng)``
    — inside the context, the listed models' ``init`` really runs through
    ``jax.eval_shape`` (zero FLOPs/memory, ShapeDtypeStruct leaves);
    restored on exit. The reference patches nn.Module.__init__ globally;
    here interception is per-model because models are plain objects."""

    _active = False

    def __init__(self, *models, dtype=None, device="meta", enabled=True):
        self.models = models
        self.dtype = dtype
        self.device = device
        self.enabled = enabled and device == "meta"
        self._saved = []

    def __enter__(self):
        OnDevice._active = self.enabled
        if self.enabled:
            for m in self.models:
                orig = m.init
                self._saved.append((m, orig))

                def abstract(rng, _orig=orig):
                    out = jax.eval_shape(_orig, rng)
                    if self.dtype is not None:
                        out = jax.tree.map(
                            lambda s: jax.ShapeDtypeStruct(s.shape,
                                                           self.dtype),
                            out)
                    return out

                m.init = abstract
        return self

    def __exit__(self, *exc):
        OnDevice._active = False
        for m, orig in self._saved:
            m.init = orig
        self._saved = []
        return False

    @classmethod
    def is_active(cls):
        return cls._active


def abstract_init(model, rng=None):
    """ShapeDtypeStruct pytree of ``model.init`` without running it."""
    if rng is None:
        rng = jax.random.key(0)
    return jax.eval_shape(model.init, rng)


def materialize(model, rng, shardings=None, dtype=None):
    """Real params, created directly into ``shardings`` (no full-size
    host copy — the zero.Init property)."""
    def init(r):
        p = model.init(r)
        if dtype is not None:
            p = jax.tree.map(lambda x: x.astype(dtype), p)
        return p

    if shardings is None:
        return jax.jit(init)(rng)
    return jax.jit(init, out_shardings=shardings)(rng)
