"""Universal checkpoint utilities.

Counterpart of reference ``deepspeed/checkpoint/`` (``ds_to_universal.py``
shard extraction + TP-slice merge, ``universal_checkpoint.py``
load_hp_checkpoint_state, ``utils/zero_to_fp32.py`` offline
consolidation). The TPU engine already writes GLOBAL logical tensors
(checkpoint_engine/serialization.py), so no shard merging is ever needed —
any ZeRO stage / mesh loads any checkpoint directly. What remains of the
reference surface:

  * ``consolidate_to_fp32`` — zero_to_fp32: extract the fp32 master
    weights from a training checkpoint into a standalone flat file for
    inference/export (no optimizer state).
  * ``ds_to_universal`` — explode a checkpoint into one file per logical
    parameter (the reference's universal layout), so external tools can
    stream single tensors without loading the whole state.
  * ``inspect_checkpoint`` — key/shape/dtype listing (debugging parity
    with the reference's inspect scripts).

All functions take a checkpoint dir (with ``latest``) or a direct
``state.npz`` path.
"""

import json
import os

import numpy as np

from ..runtime.checkpoint_engine import serialization as ser


def _resolve(path_or_dir, tag=None):
    """-> loadable location: a direct .npz file path, or a tag directory
    (legacy monolithic state.npz or the sharded per-host layout — both
    handled by serialization.load_state)."""
    if (os.path.isdir(path_or_dir)
            and not os.path.exists(os.path.join(path_or_dir, "latest"))
            and (os.path.exists(os.path.join(path_or_dir, "state.npz"))
                 or any(f.startswith("shard-")
                        for f in os.listdir(path_or_dir)))):
        return path_or_dir  # already a tag dir
    if os.path.isdir(path_or_dir):
        if tag is None:
            with open(os.path.join(path_or_dir, "latest")) as f:
                tag = f.read().strip()
        return os.path.join(path_or_dir, tag)
    return path_or_dir


def _load(path_or_dir, tag=None):
    loc = _resolve(path_or_dir, tag)
    if os.path.isdir(loc):
        return ser.load_state(loc)
    return ser.load_file(loc)


def consolidate_to_fp32(ckpt, output_path, tag=None):
    """reference utils/zero_to_fp32.py: training checkpoint -> standalone
    fp32 weights file (master subtree only). Returns #params written."""
    flat, header = _load(ckpt, tag)
    master = {k[len("master/"):]: v for k, v in flat.items()
              if k.startswith("master/")}
    if not master:
        raise ValueError("checkpoint has no master weights subtree")
    arrays = {k.replace("/", "%2F"): np.asarray(v, np.float32)
              for k, v in master.items()}
    meta = {"format": "dstpu-fp32-consolidated", "version": 1,
            "num_params": int(sum(a.size for a in arrays.values()))}
    arrays["__meta__"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    os.makedirs(os.path.dirname(os.path.abspath(output_path)),
                exist_ok=True)
    with open(output_path, "wb") as f:
        np.savez(f, **arrays)
    return meta["num_params"]


def load_consolidated(path):
    """-> flat dict param_path -> fp32 array (nest with '/' in keys)."""
    with np.load(path, allow_pickle=False) as z:
        return {k.replace("%2F", "/"): z[k] for k in z.files
                if k != "__meta__"}


def ds_to_universal(ckpt, out_dir, tag=None):
    """reference checkpoint/ds_to_universal.py: one .npy per logical
    param + index json. Returns the index dict."""
    flat, header = _load(ckpt, tag)
    os.makedirs(out_dir, exist_ok=True)
    index = {}
    for key, arr in flat.items():
        safe = key.replace("/", "%2F")
        fname = f"{safe}.npy"
        np.save(os.path.join(out_dir, fname), np.asarray(arr))
        index[key] = {"file": fname, "shape": list(np.shape(arr)),
                      "dtype": str(np.asarray(arr).dtype)}
    with open(os.path.join(out_dir, "index.json"), "w") as f:
        json.dump({"params": index, "extra": header.get("extra", {}),
                   "meta": header.get("meta", {})}, f, indent=2)
    return index


def load_universal_param(universal_dir, key):
    """Stream ONE logical parameter from a universal dir."""
    with open(os.path.join(universal_dir, "index.json")) as f:
        index = json.load(f)["params"]
    if key not in index:
        raise KeyError(f"{key} not in universal checkpoint "
                       f"({len(index)} params)")
    return np.load(os.path.join(universal_dir, index[key]["file"]))


def inspect_checkpoint(ckpt, tag=None, file=None):
    """Print key/shape/dtype/bytes for every tensor; returns total
    bytes."""
    import sys
    f = file or sys.stdout
    flat, header = _load(ckpt, tag)
    total = 0
    for key in sorted(flat):
        arr = np.asarray(flat[key])
        total += arr.nbytes
        print(f"  {key:48s} {str(arr.shape):18s} {arr.dtype} "
              f"{arr.nbytes / 1e6:8.2f}MB", file=f)
    extra = header.get("extra", {})
    print(f"total {total / 1e6:.2f}MB; step={extra.get('global_step')} "
          f"zero_stage={extra.get('zero_stage')}", file=f)
    return total


def main(argv=None):
    """CLI: ``python -m deepspeed_tpu.checkpoint.universal <cmd> ...``
    cmds: fp32 <ckpt> <out>, universal <ckpt> <out_dir>, inspect <ckpt>"""
    import argparse
    p = argparse.ArgumentParser(prog="dstpu-checkpoint")
    sub = p.add_subparsers(dest="cmd", required=True)
    f32 = sub.add_parser("fp32")
    f32.add_argument("ckpt")
    f32.add_argument("output")
    uni = sub.add_parser("universal")
    uni.add_argument("ckpt")
    uni.add_argument("out_dir")
    ins = sub.add_parser("inspect")
    ins.add_argument("ckpt")
    args = p.parse_args(argv)
    if args.cmd == "fp32":
        n = consolidate_to_fp32(args.ckpt, args.output)
        print(f"wrote {n} fp32 params to {args.output}")
    elif args.cmd == "universal":
        idx = ds_to_universal(args.ckpt, args.out_dir)
        print(f"wrote {len(idx)} tensors to {args.out_dir}")
    else:
        inspect_checkpoint(args.ckpt)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
