"""HuggingFace checkpoint ingestion: safetensors/bin -> model param trees.

Counterpart of the reference's HF loaders — the v2 serving stack's
``HuggingFaceCheckpointEngine``
(/root/reference/deepspeed/inference/v2/checkpoint/huggingface_engine.py:16)
and the v1 sharded loader
(/root/reference/deepspeed/inference/engine.py:331
``load_model_with_checkpoint``). TPU-first differences: weights land as
numpy/jax arrays mapped into each family's FUNCTIONAL param tree (stacked
per-layer tensors under ``blocks``), not injected into torch modules; TP
sharding then falls out of ``model.partition_specs()`` + device_put — no
per-family policy classes are needed beyond the key mapping itself.

Entry points:
  read_hf_state_dict(model_dir)  -> {name: np.ndarray}
  load_pretrained(model_dir, ...) -> (model, params)   # dispatch on
                                                       # config model_type
  convert_<family>(hf_cfg, sd, dtype) -> (config, params)

Supported model_type values: gpt2, opt, llama, mistral, qwen2, phi,
falcon, mixtral, bloom, gptj, gpt_neo, gpt_neox, internlm. Weights load
from *.safetensors (single or index-sharded) or pytorch_model.bin
(torch CPU).
"""

import json
import os

import numpy as np

__all__ = ["read_hf_state_dict", "read_hf_config", "load_pretrained",
           "CONVERTERS"]


# --------------------------------------------------------------------- I/O
def read_hf_config(model_dir):
    with open(os.path.join(model_dir, "config.json")) as f:
        return json.load(f)


def _load_safetensors(path):
    from safetensors.numpy import load_file
    try:
        return load_file(path)
    except Exception:
        # bf16 tensors round-trip through torch (numpy has no bf16)
        from safetensors.torch import load_file as tload
        return {k: _to_np(v) for k, v in tload(path).items()}


def _to_np(t):
    import torch
    if t.dtype == torch.bfloat16:
        # keep values exactly: bf16 -> fp32 numpy
        return t.to(torch.float32).numpy()
    return t.numpy()


def read_hf_state_dict(model_dir):
    """Read all weights under ``model_dir`` into {name: np.ndarray}."""
    idx = os.path.join(model_dir, "model.safetensors.index.json")
    single = os.path.join(model_dir, "model.safetensors")
    binf = os.path.join(model_dir, "pytorch_model.bin")
    sd = {}
    if os.path.exists(idx):
        with open(idx) as f:
            files = sorted(set(json.load(f)["weight_map"].values()))
        for fn in files:
            sd.update(_load_safetensors(os.path.join(model_dir, fn)))
    elif os.path.exists(single):
        sd.update(_load_safetensors(single))
    elif os.path.exists(binf):
        import torch
        raw = torch.load(binf, map_location="cpu", weights_only=True)
        sd.update({k: _to_np(v) for k, v in raw.items()})
    else:
        raise FileNotFoundError(
            f"no model.safetensors(.index.json) or pytorch_model.bin "
            f"under {model_dir}")
    return sd


def _stack(layers, key):
    return np.stack([l[key] for l in layers])


# --------------------------------------------------------- family converters
def convert_gpt2(hf, sd, dtype="bfloat16"):
    """HF gpt2 (Conv1D weights are stored (in, out) — no transpose)."""
    from ..models.gpt2 import GPT2Config
    pre = "transformer." if "transformer.wte.weight" in sd else ""
    L = hf["n_layer"]
    cfg = GPT2Config(vocab_size=hf["vocab_size"],
                     max_seq_len=hf["n_positions"], n_layer=L,
                     n_head=hf["n_head"], d_model=hf["n_embd"],
                     dtype=dtype)
    g = lambda k: sd[pre + k]
    layers = [{
        "ln1_scale": g(f"h.{i}.ln_1.weight"),
        "ln1_bias": g(f"h.{i}.ln_1.bias"),
        "wqkv": g(f"h.{i}.attn.c_attn.weight"),
        "bqkv": g(f"h.{i}.attn.c_attn.bias"),
        "wo": g(f"h.{i}.attn.c_proj.weight"),
        "bo": g(f"h.{i}.attn.c_proj.bias"),
        "ln2_scale": g(f"h.{i}.ln_2.weight"),
        "ln2_bias": g(f"h.{i}.ln_2.bias"),
        "wup": g(f"h.{i}.mlp.c_fc.weight"),
        "bup": g(f"h.{i}.mlp.c_fc.bias"),
        "wdown": g(f"h.{i}.mlp.c_proj.weight"),
        "bdown": g(f"h.{i}.mlp.c_proj.bias"),
    } for i in range(L)]
    params = {
        "wte": g("wte.weight"),
        "wpe": g("wpe.weight"),
        "lnf_scale": g("ln_f.weight"),
        "lnf_bias": g("ln_f.bias"),
        "blocks": {k: _stack(layers, k) for k in layers[0]},
    }
    return cfg, _model_cast(params, cfg, dtype)


def convert_opt(hf, sd, dtype="bfloat16"):
    """HF OPT: linear weights are (out, in) -> transpose; positions are
    offset by 2 padding rows (sliced off here, reference
    module_inject/containers/opt.py handles the same detail)."""
    from ..models.opt import OPTConfig
    if hf.get("word_embed_proj_dim", hf["hidden_size"]) != hf["hidden_size"] \
            or not hf.get("do_layer_norm_before", True):
        raise ValueError(
            "only standard pre-LN OPT variants are supported (opt-350m's "
            "word_embed_proj_dim / post-LN layout is not)")
    pre = "model.decoder." if "model.decoder.embed_tokens.weight" in sd \
        else "decoder."
    L = hf["num_hidden_layers"]
    D = hf["hidden_size"]
    cfg = OPTConfig(vocab_size=hf["vocab_size"],
                    max_seq_len=hf["max_position_embeddings"],
                    n_layer=L, n_head=hf["num_attention_heads"],
                    d_model=D, dtype=dtype)
    g = lambda k: sd[pre + k]

    def qkv(i):
        ws = [g(f"layers.{i}.self_attn.{m}_proj.weight").T
              for m in ("q", "k", "v")]
        bs = [g(f"layers.{i}.self_attn.{m}_proj.bias")
              for m in ("q", "k", "v")]
        return np.concatenate(ws, axis=1), np.concatenate(bs)

    layers = []
    for i in range(L):
        wqkv, bqkv = qkv(i)
        layers.append({
            "ln1_scale": g(f"layers.{i}.self_attn_layer_norm.weight"),
            "ln1_bias": g(f"layers.{i}.self_attn_layer_norm.bias"),
            "wqkv": wqkv, "bqkv": bqkv,
            "wo": g(f"layers.{i}.self_attn.out_proj.weight").T,
            "bo": g(f"layers.{i}.self_attn.out_proj.bias"),
            "ln2_scale": g(f"layers.{i}.final_layer_norm.weight"),
            "ln2_bias": g(f"layers.{i}.final_layer_norm.bias"),
            "wup": g(f"layers.{i}.fc1.weight").T,
            "bup": g(f"layers.{i}.fc1.bias"),
            "wdown": g(f"layers.{i}.fc2.weight").T,
            "bdown": g(f"layers.{i}.fc2.bias"),
        })
    params = {
        "wte": g("embed_tokens.weight"),
        "wpe": g("embed_positions.weight")[2:],   # drop the 2 pad slots
        "lnf_scale": g("final_layer_norm.weight"),
        "lnf_bias": g("final_layer_norm.bias"),
        "blocks": {k: _stack(layers, k) for k in layers[0]},
    }
    return cfg, _model_cast(params, cfg, dtype)


def _llama_like(hf, sd, cfg, dtype, *, pre="model.", qkv_bias=False,
                proj_bias=False, o_bias=False, gated=True, ln=False,
                fused_qkv=False,
                shared_ln=False, mlp_names=("gate_proj", "up_proj",
                                            "down_proj"),
                o_name="o_proj", moe=False, layer_prefix="layers"):
    L = cfg.n_layer
    H, KVH, hd = cfg.n_head, cfg.n_kv_heads, cfg.d_head
    g = lambda k: sd[pre + k]

    def maybe(k):
        return sd.get(pre + k)

    layers = []
    for i in range(L):
        lp = f"{layer_prefix}.{i}."
        e = {}
        if fused_qkv:
            # falcon-style fused query_key_value with MQA tail: rows are
            # [q (H*hd), k (KVH*hd), v (KVH*hd)] in the (out, in) weight
            w = g(lp + "self_attention.query_key_value.weight").T
            e["wq"] = w[:, :H * hd]
            e["wk"] = w[:, H * hd:(H + KVH) * hd]
            e["wv"] = w[:, (H + KVH) * hd:]
            e["wo"] = g(lp + "self_attention.dense.weight").T
        else:
            e["wq"] = g(lp + "self_attn.q_proj.weight").T
            e["wk"] = g(lp + "self_attn.k_proj.weight").T
            e["wv"] = g(lp + "self_attn.v_proj.weight").T
            e["wo"] = g(lp + f"self_attn.{o_name}.weight").T
        if qkv_bias:
            e["bq"] = g(lp + "self_attn.q_proj.bias")
            e["bk"] = g(lp + "self_attn.k_proj.bias")
            e["bv"] = g(lp + "self_attn.v_proj.bias")
        if proj_bias or o_bias:
            e["bo"] = g(lp + f"self_attn.{o_name}.bias")
        if moe:
            E = cfg.num_experts
            e["moe_gate"] = g(lp + "block_sparse_moe.gate.weight").T
            for ours, theirs in (("moe_w1", "w1"), ("moe_w3", "w3"),
                                 ("moe_w2", "w2")):
                e[ours] = np.stack([
                    g(lp + f"block_sparse_moe.experts.{j}.{theirs}.weight").T
                    for j in range(E)])
        elif gated:
            gate_n, up_n, down_n = mlp_names
            e["wgate"] = g(lp + f"mlp.{gate_n}.weight").T
            e["wup"] = g(lp + f"mlp.{up_n}.weight").T
            e["wdown"] = g(lp + f"mlp.{down_n}.weight").T
        else:
            up_n, down_n = mlp_names
            e["wup"] = g(lp + f"mlp.{up_n}.weight").T
            e["wdown"] = g(lp + f"mlp.{down_n}.weight").T
            if proj_bias:
                e["bup"] = g(lp + f"mlp.{up_n}.bias")
                e["bdown"] = g(lp + f"mlp.{down_n}.bias")
        if ln:
            ln1 = "input_layernorm" if maybe(lp + "input_layernorm.weight") \
                is not None else "ln_attn"
            e["rms1"] = g(lp + f"{ln1}.weight")
            e["b1"] = g(lp + f"{ln1}.bias")
            if shared_ln:
                # falcon-7b/phi parallel block: ONE input LN feeds both
                # branches; the tree keeps both slots, tied at load
                e["rms2"], e["b2"] = e["rms1"], e["b1"]
            else:
                e["rms2"] = g(lp + "post_attention_layernorm.weight")
                e["b2"] = g(lp + "post_attention_layernorm.bias")
        else:
            e["rms1"] = g(lp + "input_layernorm.weight")
            e["rms2"] = g(lp + "post_attention_layernorm.weight")
        layers.append(e)

    params = {"blocks": {k: _stack(layers, k) for k in layers[0]}}
    return params, g, maybe


def convert_llama(hf, sd, dtype="bfloat16"):
    from ..models.llama import Llama, LlamaConfig
    window = hf.get("sliding_window") or 0
    if window >= hf["max_position_embeddings"]:
        window = 0                      # window never binds: full causal
    qkv_bias = bool(hf.get("attention_bias", False))
    cfg = LlamaConfig(
        qkv_bias=qkv_bias, sliding_window=window,
        vocab_size=hf["vocab_size"],
        max_seq_len=hf["max_position_embeddings"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads",
                          hf["num_attention_heads"]),
        d_model=hf["hidden_size"], d_ff=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_eps=hf.get("rms_norm_eps", 1e-5),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        dtype=dtype)
    params, g, maybe = _llama_like(hf, sd, cfg, dtype, qkv_bias=qkv_bias)
    params["wte"] = g("embed_tokens.weight")
    params["norm_f"] = g("norm.weight")
    if not cfg.tie_embeddings:
        params["lm_head"] = sd["lm_head.weight"]
    return cfg, _model_cast(params, cfg, dtype)


def convert_qwen2(hf, sd, dtype="bfloat16"):
    from ..models.qwen import QwenConfig
    cfg = QwenConfig(
        vocab_size=hf["vocab_size"],
        max_seq_len=hf["max_position_embeddings"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads",
                          hf["num_attention_heads"]),
        d_model=hf["hidden_size"], d_ff=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 1000000.0),
        rms_eps=hf.get("rms_norm_eps", 1e-6),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        dtype=dtype)
    params, g, maybe = _llama_like(hf, sd, cfg, dtype, qkv_bias=True)
    params["wte"] = g("embed_tokens.weight")
    params["norm_f"] = g("norm.weight")
    if not cfg.tie_embeddings:
        params["lm_head"] = sd["lm_head.weight"]
    return cfg, _model_cast(params, cfg, dtype)


def convert_phi(hf, sd, dtype="bfloat16"):
    from ..models.phi import PhiConfig
    cfg = PhiConfig(
        vocab_size=hf["vocab_size"],
        max_seq_len=hf["max_position_embeddings"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads")
        or hf["num_attention_heads"],
        d_model=hf["hidden_size"], d_ff=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_eps=hf.get("layer_norm_eps", 1e-5),
        rotary_pct=hf.get("partial_rotary_factor", 0.4),
        dtype=dtype)
    params, g, maybe = _llama_like(
        hf, sd, cfg, dtype, qkv_bias=True, proj_bias=True, gated=False,
        ln=True, shared_ln=True, mlp_names=("fc1", "fc2"), o_name="dense")
    params["wte"] = g("embed_tokens.weight")
    params["norm_f"] = g("final_layernorm.weight")
    params["norm_f_b"] = g("final_layernorm.bias")
    params["lm_head"] = sd["lm_head.weight"]
    params["lm_head_b"] = sd["lm_head.bias"]
    return cfg, _model_cast(params, cfg, dtype)


def convert_falcon(hf, sd, dtype="bfloat16"):
    """All three HF falcon generations. The fused query_key_value weight
    has three row layouts (HF modeling_falcon.py ``_split_heads``):

      new_decoder_architecture (40b/180b/11b): grouped per KV head —
        rows reshape to (KVH, G+2, hd) with G = H // KVH queries then
        that group's k and v;
      old arch, multi_query (7b): flat [q (H*hd) | k (hd) | v (hd)];
      old arch, no multi_query (falcon-rw): per-head interleave (H, 3, hd).

    Norms likewise: new arch carries ln_attn + ln_mlp (one per parallel
    branch) unless num_ln_in_parallel_attn == 1; falcon-rw
    (parallel_attn=False) carries standard input/post_attention norms;
    7b shares one input LN between branches. Detected from the state
    dict so sub-variants (falcon2-11b single-LN) load correctly."""
    from ..models.falcon import FalconConfig
    n_head = hf["num_attention_heads"]
    H = n_head
    D = hf["hidden_size"]
    hd = D // H
    L = hf["num_hidden_layers"]
    new_arch = bool(hf.get("new_decoder_architecture", False))
    multi_query = bool(hf.get("multi_query", True))
    # mirror HF FalconConfig.num_kv_heads resolution exactly
    KVH = hf.get("num_kv_heads", n_head) if new_arch \
        else (1 if multi_query else n_head)
    parallel = bool(hf.get("parallel_attn", True))
    alibi = bool(hf.get("alibi", False))
    has_bias = bool(hf.get("bias", False))
    cfg = FalconConfig(
        vocab_size=hf["vocab_size"],
        max_seq_len=hf.get("max_position_embeddings", 2048),
        n_layer=L, n_head=n_head, n_kv_heads=KVH,
        d_model=D, d_ff=hf.get("ffn_hidden_size") or 4 * D,
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_eps=hf.get("layer_norm_epsilon", 1e-5),
        parallel_block=parallel, alibi=alibi, alibi_inv_norm=alibi,
        qkv_bias=has_bias, proj_bias=has_bias,
        tie_embeddings=True, dtype=dtype)
    pre = "transformer."
    g = lambda k: sd[pre + k]

    def split_qkv(w):
        """(D, fused) -> wq (D, H*hd), wk/wv (D, KVH*hd); also splits the
        fused bias when given a 1-D array (leading axis is the fused
        dim either way)."""
        lead = w.shape[:-1]                 # (D,) for weights, () for bias
        if new_arch:
            G = H // KVH
            t = w.reshape(*lead, KVH, G + 2, hd)
            q = t[..., :, :G, :].reshape(*lead, H * hd)
            k = t[..., :, G, :].reshape(*lead, KVH * hd)
            v = t[..., :, G + 1, :].reshape(*lead, KVH * hd)
        elif multi_query:
            q = w[..., :H * hd]
            k = w[..., H * hd:(H + 1) * hd]
            v = w[..., (H + 1) * hd:]
        else:
            t = w.reshape(*lead, H, 3, hd)
            q = t[..., :, 0, :].reshape(*lead, H * hd)
            k = t[..., :, 1, :].reshape(*lead, H * hd)
            v = t[..., :, 2, :].reshape(*lead, H * hd)
        return q, k, v

    layers = []
    for i in range(L):
        lp = f"h.{i}."
        wq, wk, wv = split_qkv(g(lp + "self_attention.query_key_value"
                                 ".weight").T)
        e = {"wq": wq, "wk": wk, "wv": wv,
             "wo": g(lp + "self_attention.dense.weight").T,
             "wup": g(lp + "mlp.dense_h_to_4h.weight").T,
             "wdown": g(lp + "mlp.dense_4h_to_h.weight").T}
        if has_bias:
            e["bq"], e["bk"], e["bv"] = split_qkv(
                g(lp + "self_attention.query_key_value.bias"))
            e["bo"] = g(lp + "self_attention.dense.bias")
            e["bup"] = g(lp + "mlp.dense_h_to_4h.bias")
            e["bdown"] = g(lp + "mlp.dense_4h_to_h.bias")
        if pre + lp + "ln_attn.weight" in sd:      # new arch, 2 norms
            e["rms1"] = g(lp + "ln_attn.weight")
            e["b1"] = g(lp + "ln_attn.bias")
            e["rms2"] = g(lp + "ln_mlp.weight")
            e["b2"] = g(lp + "ln_mlp.bias")
        else:
            e["rms1"] = g(lp + "input_layernorm.weight")
            e["b1"] = g(lp + "input_layernorm.bias")
            if pre + lp + "post_attention_layernorm.weight" in sd:
                e["rms2"] = g(lp + "post_attention_layernorm.weight")
                e["b2"] = g(lp + "post_attention_layernorm.bias")
            else:                                  # 7b: one shared LN
                e["rms2"], e["b2"] = e["rms1"], e["b1"]
        layers.append(e)

    params = {"blocks": {k: _stack(layers, k) for k in layers[0]}}
    params["wte"] = g("word_embeddings.weight")
    params["norm_f"] = g("ln_f.weight")
    params["norm_f_b"] = g("ln_f.bias")
    if has_bias:
        params["lm_head_b"] = np.zeros((hf["vocab_size"],), np.float32)
    return cfg, _model_cast(params, cfg, dtype)


def convert_gptj(hf, sd, dtype="bfloat16"):
    """HF gptj: separate unbiased q/k/v/out projections, biased
    fc_in/fc_out MLP, one shared input LN per layer (tied into both
    branch slots), biased untied lm_head, interleaved partial rotary
    (reference module_inject/containers/gptj.py)."""
    from ..models.gptj import GPTJConfig
    L = hf["n_layer"]
    D = hf["n_embd"]
    hd = D // hf["n_head"]
    cfg = GPTJConfig(
        vocab_size=hf["vocab_size"], max_seq_len=hf["n_positions"],
        n_layer=L, n_head=hf["n_head"], n_kv_heads=hf["n_head"],
        d_model=D, d_ff=hf.get("n_inner") or 4 * D,
        rms_eps=hf.get("layer_norm_epsilon", 1e-5),
        # HF configs may carry an explicit "rotary_dim": null — that
        # means full-head rotary, same as the key being absent (but an
        # explicit 0 stays 0: rotate nothing)
        rotary_pct=(hd if hf.get("rotary_dim") is None
                    else hf["rotary_dim"]) / hd,
        dtype=dtype)
    pre = "transformer."
    g = lambda k: sd[pre + k]
    layers = []
    for i in range(L):
        lp = f"h.{i}."
        e = {
            "wq": g(lp + "attn.q_proj.weight").T,
            "wk": g(lp + "attn.k_proj.weight").T,
            "wv": g(lp + "attn.v_proj.weight").T,
            "wo": g(lp + "attn.out_proj.weight").T,
            "wup": g(lp + "mlp.fc_in.weight").T,
            "bup": g(lp + "mlp.fc_in.bias"),
            "wdown": g(lp + "mlp.fc_out.weight").T,
            "bdown": g(lp + "mlp.fc_out.bias"),
            "rms1": g(lp + "ln_1.weight"),
            "b1": g(lp + "ln_1.bias"),
        }
        e["rms2"], e["b2"] = e["rms1"], e["b1"]  # shared-LN parallel block
        layers.append(e)
    params = {
        "blocks": {k: _stack(layers, k) for k in layers[0]},
        "wte": g("wte.weight"),
        "norm_f": g("ln_f.weight"),
        "norm_f_b": g("ln_f.bias"),
        "lm_head": sd["lm_head.weight"],
        "lm_head_b": sd["lm_head.bias"],
    }
    return cfg, _model_cast(params, cfg, dtype)


def convert_gpt_neo(hf, sd, dtype="bfloat16"):
    """HF gpt_neo: gpt2-family blocks with nn.Linear weights
    (transposed at load), NO qkv bias (zero rows in the fused bqkv), NO
    score scaling, and the attention_types global/local layer pattern
    (reference module_inject/containers/gptneo.py)."""
    from ..models.gpt_neo import GPTNeoConfig
    L = hf["num_layers"]
    D = hf["hidden_size"]
    inner = hf.get("intermediate_size") or 4 * D
    if inner != 4 * D:
        raise ValueError(
            f"gpt_neo intermediate_size {inner} != 4*hidden {4 * D}: the "
            f"GPT2 family derives d_ff as 4*d_model")
    # expand attention_types [[['global','local'], k], ...] -> per-layer
    # windows (0 = global)
    pattern = []
    for kinds, reps in hf.get("attention_types",
                              [[["global"], L]]):
        pattern.extend(kinds * reps)
    if len(pattern) != L:
        raise ValueError(f"attention_types expands to {len(pattern)} "
                         f"layers, config has {L}")
    win = hf.get("window_size", 256)
    windows = tuple(win if k == "local" else 0 for k in pattern)
    cfg = GPTNeoConfig(
        vocab_size=hf["vocab_size"],
        max_seq_len=hf["max_position_embeddings"], n_layer=L,
        n_head=hf["num_heads"], d_model=D,
        attn_layer_windows=() if not any(windows) else windows,
        dtype=dtype)
    pre = "transformer."
    g = lambda k: sd[pre + k]
    layers = []
    for i in range(L):
        lp = f"h.{i}."
        wq = g(lp + "attn.attention.q_proj.weight").T
        wk = g(lp + "attn.attention.k_proj.weight").T
        wv = g(lp + "attn.attention.v_proj.weight").T
        layers.append({
            "ln1_scale": g(lp + "ln_1.weight"),
            "ln1_bias": g(lp + "ln_1.bias"),
            "wqkv": np.concatenate([wq, wk, wv], axis=1),
            "bqkv": np.zeros((3 * D,), np.float32),
            "wo": g(lp + "attn.attention.out_proj.weight").T,
            "bo": g(lp + "attn.attention.out_proj.bias"),
            "ln2_scale": g(lp + "ln_2.weight"),
            "ln2_bias": g(lp + "ln_2.bias"),
            "wup": g(lp + "mlp.c_fc.weight").T,
            "bup": g(lp + "mlp.c_fc.bias"),
            "wdown": g(lp + "mlp.c_proj.weight").T,
            "bdown": g(lp + "mlp.c_proj.bias"),
        })
    params = {
        "wte": g("wte.weight"),
        "wpe": g("wpe.weight"),
        "lnf_scale": g("ln_f.weight"),
        "lnf_bias": g("ln_f.bias"),
        "blocks": {k: _stack(layers, k) for k in layers[0]},
    }
    return cfg, _model_cast(params, cfg, dtype)


def convert_gpt_neox(hf, sd, dtype="bfloat16"):
    """HF gpt_neox / pythia: fused query_key_value is INTERLEAVED per
    head ((H, 3, hd) rows, megatron layout — reference
    module_inject/containers/gptneox.py notes the same split), biases
    on qkv/dense/MLP, bias-free untied embed_out, use_parallel_residual
    with two independent branch norms."""
    from ..models.gpt_neox import GPTNeoXConfig
    L = hf["num_hidden_layers"]
    D = hf["hidden_size"]
    H = hf["num_attention_heads"]
    hd = D // H
    cfg = GPTNeoXConfig(
        vocab_size=hf["vocab_size"],
        max_seq_len=hf["max_position_embeddings"], n_layer=L,
        n_head=H, n_kv_heads=H, d_model=D,
        d_ff=hf.get("intermediate_size") or 4 * D,
        rope_theta=hf.get("rotary_emb_base", 10000.0),
        rms_eps=hf.get("layer_norm_eps", 1e-5),
        rotary_pct=hf.get("rotary_pct", 0.25),
        parallel_block=hf.get("use_parallel_residual", True),
        mlp_act={"gelu": "gelu", "gelu_new": "gelu_tanh",
                 "gelu_fast": "gelu_tanh"}.get(
            hf.get("hidden_act", "gelu"), "gelu"),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        dtype=dtype)
    pre = "gpt_neox."
    g = lambda k: sd[pre + k]

    def deinterleave(w):
        """(..., 3*D) fused qkv with per-head (H, 3, hd) layout ->
        q/k/v (..., D) each; works for the (D, 3D) weight (transposed
        from HF's (3D, D)) and the (3D,) bias alike."""
        lead = w.shape[:-1]
        t = w.reshape(*lead, H, 3, hd)
        return tuple(t[..., :, j, :].reshape(*lead, D) for j in range(3))

    layers = []
    for i in range(L):
        lp = f"layers.{i}."
        wq, wk, wv = deinterleave(
            g(lp + "attention.query_key_value.weight").T)
        bq, bk, bv = deinterleave(g(lp + "attention.query_key_value.bias"))
        layers.append({
            "wq": wq, "wk": wk, "wv": wv,
            "bq": bq, "bk": bk, "bv": bv,
            "wo": g(lp + "attention.dense.weight").T,
            "bo": g(lp + "attention.dense.bias"),
            "wup": g(lp + "mlp.dense_h_to_4h.weight").T,
            "bup": g(lp + "mlp.dense_h_to_4h.bias"),
            "wdown": g(lp + "mlp.dense_4h_to_h.weight").T,
            "bdown": g(lp + "mlp.dense_4h_to_h.bias"),
            "rms1": g(lp + "input_layernorm.weight"),
            "b1": g(lp + "input_layernorm.bias"),
            "rms2": g(lp + "post_attention_layernorm.weight"),
            "b2": g(lp + "post_attention_layernorm.bias"),
        })
    params = {
        "blocks": {k: _stack(layers, k) for k in layers[0]},
        "wte": g("embed_in.weight"),
        "norm_f": g("final_layer_norm.weight"),
        "norm_f_b": g("final_layer_norm.bias"),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = sd["embed_out.weight"]
    return cfg, _model_cast(params, cfg, dtype)


def convert_internlm(hf, sd, dtype="bfloat16"):
    """HF internlm (v1): the llama block with learned biases on the
    q/k/v AND output projections when config ``bias`` is true
    (reference module_inject/containers/internlm.py)."""
    from ..models.internlm import InternLMConfig
    has_bias = bool(hf.get("bias", True))
    cfg = InternLMConfig(
        vocab_size=hf["vocab_size"],
        max_seq_len=hf["max_position_embeddings"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads",
                          hf["num_attention_heads"]),
        d_model=hf["hidden_size"], d_ff=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_eps=hf.get("rms_norm_eps", 1e-6),
        qkv_bias=has_bias, o_bias=has_bias,
        tie_embeddings=hf.get("tie_word_embeddings", False),
        dtype=dtype)
    params, g, maybe = _llama_like(hf, sd, cfg, dtype, qkv_bias=has_bias,
                                   o_bias=has_bias)
    params["wte"] = g("embed_tokens.weight")
    params["norm_f"] = g("norm.weight")
    if not cfg.tie_embeddings:
        params["lm_head"] = sd["lm_head.weight"]
    return cfg, _model_cast(params, cfg, dtype)


def convert_mixtral(hf, sd, dtype="bfloat16"):
    from ..models.mixtral import MixtralConfig
    cfg = MixtralConfig(
        vocab_size=hf["vocab_size"],
        max_seq_len=hf["max_position_embeddings"],
        n_layer=hf["num_hidden_layers"],
        n_head=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads",
                          hf["num_attention_heads"]),
        d_model=hf["hidden_size"], d_ff=hf["intermediate_size"],
        rope_theta=hf.get("rope_theta", 1000000.0),
        rms_eps=hf.get("rms_norm_eps", 1e-5),
        num_experts=hf["num_local_experts"],
        moe_top_k=hf.get("num_experts_per_tok", 2),
        tie_embeddings=hf.get("tie_word_embeddings", False),
        dtype=dtype)
    params, g, maybe = _llama_like(hf, sd, cfg, dtype, moe=True)
    params["wte"] = g("embed_tokens.weight")
    params["norm_f"] = g("norm.weight")
    if not cfg.tie_embeddings:
        params["lm_head"] = sd["lm_head.weight"]
    # router stays fp32 (routing is precision-sensitive)
    return cfg, _model_cast(params, cfg, dtype,
                            fp32_keys=("moe_gate",))


def convert_bloom(hf, sd, dtype="bfloat16"):
    """HF bloom: fused query_key_value is INTERLEAVED per head — rows
    group as (H, 3, hd), unlike falcon's [q..., k, v] layout."""
    from ..models.bloom import BloomConfig
    H = hf["n_head"]
    D = hf["hidden_size"]
    hd = D // H
    L = hf["n_layer"]
    cfg = BloomConfig(
        vocab_size=hf["vocab_size"], max_seq_len=2048, n_layer=L,
        n_head=H, n_kv_heads=H, d_model=D, d_ff=4 * D,
        rms_eps=hf.get("layer_norm_epsilon", 1e-5), dtype=dtype)
    pre = "transformer." if "transformer.word_embeddings.weight" in sd \
        else ""
    g = lambda k: sd[pre + k]

    layers = []
    for i in range(L):
        lp = f"h.{i}."
        w = g(lp + "self_attention.query_key_value.weight").T  # (D, 3Hhd)
        b = g(lp + "self_attention.query_key_value.bias")
        w = w.reshape(D, H, 3, hd)
        b = b.reshape(H, 3, hd)
        layers.append({
            "rms1": g(lp + "input_layernorm.weight"),
            "b1": g(lp + "input_layernorm.bias"),
            "wq": w[:, :, 0].reshape(D, H * hd),
            "wk": w[:, :, 1].reshape(D, H * hd),
            "wv": w[:, :, 2].reshape(D, H * hd),
            "bq": b[:, 0].reshape(H * hd),
            "bk": b[:, 1].reshape(H * hd),
            "bv": b[:, 2].reshape(H * hd),
            "wo": g(lp + "self_attention.dense.weight").T,
            "bo": g(lp + "self_attention.dense.bias"),
            "rms2": g(lp + "post_attention_layernorm.weight"),
            "b2": g(lp + "post_attention_layernorm.bias"),
            "wup": g(lp + "mlp.dense_h_to_4h.weight").T,
            "bup": g(lp + "mlp.dense_h_to_4h.bias"),
            "wdown": g(lp + "mlp.dense_4h_to_h.weight").T,
            "bdown": g(lp + "mlp.dense_4h_to_h.bias"),
        })
    params = {
        "wte": g("word_embeddings.weight"),
        "embed_ln_s": g("word_embeddings_layernorm.weight"),
        "embed_ln_b": g("word_embeddings_layernorm.bias"),
        "norm_f": g("ln_f.weight"),
        "norm_f_b": g("ln_f.bias"),
        # bloom's tied head has no bias; proj_bias adds the slot
        "lm_head_b": np.zeros((hf["vocab_size"],), np.float32),
        "blocks": {k: _stack(layers, k) for k in layers[0]},
    }
    return cfg, _model_cast(params, cfg, dtype)


CONVERTERS = {
    "gpt2": convert_gpt2,
    "opt": convert_opt,
    "llama": convert_llama,
    "mistral": convert_llama,      # same weight tree; sliding_window is
                                   # converted and honored by all paths
    "qwen2": convert_qwen2,
    "phi": convert_phi,
    "falcon": convert_falcon,
    "mixtral": convert_mixtral,
    "bloom": convert_bloom,
    "gptj": convert_gptj,
    "gpt_neo": convert_gpt_neo,
    "gpt_neox": convert_gpt_neox,
    "internlm": convert_internlm,
}

_MODEL_CLASSES = {
    "gpt2": ("..models.gpt2", "GPT2"),
    "opt": ("..models.opt", "OPT"),
    "llama": ("..models.llama", "Llama"),
    "mistral": ("..models.llama", "Llama"),
    "qwen2": ("..models.qwen", "Qwen"),
    "phi": ("..models.phi", "Phi"),
    "falcon": ("..models.falcon", "Falcon"),
    "mixtral": ("..models.mixtral", "Mixtral"),
    "bloom": ("..models.bloom", "Bloom"),
    "gptj": ("..models.gptj", "GPTJ"),
    "gpt_neo": ("..models.gpt_neo", "GPTNeo"),
    "gpt_neox": ("..models.gpt_neox", "GPTNeoX"),
    "internlm": ("..models.internlm", "InternLM"),
}


def _model_cast(params, cfg, dtype, fp32_keys=()):
    """Cast the numpy tree to the model dtype ON HOST (fp32_keys stay
    f32). bf16 works as a host dtype via ml_dtypes. Returning host
    arrays — not committed jax arrays — is load-bearing: device
    placement is deferred to ``shard_params``/``device_put`` so
    ZeRO-Inference can quantize and TP serving can shard models whose
    full bf16 tree would not fit one chip (reference loads to torch CPU
    for the same reason, inference/engine.py:331)."""
    import jax.numpy as jnp
    dt = np.dtype(jnp.dtype(dtype))

    def walk(tree, path=()):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        keep = any(k in fp32_keys for k in path)
        return np.asarray(tree).astype(np.float32 if keep else dt,
                                       copy=False)
    return walk(params)


def load_pretrained(model_dir, dtype="bfloat16"):
    """Load an HF checkpoint directory -> (model, params).

    The model is one of this repo's functional families; params are in
    the family's stacked-layer tree, cast to ``dtype``. Dispatches on
    config.json model_type.
    """
    import importlib
    hf = read_hf_config(model_dir)
    mt = hf.get("model_type")
    if mt not in CONVERTERS:
        raise ValueError(
            f"unsupported model_type {mt!r}; supported: "
            f"{sorted(CONVERTERS)}")
    sd = read_hf_state_dict(model_dir)
    cfg, params = CONVERTERS[mt](hf, sd, dtype=dtype)
    mod_name, cls_name = _MODEL_CLASSES[mt]
    mod = importlib.import_module(mod_name, package=__package__)
    return getattr(mod, cls_name)(cfg), params
