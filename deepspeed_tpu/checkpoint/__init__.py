from .universal import (consolidate_to_fp32, load_consolidated,
                        ds_to_universal, load_universal_param,
                        inspect_checkpoint)
