"""HF checkpoint EXPORT: model param trees -> safetensors + config.json.

Counterpart of the reference's ``save_16bit_model`` / zero_to_fp32 HF
export path (/root/reference/deepspeed/runtime/engine.py:3625
``save_16bit_model``, ``utils/zero_to_fp32.py``
``convert_zero_checkpoint_to_fp32_state_dict``): a trained model leaves
the framework as a standard HuggingFace checkpoint directory that
``transformers`` loads directly. The inverse of ``checkpoint/hf.py`` —
stacked functional trees are sliced per layer and renamed to each
family's HF key set.

TPU-first difference: there is no per-rank partitioned state to stitch
offline — the engine consolidates by reading the GLOBAL jax.Arrays
(single process) or a process_allgather (multi-host), then one writer
emits the file. Supported families: gpt2, opt, llama, mistral, qwen2,
internlm, gpt_neox.

Entry points:
  export_hf(model, params, save_dir, dtype=...)   # numpy/jax tree in
  DeepSpeedEngine.save_16bit_model(save_dir)      # runtime/engine.py
"""

import json
import os

import numpy as np

__all__ = ["export_hf"]


def _to_host(tree):
    import jax
    return jax.tree.map(lambda a: np.asarray(a), tree)


def _save(sd, save_dir, dtype):
    """Write {name: np.ndarray} as model.safetensors in ``dtype``
    (bf16 rides through torch — numpy has no bf16 serialization)."""
    os.makedirs(save_dir, exist_ok=True)
    import torch
    tdt = {"bfloat16": torch.bfloat16, "float16": torch.float16,
           "float32": torch.float32}[dtype]
    out = {k: torch.from_numpy(
        np.array(v, np.float32, copy=True)).to(tdt).contiguous()
        for k, v in sd.items()}
    from safetensors.torch import save_file
    save_file(out, os.path.join(save_dir, "model.safetensors"))


def _write_config(save_dir, cfg_dict):
    os.makedirs(save_dir, exist_ok=True)
    with open(os.path.join(save_dir, "config.json"), "w") as f:
        json.dump(cfg_dict, f, indent=1)


def _unstack(blocks, i):
    return {k: np.asarray(v[i]) for k, v in blocks.items()}


# ------------------------------------------------------------- gpt2 / opt
def _export_gpt2(cfg, params, save_dir, dtype):
    sd = {}
    pre = "transformer."
    sd[pre + "wte.weight"] = params["wte"]
    sd[pre + "wpe.weight"] = params["wpe"]
    sd[pre + "ln_f.weight"] = params["lnf_scale"]
    sd[pre + "ln_f.bias"] = params["lnf_bias"]
    for i in range(cfg.n_layer):
        e = _unstack(params["blocks"], i)
        lp = f"{pre}h.{i}."
        sd[lp + "ln_1.weight"] = e["ln1_scale"]
        sd[lp + "ln_1.bias"] = e["ln1_bias"]
        sd[lp + "attn.c_attn.weight"] = e["wqkv"]     # Conv1D (in, out)
        sd[lp + "attn.c_attn.bias"] = e["bqkv"]
        sd[lp + "attn.c_proj.weight"] = e["wo"]
        sd[lp + "attn.c_proj.bias"] = e["bo"]
        sd[lp + "ln_2.weight"] = e["ln2_scale"]
        sd[lp + "ln_2.bias"] = e["ln2_bias"]
        sd[lp + "mlp.c_fc.weight"] = e["wup"]
        sd[lp + "mlp.c_fc.bias"] = e["bup"]
        sd[lp + "mlp.c_proj.weight"] = e["wdown"]
        sd[lp + "mlp.c_proj.bias"] = e["bdown"]
    _write_config(save_dir, {
        "model_type": "gpt2", "architectures": ["GPT2LMHeadModel"],
        "vocab_size": cfg.vocab_size, "n_positions": cfg.max_seq_len,
        "n_ctx": cfg.max_seq_len, "n_embd": cfg.d_model,
        "n_layer": cfg.n_layer, "n_head": cfg.n_head,
        "activation_function": ("gelu_new" if cfg.activation == "gelu"
                                else cfg.activation),
        "layer_norm_epsilon": 1e-5, "tie_word_embeddings": True,
        "torch_dtype": dtype,
    })
    _save(sd, save_dir, dtype)


def _export_opt(cfg, params, save_dir, dtype):
    sd = {}
    pre = "model.decoder."
    D = cfg.d_model
    sd[pre + "embed_tokens.weight"] = params["wte"]
    # HF OPT positions carry 2 leading pad slots (see convert_opt)
    wpe = np.asarray(params["wpe"], np.float32)
    sd[pre + "embed_positions.weight"] = np.concatenate(
        [np.zeros((2, D), np.float32), wpe])
    sd[pre + "final_layer_norm.weight"] = params["lnf_scale"]
    sd[pre + "final_layer_norm.bias"] = params["lnf_bias"]
    sd["lm_head.weight"] = params["wte"]
    for i in range(cfg.n_layer):
        e = _unstack(params["blocks"], i)
        lp = f"{pre}layers.{i}."
        w = np.asarray(e["wqkv"], np.float32)
        b = np.asarray(e["bqkv"], np.float32)
        for j, m in enumerate(("q", "k", "v")):
            sd[lp + f"self_attn.{m}_proj.weight"] = \
                w[:, j * D:(j + 1) * D].T
            sd[lp + f"self_attn.{m}_proj.bias"] = b[j * D:(j + 1) * D]
        sd[lp + "self_attn.out_proj.weight"] = np.asarray(e["wo"]).T
        sd[lp + "self_attn.out_proj.bias"] = e["bo"]
        sd[lp + "self_attn_layer_norm.weight"] = e["ln1_scale"]
        sd[lp + "self_attn_layer_norm.bias"] = e["ln1_bias"]
        sd[lp + "final_layer_norm.weight"] = e["ln2_scale"]
        sd[lp + "final_layer_norm.bias"] = e["ln2_bias"]
        sd[lp + "fc1.weight"] = np.asarray(e["wup"]).T
        sd[lp + "fc1.bias"] = e["bup"]
        sd[lp + "fc2.weight"] = np.asarray(e["wdown"]).T
        sd[lp + "fc2.bias"] = e["bdown"]
    _write_config(save_dir, {
        "model_type": "opt", "architectures": ["OPTForCausalLM"],
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_seq_len,
        "hidden_size": cfg.d_model, "ffn_dim": 4 * cfg.d_model,
        "num_hidden_layers": cfg.n_layer,
        "num_attention_heads": cfg.n_head,
        "word_embed_proj_dim": cfg.d_model,
        "do_layer_norm_before": True, "activation_function": "relu",
        "tie_word_embeddings": True, "torch_dtype": dtype,
    })
    _save(sd, save_dir, dtype)


# --------------------------------------------------------- llama family
def _export_llama_like(cfg, params, save_dir, dtype, model_type):
    sd = {}
    pre = "model."
    sd[pre + "embed_tokens.weight"] = params["wte"]
    sd[pre + "norm.weight"] = params["norm_f"]
    sd["lm_head.weight"] = params["wte"] if cfg.tie_embeddings \
        else params["lm_head"]
    for i in range(cfg.n_layer):
        e = _unstack(params["blocks"], i)
        lp = f"{pre}layers.{i}."
        sd[lp + "self_attn.q_proj.weight"] = np.asarray(e["wq"]).T
        sd[lp + "self_attn.k_proj.weight"] = np.asarray(e["wk"]).T
        sd[lp + "self_attn.v_proj.weight"] = np.asarray(e["wv"]).T
        sd[lp + "self_attn.o_proj.weight"] = np.asarray(e["wo"]).T
        if cfg.qkv_bias:
            sd[lp + "self_attn.q_proj.bias"] = e["bq"]
            sd[lp + "self_attn.k_proj.bias"] = e["bk"]
            sd[lp + "self_attn.v_proj.bias"] = e["bv"]
        if cfg.o_bias_on:
            sd[lp + "self_attn.o_proj.bias"] = e["bo"]
        sd[lp + "mlp.gate_proj.weight"] = np.asarray(e["wgate"]).T
        sd[lp + "mlp.up_proj.weight"] = np.asarray(e["wup"]).T
        sd[lp + "mlp.down_proj.weight"] = np.asarray(e["wdown"]).T
        sd[lp + "input_layernorm.weight"] = e["rms1"]
        sd[lp + "post_attention_layernorm.weight"] = e["rms2"]
    c = {
        "model_type": model_type,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_seq_len,
        "hidden_size": cfg.d_model, "intermediate_size": cfg.ffn_dim,
        "num_hidden_layers": cfg.n_layer,
        "num_attention_heads": cfg.n_head,
        "num_key_value_heads": cfg.n_kv_heads,
        "rope_theta": cfg.rope_theta, "rms_norm_eps": cfg.rms_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "hidden_act": "silu", "torch_dtype": dtype,
    }
    if model_type == "llama":
        c["architectures"] = ["LlamaForCausalLM"]
        c["attention_bias"] = cfg.qkv_bias
    elif model_type == "mistral":
        c["architectures"] = ["MistralForCausalLM"]
        c["sliding_window"] = cfg.sliding_window or None
    elif model_type == "qwen2":
        c["architectures"] = ["Qwen2ForCausalLM"]
    elif model_type == "internlm":
        c["architectures"] = ["InternLMForCausalLM"]
        c["bias"] = cfg.qkv_bias
    _write_config(save_dir, c)
    _save(sd, save_dir, dtype)


def _export_gpt_neox(cfg, params, save_dir, dtype):
    H, hd = cfg.n_head, cfg.d_head
    D = cfg.d_model
    sd = {}
    pre = "gpt_neox."
    sd[pre + "embed_in.weight"] = params["wte"]
    sd[pre + "final_layer_norm.weight"] = params["norm_f"]
    sd[pre + "final_layer_norm.bias"] = params["norm_f_b"]
    sd["embed_out.weight"] = params["wte"] if cfg.tie_embeddings \
        else params["lm_head"]

    def interleave(q, k, v):
        """inverse of the loader's per-head de-interleave: stack
        (..., D) x3 -> (..., H, 3, hd) -> (..., 3D)"""
        parts = [np.asarray(t, np.float32).reshape(
            *t.shape[:-1], H, 1, hd) for t in (q, k, v)]
        t = np.concatenate(parts, axis=-2)
        return t.reshape(*t.shape[:-3], 3 * D)

    for i in range(cfg.n_layer):
        e = _unstack(params["blocks"], i)
        lp = f"{pre}layers.{i}."
        sd[lp + "attention.query_key_value.weight"] = interleave(
            e["wq"], e["wk"], e["wv"]).T
        sd[lp + "attention.query_key_value.bias"] = interleave(
            e["bq"], e["bk"], e["bv"])
        sd[lp + "attention.dense.weight"] = np.asarray(e["wo"]).T
        sd[lp + "attention.dense.bias"] = e["bo"]
        sd[lp + "mlp.dense_h_to_4h.weight"] = np.asarray(e["wup"]).T
        sd[lp + "mlp.dense_h_to_4h.bias"] = e["bup"]
        sd[lp + "mlp.dense_4h_to_h.weight"] = np.asarray(e["wdown"]).T
        sd[lp + "mlp.dense_4h_to_h.bias"] = e["bdown"]
        sd[lp + "input_layernorm.weight"] = e["rms1"]
        sd[lp + "input_layernorm.bias"] = e["b1"]
        sd[lp + "post_attention_layernorm.weight"] = e["rms2"]
        sd[lp + "post_attention_layernorm.bias"] = e["b2"]
    _write_config(save_dir, {
        "model_type": "gpt_neox", "architectures": ["GPTNeoXForCausalLM"],
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_seq_len,
        "hidden_size": cfg.d_model, "intermediate_size": cfg.ffn_dim,
        "num_hidden_layers": cfg.n_layer,
        "num_attention_heads": cfg.n_head,
        "rotary_pct": cfg.rotary_pct, "rotary_emb_base": cfg.rope_theta,
        "layer_norm_eps": cfg.rms_eps,
        "use_parallel_residual": cfg.parallel_block,
        "hidden_act": "gelu" if cfg.mlp_act == "gelu" else "gelu_new",
        "tie_word_embeddings": cfg.tie_embeddings, "torch_dtype": dtype,
    })
    _save(sd, save_dir, dtype)


def export_hf(model, params, save_dir, dtype="bfloat16"):
    """Write ``params`` of ``model`` as an HF checkpoint directory.
    Dispatches on the model's config class. params may be jax or numpy
    arrays (jax arrays must be fully addressable — consolidate first)."""
    from ..models.gpt2 import GPT2Config
    from ..models.opt import OPTConfig
    from ..models.llama import LlamaConfig
    from ..models.qwen import QwenConfig
    from ..models.internlm import InternLMConfig
    from ..models.gpt_neox import GPTNeoXConfig
    cfg = model.config
    params = _to_host(params)
    if isinstance(cfg, OPTConfig):
        _export_opt(cfg, params, save_dir, dtype)
    elif isinstance(cfg, GPT2Config) and type(cfg) is GPT2Config:
        _export_gpt2(cfg, params, save_dir, dtype)
    elif isinstance(cfg, GPTNeoXConfig):
        _export_gpt_neox(cfg, params, save_dir, dtype)
    elif isinstance(cfg, QwenConfig):
        _export_llama_like(cfg, params, save_dir, dtype, "qwen2")
    elif isinstance(cfg, InternLMConfig):
        _export_llama_like(cfg, params, save_dir, dtype, "internlm")
    elif isinstance(cfg, LlamaConfig) and type(cfg) is LlamaConfig:
        mt = "mistral" if cfg.sliding_window else "llama"
        _export_llama_like(cfg, params, save_dir, dtype, mt)
    else:
        raise ValueError(
            f"no HF exporter for config {type(cfg).__name__}; supported: "
            f"GPT2, OPT, Llama/Mistral, Qwen, InternLM, GPTNeoX")
