"""CLI: reconcile a captured ``jax.profiler`` trace against the
planner's cost model.

    python -m deepspeed_tpu.profiling.reconcile <trace_dir> \
        --mesh dp=2,tp=4 [--steps N] [--json] [--seed-cache] [...]

Parses the newest ``*.trace.json.gz`` under ``trace_dir`` into a
``StepDecomposition``, scores the given mesh with ``planner._score``
for the described model, and prints the modeled-vs-measured drift
table (``--json`` for the machine-readable report). ``--seed-cache``
distills the measured run into ``comm_link`` + ``op_cost`` winner-cache
rows so the next ``plan()`` prices meshes from measured numbers.

This module is the thin argv shell; the library lives in
``deepspeed_tpu/autotuning/reconcile.py``.
"""

import argparse
import json
import sys

from ..autotuning.planner import ModelDesc, PodDesc
from ..autotuning import reconcile as _rec
from . import step_trace


def _parse_mesh(spec):
    """'dp=2,tp=4' -> planner mesh dict (unnamed axes default to 1)."""
    short = {"pp": "pipe", "do": "data_outer", "dp": "data",
             "ep": "expert", "sp": "seq", "tp": "tensor"}
    out = {}
    for part in (spec or "").split(","):
        if not part.strip():
            continue
        k, _, v = part.partition("=")
        k = k.strip()
        out[short.get(k, k)] = int(v)
    return out


def _count(s):
    """int that also accepts '13e9'-style scientific notation."""
    return int(float(s))


def build_parser():
    p = argparse.ArgumentParser(
        prog="python -m deepspeed_tpu.profiling.reconcile",
        description="modeled-vs-measured drift report for a profiler "
                    "trace")
    p.add_argument("trace_dir",
                   help="dir holding the capture (searched recursively "
                        "for *.trace.json.gz) or a trace file")
    p.add_argument("--steps", type=int, default=1,
                   help="train steps the capture covered (per-step "
                        "normalization; default 1)")
    p.add_argument("--mesh", default="",
                   help="mesh the trace ran on, e.g. dp=2,tp=4 "
                        "(axes: pp do dp ep sp tp; default all 1)")
    p.add_argument("--schedule", default="none",
                   choices=["none", "gpipe", "1f1b", "zb"])
    p.add_argument("--micro-batches", type=int, default=1)
    p.add_argument("--offload", action="store_true",
                   help="score the host_offload term")
    p.add_argument("--batch-tokens", type=_count, default=None)
    # model description (defaults = the planner's tiny placeholder)
    p.add_argument("--params", type=_count, default=1 << 20)
    p.add_argument("--layers", type=int, default=1)
    p.add_argument("--d-model", type=int, default=64)
    p.add_argument("--heads", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--experts", type=int, default=0)
    p.add_argument("--json", action="store_true",
                   help="emit the full decomposition + drift report as "
                        "JSON instead of the table")
    p.add_argument("--seed-cache", action="store_true",
                   help="seed measured comm_link/op_cost rows into the "
                        "winner cache")
    p.add_argument("--cache", default=None,
                   help="winner-cache path for --seed-cache (default: "
                        "the dispatch cache path)")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    model = ModelDesc(params=args.params, n_layer=args.layers,
                      d_model=args.d_model, n_head=args.heads,
                      max_seq_len=args.seq_len, experts=args.experts,
                      name="cli")
    pod = PodDesc.from_devices()
    mesh_shape = _parse_mesh(args.mesh)
    decomp, report = _rec.reconcile_trace(
        args.trace_dir, steps=max(1, args.steps), model=model, pod=pod,
        mesh_shape=mesh_shape, schedule=args.schedule,
        micro_batches=args.micro_batches, offload=args.offload,
        batch_tokens=args.batch_tokens)
    if decomp is None:
        print("no parseable trace found", file=sys.stderr)
        return 2
    seeded = 0
    if args.seed_cache and report is not None:
        rows = _rec.seed_rows(decomp, report)
        seeded = _rec.seed_cache(rows, path=args.cache)
    if args.json:
        out = {"decomposition": decomp.to_dict(),
               "drift": None if report is None else report.to_dict()}
        if args.seed_cache:
            out["seeded_rows"] = seeded
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0
    print(f"trace: {decomp.trace_path}")
    print(f"steps: {decomp.steps}  coverage: {decomp.coverage_pct:.1f}%"
          f"  occupancy: {decomp.occupancy_pct:.1f}%")
    if report is not None:
        print(report.table())
    else:
        print("(planner scoring unavailable — decomposition only)")
        for k, v in sorted(decomp.terms.items()):
            print(f"  {k:>14}: {v:.4f} ms")
    if args.seed_cache:
        print(f"seeded {seeded} winner-cache rows")
    return 0


if __name__ == "__main__":
    sys.exit(main())
