"""Step-anatomy tracing: a ``jax.profiler`` Chrome trace parsed into a
:class:`StepDecomposition` — where one optimizer step's device time went,
in the planner's own cost-term vocabulary.

This promotes the parsing that was stranded in
``benchmarks/trace_summary.py`` into a library the telemetry layer and
the reconcile CLI share. The pipeline:

  1. **Track selection** — device-side tracks are processes whose name
     carries ``TPU``/``/device``/``Core`` and whose ``XLA Ops`` thread
     holds the leaf op events (Steps/Modules tracks are whole-step
     envelopes that would double count). On CPU backends there is no
     device track; the XLA CPU client's thunk-executor threads
     (``tf_XLATfrtCpuClient/*``) carry the op events instead, so they
     serve as a fallback (``cpu_fallback=True`` in the result) with an
     HLO-op-name filter that drops the runtime scaffolding frames.
  2. **Self time** — per track, an event's duration minus its nested
     children (the trace_summary stack walk), so envelopes never double
     count their contents.
  3. **Classification** — every op self-time lands in exactly one
     decomposition key: a collective op kind (mapped to a planner term
     via its replica groups, see below), a host-staging copy
     (``host_offload``), a device-side layout copy (the one explicitly
     *unmodeled* key), or ``compute`` (matmul/fusion/Pallas/everything
     else). Pallas custom-call time is additionally broken out per
     tunable-op name from the autotune registry (``kernels``).
  4. **Collective legs** — when an event's args carry the HLO
     ``replica_groups=...`` text, the PR-3 parse
     (``runtime/zero/overlap.parse_replica_groups`` + ``match_axes``)
     resolves which mesh axes the collective spans; an axis set touching
     ``data_outer`` is a DCN leg, anything else ICI.
  5. **Exposed vs hidden** — async collectives appear as
     ``*-start``/``*-done`` event pairs; the window between the start
     event's end and the done event's begin overlapped compute (hidden),
     the start/done durations themselves did not (exposed). Synchronous
     collectives are fully exposed. Planner terms accumulate EXPOSED
     time only — that is what the ``_score`` breakdown models (its
     ``_HIDDEN_FRAC`` discount plays the same role on the modeled side).

The decomposition's ``terms`` keys are exactly
``autotuning.planner.SCORE_TERMS`` and its ``unmodeled`` keys exactly
:data:`UNMODELED_KEYS` — the two-direction lint in
``tests/unit/test_reconcile.py`` keeps tracer and planner vocabularies
from silently diverging.

JSON schema: :meth:`StepDecomposition.to_dict` is versioned
(:data:`SCHEMA_VERSION`); consumers (``extras.reconcile`` in
``BENCH_local.json``, the flight recorder, the CLI ``--json`` outputs)
key off the field names below, so additions bump the version.
"""

import collections
import glob
import gzip
import json
import os
import re
from dataclasses import dataclass, field, asdict

from ..utils.logging import logger

SCHEMA_VERSION = 1

# the planner-aligned decomposition keys (== planner.SCORE_TERMS; the
# reconcile lint asserts the equality) ...
DECOMP_TERMS = ("compute", "grad_reduce", "tp_reduce", "pipe_handoff",
                "ring_rotate", "expert_a2a", "host_offload")
# ... plus the device time the planner deliberately does NOT model:
# device-side layout copies (transpose/bitcast/non-host copy). Keys
# here are the tracer's explicit "unmodeled" declaration — a new
# decomposition key must join one list or the other or the lint fails.
UNMODELED_KEYS = ("copy_layout",)

# collective opcode -> default planner term when no replica groups are
# available (sync CPU lowerings, stripped traces); with groups + a mesh
# the axis match refines the choice (tensor -> tp_reduce, etc.)
_COLL_RE = re.compile(
    r"^(all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute|send|recv)(-start|-done)?(?:\.(\d+))?$")
_COPY_RE = re.compile(r"^copy(-start|-done)?(?:\.(\d+))?$")
# HLO-op-shaped names (lowercase opcode [+ .N]); the CPU-client
# fallback tracks interleave runtime frames (TfrtCpuExecutable::Execute,
# ParseArguments) with real op events and only the latter may count as
# device time
_HLO_NAME_RE = re.compile(r"^[a-z][a-z0-9_\-]*(?:\.\d+)?$")

# fragments of our Pallas kernel symbol names -> the tunable-op name in
# autotuning/kernel_registry.REGISTRY the kernel time is keyed under
# (first match wins; specific before generic)
KERNEL_OP_HINTS = (
    ("paged_chunk", ("paged_chunk", "chunk_prefill", "_chunk_kernel")),
    ("paged_decode", ("paged", "_decode_kernel")),
    ("moe_grouped_mm", ("gmm", "tgmm", "swiglu", "grouped")),
    ("ring_block", ("ring_block", "fwd_block")),
    ("flash_attention", ("flash", "block_sparse",
                         "_fwd_kernel", "_bwd_kernel")),
    ("mlp_matmul", ("mlp", "_mm_kernel", "_dw_kernel")),
    ("layernorm", ("layernorm", "rmsnorm", "_ln_", "_rms_")),
    ("fused_ce", ("fused_ce", "_ce_kernel", "cross_entropy")),
)


def family_of(name):
    """Coarse op family (the trace_summary table's grouping)."""
    n = name.lower()
    if _COLL_RE.match(n):
        return "collective"
    if "custom-call" in n or "pallas" in n or "flash" in n:
        return "pallas/custom-call"
    if re.search(r"convolution|dot|einsum", n):
        return "matmul"
    if "fusion" in n:
        return "fusion(elementwise/other)"
    if "copy" in n or "transpose" in n or "bitcast" in n:
        return "copy/layout"
    if "scatter" in n or "gather" in n or "dynamic" in n:
        return "gather/scatter/DUS"
    return "other"


def kernel_op_for(text):
    """Registry tunable-op name for a Pallas/custom-call event, matched
    on kernel-symbol fragments in the event name + args; None when the
    call is not one of ours."""
    t = text.lower()
    for op, hints in KERNEL_OP_HINTS:
        if any(h in t for h in hints):
            return op
    return None


# ------------------------------------------------------------- trace io

def find_trace_file(root):
    """Newest ``*.trace.json.gz`` under ``root`` (recursive — jax nests
    traces under ``plugins/profile/<timestamp>/``), or ``root`` itself
    when it already names a trace file. None when nothing is there."""
    if os.path.isfile(root):
        return root
    paths = glob.glob(os.path.join(glob.escape(root),
                                   "**", "*.trace.json.gz"),
                      recursive=True)
    return sorted(paths)[-1] if paths else None


def load_trace_events(path):
    """The ``traceEvents`` list of one Chrome trace (.json or .json.gz)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        trace = json.load(f)
    events = trace.get("traceEvents", [])
    return events if isinstance(events, list) else []


# ------------------------------------------------------- track selection

def _meta_names(events):
    pid_names, tid_names = {}, {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pid_names[e.get("pid")] = (e.get("args") or {}).get("name", "")
        elif e.get("name") == "thread_name":
            tid_names[(e.get("pid"), e.get("tid"))] = \
                (e.get("args") or {}).get("name", "")
    return pid_names, tid_names


def _op_tracks(pid_names, tid_names):
    """-> (op_tids, track_labels, cpu_fallback). Device tracks first;
    the XLA CPU client's thunk threads as the fallback so a CPU dev
    container still yields a (compute-only) decomposition."""
    dev_pids = {p for p, n in pid_names.items()
                if "TPU" in n or "/device" in n.lower() or "Core" in n}
    op_tids = {k for k, n in tid_names.items()
               if k[0] in dev_pids and n == "XLA Ops"}
    if op_tids:
        labels = sorted({pid_names[p] for p in dev_pids})
        return op_tids, labels, False
    op_tids = {k for k, n in tid_names.items()
               if "XLATfrtCpuClient" in n}
    labels = sorted({pid_names.get(k[0], "?") for k in op_tids})
    return op_tids, labels, bool(op_tids)


# ----------------------------------------------------------- self times

def _self_times(events, op_tids, hlo_only=False):
    """[(event, self_dur_us)] per the trace_summary stack walk: sort by
    (ts, -dur), subtract each child's duration from its innermost
    enclosing parent on the same (pid, tid)."""
    by_tid = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) \
                not in op_tids:
            continue
        if hlo_only and not _HLO_NAME_RE.match(str(e.get("name", ""))):
            continue
        by_tid[(e.get("pid"), e.get("tid"))].append(e)
    out = []
    for evs in by_tid.values():
        evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack = []            # (end_ts, index into selfs)
        selfs = []
        for e in evs:
            ts, dur = e["ts"], e.get("dur", 0)
            while stack and stack[-1][0] <= ts:
                stack.pop()
            if stack:
                selfs[stack[-1][1]][1] -= dur
            selfs.append([e, dur])
            stack.append((ts + dur, len(selfs) - 1))
        out.extend((e, max(0.0, s)) for e, s in selfs)
    return out


# -------------------------------------------------------- classification

def _args_text(e):
    args = e.get("args") or {}
    return " ".join(str(v) for v in args.values())


def _coll_axes(e, mesh):
    """Mesh axes of a collective event via the replica-group text its
    args carry (the HLO long name xprof attaches), or None."""
    if mesh is None:
        return None
    text = _args_text(e)
    if "replica_groups" not in text:
        return None
    try:
        from ..runtime.zero.overlap import parse_replica_groups, \
            match_axes
        groups = parse_replica_groups(text)
        axes = match_axes(groups, mesh) if groups else None
        return tuple(axes) if axes else None
    except Exception:  # noqa: BLE001 - classification is best-effort
        return None


def _term_for_collective(kind, axes, mesh):
    """Planner term for one collective: axes decide when known, the op
    kind's canonical role otherwise."""
    if axes:
        s = set(axes)
        if s <= {"tensor"}:
            return "tp_reduce"
        if kind == "all-to-all":
            return "expert_a2a"
        if s <= {"pipe"}:
            return "pipe_handoff"
        if s <= {"seq"}:
            return "ring_rotate"
        if kind in ("collective-permute", "send", "recv"):
            return "pipe_handoff" if "pipe" in s else "ring_rotate"
        return "grad_reduce"
    if kind == "all-to-all":
        return "expert_a2a"
    if kind in ("collective-permute", "send", "recv"):
        shape = dict(mesh.shape) if mesh is not None else {}
        if shape.get("seq", 1) > 1 and shape.get("pipe", 1) <= 1:
            return "ring_rotate"
        return "pipe_handoff"
    return "grad_reduce"


def _is_host_copy(e):
    text = (str(e.get("name", "")) + " " + _args_text(e)).lower()
    return "s(5)" in text or "host" in text


# ---------------------------------------------------------- decomposition

@dataclass
class StepDecomposition:
    """Per-step device-time attribution (all ``*_ms`` fields are per
    step — raw trace totals divided by ``steps``)."""
    schema: int = SCHEMA_VERSION
    steps: int = 1
    trace_path: str = ""
    device_tracks: list = field(default_factory=list)
    cpu_fallback: bool = False
    total_device_ms: float = 0.0       # sum(terms) + sum(unmodeled)
    terms: dict = field(default_factory=dict)      # DECOMP_TERMS -> ms
    unmodeled: dict = field(default_factory=dict)  # UNMODELED_KEYS -> ms
    collectives: list = field(default_factory=list)
    kernels: dict = field(default_factory=dict)    # registry op -> ms
    per_op: list = field(default_factory=list)
    host_copy_ms: float = 0.0
    collective_total_ms: float = 0.0
    collective_exposed_ms: float = 0.0
    collective_hidden_ms: float = 0.0
    occupancy_pct: float = 0.0         # busy / track span (tick fill)
    span_ms: float = 0.0               # device-track span per step
    coverage_pct: float = 0.0          # 100 * sum(terms) / total

    def to_dict(self):
        return asdict(self)

    def to_json(self):
        return json.dumps(self.to_dict(), indent=2, sort_keys=True) + "\n"


def _pair_async(rows):
    """Match ``*-start``/``*-done`` rows of one collective kind: exact
    ``.N`` suffix first, then FIFO for the suffix-less leftovers.
    Returns (pairs, leftovers); each pair is (start_row, done_row)."""
    starts = [r for r in rows if r["phase"] == "start"]
    dones = [r for r in rows if r["phase"] == "done"]
    by_sfx = {r["sfx"]: r for r in dones if r["sfx"] is not None}
    pairs, used = [], set()
    rest_starts = []
    for s in starts:
        d = by_sfx.get(s["sfx"]) if s["sfx"] is not None else None
        if d is not None and id(d) not in used:
            used.add(id(d))
            pairs.append((s, d))
        else:
            rest_starts.append(s)
    rest_dones = sorted((d for d in dones if id(d) not in used),
                        key=lambda r: r["ts"])
    rest_starts.sort(key=lambda r: r["ts"])
    k = min(len(rest_starts), len(rest_dones))
    pairs.extend(zip(rest_starts[:k], rest_dones[:k]))
    leftovers = rest_starts[k:] + rest_dones[k:]
    return pairs, leftovers


def decompose(events, steps=1, mesh=None, trace_path=""):
    """Classify one trace's device op events into a
    :class:`StepDecomposition`. Returns None when the trace carries no
    recognizable op track (the caller degrades with one warning)."""
    steps = max(1, int(steps))
    pid_names, tid_names = _meta_names(events)
    op_tids, labels, cpu_fallback = _op_tracks(pid_names, tid_names)
    if not op_tids:
        return None
    selfs = _self_times(events, op_tids, hlo_only=cpu_fallback)
    if not selfs:
        return None

    terms = {k: 0.0 for k in DECOMP_TERMS}
    unmodeled = {k: 0.0 for k in UNMODELED_KEYS}
    kernels = collections.Counter()
    per_op_ms = collections.Counter()
    per_op_n = collections.Counter()
    coll_rows = collections.defaultdict(list)   # (kind, term) -> rows
    copy_async = []                             # host-copy start/done rows
    host_copy_us = 0.0

    for e, sdur in selfs:
        name = str(e.get("name", "?"))
        per_op_ms[name] += sdur / 1e3
        per_op_n[name] += 1
        m = _COLL_RE.match(name.lower())
        if m:
            kind = m.group(1)
            axes = _coll_axes(e, mesh)
            term = _term_for_collective(kind, axes, mesh)
            coll_rows[(kind, term, axes)].append({
                "phase": (m.group(2) or "").lstrip("-") or None,
                "sfx": m.group(3),
                "ts": e.get("ts", 0),
                "dur": e.get("dur", 0),
                "self": sdur,
            })
            continue
        mc = _COPY_RE.match(name.lower())
        if mc and _is_host_copy(e):
            phase = (mc.group(1) or "").lstrip("-") or None
            if phase:
                copy_async.append({"phase": phase, "sfx": mc.group(2),
                                   "ts": e.get("ts", 0),
                                   "dur": e.get("dur", 0), "self": sdur})
            else:
                host_copy_us += sdur
            continue
        fam = family_of(name)
        if fam == "copy/layout":
            unmodeled["copy_layout"] += sdur / 1e3
            continue
        text = name + " " + _args_text(e)
        kop = kernel_op_for(text) if (
            fam == "pallas/custom-call" or "kernel" in text.lower()) \
            else None
        if kop is not None:
            kernels[kop] += sdur / 1e3
        terms["compute"] += sdur / 1e3

    # collectives: exposed/hidden per async pair, sync fully exposed
    collectives = []
    for (kind, term, axes), rows in sorted(
            coll_rows.items(), key=lambda kv: (kv[0][0], kv[0][1])):
        sync_rows = [r for r in rows if r["phase"] is None]
        pairs, leftovers = _pair_async(rows)
        total = sum(r["self"] for r in sync_rows)
        exposed = total
        hidden = 0.0
        for s, d in pairs:
            window = (d["ts"] + d["dur"]) - s["ts"]
            gap = max(0.0, d["ts"] - (s["ts"] + s["dur"]))
            hidden += gap
            exposed += max(0.0, window - gap)
            total += window
        for r in leftovers:      # unmatched start/done: count as exposed
            total += r["self"]
            exposed += r["self"]
        n = len(sync_rows) + len(pairs) + len(leftovers)
        leg = None
        if axes is not None:
            leg = "dcn" if "data_outer" in axes else "ici"
        collectives.append({
            "op": kind, "term": term,
            "axes": list(axes) if axes else None, "leg": leg,
            "count_per_step": round(n / steps, 3),
            "total_ms": round(total / 1e3 / steps, 6),
            "exposed_ms": round(exposed / 1e3 / steps, 6),
            "hidden_ms": round(hidden / 1e3 / steps, 6),
        })
        terms[term] += exposed / 1e3

    # host copies: async staging pairs + sync copies -> host_offload
    if copy_async:
        pairs, leftovers = _pair_async(copy_async)
        for s, d in pairs:
            window = (d["ts"] + d["dur"]) - s["ts"]
            gap = max(0.0, d["ts"] - (s["ts"] + s["dur"]))
            host_copy_us += max(0.0, window - gap)
        for r in leftovers:
            host_copy_us += r["self"]
    terms["host_offload"] += host_copy_us / 1e3

    # per-step scaling + occupancy
    terms = {k: round(v / steps, 6) for k, v in terms.items()}
    unmodeled = {k: round(v / steps, 6) for k, v in unmodeled.items()}
    total = sum(terms.values()) + sum(unmodeled.values())
    spans, busy = [], 0.0
    by_tid = collections.defaultdict(list)
    for e, sdur in selfs:
        by_tid[(e.get("pid"), e.get("tid"))].append((e, sdur))
        busy += sdur
    for rows in by_tid.values():
        t0 = min(e["ts"] for e, _ in rows)
        t1 = max(e["ts"] + e.get("dur", 0) for e, _ in rows)
        spans.append(max(0.0, t1 - t0))
    span = sum(spans)
    per_op = [{"op": nm, "ms": round(ms / steps, 6),
               "count": per_op_n[nm], "family": family_of(nm)}
              for nm, ms in per_op_ms.most_common()]

    d = StepDecomposition(
        steps=steps, trace_path=trace_path, device_tracks=labels,
        cpu_fallback=cpu_fallback,
        total_device_ms=round(total, 6),
        terms=terms, unmodeled=unmodeled,
        collectives=collectives,
        kernels={k: round(v / steps, 6)
                 for k, v in sorted(kernels.items())},
        per_op=per_op,
        host_copy_ms=round(host_copy_us / 1e3 / steps, 6),
        collective_total_ms=round(
            sum(c["total_ms"] for c in collectives), 6),
        collective_exposed_ms=round(
            sum(c["exposed_ms"] for c in collectives), 6),
        collective_hidden_ms=round(
            sum(c["hidden_ms"] for c in collectives), 6),
        occupancy_pct=round(
            min(100.0, 100.0 * busy / span) if span > 0 else 0.0, 3),
        span_ms=round(span / 1e3 / steps / max(1, len(spans)), 6),
        coverage_pct=round(
            100.0 * sum(terms.values()) / total if total > 0 else 0.0,
            3),
    )
    return d


def decompose_dir(root, steps=1, mesh=None):
    """Find + parse the newest trace under ``root``. Returns None (with
    ONE warning, never an exception — the step path rides on this) when
    no trace or no op track exists: CPU-only hosts and platforms
    without a profiler degrade to a no-op."""
    try:
        path = find_trace_file(root)
        if path is None:
            logger.warning(f"step_trace: no *.trace.json.gz under "
                           f"{root!r}; decomposition skipped")
            return None
        d = decompose(load_trace_events(path), steps=steps, mesh=mesh,
                      trace_path=path)
        if d is None:
            logger.warning(f"step_trace: trace {path!r} carries no "
                           f"recognizable device/op track; "
                           f"decomposition skipped")
        return d
    except Exception as e:  # noqa: BLE001 - observability never fatal
        logger.warning(f"step_trace: parsing trace under {root!r} "
                       f"failed ({type(e).__name__}: {e}); skipped")
        return None
