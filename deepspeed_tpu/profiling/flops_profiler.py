"""Flops profiler — compiled-program cost accounting.

Counterpart of reference ``profiling/flops_profiler/profiler.py:28``.
The reference monkeypatches torch functionals and walks module hooks to
count MACs; on TPU the compiler already knows: ``jax.jit(fn).lower(...)
.compile().cost_analysis()`` returns XLA's flop/byte counts for the exact
program that runs. The profiler wraps that, adds parameter counts and
wall-clock measurement, and keeps the reference's report surface
(get_total_flops/macs/params/duration, print_model_profile).
"""

import time

import numpy as np
import jax


def _param_count(params):
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def compiled_costs(compiled):
    """Normalize ``Compiled.cost_analysis()`` across jax versions into
    one flat dict (older jax returns ``[dict]``; key spellings vary
    between ``bytes accessed`` and ``bytes_accessed``). The single
    extraction point the engine's flops hook and the telemetry layer's
    MFU both read — the two can never disagree on what "step flops"
    means."""
    try:
        costs = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - some backends ship no analysis
        return {}
    if isinstance(costs, (list, tuple)):
        costs = costs[0] if costs else {}
    costs = dict(costs or {})
    if "bytes accessed" not in costs and "bytes_accessed" in costs:
        costs["bytes accessed"] = costs["bytes_accessed"]
    return costs


def _cost_analysis(fn, *args, static_argnums=()):
    compiled = jax.jit(fn, static_argnums=static_argnums).lower(
        *args).compile()
    return compiled, compiled_costs(compiled)


class FlopsProfiler:
    """``prof = FlopsProfiler(model); prof.start_profile()`` then run the
    engine / call ``profile_fn``; read totals.

    For jitted work the unit of accounting is a compiled program, not a
    module hook, so ``profile_fn(fn, *args)`` is the native entry; the
    engine drives it on the train-step program when
    ``flops_profiler.enabled`` (engine.py parity with reference
    engine.py:2240-2252).
    """

    def __init__(self, model=None, config=None):
        self.model = model
        self.config = config
        self.reset()

    def reset(self):
        self._flops = 0.0
        self._bytes = 0.0
        self._params = 0
        self._duration = 0.0
        self._per_program = {}
        self._started = False

    # -- reference API surface --
    def start_profile(self, **kw):
        self.reset()
        self._started = True

    def stop_profile(self):
        self._started = False

    def end_profile(self):
        self.reset()

    def record(self, name, flops, nbytes=0.0, duration=0.0):
        """Account an externally-measured program (e.g. the engine's
        already-built train step)."""
        self._per_program[name] = {"flops": float(flops),
                                   "bytes": float(nbytes),
                                   "duration": float(duration)}
        self._flops += float(flops)
        self._bytes += float(nbytes)
        self._duration += float(duration)

    def profile_fn(self, fn, *args, name="program", static_argnums=(),
                   measure_time=True):
        """Account one jitted callable on example args. Returns its flops."""
        compiled, costs = _cost_analysis(fn, *args,
                                         static_argnums=static_argnums)
        flops = float(costs.get("flops", 0.0))
        nbytes = float(costs.get("bytes accessed", 0.0))
        dur = 0.0
        if measure_time:
            out = compiled(*args)
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            out = compiled(*args)
            jax.block_until_ready(out)
            dur = time.perf_counter() - t0
        self._per_program[name] = {"flops": flops, "bytes": nbytes,
                                   "duration": dur}
        self._flops += flops
        self._bytes += nbytes
        self._duration += dur
        return flops

    def set_params(self, params):
        self._params = _param_count(params)

    def get_total_flops(self, as_string=False):
        return _fmt(self._flops, "FLOPs") if as_string else self._flops

    def get_total_macs(self, as_string=False):
        macs = self._flops / 2  # XLA counts mul+add
        return _fmt(macs, "MACs") if as_string else macs

    def get_total_params(self, as_string=False):
        return (_fmt(self._params, "params") if as_string
                else self._params)

    def get_total_duration(self, as_string=False):
        return (f"{self._duration * 1e3:.2f} ms" if as_string
                else self._duration)

    def get_flops_per_sec(self):
        return self._flops / self._duration if self._duration else 0.0

    def print_model_profile(self, file=None):
        import sys
        f = file or sys.stdout
        print("-" * 60, file=f)
        print("DeepSpeed-TPU flops profiler", file=f)
        print(f"params:   {self.get_total_params(True)}", file=f)
        print(f"flops:    {self.get_total_flops(True)}", file=f)
        print(f"macs:     {self.get_total_macs(True)}", file=f)
        print(f"duration: {self.get_total_duration(True)}", file=f)
        if self._duration:
            print(f"flops/s:  {_fmt(self.get_flops_per_sec(), 'FLOPS')}",
                  file=f)
        for name, d in self._per_program.items():
            line = f"  {name:24s} {_fmt(d['flops'], 'FLOPs'):>14s}"
            if d["duration"]:
                line += f"  {d['duration'] * 1e3:8.2f} ms"
            print(line, file=f)
        print("-" * 60, file=f)


def get_model_profile(model, batch, rng=None, train=False,
                      print_profile=False):
    """(flops, macs, params) for one forward of ``model`` on ``batch``
    (reference get_model_profile: builds the model, runs with shape args).
    """
    if rng is None:
        rng = jax.random.key(0)
    params = model.init(rng)
    prof = FlopsProfiler(model)
    prof.set_params(params)

    def fwd(p, b):
        return model.loss(p, b, train=train) if train else \
            model.apply(p, b["input_ids"])

    prof.profile_fn(fwd, params, batch, name="forward", measure_time=False)
    if print_profile:
        prof.print_model_profile()
    return prof.get_total_flops(), prof.get_total_macs(), \
        prof.get_total_params()


def _fmt(x, unit):
    for scale, pre in [(1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")]:
        if abs(x) >= scale:
            return f"{x / scale:.2f} {pre}{unit}"
    return f"{x:.0f} {unit}"


# ----------------------------------------------------- per-module breakdown
def _dot_flops(eqn):
    """2 * batch * M * N * K for a dot_general eqn."""
    import numpy as np
    lhs, rhs = (v.aval for v in eqn.invars[:2])
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    batch = int(np.prod([lhs.shape[d] for d in lb], dtype=np.int64)) \
        if lb else 1
    k = int(np.prod([lhs.shape[d] for d in lc], dtype=np.int64)) if lc else 1
    m = int(np.prod([lhs.shape[d] for d in range(lhs.ndim)
                     if d not in tuple(lc) + tuple(lb)], dtype=np.int64))
    n = int(np.prod([rhs.shape[d] for d in range(rhs.ndim)
                     if d not in tuple(rc) + tuple(rb)], dtype=np.int64))
    return 2.0 * batch * m * n * k


_ELEMENTWISE = {
    "add", "sub", "mul", "div", "max", "min", "exp", "log", "tanh",
    "logistic", "rsqrt", "sqrt", "erf", "pow", "integer_pow", "neg",
    "select_n", "convert_element_type", "and", "or", "xor", "sign",
    "abs", "floor", "ceil", "round",
}


def _eqn_flops(eqn):
    import numpy as np
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_flops(eqn)
    if name == "ragged_dot":
        # grouped GEMM: rows x (per-group N*K summed = total expert mats)
        lhs, rhs = (v.aval for v in eqn.invars[:2])
        return 2.0 * lhs.shape[0] * rhs.shape[-2] * rhs.shape[-1]
    if name in _ELEMENTWISE or name.startswith("reduce_"):
        out = eqn.outvars[0].aval
        return float(np.prod(out.shape, dtype=np.int64)) if out.shape \
            else 1.0
    return 0.0


def _module_of(eqn, code_root):
    """Attribute an eqn to the innermost model-code frame 'fn:line'."""
    src = eqn.source_info
    try:
        frames = list(src.traceback.frames)
    except Exception:  # noqa: BLE001
        return "<unknown>"
    for fr in frames:
        fname = getattr(fr, "file_name", "")
        if code_root in fname:
            short = fname.split("/")[-1].rsplit(".", 1)[0]
            return f"{short}.{fr.function_name}"
    return "<outside-model>"


def per_module_flops(fn, *args, code_root="models"):
    """Walk the jaxpr of ``fn(*args)`` and attribute flops to the model
    source function that emitted each op (reference
    print_model_profile's per-module rows, realized as a jaxpr walk:
    module hooks don't exist under jit, source provenance does).

    Returns {module_name: flops} including scan bodies scaled by trip
    count. Elementwise ops count 1 flop/element; dots count 2*M*N*K.
    """
    jaxpr = jax.make_jaxpr(fn)(*args)
    groups = {}

    def add(name, fl):
        groups[name] = groups.get(name, 0.0) + fl

    def walk(jaxpr, scale):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            sub = None
            sub_scale = scale
            if name == "scan":
                sub = eqn.params["jaxpr"].jaxpr
                sub_scale = scale * eqn.params["length"]
            elif name in ("pjit", "closed_call", "core_call",
                          "remat_call", "checkpoint", "custom_jvp_call",
                          "custom_vjp_call", "custom_vjp_call_jaxpr"):
                p = eqn.params
                j = (p.get("jaxpr") or p.get("call_jaxpr")
                     or p.get("fun_jaxpr"))
                if j is not None:
                    sub = getattr(j, "jaxpr", j)
            elif name == "while":
                sub = eqn.params["body_jaxpr"].jaxpr
                # trip count unknown statically; count one iteration
            elif name == "cond":
                for br in eqn.params["branches"]:
                    walk(br.jaxpr, scale)
                continue
            if sub is not None:
                walk(sub, sub_scale)
                continue
            fl = _eqn_flops(eqn)
            if fl:
                add(_module_of(eqn, code_root), fl * scale)
    walk(jaxpr.jaxpr, 1.0)
    return groups


def print_module_profile(fn, *args, code_root="models", file=None):
    """Reference ``print_model_profile`` analogue: per-module flops table
    sorted by share."""
    groups = per_module_flops(fn, *args, code_root=code_root)
    total = sum(groups.values()) or 1.0
    lines = [f"{'module':44s} {'GFLOPs':>12s} {'share':>7s}"]
    for name, fl in sorted(groups.items(), key=lambda kv: -kv[1]):
        lines.append(f"{name:44s} {fl / 1e9:12.3f} {fl / total:6.1%}")
    lines.append(f"{'TOTAL':44s} {total / 1e9:12.3f} {1:6.1%}")
    out = "\n".join(lines)
    print(out, file=file)
    return groups
