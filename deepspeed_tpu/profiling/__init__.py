from .flops_profiler import FlopsProfiler, get_model_profile, \
    compiled_costs
from .step_trace import StepDecomposition, decompose, decompose_dir, \
    find_trace_file
