from .flops_profiler import FlopsProfiler, get_model_profile, \
    compiled_costs
