"""Persistent winner cache for the measured kernel dispatch.

Counterpart of the reference autotuner's ``autotuning_results/`` json
artifacts, but at kernel granularity: one entry per
(device_kind, op, shape-bucket, dtype) holding the measured winner's
tunable parameters. The cache is consulted at TRACE time by
``ops/pallas/_common.dispatch`` — once a program is jitted the choice is
baked into the HLO and costs zero per-step host work.

Hard rule (the interpret-mode trap): entries record the ``device_kind``
they were measured on (``jax.devices()[0].device_kind``) and ``lookup``
REFUSES entries measured on a different chip — a cache produced in
Pallas interpreter mode on CPU must never steer a real TPU (interpreter
timings order candidates by host emulation cost, not MXU/VPU cost), and
a v5e cache must not steer a v4. A refused entry is a miss, so dispatch
falls back to the proven defaults instead of applying foreign timings.

File format (versioned, deterministically serialized so a round trip is
byte-identical — tested):

    {"version": 1,
     "entries": {
       "<device_kind>|<op>|<bucket>|<dtype>": {
         "device_kind": ..., "op": ..., "bucket": ..., "dtype": ...,
         "params": {...}, "measured_ms": ..., "default_ms": ...,
         "candidates": N}}}

Writes are atomic (tmp + fsync + rename, the serialization.py rule): a
crash mid-save never corrupts the previous cache generation.
"""

import json
import os

from ..utils.logging import logger

CACHE_VERSION = 1

# env overrides consulted by default_cache_path(); the config block's
# cache_path wins over both
CACHE_PATH_ENV = "DSTPU_AUTOTUNE_CACHE"
_DEFAULT_DIRNAME = os.path.join("~", ".cache", "deepspeed_tpu")
_DEFAULT_BASENAME = "kernel_autotune.json"


def default_cache_path():
    """Resolved default cache file location: $DSTPU_AUTOTUNE_CACHE if
    set, else ~/.cache/deepspeed_tpu/kernel_autotune.json."""
    env = os.environ.get(CACHE_PATH_ENV)
    if env:
        return env
    return os.path.join(os.path.expanduser(_DEFAULT_DIRNAME),
                        _DEFAULT_BASENAME)


def entry_key(device_kind, op, bucket, dtype):
    return f"{device_kind}|{op}|{bucket}|{dtype}"


class KernelCache:
    """In-memory view of one cache file; load/save are explicit."""

    def __init__(self, entries=None):
        self.entries = dict(entries or {})

    # ------------------------------------------------------------- io
    @classmethod
    def load(cls, path):
        """Read ``path``; a missing/corrupt/foreign-version file is an
        EMPTY cache (every lookup then falls back to defaults) — a bad
        cache must degrade, never crash a training run."""
        try:
            with open(path) as f:
                raw = json.load(f)
        except FileNotFoundError:
            return cls()
        except (OSError, ValueError) as e:
            logger.warning(f"autotune cache {path!r} unreadable "
                           f"({type(e).__name__}: {e}); ignoring it")
            return cls()
        if not isinstance(raw, dict) or raw.get("version") != CACHE_VERSION:
            logger.warning(
                f"autotune cache {path!r} has version "
                f"{raw.get('version') if isinstance(raw, dict) else '?'} "
                f"(want {CACHE_VERSION}); ignoring it")
            return cls()
        entries = raw.get("entries", {})
        if not isinstance(entries, dict):
            return cls()
        return cls(entries)

    def to_json(self):
        """Deterministic serialization: sorted keys, fixed indent — the
        same entries always produce the same bytes (round-trip test)."""
        return json.dumps({"version": CACHE_VERSION,
                           "entries": self.entries},
                          indent=2, sort_keys=True) + "\n"

    def save(self, path):
        """Atomic write: tmp + fsync + rename (a crash mid-save leaves
        the previous cache intact — the serialization.py shard rule)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    # ------------------------------------------------------- accessors
    def lookup(self, device_kind, op, bucket, dtype):
        """Winner params for the key, or None. Entries whose recorded
        device_kind disagrees with the requested one are REFUSED (an
        interpret-mode/CPU cache applied on device would steer kernels
        by emulation timings) — the caller sees a plain miss."""
        e = self.entries.get(entry_key(device_kind, op, bucket, dtype))
        if e is None:
            return None
        if e.get("device_kind") != device_kind:
            logger.warning(
                f"autotune cache: refusing entry for op={op!r} "
                f"bucket={bucket!r}: measured on "
                f"{e.get('device_kind')!r}, running on {device_kind!r}")
            return None
        params = e.get("params")
        return dict(params) if isinstance(params, dict) else None

    def put(self, device_kind, op, bucket, dtype, params,
            measured_ms=None, default_ms=None, candidates=None):
        def fin(v):
            # non-finite floats would serialize as the non-standard
            # 'Infinity'/'NaN' tokens and break every strict-JSON
            # consumer of the cache/bench artifacts
            import math
            return v if v is None or (isinstance(v, (int, float))
                                      and math.isfinite(v)) else None

        self.entries[entry_key(device_kind, op, bucket, dtype)] = {
            "device_kind": device_kind, "op": op, "bucket": bucket,
            "dtype": dtype, "params": dict(params),
            "measured_ms": fin(measured_ms), "default_ms": fin(default_ms),
            "candidates": candidates,
        }

    def for_device(self, device_kind):
        """All entries measured on ``device_kind`` (the bench artifact's
        tuned table)."""
        return {k: v for k, v in self.entries.items()
                if v.get("device_kind") == device_kind}

    def __len__(self):
        return len(self.entries)


def seed_entries(rows, path=None):
    """Merge externally measured rows into the winner cache file —
    the ``comm_bench --seed-cache`` ingest path. Each row is a dict in
    cache-entry shape (device_kind/op/bucket/dtype/params [+
    measured_ms]); malformed rows are skipped, the write is the same
    atomic tmp+rename as save(). Returns the number merged."""
    path = path or default_cache_path()
    cache = KernelCache.load(path)
    n = 0
    for r in rows or []:
        if not isinstance(r, dict):
            continue
        try:
            cache.put(str(r["device_kind"]), str(r["op"]),
                      str(r["bucket"]), str(r.get("dtype", "float32")),
                      dict(r.get("params") or {}),
                      measured_ms=r.get("measured_ms"),
                      default_ms=r.get("default_ms"),
                      candidates=r.get("candidates"))
            n += 1
        except (KeyError, TypeError, ValueError):
            continue
    cache.save(path)
    return n
