from .autotuner import Autotuner, ModelInfo
from .scheduler import Node, Reservation, ResourceManager, SubprocessRunner
from .tuner import CostModel, GridSearchTuner, ModelBasedTuner, RandomTuner
from . import kernel_dispatch
from .kernel_cache import KernelCache, default_cache_path
