from .autotuner import Autotuner, ModelInfo
from .scheduler import Node, Reservation, ResourceManager, SubprocessRunner
from .tuner import CostModel, GridSearchTuner, ModelBasedTuner, RandomTuner
from . import kernel_dispatch
from .kernel_cache import KernelCache, default_cache_path
# NOTE: the module is exported, not the bare reconcile() function —
# `autotuning.reconcile` must stay addressable as a module
from . import reconcile
from .reconcile import DriftReport, reconcile_trace, seed_rows, \
    seed_cache
