from .autotuner import Autotuner, ModelInfo
from .tuner import GridSearchTuner, RandomTuner
