from .autotuner import Autotuner, ModelInfo
from .scheduler import Node, Reservation, ResourceManager, SubprocessRunner
from .tuner import CostModel, GridSearchTuner, ModelBasedTuner, RandomTuner
