"""Experiment scheduler — run autotuning trials across a resource pool.

Counterpart of reference ``autotuning/scheduler.py`` (ResourceManager +
Node/Reservation): the reference reserves GPU slots on hosts and launches
each experiment as its own ``deepspeed`` job, polling for completion and
parsing metrics from the experiment directory. TPU translation: a slot is
a host's worth of chips (JAX is one process per host), an experiment runs
as a subprocess with the reservation exported through env, and results
come back as one JSON line on stdout (the bench.py convention) or via an
injectable runner — which is also what the tests fake.

Capacity > 1 runs independent trials concurrently (grid/random search);
the model-based tuner proposes per-round batches sized to the free
capacity, records them, and proposes again — the reference's
"experiment queue + scheduler loop" shape.
"""

import json
import os
import shlex
import subprocess
import sys
import threading

from ..utils.logging import logger
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner


class Node:
    """reference scheduler.py Node: a host with ``max_slots`` chip slots.
    Reserve/restore are called from the manager thread AND worker
    threads (Reservation.release), so the node carries its own lock."""

    def __init__(self, host, max_slots):
        self.host = host
        self.max_slots = int(max_slots)
        self.free = list(range(self.max_slots))
        self._lock = threading.Lock()

    def reserve(self, n):
        with self._lock:
            if len(self.free) < n:
                return None
            slots, self.free = self.free[:n], self.free[n:]
            return slots

    def restore(self, slots):
        with self._lock:
            self.free.extend(slots)


class Reservation:
    def __init__(self, node, slots):
        self.node = node
        self.slots = slots

    def release(self):
        self.node.restore(self.slots)

    def env(self):
        """Env the launched experiment sees (which host/chips it owns)."""
        return {"DSTPU_EXP_HOST": self.node.host,
                "DSTPU_EXP_SLOTS": ",".join(map(str, self.slots))}


class SubprocessRunner:
    """Launch one experiment as ``python script --exp '<json>'`` on the
    reserved host (ssh for remote hosts, direct for local), parse the
    LAST JSON line of stdout as the result (the bench.py convention;
    reference scheduler parses the experiment dir instead)."""

    def __init__(self, script, timeout_s=1800, python=None):
        self.script = script
        self.timeout_s = timeout_s
        self.python = python or sys.executable

    def __call__(self, exp, reservation):
        argv = [self.python, self.script, "--exp", json.dumps(exp)]
        env = dict(os.environ, **reservation.env())
        if reservation.node.host not in ("localhost", "127.0.0.1"):
            exports = " ".join(f"{k}={shlex.quote(v)}"
                               for k, v in reservation.env().items())
            argv = ["ssh", reservation.node.host,
                    f"cd {shlex.quote(os.getcwd())} && {exports} "
                    + " ".join(shlex.quote(a) for a in argv)]
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=self.timeout_s, env=env)
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"error": f"no JSON result (rc={proc.returncode}): "
                         f"{proc.stderr[-300:]}"}


class ResourceManager:
    """Schedule experiments over the node pool.

    ``run(experiments, run_fn, slots_per_exp=...)`` executes every
    experiment, up to pool capacity concurrently, returning results in
    submission order. ``run_model_based(space, run_fn, metric, ...)``
    drives a :class:`ModelBasedTuner` in rounds: propose as many trials
    as there is capacity, run them concurrently, record, repeat — the
    cost model stays sequential-in-rounds while the pool stays busy.
    """

    def __init__(self, nodes):
        self.nodes = [n if isinstance(n, Node) else Node(*n)
                      for n in nodes]
        self._lock = threading.Lock()

    @property
    def capacity(self):
        return sum(n.max_slots for n in self.nodes)

    def _reserve(self, n_slots):
        with self._lock:
            for node in self.nodes:
                slots = node.reserve(n_slots)
                if slots is not None:
                    return Reservation(node, slots)
        return None

    def _run_batch(self, batch, run_fn, slots_per_exp):
        """Run up to capacity concurrently; block until all done."""
        if slots_per_exp > max(n.max_slots for n in self.nodes):
            raise ValueError(
                f"slots_per_exp={slots_per_exp} exceeds every node's "
                f"capacity (max "
                f"{max(n.max_slots for n in self.nodes)}) — no "
                "reservation can ever succeed")
        results = [None] * len(batch)
        sem = threading.Semaphore(0)
        pending = list(enumerate(batch))
        running = []

        def work(i, exp, res):
            try:
                results[i] = run_fn(exp, res)
            except Exception as e:  # noqa: BLE001 - trial failure is data
                results[i] = {"error": f"{type(e).__name__}: {e}"}
            finally:
                res.release()
                sem.release()

        launched = 0
        while pending or launched:
            while pending:
                res = self._reserve(slots_per_exp)
                if res is None:
                    break
                i, exp = pending.pop(0)
                t = threading.Thread(target=work, args=(i, exp, res),
                                     daemon=True)
                t.start()
                running.append(t)
                launched += 1
            if launched:
                sem.acquire()
                launched -= 1
        for t in running:
            t.join()
        return results

    def run(self, experiments, run_fn, slots_per_exp=1):
        experiments = list(experiments)
        logger.info(f"scheduler: {len(experiments)} experiments over "
                    f"capacity {self.capacity}")
        return self._run_batch(experiments, run_fn, slots_per_exp)

    def run_model_based(self, space, run_fn, metric="samples_per_sec",
                        max_trials=None, slots_per_exp=1, **tuner_kw):
        """Model-guided search over the pool. Returns (best_exp,
        best_result, all (exp, result) pairs)."""
        tuner = ModelBasedTuner(space, max_trials=max_trials, **tuner_kw)
        per_round = max(1, self.capacity // slots_per_exp)
        all_results = []
        it = iter(tuner)
        done = False
        while not done:
            batch = []
            for _ in range(per_round):
                try:
                    batch.append(next(it))
                except StopIteration:
                    done = True
                    break
            if not batch:
                break
            results = self._run_batch(batch, run_fn, slots_per_exp)
            for exp, res in zip(batch, results):
                failed = bool(res.get("error")) or metric not in res
                if not failed:
                    tuner.record(exp, float(res[metric]))
                # failed trials simply stay unrecorded: the tuner keeps
                # yielded-but-unrecorded configs in its pending set, so
                # they are excluded from re-proposal, from best(), and —
                # critically — from the cost-model fit (an -inf
                # observation would NaN the ridge solve)
                all_results.append((exp, res))
        if not tuner.observed:
            raise RuntimeError(
                "model-based tuning: every trial failed; see results")
        best_exp, _ = tuner.best()
        best_res = next(r for e, r in all_results if e == best_exp)
        return best_exp, best_res, all_results
