"""Autotune dispatch state: mode + winner cache, consulted at trace time.

``ops/pallas/_common.dispatch`` routes here. The state is process-global
(kernel choice must be consistent across every trace in a process) and
is configured by the engine from the ``autotune`` config block, by
``configure()`` directly, or by env:

  DSTPU_AUTOTUNE        off | cache_only | on_first_use | search
                        (default cache_only: a shipped cache activates,
                        no cache file means the r05 defaults — zero
                        behavior change)
  DSTPU_AUTOTUNE_CACHE  cache file path (default
                        ~/.cache/deepspeed_tpu/kernel_autotune.json)

Modes:
  off          never consult the cache; every "auto" tunable takes its
               hand-set default
  cache_only   use cached winners, NEVER search (production: a cold key
               silently falls back to defaults)
  on_first_use cache hit wins; a miss triggers a measured search for
               that (op, shape-bucket, dtype) right then — once per
               process — and persists the winner
  search       re-measure every key once per process even if cached
               (cache pre-warming / re-validation after a toolchain
               bump), persisting the new winners

Resolution is memoized per process, so after the first trace each
dispatch is a dict lookup; the compiled program carries only the chosen
constants (zero per-step host work).
"""

import os

from ..utils.logging import logger
from .kernel_cache import KernelCache, default_cache_path

MODES = ("off", "cache_only", "on_first_use", "search")
MODE_ENV = "DSTPU_AUTOTUNE"

_STATE = {
    "mode": None,          # None -> env/default at use time
    "cache_path": None,    # None -> env/default at use time
    "cache": None,         # lazily loaded KernelCache
    "resolved": {},        # key -> winner params (or None for miss)
    "reports": {},         # key -> last search report
    "chain_lengths": (8, 24),
    "reps": 3,
    "searching": False,    # re-entrancy guard: a search never searches
}


def configure(mode=None, cache_path=None, chain_lengths=None, reps=None):
    """Set the process-global autotune state; None keeps env/default
    resolution for that field. Clears the memo and the loaded cache so
    new settings apply to subsequent traces."""
    if mode is not None:
        if mode not in MODES:
            raise ValueError(
                f"autotune mode must be one of {MODES}, got {mode!r}")
        _STATE["mode"] = mode
    if cache_path is not None:
        _STATE["cache_path"] = cache_path or None
    if chain_lengths is not None:
        k1, k2 = chain_lengths
        _STATE["chain_lengths"] = (int(k1), int(k2))
    if reps is not None:
        _STATE["reps"] = int(reps)
    _STATE["cache"] = None
    _STATE["resolved"] = {}


def configure_from_config(cfg):
    """Engine hook: apply the ``autotune`` config block
    (runtime/config.py AutotuneConfig) as the COMPLETE new state —
    empty-string fields revert to env/default resolution rather than
    keeping a previous engine's explicit setting (two engines in one
    process must not leak modes or cache paths into each other)."""
    if cfg.mode and cfg.mode not in MODES:
        raise ValueError(
            f"autotune mode must be one of {MODES}, got {cfg.mode!r}")
    _STATE["mode"] = cfg.mode or None
    _STATE["cache_path"] = cfg.cache_path or None
    _STATE["chain_lengths"] = tuple(int(k) for k in cfg.chain_lengths)
    _STATE["reps"] = int(cfg.reps)
    _STATE["cache"] = None
    _STATE["resolved"] = {}


def configure_serving(mode="", cache_path=""):
    """v2-engine hook: apply mode + cache path as the COMPLETE new
    state (empty string = revert that field to env/default resolution),
    preserving the search timing knobs — the serving counterpart of
    ``configure_from_config``, with the same complete-state contract:
    each engine's construction (and, for the v2 engine, each of its
    program traces) owns the process dispatch state; explicit modes or
    cache paths never leak between engines.

    No-op when the target state is already installed, so the v2
    engine's per-trace re-install keeps the resolution memo and the
    loaded cache — search mode still measures once per process, and
    the cache file is not re-read per trace."""
    if mode and mode not in MODES:
        raise ValueError(
            f"autotune mode must be one of {MODES}, got {mode!r}")
    new_mode, new_path = mode or None, cache_path or None
    if (_STATE["mode"] == new_mode
            and _STATE["cache_path"] == new_path):
        return
    _STATE["mode"] = new_mode
    _STATE["cache_path"] = new_path
    _STATE["cache"] = None
    _STATE["resolved"] = {}


def reset():
    """Back to pristine env-driven state (tests)."""
    _STATE.update(mode=None, cache_path=None, cache=None, resolved={},
                  reports={}, chain_lengths=(8, 24), reps=3,
                  searching=False)


def current_mode():
    if _STATE["mode"] is not None:
        return _STATE["mode"]
    env = os.environ.get(MODE_ENV, "cache_only")
    if env not in MODES:
        logger.warning(f"{MODE_ENV}={env!r} is not one of {MODES}; "
                       f"using cache_only")
        return "cache_only"
    return env


def cache_path():
    return _STATE["cache_path"] or default_cache_path()


def device_kind():
    """The chip the process computes on — part of every cache key, so
    interpret-mode (CPU) winners can never steer a real TPU."""
    import jax
    return jax.devices()[0].device_kind


def _cache():
    if _STATE["cache"] is None:
        _STATE["cache"] = KernelCache.load(cache_path())
    return _STATE["cache"]


def resolve(op, bucket, dtype, defaults):
    """Winner params for (device_kind, op, bucket, dtype) under the
    active mode, merged over ``defaults``; plain ``defaults`` on any
    miss/refusal. Only keys present in ``defaults`` are returned, so a
    caller tuning a subset of an op's parameters gets exactly its own
    knobs back."""
    mode = current_mode()
    defaults = dict(defaults)
    if mode == "off" or _STATE["searching"]:
        return defaults
    from .kernel_cache import entry_key
    dk = device_kind()
    key = entry_key(dk, op, bucket, str(dtype))
    if key in _STATE["resolved"]:
        winner = _STATE["resolved"][key]
    else:
        winner = None
        if mode != "search":
            winner = _cache().lookup(dk, op, bucket, str(dtype))
        if winner is None and mode in ("on_first_use", "search"):
            winner = _search_and_store(op, bucket, str(dtype), defaults,
                                       dk, key)
        _STATE["resolved"][key] = winner
    if winner is None:
        return defaults
    return {**defaults,
            **{k: v for k, v in winner.items() if k in defaults}}


def _search_and_store(op, bucket, dtype, defaults, dk, key):
    from . import kernel_autotuner, kernel_registry
    if op not in kernel_registry.REGISTRY:
        return None
    _STATE["searching"] = True
    try:
        winner, report = kernel_autotuner.search(
            op, bucket, dtype, defaults=defaults,
            chain_lengths=_STATE["chain_lengths"], reps=_STATE["reps"])
    except Exception as e:  # noqa: BLE001 — tuning must degrade, not crash
        logger.warning(f"autotune search failed for {key}: "
                       f"{type(e).__name__}: {e}; using defaults")
        return None
    finally:
        _STATE["searching"] = False
    _STATE["reports"][key] = report
    cache = _cache()
    cache.put(dk, op, bucket, dtype, winner,
              measured_ms=report["winner_ms"],
              default_ms=report["default_ms"],
              candidates=len(report["candidates"]))
    try:
        cache.save(cache_path())
    except OSError as e:
        logger.warning(f"autotune cache save to {cache_path()!r} "
                       f"failed: {e} (winner still applies in-process)")
    return winner


def table():
    """The tuned table for the CURRENT device kind — what bench.py
    embeds in the artifact so winners travel with the measurements.
    Reads the cache FILE fresh: searches from earlier engines in this
    process have persisted there, and the in-memory view may predate
    them."""
    return KernelCache.load(cache_path()).for_device(device_kind())
