"""Measured kernel-variant search (the dispatch layer's slow path).

Counterpart in spirit of the TVM/AlphaTensor measured-schedule-search
lineage (PAPERS.md) and of this package's engine-level ``Autotuner``:
instead of trusting hand-set defaults frozen at r05, each candidate in
``kernel_registry.REGISTRY`` is TIMED ON THE CHIP and the winner cached
per (device_kind, op, shape-bucket, dtype).

Timing method: the candidate step (fwd+bwd where the kernel is
differentiable) is chained data-dependently through ``lax.scan`` inside
ONE jit, at two chain lengths; the slope between them is the per-step
time. Rationale (round-2 dispatch-latency lesson, also
benchmarks/kernel_microbench.py): per-dispatch overhead is ~3.3 ms on
the axon tunnel — longer than most kernel steps — so anything not
measured inside a single dispatch measures the transport. The slope
additionally cancels jit constants and scan setup.

Every winner is parity-checked against the dense reference before it is
cached; a candidate that is fastest but numerically wrong is discarded
(next-fastest wins, ultimately the defaults).
"""

import math
import threading
import time

import jax
from jax import lax

from ..utils.logging import logger
from . import kernel_registry


def time_step(step_fn, args, chain_lengths=(8, 24), reps=3):
    """Per-step milliseconds of ``step_fn`` (pytree -> same-structure
    pytree) via the two-length scan-chain slope, best-of-``reps``."""
    k1, k2 = chain_lengths
    if not (0 < k1 < k2):
        raise ValueError(f"need 0 < k1 < k2, got {chain_lengths}")
    times = []
    for k in (k1, k2):
        def chain(a, k=k):
            def body(c, _):
                return step_fn(c), None
            out, _ = lax.scan(body, a, None, length=k)
            return out

        f = jax.jit(chain)
        jax.block_until_ready(f(args))          # compile + warm
        best = float("inf")
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            jax.block_until_ready(f(args))
            best = min(best, time.perf_counter() - t0)
        times.append(best)
    return 1e3 * (times[1] - times[0]) / (k2 - k1)


def search(op, bucket, dtype, defaults=None, chain_lengths=(8, 24),
           reps=3, validate=True):
    """Measure every candidate for (op, bucket, dtype); returns
    ``(winner_params, report)`` where report carries per-candidate
    timings. ``defaults`` (if given) is always candidate 0, so the
    fallback config is measured alongside and ``default_ms`` lands in
    the cache entry. Candidates that fail to build/compile/run are
    recorded with ``ms=inf`` (invalid configs are data, like the
    engine autotuner's OOM experiments); a winner failing the parity
    check is discarded for the next-fastest."""
    spec = kernel_registry.REGISTRY.get(op)
    if spec is None:
        raise KeyError(f"no tunable registry entry for op {op!r}")
    # Dispatch fires at TRACE time, so an on_first_use search usually
    # runs while an outer jit is mid-trace — under omnistaging every
    # jax op issued here on the SAME thread would be staged into that
    # trace (tracer args, no real timings, parity concretization
    # errors). jax trace state is thread-local, so a worker thread is a
    # clean eval context: the whole measurement runs there, eagerly and
    # jit-as-usual, on any jax version. (ensure_compile_time_eval is
    # NOT equivalent: it has no eval rule for pallas interpret-mode
    # kernels — 'program_id' — so it would silently disqualify every
    # Pallas candidate.)
    result, error = [], []

    def _run():
        try:
            result.append(_search_eager(op, bucket, dtype, spec,
                                        defaults, chain_lengths, reps,
                                        validate))
        except BaseException as e:  # noqa: BLE001 — re-raised below
            error.append(e)

    t = threading.Thread(target=_run, name=f"autotune-{op}", daemon=True)
    t.start()
    t.join()
    if error:
        raise error[0]
    return result[0]


def _search_eager(op, bucket, dtype, spec, defaults, chain_lengths,
                  reps, validate):
    b = kernel_registry.parse_bucket(bucket)
    # candidate 0 is always a COMPLETE defaults dict: callers may tune a
    # subset of an op's params (the layernorm wrapper passes only
    # block_rows), so their defaults merge over the registry's — the
    # baseline must build, or default_ms would be garbage
    base = spec["defaults"](b)
    cands = [dict(base, **{k: v for k, v in (defaults or {}).items()
                           if k in base})]
    cands.extend(spec["candidates"](b))
    cands = kernel_registry._dedup(cands)

    rows = []
    for params in cands:
        try:
            step_fn, args = spec["make_step"](b, dtype, params)
            ms = time_step(step_fn, args, chain_lengths, reps)
        except Exception as e:  # noqa: BLE001 — invalid tilings are data
            rows.append({"params": params, "ms": float("inf"),
                         "error": f"{type(e).__name__}: {e}"[:200]})
            continue
        # the two chain lengths are timed independently, so host noise
        # can drive the slope through zero on very cheap steps; clamp —
        # the sort below is stable, so among all-noise ties the
        # defaults (candidate 0) win rather than a measurement artifact
        rows.append({"params": params, "ms": max(ms, 0.0),
                     "error": None})

    ok = sorted((r for r in rows if r["error"] is None),
                key=lambda r: r["ms"])
    if not ok:
        raise RuntimeError(
            f"autotune search {op}/{bucket}/{dtype}: every candidate "
            f"failed: {[r['error'] for r in rows]}")
    winner = None
    for r in ok:
        if not validate:
            winner = r
            break
        try:
            spec["parity"](b, dtype, r["params"])
            winner = r
            break
        except Exception as e:  # noqa: BLE001
            r["error"] = f"parity: {type(e).__name__}: {e}"[:200]
            logger.warning(
                f"autotune {op}/{bucket}: discarding fastest candidate "
                f"{r['params']} — failed parity ({e})")
    if winner is None:
        raise RuntimeError(
            f"autotune search {op}/{bucket}/{dtype}: no candidate "
            f"passed the parity check")
    default_ms = rows[0]["ms"]
    if not math.isfinite(default_ms):
        default_ms = None       # keeps every artifact strict JSON
    report = {"op": op, "bucket": bucket, "dtype": dtype,
              "candidates": rows, "winner": winner["params"],
              "winner_ms": winner["ms"], "default_ms": default_ms}
    logger.info(
        f"autotune {op}/{bucket}/{dtype}: winner {winner['params']} "
        f"({winner['ms']:.3f} ms/step over {len(rows)} candidates)")
    return dict(winner["params"]), report
