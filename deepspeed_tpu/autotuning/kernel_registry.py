"""Tunable-parameter registry over the Pallas kernels.

One entry per autotunable op. Each entry owns, for a given shape
bucket (the strings built by ``ops/pallas/_common``):

  ``defaults(b)``     the r05-proven hand-set parameters — what dispatch
                      falls back to on a cache miss, and the baseline
                      candidate every search times first
  ``candidates(b)``   the measured search space (curated, not a full
                      grid: each candidate is a lever PERF_NOTES has
                      named, so a search run doubles as a lever A/B)
  ``make_step(b, dtype, params)``
                      -> (step_fn, args): a data-dependent train-shaped
                      step (forward AND backward where the kernel has
                      one) suitable for lax.scan chaining inside ONE
                      jit — the round-2 dispatch-latency lesson
                      (~3.3 ms/dispatch on the axon tunnel) means
                      per-candidate timing must amortize dispatch or it
                      measures the transport, not the kernel
  ``parity(b, dtype, params)``
                      numerics check of the candidate against the dense
                      reference (raises on mismatch) — run on every
                      winner before it is cached, and re-run by
                      ``benchmarks/kernel_parity.py`` for every cached
                      winner so a stale/wrong cache entry fails loudly

Buckets are exact in variant-gating dims (feature/head/vocab) and
power-of-two in data-volume dims; ``parse_bucket`` recovers the dict.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

# Single source of truth for each op's r05 KERNEL-level defaults is the
# kernel module itself (its TUNE_DEFAULTS — what dispatch falls back to
# on a cache miss); the registry re-exports and extends them with the
# MODEL-level knobs it alone owns (layernorm variant, mlp path), so
# flipping a proven default in ops/ flips the search baseline too.
from ..ops.pallas.flash_attention import RING_TUNE_DEFAULTS as \
    _RING_KERNEL_DEFAULTS
from ..ops.pallas.flash_attention import TUNE_DEFAULTS as FLASH_DEFAULTS
from ..ops.pallas.fused_ce import TUNE_DEFAULTS as CE_DEFAULTS
from ..ops.pallas.grouped_matmul import TUNE_DEFAULTS as \
    MOE_GROUPED_DEFAULTS
from ..ops.pallas.layernorm import TUNE_DEFAULTS as _LN_KERNEL_DEFAULTS

# small perturbation chaining step i's gradients into step i+1's inputs:
# keeps the scan body data-dependent (XLA cannot DCE or reorder the
# repetitions) without drifting activations out of a realistic range
_EPS = 1e-3

_TOL = dict(rtol=5e-2, atol=5e-2)


def parse_bucket(bucket):
    """'T1024,d64,c1,q1' -> {'T': 1024, 'd': 64, 'c': 1, 'q': 1}."""
    out = {}
    for part in bucket.split(","):
        i = 1
        while i < len(part) and not (part[i].isdigit() or part[i] == "-"):
            i += 1
        out[part[:i]] = int(part[i:])
    return out


def _close(a, b, what, tol=_TOL):
    np.testing.assert_allclose(np.asarray(a, np.float32),
                               np.asarray(b, np.float32),
                               err_msg=what, **tol)


def _dedup(cands):
    seen, out = set(), []
    for c in cands:
        key = tuple(sorted((k, repr(v)) for k, v in c.items()))
        if key not in seen:
            seen.add(key)
            out.append(dict(c))
    return out


# ------------------------------------------------------------------ flash


def _flash_defaults(b):
    return dict(FLASH_DEFAULTS)


def _flash_candidates(b):
    """The round-6 lever set: full-T blocks + block_h=1 (the measured
    r05 headline config), the 128/256 tilings, 512-wide backward
    blocks, and the q-major fused backward on qkv_t layouts."""
    T, qkv_t = b["T"], bool(b["q"])
    full = min(T, 1024)
    cands = [dict(FLASH_DEFAULTS)]
    cands.append(dict(FLASH_DEFAULTS, block_q=full, block_k=full,
                      block_h=1))
    cands.append(dict(FLASH_DEFAULTS, block_q=min(256, T),
                      block_k=min(256, T), block_h=1))
    if T > 512:
        cands.append(dict(FLASH_DEFAULTS, block_q=full, block_k=full,
                          block_h=1, block_q_bwd=512, block_k_bwd=512))
    if qkv_t:
        cands.append(dict(FLASH_DEFAULTS, block_q=full, block_k=full,
                          block_h=1, bwd_qmajor=True))
        if T > 512:
            cands.append(dict(FLASH_DEFAULTS, block_q=full, block_k=full,
                              block_h=1, block_q_bwd=512,
                              block_k_bwd=512, bwd_qmajor=True))
    return _dedup(cands)


def _flash_shapes(b):
    # representative (batch, heads): enough instances that block_h=2
    # divides, small enough that a search step stays cheap
    B, H = 2, 2
    return B, H, b["T"], b["d"]


def _flash_fn(b, params):
    from ..ops.pallas.flash_attention import flash_attention
    causal, qkv_t = bool(b["c"]), bool(b["q"])

    def f(q, k, v):
        return flash_attention(
            q, k, v, causal=causal, qkv_t=qkv_t,
            heads_major=not qkv_t,
            block_q=int(params["block_q"]),
            block_k=int(params["block_k"]),
            block_h=int(params["block_h"]),
            block_q_bwd=int(params["block_q_bwd"]) or None,
            block_k_bwd=int(params["block_k_bwd"]) or None,
            bwd_qmajor=bool(params["bwd_qmajor"]))
    return f


def _flash_args(b, dtype, rng):
    B, H, T, d = _flash_shapes(b)
    shape = (B, H, d, T) if b["q"] else (B, H, T, d)
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


def _flash_step(b, dtype, params):
    f = _flash_fn(b, params)

    def loss(q, k, v):
        return jnp.sum(f(q, k, v).astype(jnp.float32) ** 2)

    g = jax.grad(loss, (0, 1, 2))

    def step(carry):
        q, k, v = carry
        dq, dk, dv = g(q, k, v)
        return (q + _EPS * dq.astype(q.dtype),
                k + _EPS * dk.astype(k.dtype),
                v + _EPS * dv.astype(v.dtype))

    return step, _flash_args(b, dtype, jax.random.key(0))


def _flash_parity(b, dtype, params):
    from ..ops.pallas.flash_attention import attention_reference
    bp = dict(b, T=min(b["T"], 1024))    # cap parity cost; blocks clamp
    q, k, v = _flash_args(bp, dtype, jax.random.key(1))
    f = _flash_fn(bp, params)
    causal = bool(bp["c"])

    if bp["q"]:
        to_std = lambda x: x.transpose(0, 3, 1, 2)   # (B,H,d,T)->(B,T,H,d)
        from_std = lambda x: x.transpose(0, 2, 1, 3)  # ->(B,H,T,d)
    else:
        to_std = lambda x: x.swapaxes(1, 2)
        from_std = lambda x: x.swapaxes(1, 2)

    def ref(q, k, v):
        return from_std(attention_reference(
            to_std(q), to_std(k), to_std(v), causal=causal))

    do = jax.random.normal(jax.random.key(2),
                           jax.eval_shape(ref, q, k, v).shape, dtype)
    of, pull_f = jax.vjp(f, q, k, v)
    orf, pull_r = jax.vjp(ref, q, k, v)
    _close(of, orf, f"flash tuned fwd {params}")
    for a, bb, n in zip(pull_f(do), pull_r(do), "qkv"):
        _close(a, bb, f"flash tuned d{n} {params}")


# ------------------------------------------------------------------- mlp
MLP_DEFAULTS = {"mode": "xla", "fuse_dw": True,
                "block_t": 256, "block_o": 256, "block_k": 512}


def _mlp_defaults(b):
    return dict(MLP_DEFAULTS)


def _mlp_candidates(b):
    """Layout/epilogue choice for the MLP projections: XLA einsums
    (r05 default), the layout-owning down-projection kernel, both
    projections kernel-owned, and the fused-vs-XLA dw epilogue."""
    cands = [dict(MLP_DEFAULTS)]
    for mode in ("down", "both"):
        cands.append(dict(MLP_DEFAULTS, mode=mode))
        cands.append(dict(MLP_DEFAULTS, mode=mode, fuse_dw=False))
    cands.append(dict(MLP_DEFAULTS, mode="down", block_t=512,
                      block_o=512))
    return _dedup(cands)


def _mlp_fn(b, params):
    mode = params["mode"]

    def f(h, wu, wd):
        if mode == "xla":
            u = h @ wu
            out = jax.nn.gelu(u) @ wd
            return out
        from ..ops.pallas.mlp_matmul import mlp_matmul
        kw = dict(fuse_dw=bool(params["fuse_dw"]),
                  block_t=int(params["block_t"]),
                  block_o=int(params["block_o"]),
                  block_k=int(params["block_k"]))
        if mode == "both":
            u = mlp_matmul(h, wu, out_t=True, **kw)
        else:
            u = jnp.einsum("btd,df->bft", h, wu)
        up = jax.nn.gelu(u)
        return mlp_matmul(up, wd, x_t=True, **kw)
    return f


def _mlp_args(b, dtype, rng):
    T, D, F = min(b["T"], 512), b["D"], b["F"]
    ks = jax.random.split(rng, 3)
    h = jax.random.normal(ks[0], (2, T, D), dtype)
    wu = jax.random.normal(ks[1], (D, F), dtype) * (1 / math.sqrt(D))
    wd = jax.random.normal(ks[2], (F, D), dtype) * (1 / math.sqrt(F))
    return h, wu, wd


def _mlp_step(b, dtype, params):
    f = _mlp_fn(b, params)

    def loss(h, wu, wd):
        return jnp.sum(f(h, wu, wd).astype(jnp.float32) ** 2)

    g = jax.grad(loss, (0, 1, 2))

    def step(carry):
        h, wu, wd = carry
        dh, dwu, dwd = g(h, wu, wd)
        return (h + _EPS * dh.astype(h.dtype),
                wu + _EPS * dwu.astype(wu.dtype),
                wd + _EPS * dwd.astype(wd.dtype))

    return step, _mlp_args(b, dtype, jax.random.key(0))


def _mlp_parity(b, dtype, params):
    h, wu, wd = _mlp_args(b, dtype, jax.random.key(1))
    f = _mlp_fn(b, params)
    ref = _mlp_fn(b, dict(params, mode="xla"))
    _close(f(h, wu, wd), ref(h, wu, wd), f"mlp tuned fwd {params}")

    def lf(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2)

    ga = jax.grad(lf(f), (0, 1, 2))(h, wu, wd)
    gr = jax.grad(lf(ref), (0, 1, 2))(h, wu, wd)
    for a, bb, n in zip(ga, gr, ("dh", "dwu", "dwd")):
        _close(a, bb, f"mlp tuned {n} {params}",
               dict(rtol=5e-2, atol=5e-1 if n != "dh" else 5e-2))


# ------------------------------------------------------------ mlp int8
# W8A8 dense-MLP compute lever (quantize.int8_matmul="auto"): both
# projections through ops/pallas/quantization.int8_matmul — dynamic
# rowwise activation codes x channelwise weight codes, int32
# accumulate, straight-through fp grads. The {int8: 0} default IS the
# exact fp program (cold-cache contract); a measured winner flipping to
# 1 must first survive the parity gate below, so the cache can never
# hold an int8 winner whose numerics drifted past the gate.

MLP_INT8_DEFAULTS = {"int8": 0}

# quantization error tolerance for the W8A8 gate: symmetric 8-bit codes
# carry ~0.4% rms error per operand; through two projections + gelu the
# forward drifts ~1-2%, and the straight-through weight grads (up^T dy,
# where 'up' came through the quantized forward) reach O(60) magnitude
# in these step shapes with a few-per-mille tail at ~5% elementwise
# drift. The gate exists to catch BROKEN numerics (wrong scales, sign
# flips, garbage tiles — errors of order the activations themselves),
# not to bound the quantization envelope, so the grad term is wide.
_INT8_FWD_TOL = dict(rtol=1e-1, atol=1e-1)
_INT8_GRAD_TOL = dict(rtol=2e-1, atol=4.0)


def _mlp8_defaults(b):
    return dict(MLP_INT8_DEFAULTS)


def _mlp8_candidates(b):
    return _dedup([dict(MLP_INT8_DEFAULTS), {"int8": 1}])


def _mlp8_fn(params):
    use8 = bool(params["int8"])

    def f(h, wu, wd):
        if use8:
            from ..ops.pallas.quantization import int8_matmul
            u = int8_matmul(h, wu)
            return int8_matmul(jax.nn.gelu(u), wd)
        return jax.nn.gelu(h @ wu) @ wd
    return f


def _mlp8_step(b, dtype, params):
    f = _mlp8_fn(params)

    def loss(h, wu, wd):
        return jnp.sum(f(h, wu, wd).astype(jnp.float32) ** 2)

    g = jax.grad(loss, (0, 1, 2))

    def step(carry):
        h, wu, wd = carry
        dh, dwu, dwd = g(h, wu, wd)
        return (h + _EPS * dh.astype(h.dtype),
                wu + _EPS * dwu.astype(wu.dtype),
                wd + _EPS * dwd.astype(wd.dtype))

    return step, _mlp_args(b, dtype, jax.random.key(0))


def _mlp8_parity(b, dtype, params):
    h, wu, wd = _mlp_args(b, dtype, jax.random.key(1))
    f = _mlp8_fn(params)
    ref = _mlp8_fn(MLP_INT8_DEFAULTS)
    exact = not params["int8"]
    _close(f(h, wu, wd), ref(h, wu, wd), f"mlp_int8 fwd {params}",
           _TOL if exact else _INT8_FWD_TOL)

    def lf(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2)

    ga = jax.grad(lf(f), (0, 1, 2))(h, wu, wd)
    gr = jax.grad(lf(ref), (0, 1, 2))(h, wu, wd)
    for a, bb, n in zip(ga, gr, ("dh", "dwu", "dwd")):
        _close(a, bb, f"mlp_int8 {n} {params}",
               _TOL if exact else _INT8_GRAD_TOL)


# ------------------------------------------------------------- layernorm
# 'jnp' is the r05-proven model-level choice (fused_layernorm=False:
# XLA's fused form wins inside real programs on v5e)
LN_DEFAULTS = {"variant": "jnp", **_LN_KERNEL_DEFAULTS}


def _ln_defaults(b):
    return dict(LN_DEFAULTS)


def _ln_candidates(b):
    """jnp (XLA-fused, the measured r05 winner inside real programs) vs
    the fully fused Pallas kernel vs the hybrid jnp-fwd/Pallas-bwd, at
    the row tilings the row-blocked scaffold accepts."""
    cands = [dict(LN_DEFAULTS)]
    if b["D"] % 128 == 0:
        for br in (128, 256, 512):
            cands.append({"variant": "fused", "block_rows": br})
        cands.append({"variant": "bwd", "block_rows": 256})
    return _dedup(cands)


def _ln_fn(b, params):
    variant = params["variant"]

    def f(x, s, bias):
        if variant == "fused":
            from ..ops.pallas.layernorm import fused_layernorm
            return fused_layernorm(x, s, bias,
                                   block_rows=int(params["block_rows"]))
        if variant == "bwd":
            from ..ops.pallas.layernorm import layernorm_fused_bwd
            return layernorm_fused_bwd(
                x, s, bias, block_rows=int(params["block_rows"]))
        from ..ops.pallas.layernorm import _ln_jnp
        return _ln_jnp(x, s, bias, 1e-5)
    return f


def _ln_args(b, dtype, rng):
    R, D = min(b["R"], 4096), b["D"]
    ks = jax.random.split(rng, 3)
    x = jax.random.normal(ks[0], (R, D), dtype)
    s = 1 + 0.1 * jax.random.normal(ks[1], (D,), dtype)
    bias = 0.1 * jax.random.normal(ks[2], (D,), dtype)
    return x, s.astype(dtype), bias.astype(dtype)


def _ln_step(b, dtype, params):
    f = _ln_fn(b, params)

    def loss(x, s, bias):
        return jnp.sum(f(x, s, bias).astype(jnp.float32) ** 2)

    g = jax.grad(loss, (0, 1, 2))

    def step(carry):
        x, s, bias = carry
        dx, ds, db = g(x, s, bias)
        return (x + _EPS * dx.astype(x.dtype),
                s + _EPS * ds.astype(s.dtype),
                bias + _EPS * db.astype(bias.dtype))

    return step, _ln_args(b, dtype, jax.random.key(0))


def _ln_parity(b, dtype, params):
    from ..ops.pallas.layernorm import _ln_jnp
    x, s, bias = _ln_args(b, dtype, jax.random.key(1))
    f = _ln_fn(b, params)
    _close(f(x, s, bias), _ln_jnp(x, s, bias, 1e-5),
           f"layernorm tuned fwd {params}")

    def lf(fn):
        return lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2)

    ga = jax.grad(lf(f), (0, 1, 2))(x, s, bias)
    gr = jax.grad(lf(lambda x, s, b_: _ln_jnp(x, s, b_, 1e-5)),
                  (0, 1, 2))(x, s, bias)
    for a, bb, n in zip(ga, gr, ("dx", "dscale", "dbias")):
        _close(a, bb, f"layernorm tuned {n} {params}")


# ------------------------------------------------------------ ring_block
# The carry-state blockwise flash step (ring attention's per-chunk-pair
# kernel, ops/pallas/flash_attention.py flash_block_fwd). The bucket's T
# is the ring CHUNK length (T_global / (2 * ring) under zigzag), so block
# tiles resolve per chunk shape, not per global sequence.
RING_DEFAULTS = dict(_RING_KERNEL_DEFAULTS)


def _ring_defaults(b):
    return dict(RING_DEFAULTS)


def _ring_candidates(b):
    T = b["T"]
    full = min(T, 1024)
    cands = [dict(RING_DEFAULTS)]
    cands.append(dict(RING_DEFAULTS, block_q=full, block_k=full,
                      block_h=1))
    cands.append(dict(RING_DEFAULTS, block_q=min(256, T),
                      block_k=min(256, T), block_h=1))
    return _dedup(cands)


def _ring_args(b, dtype, rng):
    G, T, d = 4, b["T"], b["d"]
    ks = jax.random.split(rng, 4)
    q, k1, v1, k2 = (jax.random.normal(k, (G, T, d), dtype) for k in ks)
    return q, k1, v1, k2


def _ring_chain(b, params, q, k1, v1, k2):
    """Two chained chunk pairs (diagonal-causal then full — one ring
    step's worth of state carry) finalized to an output."""
    from ..ops.pallas.flash_attention import (flash_block_finalize,
                                              flash_block_fwd,
                                              flash_block_state)
    G, T, d = q.shape
    kw = dict(block_q=int(params["block_q"]),
              block_k=int(params["block_k"]),
              block_h=int(params["block_h"]))
    st = flash_block_state(G, T, d)
    st = flash_block_fwd(q, k1, v1, st, causal=True, **kw)
    st = flash_block_fwd(q, k2, v1, st, causal=False, **kw)
    o, _ = flash_block_finalize(st)
    return o


def _ring_step(b, dtype, params):
    def step(carry):
        q, k1, v1, k2 = carry
        o = _ring_chain(b, params, q, k1, v1, k2)
        # fwd-only op (the ring backward reuses the tuned flash bwd):
        # chain the output back into q for data dependence
        return (q + _EPS * o.astype(q.dtype), k1, v1, k2)

    return step, _ring_args(b, dtype, jax.random.key(0))


def _ring_parity(b, dtype, params):
    bp = dict(b, T=min(b["T"], 1024))
    q, k1, v1, k2 = _ring_args(bp, dtype, jax.random.key(1))
    o = _ring_chain(bp, params, q, k1, v1, k2)
    # dense reference over the concatenated kv: causal on chunk 1 (the
    # diagonal pair), fully visible chunk 2 — the carried-state algebra
    # must reproduce one softmax over both
    T = q.shape[1]
    k = jnp.concatenate([k1, k2], axis=1)
    v = jnp.concatenate([v1, v1], axis=1)
    s = jnp.einsum("gtd,gsd->gts", q, k,
                   preferred_element_type=jnp.float32)
    mask = jnp.concatenate(
        [jnp.tril(jnp.ones((T, T), jnp.bool_)),
         jnp.ones((T, T), jnp.bool_)], axis=1)
    s = jnp.where(mask[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("gts,gsd->gtd", p,
                     v.astype(jnp.float32))
    _close(o, ref, f"ring_block tuned chain {params}")


# -------------------------------------------------------------- fused_ce


def _ce_defaults(b):
    return dict(CE_DEFAULTS)


def _ce_candidates(b):
    cands = [dict(CE_DEFAULTS)]
    for bm, bn in ((256, 512), (512, 1024), (1024, 512), (256, 256)):
        cands.append({"block_m": bm, "block_n": bn})
    return _dedup(cands)


def _ce_args(b, dtype, rng):
    N, D, V = min(b["N"], 2048), b["D"], b["V"]
    ks = jax.random.split(rng, 3)
    h = jax.random.normal(ks[0], (N, D), dtype)
    w = jax.random.normal(ks[1], (V, D), dtype) * (1 / math.sqrt(D))
    t = jax.random.randint(ks[2], (N,), 0, V, jnp.int32)
    return h, w, t


def _ce_step(b, dtype, params):
    from ..ops.pallas.fused_ce import unembed_logits_stats

    def step(carry):
        h, w, t = carry
        # forward-only op (the grad-in-forward CE forms d_logits outside
        # the kernel): chain logz back into h for data dependence
        _, logz, gold = unembed_logits_stats(
            h, w, t, block_m=int(params["block_m"]),
            block_n=int(params["block_n"]))
        h = h + _EPS * (logz - gold)[:, None].astype(h.dtype)
        return (h, w, t)

    return step, _ce_args(b, dtype, jax.random.key(0))


def _ce_parity(b, dtype, params):
    from deepspeed_tpu.ops.pallas.fused_ce import unembed_logits_stats
    h, w, t = _ce_args(dict(b, N=min(b["N"], 512)), dtype,
                       jax.random.key(1))
    logits, logz, gold = unembed_logits_stats(
        h, w, t, block_m=int(params["block_m"]),
        block_n=int(params["block_n"]))
    ref = jnp.einsum("nd,vd->nv", h, w,
                     preferred_element_type=jnp.float32)
    _close(logits, ref.astype(logits.dtype), f"fused_ce logits {params}",
           dict(rtol=2e-2, atol=2e-2))
    _close(logz, jax.nn.logsumexp(ref, axis=-1),
           f"fused_ce logz {params}", dict(rtol=2e-2, atol=2e-2))
    _close(gold, jnp.take_along_axis(ref, t[:, None], axis=1)[:, 0],
           f"fused_ce gold {params}", dict(rtol=2e-2, atol=2e-2))


# ---------------------------------------------------- moe grouped gemm
# The dropless-MoE expert FFN (ops/pallas/grouped_matmul.py routed
# through moe/sharded_moe.py): one grouped product per projection with
# per-group tile maps vs the generic lax.ragged_dot. The bucket's S is
# the rows entering the grouped product on ONE shard (tokens * top-k,
# incl. the EP transport capacity), E the LOCAL expert count, M/F the
# model/FFN dims. The 'ragged' default IS the pre-kernel program, so a
# cold cache changes nothing (the established cold-cache contract).


def _moe_defaults(b):
    return dict(MOE_GROUPED_DEFAULTS)


def _moe_candidates(b):
    """kernel-vs-ragged_dot plus the grouped tile sweep: the ragged
    baseline (current behavior), the 128-cube kernel tiling, and wider
    row/column tiles for the large-token buckets."""
    cands = [dict(MOE_GROUPED_DEFAULTS)]
    for bm, bn, bk in ((128, 128, 128), (256, 256, 128),
                       (512, 256, 256)):
        cands.append({"backend": "kernel", "block_m": bm, "block_n": bn,
                      "block_k": bk})
    return _dedup(cands)


def _moe_args(b, dtype, rng):
    S, E = min(b["S"], 2048), b["E"]
    M, F = b["M"], b["F"]
    ks = jax.random.split(rng, 4)
    x = jax.random.normal(ks[0], (S, M), dtype) * 0.3
    w1 = jax.random.normal(ks[1], (E, M, F), dtype) * (1 / math.sqrt(M))
    w3 = jax.random.normal(ks[2], (E, M, F), dtype) * (1 / math.sqrt(M))
    w2 = jax.random.normal(ks[3], (E, F, M), dtype) * (1 / math.sqrt(F))
    # deterministic UNEVEN groups summing to S (the kernels only consult
    # group_sizes; a balanced split would hide boundary-tile handling)
    sizes = np.bincount(np.arange(S) * 7919 % E, minlength=E)
    return x, w1, w3, w2, jnp.asarray(sizes, jnp.int32)


def _moe_fn(params):
    from ..moe.sharded_moe import _grouped_swiglu_ffn

    def f(x, w1, w3, w2, group_sizes):
        return _grouped_swiglu_ffn(x, w1, w3, w2, group_sizes,
                                   dict(params))
    return f


def _moe_step(b, dtype, params):
    f = _moe_fn(params)
    x, w1, w3, w2, gs = _moe_args(b, dtype, jax.random.key(0))

    def loss(x, w1, w3, w2):
        return jnp.sum(f(x, w1, w3, w2, gs).astype(jnp.float32) ** 2)

    g = jax.grad(loss, (0, 1, 2, 3))

    def step(carry):
        x, w1, w3, w2 = carry
        dx, d1, d3, d2 = g(x, w1, w3, w2)
        return (x + _EPS * dx.astype(x.dtype),
                w1 + _EPS * d1.astype(w1.dtype),
                w3 + _EPS * d3.astype(w3.dtype),
                w2 + _EPS * d2.astype(w2.dtype))

    return step, (x, w1, w3, w2)


def _moe_parity(b, dtype, params):
    bp = dict(b, S=min(b["S"], 512))     # cap parity cost
    x, w1, w3, w2, gs = _moe_args(bp, dtype, jax.random.key(1))
    f = _moe_fn(params)
    ref = _moe_fn(dict(MOE_GROUPED_DEFAULTS))   # backend 'ragged'
    _close(f(x, w1, w3, w2, gs), ref(x, w1, w3, w2, gs),
           f"moe_grouped fwd {params}")

    def lf(fn):
        return lambda *a: jnp.sum(fn(*a, gs).astype(jnp.float32) ** 2)

    ga = jax.grad(lf(f), (0, 1, 2, 3))(x, w1, w3, w2)
    gr = jax.grad(lf(ref), (0, 1, 2, 3))(x, w1, w3, w2)
    for a, bb, n in zip(ga, gr, ("dx", "dw1", "dw3", "dw2")):
        _close(a, bb, f"moe_grouped {n} {params}",
               dict(rtol=5e-2, atol=5e-1 if n != "dx" else 5e-2))


# ------------------------------------------------- moe grouped int8
# W8A8 expert-FFN compute lever (quantize.moe_int8_matmul="auto"): the
# three grouped products through grouped_int8_matmul (int8 ragged_dot,
# per-expert channelwise weight codes repeated onto rows by
# group_sizes). {int8: 0} is the exact fp grouped-SwiGLU (cold-cache
# contract); winners flipping to 1 must survive the parity gate.

MOE_INT8_DEFAULTS = {"int8": 0}


def _moe8_defaults(b):
    return dict(MOE_INT8_DEFAULTS)


def _moe8_candidates(b):
    return _dedup([dict(MOE_INT8_DEFAULTS), {"int8": 1}])


def _moe8_fn(params):
    from ..moe.sharded_moe import _grouped_swiglu_ffn

    def f(x, w1, w3, w2, group_sizes):
        return _grouped_swiglu_ffn(
            x, w1, w3, w2, group_sizes,
            dict(MOE_GROUPED_DEFAULTS, int8=int(params["int8"])))
    return f


def _moe8_step(b, dtype, params):
    f = _moe8_fn(params)
    x, w1, w3, w2, gs = _moe_args(b, dtype, jax.random.key(0))

    def loss(x, w1, w3, w2):
        return jnp.sum(f(x, w1, w3, w2, gs).astype(jnp.float32) ** 2)

    g = jax.grad(loss, (0, 1, 2, 3))

    def step(carry):
        x, w1, w3, w2 = carry
        dx, d1, d3, d2 = g(x, w1, w3, w2)
        return (x + _EPS * dx.astype(x.dtype),
                w1 + _EPS * d1.astype(w1.dtype),
                w3 + _EPS * d3.astype(w3.dtype),
                w2 + _EPS * d2.astype(w2.dtype))

    return step, (x, w1, w3, w2)


def _moe8_parity(b, dtype, params):
    bp = dict(b, S=min(b["S"], 512))     # cap parity cost
    x, w1, w3, w2, gs = _moe_args(bp, dtype, jax.random.key(1))
    f = _moe8_fn(params)
    ref = _moe8_fn(MOE_INT8_DEFAULTS)
    exact = not params["int8"]
    _close(f(x, w1, w3, w2, gs), ref(x, w1, w3, w2, gs),
           f"moe_grouped_int8 fwd {params}",
           _TOL if exact else _INT8_FWD_TOL)

    def lf(fn):
        return lambda *a: jnp.sum(fn(*a, gs).astype(jnp.float32) ** 2)

    ga = jax.grad(lf(f), (0, 1, 2, 3))(x, w1, w3, w2)
    gr = jax.grad(lf(ref), (0, 1, 2, 3))(x, w1, w3, w2)
    for a, bb, n in zip(ga, gr, ("dx", "dw1", "dw3", "dw2")):
        _close(a, bb, f"moe_grouped_int8 {n} {params}",
               _TOL if exact else _INT8_GRAD_TOL)


# ------------------------------------------------- paged serving kernels
# The v2 engine's decode step and SplitFuse chunk program (ops/pallas/
# paged_attention.py). Buckets are the engine's decode shapes — (batch
# slots | chunk tokens, blocks-per-seq, block size, kv-heads, GQA
# group, head dim) — so each compiled per-bucket serving program
# resolves its own winner. Both are forward-only serving ops: steps
# chain the attention output back into q for data dependence.


def _pgd_defaults(b):
    from ..ops.pallas.paged_attention import PAGED_DECODE_DEFAULTS
    return dict(PAGED_DECODE_DEFAULTS)


def _pgd_candidates(b):
    """The serving lever: blocked-stream Pallas kernel vs the
    dense-gather program (the measured choice the engine's
    paged_kernel="auto" takes per decode-shape bucket)."""
    return _dedup([_pgd_defaults(b), {"mode": "dense"}])


def _pgd_args(b, dtype, rng):
    B, MB, BS = b["B"], b["MB"], b["BS"]
    KVH, G, d = b["kh"], b["g"], b["d"]
    NB = 2 * MB + 1
    ks = jax.random.split(rng, 5)
    q = jax.random.normal(ks[0], (B, KVH * G, d), dtype)
    kc = jax.random.normal(ks[1], (NB, KVH, BS, d), dtype)
    vc = jax.random.normal(ks[2], (NB, KVH, BS, d), dtype)
    tables = jax.random.randint(ks[3], (B, MB), 0, NB, jnp.int32)
    lengths = jax.random.randint(ks[4], (B,), 0, MB * BS, jnp.int32)
    return q, kc, vc, tables, lengths


def _pgd_fn(params):
    from ..ops.pallas.paged_attention import (
        paged_decode_attention, paged_decode_attention_reference)
    return paged_decode_attention_reference if params["mode"] == "dense" \
        else paged_decode_attention


def _pgd_step(b, dtype, params):
    f = _pgd_fn(params)

    def step(carry):
        q, kc, vc, tables, lengths = carry
        o = f(q, kc, vc, tables, lengths)
        return (q + _EPS * o.astype(q.dtype), kc, vc, tables, lengths)

    return step, _pgd_args(b, dtype, jax.random.key(0))


def _pgd_parity(b, dtype, params):
    from ..ops.pallas.paged_attention import (
        paged_decode_attention_reference)
    q, kc, vc, tables, lengths = _pgd_args(b, dtype, jax.random.key(1))
    got = _pgd_fn(params)(q, kc, vc, tables, lengths)
    ref = paged_decode_attention_reference(q, kc, vc, tables, lengths)
    _close(got, ref, f"paged_decode tuned {params}")


def _pgc_defaults(b):
    from ..ops.pallas.paged_attention import paged_chunk_tune_defaults
    return paged_chunk_tune_defaults()


def _pgc_candidates(b):
    """Kernel-vs-dense plus the chunk-token tile sweep. Sweep entries
    carry the CLAMPED tile (min(bc, C) — what the wrapper executes), so
    two nominal tiles that clamp to one program are never both timed
    and the cached winner records the tile that actually ran."""
    from ..ops.pallas.paged_attention import PAGED_CHUNK_BLOCK_C
    C = b["C"]
    d = _pgc_defaults(b)
    cands = [d, {"mode": "dense", "block_c": PAGED_CHUNK_BLOCK_C}]
    eff_seen = {min(int(d["block_c"]), C)} if d["mode"] == "kernel" \
        else set()
    for bc in (64, 128, 256):
        eff = min(bc, C)
        if eff not in eff_seen:
            eff_seen.add(eff)
            cands.append({"mode": "kernel", "block_c": eff})
    return _dedup(cands)


def _pgc_shapes(b):
    C, MB, BS = b["C"], b["MB"], b["BS"]
    # a mid-sequence chunk straddling block boundaries, partially real
    S = MB * BS
    start = min(max(S // 2, 1), max(S - C, 0))
    true_len = max(1, min(C - 1, S - start))
    return start, true_len


def _pgc_args(b, dtype, rng):
    C, MB, BS = b["C"], b["MB"], b["BS"]
    KVH, G, d = b["kh"], b["g"], b["d"]
    NB = 2 * MB + 1
    ks = jax.random.split(rng, 4)
    q = jax.random.normal(ks[0], (C, KVH * G, d), dtype)
    kc = jax.random.normal(ks[1], (NB, KVH, BS, d), dtype)
    vc = jax.random.normal(ks[2], (NB, KVH, BS, d), dtype)
    table = jax.random.randint(ks[3], (MB,), 0, NB, jnp.int32)
    return q, kc, vc, table


def _pgc_fn(b, params):
    from ..ops.pallas.paged_attention import (
        paged_chunk_attention, paged_chunk_attention_reference)
    start, true_len = _pgc_shapes(b)

    def f(q, kc, vc, table):
        if params["mode"] == "dense":
            return paged_chunk_attention_reference(
                q, kc, vc, table, jnp.int32(start), jnp.int32(true_len))
        return paged_chunk_attention(
            q, kc, vc, table, jnp.int32(start), jnp.int32(true_len),
            block_c=int(params["block_c"]))
    return f


def _pgc_step(b, dtype, params):
    f = _pgc_fn(b, params)

    def step(carry):
        q, kc, vc, table = carry
        o = f(q, kc, vc, table)
        return (q + _EPS * o.astype(q.dtype), kc, vc, table)

    return step, _pgc_args(b, dtype, jax.random.key(0))


def _pgc_parity(b, dtype, params):
    from ..ops.pallas.paged_attention import (
        paged_chunk_attention_reference)
    start, true_len = _pgc_shapes(b)
    q, kc, vc, table = _pgc_args(b, dtype, jax.random.key(1))
    got = _pgc_fn(b, params)(q, kc, vc, table)
    ref = paged_chunk_attention_reference(
        q, kc, vc, table, jnp.int32(start), jnp.int32(true_len))
    # pad q rows (>= true_len) attend partly-garbage positions by
    # design; their outputs are discarded by the chunk program
    _close(got[:true_len], ref[:true_len],
           f"paged_chunk tuned {params}")


# ------------------------------------------------- pipeline step shape
# The pipeline executors' two schedule-level knobs (runtime/pipe/):
# microbatch count M (more microbatches amortize the fill/drain bubble
# but shrink the per-tick batch below MXU efficiency — the knee is a
# MEASURED property of the chip) and the host-offload round trip. The
# step emulates the lock-step executor's cost structure on one device:
# a scan over the schedule's tick count, each tick a block fwd+bwd at
# the candidate's per-tick token count (plus the host staging round
# trip when the candidate offloads), so one chain step prices one
# global batch through the pipe and candidates are directly comparable.


def _pipe_micro_grid(S, B):
    """Candidate microbatch counts that the bucket's batch grid can
    actually run (B % m == 0 — GPT2Pipe's hard requirement; a cached
    winner the model cannot execute would turn 'auto' into a crash).
    Never empty: 1 divides everything."""
    grid = [m for m in (S, 2 * S, 4 * S) if m <= B and B % m == 0]
    return grid or [1]


def _pipe_defaults(b):
    grid = _pipe_micro_grid(b["S"], b["B"])
    # the 2S guidance when the grid admits it, else the largest valid
    return {"micro": 2 * b["S"] if 2 * b["S"] in grid else grid[-1],
            "offload": 0}


def _pipe_candidates(b):
    cands = [_pipe_defaults(b)]
    for m in _pipe_micro_grid(b["S"], b["B"]):
        cands.append({"micro": m, "offload": 0})
    from ..runtime.swap_tensor import host_stage
    if host_stage.available():
        for c in list(cands):
            cands.append(dict(c, offload=1))
    return _dedup(cands)


def _pipe_tokens(b, params):
    """Per-tick token count for the candidate, capped so a search step
    stays affordable; the cap formula is shared by every candidate so
    clamped comparisons stay fair."""
    micro = max(1, int(params["micro"]))
    return max(1, min((b["B"] * b["T"]) // micro, 1 << 13))


def _pipe_step(b, dtype, params):
    from ..runtime.swap_tensor import host_stage
    D = b["D"]
    F = 4 * D
    micro = max(1, int(params["micro"]))
    n_ticks = micro + 2 * (b["S"] - 1)
    rows = _pipe_tokens(b, params)
    offload = bool(params.get("offload"))
    ks = jax.random.split(jax.random.key(0), 3)
    x = jax.random.normal(ks[0], (rows, D), dtype) * 0.3
    w1 = jax.random.normal(ks[1], (D, F), dtype) / math.sqrt(D)
    w2 = jax.random.normal(ks[2], (F, D), dtype) / math.sqrt(F)

    def block(x, w1, w2):
        return x + jax.nn.gelu(x @ w1) @ w2

    def tick_loss(x, w1, w2):
        return jnp.sum(block(x, w1, w2).astype(jnp.float32) ** 2)

    g = jax.grad(tick_loss, (0, 1, 2))

    def step(carry):
        x, w1, w2 = carry

        def tick(c, _):
            x_, w1_, w2_ = c
            if offload:
                # the ring round trip: stage the tick's activation to
                # host and read it back (what the executor's offloaded
                # input ring costs per tick)
                x_ = host_stage.to_device(host_stage.to_host(x_))
            dx, d1, d2 = g(x_, w1_, w2_)
            return (x_ + _EPS * dx.astype(x_.dtype),
                    w1_ + _EPS * d1.astype(w1_.dtype),
                    w2_ + _EPS * d2.astype(w2_.dtype)), None

        (x, w1, w2), _ = jax.lax.scan(tick, (x, w1, w2), None,
                                      length=n_ticks)
        return (x, w1, w2)

    return step, (x, w1, w2)


def _pipe_parity(b, dtype, params):
    """The candidate changes scheduling shape, not math: the host
    round trip must be an identity, and the microbatch count must
    divide the bucket's batch grid."""
    from ..runtime.swap_tensor import host_stage
    micro = max(1, int(params["micro"]))
    if b["B"] % micro:
        raise AssertionError(
            f"pipe_microbatch candidate micro={micro} does not divide "
            f"batch bucket B={b['B']} — the model could never run it")
    x = jax.random.normal(jax.random.key(2), (64, b["D"]), dtype)
    if params.get("offload"):
        _close(host_stage.to_device(host_stage.to_host(x)), x,
               f"pipe_microbatch offload round trip {params}",
               dict(rtol=0, atol=0))


# ------------------------------------------------ prefix-cache policy
# The serving prefix cache (inference/v2/prefix_cache.py) is host-side
# scheduling policy, not a kernel — but whether it pays for itself, and
# where the min-match knee sits, is a MEASURED property of the chip:
# the lever trades skipped prefill compute against CoW copies and
# scheduling overhead. Like pipe_microbatch, the step emulates the cost
# structure on one device: a prefill-shaped forward over however much
# of a synthetic shared-prefix prompt the candidate's policy does NOT
# serve from cache (the bucket's traffic model: prompts span the pool's
# per-slot block share and half of each prompt is a shared template).
# The eviction watermark rides along untimed (it moves host-side
# latency, not device compute); wm=0 candidates are listed first so
# ties resolve to the hand-set on-demand policy.


def _pfx_defaults(b):
    from ..inference.v2.prefix_cache import PREFIX_CACHE_DEFAULTS
    return dict(PREFIX_CACHE_DEFAULTS)


def _pfx_prompt_blocks(b):
    """Synthetic per-request prompt blocks for the bucket: the pool's
    per-slot share (capped for step affordability)."""
    return max(2, min(b["NB"] // max(1, b["B"]), 64))


def _pfx_candidates(b):
    cands = [_pfx_defaults(b)]
    half = _pfx_prompt_blocks(b) // 2
    for wm in (0, 25):
        for mm in (1, 2, 4):
            if mm > max(1, half):
                continue          # a knee the traffic can never reach
            cands.append({"enabled": 1, "min_match_blocks": mm,
                          "evict_watermark_pct": wm})
    return _dedup(cands)


def _pfx_step(b, dtype, params):
    BS = b["BS"]
    pb = _pfx_prompt_blocks(b)
    shared = pb // 2
    skip = 0
    if int(params["enabled"]) and shared >= int(
            params["min_match_blocks"]):
        skip = shared
    rows = max(BS, (pb - skip) * BS)
    D = 128
    ks = jax.random.split(jax.random.key(0), 2)
    x = jax.random.normal(ks[0], (pb * BS, D), dtype) * 0.3
    w = jax.random.normal(ks[1], (D, D), dtype) / math.sqrt(D)

    def step(carry):
        x, w = carry
        # the recomputed suffix's prefill-shaped forward; the cached
        # prefix contributes nothing (that is the lever)
        y = jax.nn.gelu(x[:rows] @ w) @ w.T
        x = x.at[:rows].add(_EPS * y.astype(x.dtype))
        return (x, w)

    return step, (x, w)


def _pfx_parity(b, dtype, params):
    """The candidate changes admission policy, not math — check the
    policy invariants on a live tree: knob ranges, and the hard rule
    that a match never covers the whole prompt (the last token is
    always recomputed so the first sampled token comes from a real
    forward)."""
    mm = int(params["min_match_blocks"])
    if mm < 1:
        raise AssertionError(
            f"prefix_cache candidate min_match_blocks={mm} < 1")
    wm = int(params["evict_watermark_pct"])
    if not 0 <= wm <= 100:
        raise AssertionError(
            f"prefix_cache candidate evict_watermark_pct={wm} "
            f"outside [0, 100]")
    from ..inference.v2.blocked_allocator import BlockedAllocator
    from ..inference.v2.prefix_cache import PrefixCache
    BS = b["BS"]
    alloc = BlockedAllocator(4)
    pc = PrefixCache(alloc, BS, min_match_blocks=mm,
                     evict_watermark_pct=wm)
    toks = list(range(2 * BS))
    pc.release(toks, alloc.allocate(2))
    m = pc.match(toks)
    if m.cached_len > len(toks) - 1:
        raise AssertionError(
            f"prefix_cache match covered the whole prompt "
            f"(cached_len={m.cached_len}, T={len(toks)})")
    if mm == 1 and m.cached_len != 2 * BS - 1:
        raise AssertionError(
            f"prefix_cache full-prompt re-match expected BS-1 partial "
            f"tail (cached_len {2 * BS - 1}), got {m.cached_len}")


# -------------------------------------------- speculative-decode policy
# Draft-model speculation (inference/v2/speculative.py) is scheduling
# policy like prefix_cache, but its payoff is an acceptance-rate bet:
# one verify round costs a (k+1)-position target pass plus k draft
# decode steps, and commits 1 + (accepted) tokens. The step prices that
# trade on one device with the same matmul-rows emulation as
# prefix_cache: per-COMMITTED-token work for the candidate under a
# fixed synthetic acceptance model (per-token acceptance p=0.7 — the
# shared-template serving traffic the bench's high-acceptance workload
# models; r=0.125 draft/target cost ratio, the "narrow draft" sizing
# the README recommends). k too large for the traffic's acceptance
# decays committed tokens toward 1 + p/(1-p) while the verify span
# keeps growing — the cost term prices exactly that knee.


def _spec_defaults(b):
    from ..inference.v2.speculative import SPEC_DEFAULTS
    return dict(SPEC_DEFAULTS)


def _spec_candidates(b):
    cands = [_spec_defaults(b)]
    cands.append({"enabled": 0, "spec_k": 0, "floor_pct": 35})
    for k in (2, 4, 8):
        cands.append({"enabled": 1, "spec_k": k, "floor_pct": 35})
    return _dedup(cands)


def _spec_per_token_cost(params):
    """Target-pass-equivalents per committed token under the synthetic
    acceptance model: verify touches k+1 positions, the draft adds
    k*r, and the round commits the expected accepted prefix + bonus.
    Disabled = plain decode = 1.0 by construction."""
    k = int(params["spec_k"])
    if not int(params["enabled"]) or k < 1:
        return 1.0
    p, r = 0.7, 0.125
    committed = 1.0 + sum(p ** j for j in range(1, k + 1))
    return ((k + 1) + k * r) / committed


def _spec_step(b, dtype, params):
    rows = max(8, int(8 * b["B"] * _spec_per_token_cost(params)))
    D = 128
    ks = jax.random.split(jax.random.key(3), 2)
    x = jax.random.normal(ks[0], (rows, D), dtype) * 0.3
    w = jax.random.normal(ks[1], (D, D), dtype) / math.sqrt(D)

    def step(carry):
        x, w = carry
        y = jax.nn.gelu(x @ w) @ w.T
        x = x + _EPS * y.astype(x.dtype)
        return (x, w)

    return step, (x, w)


def _spec_parity(b, dtype, params):
    """The candidate changes scheduling, not math — check knob ranges
    and the acceptance rule's invariants (greedy acceptance is the
    byte-identity guardrail, so its host kernel is pinned here too)."""
    k = int(params["spec_k"])
    if int(params["enabled"]) and k < 1:
        raise AssertionError(
            f"spec_decode candidate enabled with spec_k={k} < 1")
    fl = int(params["floor_pct"])
    if not 0 <= fl <= 100:
        raise AssertionError(
            f"spec_decode candidate floor_pct={fl} outside [0, 100]")
    from ..inference.v2.speculative import longest_accept
    if longest_accept([5, 6, 7], [5, 6, 7, 8]) != 3:
        raise AssertionError("longest_accept full-accept broken")
    if longest_accept([5, 9, 7], [5, 6, 7, 8]) != 1:
        raise AssertionError(
            "longest_accept must stop at the FIRST mismatch")
    if longest_accept([9, 6, 7], [5, 6, 7, 8]) != 0:
        raise AssertionError("longest_accept first-token reject broken")


# ------------------------------------------------- op: kv_handoff
# Disaggregated prefill/decode serving (inference/v2/kv_transfer.py +
# router phase-aware dispatch). The knob is WHERE decode happens, not a
# kernel shape: colocated decode pays for the split-fuse prefill chunks
# interleaved into its batch (each long prefill steals decode
# iterations from every co-resident sequence), disaggregated decode
# pays the one-time KV-block stream over DCN instead. The cost model
# prices exactly that trade per committed decode token; the candidate
# emulation scales a fixed matmul step by it, same device-honest idiom
# as spec_decode.


def _kvh_defaults(b):
    # colocated is the cold default: the disabled program must stay
    # byte-identical to the pre-disaggregation engine
    return {"disaggregate": 0}


def _kvh_candidates(b):
    return [{"disaggregate": 0}, {"disaggregate": 1}]


def _kvh_per_token_cost(b, params):
    """Decode-iteration-equivalents per committed token. Colocated: a
    P-token prompt arriving mid-decode injects ceil(P/C) split-fuse
    chunk dispatches into the decode stream, amortized over G decode
    tokens per sequence. Disaggregated: the KV stream for the same
    prompt costs wire_bytes/DCN_rate, measured in decode-step units,
    amortized over the same G."""
    P, C, G = 1024.0, 256.0, 128.0           # prompt, chunk, gen tokens
    if not int(params["disaggregate"]):
        return 1.0 + (P / C) / G
    # KV wire bytes for the prompt: 2 (k+v) * layers * kv_heads *
    # head_dim * itemsize, padded to the block grid
    L, Hkv, hd, itemsize, BS = 24.0, 8.0, 128.0, 2.0, 64.0
    wire = 2.0 * L * Hkv * hd * itemsize * math.ceil(P / BS) * BS
    # DCN effective rate per decode-step-time: ~25 GB/s link, ~4 ms
    # decode step -> bytes movable in one decode iteration
    dcn_bytes_per_step = 25e9 * 0.004
    return 1.0 + (wire / dcn_bytes_per_step) / G


def _kvh_step(b, dtype, params):
    rows = max(8, int(8 * b["B"] * _kvh_per_token_cost(b, params)))
    D = 128
    ks = jax.random.split(jax.random.key(11), 2)
    x = jax.random.normal(ks[0], (rows, D), dtype) * 0.3
    w = jax.random.normal(ks[1], (D, D), dtype) / math.sqrt(D)

    def step(carry):
        x, w = carry
        y = jax.nn.gelu(x @ w) @ w.T
        x = x + _EPS * y.astype(x.dtype)
        return (x, w)

    return step, (x, w)


def _kvh_parity(b, dtype, params):
    """The candidate changes placement, not math — pin the knob range
    and the wire format's integrity contract: a handoff payload must
    round-trip state + KV bytes exactly, and a corrupted payload must
    be REJECTED, never imported (silent KV corruption would break the
    colocated-vs-disaggregated byte-identity guarantee)."""
    d = int(params["disaggregate"])
    if d not in (0, 1):
        raise AssertionError(
            f"kv_handoff candidate disaggregate={d} outside (0, 1)")
    from ..inference.v2 import kv_transfer
    state = {"uid": 7, "prompt": [1, 2, 3], "generated": [4],
             "cached_len": 0}
    tree = {"k": [np.arange(12, dtype=np.float32).reshape(3, 4)],
            "v": [np.ones((3, 4), np.float32) * 0.5]}
    payload = kv_transfer.pack_handoff(state, tree)
    got_state, flat = kv_transfer.unpack_handoff(payload)
    if got_state != state:
        raise AssertionError("kv_handoff state round-trip broken")
    for key, ref in (("k/0", tree["k"][0]), ("v/0", tree["v"][0])):
        if not np.array_equal(np.asarray(flat[key]), ref):
            raise AssertionError(
                f"kv_handoff KV leaf {key} not byte-identical")
    bad = bytearray(payload)
    bad[-1] ^= 0xFF
    try:
        kv_transfer.unpack_handoff(bytes(bad))
    except kv_transfer.KVWireError:
        pass
    else:
        raise AssertionError(
            "kv_handoff accepted a corrupted payload (CRC must reject)")


# ---------------------------------------------------------------- table
REGISTRY = {
    "flash_attention": {
        "defaults": _flash_defaults,
        "candidates": _flash_candidates,
        "make_step": _flash_step,
        "parity": _flash_parity,
    },
    "mlp_matmul": {
        "defaults": _mlp_defaults,
        "candidates": _mlp_candidates,
        "make_step": _mlp_step,
        "parity": _mlp_parity,
    },
    "layernorm": {
        "defaults": _ln_defaults,
        "candidates": _ln_candidates,
        "make_step": _ln_step,
        "parity": _ln_parity,
    },
    "fused_ce": {
        "defaults": _ce_defaults,
        "candidates": _ce_candidates,
        "make_step": _ce_step,
        "parity": _ce_parity,
    },
    "ring_block": {
        "defaults": _ring_defaults,
        "candidates": _ring_candidates,
        "make_step": _ring_step,
        "parity": _ring_parity,
    },
    "moe_grouped_mm": {
        "defaults": _moe_defaults,
        "candidates": _moe_candidates,
        "make_step": _moe_step,
        "parity": _moe_parity,
    },
    "mlp_int8": {
        "defaults": _mlp8_defaults,
        "candidates": _mlp8_candidates,
        "make_step": _mlp8_step,
        "parity": _mlp8_parity,
    },
    "moe_grouped_int8": {
        "defaults": _moe8_defaults,
        "candidates": _moe8_candidates,
        "make_step": _moe8_step,
        "parity": _moe8_parity,
    },
    "paged_decode": {
        "defaults": _pgd_defaults,
        "candidates": _pgd_candidates,
        "make_step": _pgd_step,
        "parity": _pgd_parity,
    },
    "paged_chunk": {
        "defaults": _pgc_defaults,
        "candidates": _pgc_candidates,
        "make_step": _pgc_step,
        "parity": _pgc_parity,
    },
    "pipe_microbatch": {
        "defaults": _pipe_defaults,
        "candidates": _pipe_candidates,
        "make_step": _pipe_step,
        "parity": _pipe_parity,
    },
    "prefix_cache": {
        "defaults": _pfx_defaults,
        "candidates": _pfx_candidates,
        "make_step": _pfx_step,
        "parity": _pfx_parity,
    },
    "spec_decode": {
        "defaults": _spec_defaults,
        "candidates": _spec_candidates,
        "make_step": _spec_step,
        "parity": _spec_parity,
    },
    "kv_handoff": {
        "defaults": _kvh_defaults,
        "candidates": _kvh_candidates,
        "make_step": _kvh_step,
        "parity": _kvh_parity,
    },
}

# collective/schedule ops (collective_ops.py — step builders run under a
# virtual or real mesh, winners keyed by topology signature folded into
# the bucket string) ride the SAME registry: dispatch, the measured
# search, the cache, and the kernel_parity harness treat them uniformly
from .collective_ops import COLLECTIVE_REGISTRY  # noqa: E402

REGISTRY.update(COLLECTIVE_REGISTRY)
