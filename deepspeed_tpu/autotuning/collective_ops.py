"""Collective-bearing tunable ops (the kernel registry generalized).

PR 4's registry tuned Pallas kernels; this module registers the
COLLECTIVE and SCHEDULE knobs that stayed hand-set through five PRs —
comm_overlap.bucket_mb, hierarchical grad staging, dcn_quantize, the
ring KV-rotation chunking, the prefetch scan unroll, and the hot-tier
replica count — as first-class registry ops with the exact same
contract (defaults / candidates / make_step / parity, see
kernel_registry's module docstring).

What changes vs the kernel ops:

  * step builders run under a MESH. ``_fit_mesh`` carves the bucket's
    topology signature out of the available device pool (a tier-1 CPU
    run gets the all-ones mesh, where every collective degrades to
    loopback/identity but the pattern still traces and times), so one
    registry serves both the virtual-mesh CI and a real pod search.
  * winners are cached per (device_kind, topology-signature,
    shape-bucket): the mesh shape is folded into the bucket STRING by
    the ``ops/pallas/_common`` collective bucket builders, so the cache
    file format, the CACHE_VERSION, and the device-kind refusal rule
    are all untouched.
  * ``comm_bench --json`` emits rows in the cache entry format for the
    staging/quantize ops (flat vs two-stage all_to_all, the int8 DCN
    leg), so one driver comm_bench run seeds these winners — and the
    planner's alpha-beta link calibration — without a separate search.

Every op's defaults reproduce the current hand-set config values, so a
cold cache keeps dispatch byte-identical to the pre-registry programs
(the PR 4/6/8 contract, asserted in tests/unit/test_planner.py).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from .kernel_registry import _EPS, _close, _dedup

# per-layer emulation width for the gradient-collective steps: enough
# rows that the reduce has a real payload, small enough that a search
# step stays affordable on one chip
_MAX_ELEMS = 1 << 16


def _fit_axis(n_avail, want):
    """Largest divisor of ``n_avail`` that is <= ``want`` — the axis
    size the device pool can actually carve."""
    w = min(max(1, int(want)), n_avail)
    while n_avail % w:
        w -= 1
    return w


def _fit_mesh(axes):
    """Mesh over the available devices approximating the bucket's
    topology signature: each requested axis is clamped to what the
    remaining pool factors (single-chip runs get all-ones — collectives
    become loopback but the program shape is the candidate's)."""
    devs = jax.devices()
    n = len(devs)
    sizes = []
    for _, want in axes:
        s = _fit_axis(n, want)
        sizes.append(s)
        n //= s
    arr = np.array(devs[: math.prod(sizes)]).reshape(sizes)
    return Mesh(arr, tuple(name for name, _ in axes))


def _grad_elems(b, per_axis=1):
    """Per-layer gradient payload (elements) for the L-MB bucket,
    capped, rounded to a multiple of ``per_axis`` (shard divisibility)."""
    n = max(256, min((int(b.get("L", 1)) << 20) // 4, _MAX_ELEMS))
    return -(-n // per_axis) * per_axis


# ------------------------------------------------- comm_overlap.bucket_mb
# The layer-granular reduce gate (runtime/zero/overlap.py): a scan layer
# whose grad bytes are below bucket_mb emits no in-scan collective (its
# reduction coalesces into the post-backward one). The candidate changes
# WHERE the reduce lands, never the math — a mean is linear, so the
# per-layer and the coalesced reductions agree exactly (the parity).

_CB_LAYERS = 4


def _cb_defaults(b):
    return {"bucket_mb": 32}


def _cb_candidates(b):
    return _dedup([_cb_defaults(b)] + [{"bucket_mb": m}
                                       for m in (0, 8, 32, 128)])


def _cb_reduce(b, dtype, params):
    mesh = _fit_mesh([("data", b.get("dp", 1))])
    W = mesh.shape["data"]
    n = _grad_elems(b, W)
    layer_bytes = (n // W) * jnp.dtype(dtype).itemsize
    bucket_bytes = int(params["bucket_mb"]) << 20
    in_scan = bucket_bytes == 0 or layer_bytes >= bucket_bytes

    def body(x):
        acc = jnp.zeros_like(x)
        g = x
        for _ in range(_CB_LAYERS):
            g = jnp.tanh(g * 1.0005)
            acc = acc + (lax.pmean(g, "data") if in_scan else g)
        return acc if in_scan else lax.pmean(acc, "data")

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"), check_vma=False)
    x0 = (jax.random.normal(jax.random.key(0), (n,), jnp.float32)
          * 0.3).astype(dtype)
    return fn, x0


def _cb_step(b, dtype, params):
    fn, x0 = _cb_reduce(b, dtype, params)

    def step(x):
        return x + _EPS * fn(x)

    return step, x0


def _cb_parity(b, dtype, params):
    got_fn, x0 = _cb_reduce(b, dtype, params)
    ref_fn, _ = _cb_reduce(b, dtype, {"bucket_mb": 32})
    _close(got_fn(x0), ref_fn(x0),
           f"comm_bucket tuned {params}", dict(rtol=1e-5, atol=1e-5))


# --------------------------------------------- comm_overlap.hierarchical
# Two-stage grad reduction (ZeRO++/MiCS): reduce-scatter over the inner
# ICI 'data' axis, cross-slice mean over 'data_outer' (DCN), gather
# back — vs the flat mean over both axes. Same value either way (the
# parity); which is faster is a measured property of the ICI/DCN links.


def _gs_defaults(b):
    # the CommOverlapConfig.resolve_hierarchical heuristic: stage iff
    # the mesh has a cross-slice axis — cold cache == today's 'auto'
    return {"hierarchical": int(b.get("do", 1) > 1)}


def _gs_candidates(b):
    return _dedup([_gs_defaults(b), {"hierarchical": 0},
                   {"hierarchical": 1}])


def _gs_reduce(b, dtype, params):
    mesh = _fit_mesh([("data_outer", b.get("do", 1)),
                      ("data", b.get("dp", 1))])
    W = mesh.shape["data"]
    Wo = mesh.shape["data_outer"]
    n = _grad_elems(b, W * Wo * W)      # scatter needs local % W == 0

    def body(x):
        if params["hierarchical"]:
            s = lax.psum_scatter(x, "data", scatter_dimension=0,
                                 tiled=True) / W
            s = lax.pmean(s, "data_outer")
            return lax.all_gather(s, "data", axis=0, tiled=True)
        return lax.pmean(x, ("data_outer", "data"))

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=P(("data_outer", "data")),
                       out_specs=P(("data_outer", "data")),
                       check_vma=False)
    x0 = (jax.random.normal(jax.random.key(1), (n,), jnp.float32)
          * 0.3).astype(dtype)
    return fn, x0


def _gs_step(b, dtype, params):
    fn, x0 = _gs_reduce(b, dtype, params)

    def step(x):
        return x + _EPS * fn(x)

    return step, x0


def _gs_parity(b, dtype, params):
    got_fn, x0 = _gs_reduce(b, dtype, params)
    ref_fn, _ = _gs_reduce(b, dtype, {"hierarchical": 0})
    _close(got_fn(x0), ref_fn(x0),
           f"grad_staging tuned {params}", dict(rtol=1e-5, atol=1e-5))


# ------------------------------------------------- moe.hierarchical_a2a
# The EP exchange: flat single-hop all_to_all over the combined
# (data_outer, expert) grid vs the staged ICI -> DCN pair
# (moe/sharded_moe.py). The step runs the full dispatch/combine round
# trip (exchange, expert compute, inverse exchange) so a candidate is
# priced the way the MoE layer pays it.


def _a2a_defaults(b):
    # resolve_hierarchical_a2a's 'auto': stage iff a cross-slice axis
    # exists (the divisibility gate stays at the consumption site)
    return {"staged": int(b.get("do", 1) > 1)}


def _a2a_candidates(b):
    return _dedup([_a2a_defaults(b), {"staged": 0}, {"staged": 1}])


def _a2a_exchange(b, dtype, params):
    mesh = _fit_mesh([("data_outer", b.get("do", 1)),
                      ("expert", b.get("ep", 1))])
    ep = mesh.shape["expert"]
    wo = mesh.shape["data_outer"]
    grid = ep * wo
    M = max(8, int(b.get("M", 64)))
    rows = max(grid * grid,
               min(int(b.get("S", 256)), _MAX_ELEMS // M)
               // (grid * grid) * (grid * grid))

    def body(x):
        loc = x.shape[0]
        if params["staged"]:
            xb = x.reshape(ep, wo, loc // grid, M)
            xb = lax.all_to_all(xb, "expert", 0, 0, tiled=False)
            xb = lax.all_to_all(xb, "data_outer", 1, 1, tiled=False)
            y = jnp.tanh(xb * 1.0005)
            y = lax.all_to_all(y, "data_outer", 1, 1, tiled=False)
            y = lax.all_to_all(y, "expert", 0, 0, tiled=False)
            return y.reshape(loc, M)
        xb = x.reshape(grid, loc // grid, M)
        xb = lax.all_to_all(xb, ("data_outer", "expert"), 0, 0,
                            tiled=False)
        y = jnp.tanh(xb * 1.0005)
        y = lax.all_to_all(y, ("data_outer", "expert"), 0, 0,
                           tiled=False)
        return y.reshape(loc, M)

    spec = P(("data_outer", "expert"))
    fn = jax.shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec,
                       check_vma=False)
    x0 = (jax.random.normal(jax.random.key(2), (rows, M), jnp.float32)
          * 0.3).astype(dtype)
    return fn, x0


def _a2a_step(b, dtype, params):
    fn, x0 = _a2a_exchange(b, dtype, params)

    def step(x):
        return x + _EPS * fn(x)

    return step, x0


def _a2a_parity(b, dtype, params):
    """Both routes are exchange/compute/inverse-exchange round trips:
    the result must equal the locally-computed tanh regardless of the
    staging (tokens come home to the rows they left)."""
    fn, x0 = _a2a_exchange(b, dtype, params)
    _close(fn(x0), jnp.tanh(x0.astype(jnp.float32) * 1.0005),
           f"a2a_staging tuned {params}", dict(rtol=1e-5, atol=1e-5))


# ------------------------------------------------------- dcn_quantize
# qgZ int8 block round trip on the cross-slice (DCN) payload
# (comm/quantized.dcn_precision_clamp). Lossy by design: the parity
# bound is the int8 block-quantization error, not exactness.


def _dq_defaults(b):
    return {"quantize": 0}


def _dq_candidates(b):
    return _dedup([_dq_defaults(b), {"quantize": 1}])


def _dq_reduce(b, dtype, params):
    from ..comm.quantized import dcn_precision_clamp
    mesh = _fit_mesh([("data_outer", b.get("do", 1))])
    Wo = mesh.shape["data_outer"]
    n = -(-_grad_elems(b) // (2048 * Wo)) * (2048 * Wo)

    def body(x):
        g = x
        if params["quantize"]:
            g = dcn_precision_clamp(g)
        return lax.pmean(g, "data_outer")

    fn = jax.shard_map(body, mesh=mesh, in_specs=P("data_outer"),
                       out_specs=P("data_outer"), check_vma=False)
    x0 = (jax.random.normal(jax.random.key(3), (n,), jnp.float32)
          * 0.3).astype(dtype)
    return fn, x0


def _dq_step(b, dtype, params):
    fn, x0 = _dq_reduce(b, dtype, params)

    def step(x):
        return x + _EPS * fn(x)

    return step, x0


def _dq_parity(b, dtype, params):
    got_fn, x0 = _dq_reduce(b, dtype, params)
    ref_fn, _ = _dq_reduce(b, dtype, {"quantize": 0})
    tol = (dict(rtol=0.1, atol=0.1) if params.get("quantize")
           else dict(rtol=1e-6, atol=1e-6))
    _close(got_fn(x0), ref_fn(x0), f"dcn_quantize tuned {params}", tol)


# ------------------------------------------------ sequence.rotate_chunks
# The ring-attention KV rotation (sequence/ring.py _rotate): one fused
# ppermute of the stacked KV buffer vs splitting it into n chunked
# ppermutes so the first chunk's landing overlaps the rest of the wire
# time. chunks=1 is bit-for-bit the pre-knob single-ppermute program.


def _rr_defaults(b):
    return {"chunks": 1}


def _rr_candidates(b):
    return _dedup([_rr_defaults(b)] + [{"chunks": c} for c in (1, 2, 4)
                                       if int(b.get("d", 64)) % c == 0])


def _rr_rotate(b, dtype, params):
    from ..sequence.ring import _rotate
    mesh = _fit_mesh([("seq", b.get("R", 1))])
    R = mesh.shape["seq"]
    T = max(8, min(int(b.get("T", 128)), 512))
    d = int(b.get("d", 64))
    chunks = int(params["chunks"])
    perm = [(j, (j + 1) % R) for j in range(R)]

    def body(kv):
        def ring_step(c, _):
            c = _rotate(c, "seq", perm, chunks)
            return jnp.tanh(c * 1.0005), None

        out, _ = lax.scan(ring_step, kv, None, length=max(R - 1, 1))
        return out

    fn = jax.shard_map(body, mesh=mesh, in_specs=P(None, None, "seq"),
                       out_specs=P(None, None, "seq"), check_vma=False)
    kv0 = (jax.random.normal(jax.random.key(4), (2, T, R * d),
                             jnp.float32) * 0.3).astype(dtype)
    return fn, kv0


def _rr_step(b, dtype, params):
    fn, kv0 = _rr_rotate(b, dtype, params)

    def step(kv):
        return kv + _EPS * fn(kv)

    return step, kv0


def _rr_parity(b, dtype, params):
    """Chunked rotation is a pure data-movement refactor: it must equal
    the single fused ppermute EXACTLY."""
    got_fn, kv0 = _rr_rotate(b, dtype, params)
    ref_fn, _ = _rr_rotate(b, dtype, {"chunks": 1})
    _close(got_fn(kv0), ref_fn(kv0),
           f"ring_rotate tuned {params}", dict(rtol=0, atol=0))


# --------------------------------------------- comm_overlap.scan_unroll
# The prefetch unroll hint (engine._install_comm_overlap -> gpt2's
# layer scan): more bodies per scan iteration give the ZeRO-3 layer
# gather more matmuls to hide under, at compile-time/code-size cost.
# Mathematically the identity transform (the parity).


def _su_defaults(b):
    return {"unroll": 2}


def _su_candidates(b):
    return _dedup([_su_defaults(b)] + [{"unroll": u} for u in (1, 2, 4)])


def _su_run(b, dtype, params):
    N = max(2, min(int(b.get("N", 4)), 12))
    D = max(32, min(int(b.get("D", 128)), 256))
    u = max(1, int(params["unroll"]))
    ks = jax.random.split(jax.random.key(5), 2)
    x0 = (jax.random.normal(ks[0], (64, D), jnp.float32) * 0.3) \
        .astype(dtype)
    w = (jax.random.normal(ks[1], (D, D), jnp.float32)
         / math.sqrt(D)).astype(dtype)

    def loss(y, w):
        return jnp.sum(jnp.tanh(y @ w).astype(jnp.float32) ** 2)

    g = jax.grad(loss)

    def run(x, w):
        def layer(c, _):
            return c + _EPS * g(c, w).astype(c.dtype), None

        y, _ = lax.scan(layer, x, None, length=N, unroll=min(u, N))
        return y

    return run, x0, w


def _su_step(b, dtype, params):
    run, x0, w = _su_run(b, dtype, params)

    def step(carry):
        x, w_ = carry
        return (run(x, w_), w_)

    return step, (x0, w)


def _su_parity(b, dtype, params):
    """Unroll changes code shape, not the op sequence: the unrolled
    scan must equal the unroll=1 scan exactly."""
    run, x0, w = _su_run(b, dtype, params)
    ref, _, _ = _su_run(b, dtype, {"unroll": 1})
    _close(run(x0, w), ref(x0, w),
           f"scan_unroll tuned {params}", dict(rtol=0, atol=0))


# ------------------------------------------ checkpoint_engine.hot_replicas
# The hot-tier replication factor K (checkpoint_engine/hot_tier.py):
# each save pushes K ring-neighbor replicas of the shard. The step
# prices the per-save host staging round trips a candidate K costs
# (swap_tensor/host_stage — identity on single-memory-space backends,
# the same degrade the tier itself has).


def _hr_defaults(b):
    return {"k": 1}


def _hr_candidates(b):
    return _dedup([_hr_defaults(b)] + [{"k": k} for k in (0, 1, 2)])


def _hr_step(b, dtype, params):
    from ..runtime.swap_tensor import host_stage
    n = max(1024, min((int(b.get("G", 1)) << 20) // 4, _MAX_ELEMS))
    k = max(0, int(params["k"]))
    x0 = (jax.random.normal(jax.random.key(6), (n,), jnp.float32)
          * 0.3).astype(dtype)

    def step(x):
        acc = x
        for _ in range(k):
            acc = host_stage.to_device(host_stage.to_host(acc))
        return jnp.tanh(acc * 1.0005)

    return step, x0


def _hr_parity(b, dtype, params):
    from ..runtime.swap_tensor import host_stage
    x = jax.random.normal(jax.random.key(7), (256,), dtype)
    for _ in range(max(0, int(params["k"]))):
        x2 = host_stage.to_device(host_stage.to_host(x))
        _close(x2, x, f"hot_replicas staging round trip {params}",
               dict(rtol=0, atol=0))


# ---------------------------------------------------------------- table
COLLECTIVE_REGISTRY = {
    "comm_bucket": {
        "defaults": _cb_defaults,
        "candidates": _cb_candidates,
        "make_step": _cb_step,
        "parity": _cb_parity,
    },
    "grad_staging": {
        "defaults": _gs_defaults,
        "candidates": _gs_candidates,
        "make_step": _gs_step,
        "parity": _gs_parity,
    },
    "a2a_staging": {
        "defaults": _a2a_defaults,
        "candidates": _a2a_candidates,
        "make_step": _a2a_step,
        "parity": _a2a_parity,
    },
    "dcn_quantize": {
        "defaults": _dq_defaults,
        "candidates": _dq_candidates,
        "make_step": _dq_step,
        "parity": _dq_parity,
    },
    "ring_rotate": {
        "defaults": _rr_defaults,
        "candidates": _rr_candidates,
        "make_step": _rr_step,
        "parity": _rr_parity,
    },
    "scan_unroll": {
        "defaults": _su_defaults,
        "candidates": _su_candidates,
        "make_step": _su_step,
        "parity": _su_parity,
    },
    "hot_replicas": {
        "defaults": _hr_defaults,
        "candidates": _hr_candidates,
        "make_step": _hr_step,
        "parity": _hr_parity,
    },
}
