"""Auto-parallelism planner: ``plan(model_desc, pod_desc)`` picks the mesh.

Counterpart of the reference fork's ``autotuning/`` config search — the
layer above the kernel-grain winner cache. Where the reference launches
real trial runs per candidate config, this planner composes the pieces
the repo already measures:

  * the PR-10 lock-step wall model (``runtime/pipe/schedule.py``
    ``executor_tick_units``) prices every pipe schedule's bubble in
    compute units, extended here with alpha-beta communication terms per
    ICI/DCN link;
  * the alpha-beta constants calibrate from the collective winner cache's
    ``comm_link`` rows (seeded by ``benchmarks/comm_bench.py --json``,
    the measured busbw table) and fall back to the pod descriptor's
    nominal link speeds;
  * the engine's ``_estimate_pipe_state_bytes``/HBM-fit heuristic prunes
    plans whose device-resident train state cannot fit, and prices the
    host-staging traffic of the offload variants that can.

``plan()`` enumerates admissible pp x do x dp x ep x sp x tp meshes and
pipe schedules, scores each, and returns a ranked :class:`PlanReport`
whose top plan converts straight into engine config keys
(:meth:`Plan.config`); ``parallelism: "auto"`` in the runtime config
makes the engine consume it when no explicit topology was given.

KNOB_TABLE is the single source of truth tying every ``"auto"``-accepting
config knob to its resolver (a registry op, a heuristic, or this
planner) — the two-direction coverage lint in
``tests/unit/test_planner_lint.py`` keeps it honest.
"""

import itertools
import math
from dataclasses import dataclass, field, asdict

MESH_AXES = ("pipe", "data_outer", "data", "expert", "seq", "tensor")

# ---------------------------------------------------------- knob table
# Every config knob that accepts "auto" maps to the thing that resolves
# it: {"op": <kernel_registry op consulted by dispatch>} or
# {"resolver": <heuristic/planner description>} (op None). Model-level
# kernel tunables ride at the bottom so every registry op is reachable
# from some "auto" knob (the lint's second direction).
KNOB_TABLE = {
    "comm_overlap.enabled": {
        "op": None, "resolver": "heuristic: on iff dp_world > 1 "
        "(CommOverlapConfig.resolve_enabled)"},
    "comm_overlap.bucket_mb": {
        "op": "comm_bucket", "resolver": "engine._install_comm_overlap "
        "dispatch over the layer-grad bucket; 32 cold"},
    "comm_overlap.hierarchical": {
        "op": "grad_staging", "resolver": "engine._resolve_grad_staging "
        "dispatch; do>1 heuristic cold"},
    "comm_overlap.dcn_quantize": {
        "op": "dcn_quantize", "resolver": "engine._install_comm_overlap "
        "dispatch; off cold (numerics)"},
    "comm_overlap.scan_unroll": {
        "op": "scan_unroll", "resolver": "engine._install_comm_overlap "
        "dispatch; 2 cold"},
    "sequence.block_kernel": {
        "op": "ring_block", "resolver": "sequence/ring._resolve_blocks "
        "dispatch; r05 tiles cold"},
    "sequence.rotate_chunks": {
        "op": "ring_rotate", "resolver": "sequence/ring._resolve_rotate "
        "dispatch; fused single ppermute cold"},
    "moe.grouped_kernel": {
        "op": "moe_grouped_mm", "resolver": "moe grouped-GEMM dispatch; "
        "lax.ragged_dot cold"},
    "moe.hierarchical_a2a": {
        "op": "a2a_staging", "resolver": "sharded_moe."
        "resolve_hierarchical_a2a dispatch behind the divisibility "
        "gate; do>1 heuristic cold"},
    "moe.dcn_quantize": {
        "op": "dcn_quantize", "resolver": "moe_swiglu_ragged_ep "
        "dispatch; off cold (numerics)"},
    "quantize.grad_dcn": {
        "op": "dcn_quantize", "resolver": "engine._install_comm_overlap "
        "override of comm_overlap.dcn_quantize (null defers); same "
        "dispatch, off cold (numerics)"},
    "quantize.moe_dcn": {
        "op": "dcn_quantize", "resolver": "engine moe-block override of "
        "moe.dcn_quantize (null defers); same dispatch, off cold "
        "(numerics)"},
    "quantize.int8_matmul": {
        "op": "mlp_int8", "resolver": "gpt2._mlp W8A8 dispatch over the "
        "mlp bucket; off cold (parity-gated winners only)"},
    "quantize.moe_int8_matmul": {
        "op": "moe_grouped_int8", "resolver": "sharded_moe."
        "resolve_moe_int8 dispatch; off cold (parity-gated winners "
        "only)"},
    "checkpoint_engine.hot_tier": {
        "op": None, "resolver": "heuristic: on iff the elastic launcher "
        "exported the ring env (resolve_hot_tier)"},
    "checkpoint_engine.hot_replicas": {
        "op": "hot_replicas", "resolver": "engine hot-store dispatch "
        "over the shard-payload bucket; K=1 cold"},
    "checkpoint_engine.preempt_drain": {
        "op": None, "resolver": "heuristic: on iff supervised — "
        "ELASTIC_GENERATION or DSTPU_PREEMPT_DRAIN exported "
        "(resolve_preempt_drain)"},
    "pipeline.schedule": {
        "op": None, "resolver": "planner: plan() schedule of the top "
        "plan under parallelism='auto'; model knob otherwise"},
    "pipeline.micro_batches": {
        "op": "pipe_microbatch", "resolver": "engine._resolve_pipeline "
        "dispatch (0 = auto sentinel); 2S cold"},
    "pipeline.offload_activations": {
        "op": None, "resolver": "heuristic: host staging available AND "
        "NOT hbm_fits (resolve_offload_activations)"},
    "pipeline.offload_moments": {
        "op": None, "resolver": "heuristic: off unless explicit "
        "(resolve_offload_moments); planner turns it on with offload "
        "plans"},
    "telemetry.enabled": {
        "op": None, "resolver": "heuristic: monitor backend / env hints "
        "(TelemetryConfig.resolve_enabled)"},
    "telemetry.cluster_agg": {
        "op": None, "resolver": "heuristic: multi-process or exported "
        "telemetry ring (resolve_cluster_agg)"},
    "parallelism": {
        "op": None, "resolver": "planner: plan() top plan builds the "
        "TopologyConfig when no explicit topology is given"},
    # model/serving-level kernel tunables (not config blocks; listed so
    # the registry-coverage direction of the lint sees their ops)
    "gpt2.flash_block_q": {
        "op": "flash_attention", "resolver": "flash_attention dispatch"},
    "gpt2.mlp_kernel": {
        "op": "mlp_matmul", "resolver": "fused MLP dispatch"},
    "gpt2.fused_layernorm": {
        "op": "layernorm", "resolver": "fused layernorm dispatch"},
    "gpt2.fused_loss_kernel": {
        "op": "fused_ce", "resolver": "fused cross-entropy dispatch"},
    "serving.paged_kernel": {
        "op": "paged_decode", "resolver": "paged decode dispatch"},
    "serving.paged_block_c": {
        "op": "paged_chunk", "resolver": "SplitFuse chunk dispatch"},
    "serving.prefix_cache": {
        "op": "prefix_cache", "resolver": "engine _resolve_prefix_cache "
        "dispatch; cold default DISABLED so the disabled program stays "
        "byte-identical"},
    "serving.prefix_cache_min_match": {
        "op": "prefix_cache", "resolver": "engine _resolve_prefix_cache "
        "dispatch; cold default 1 block (the hand-set value)"},
    "serving.spec_draft": {
        "op": "spec_decode", "resolver": "engine resolve_spec dispatch "
        "(inference/v2/speculative.py); cold default ENABLED — the real "
        "opt-in gate is the draft_model constructor argument, without "
        "which no speculative program exists"},
    "serving.spec_k": {
        "op": "spec_decode", "resolver": "engine resolve_spec dispatch; "
        "cold default 4 proposals per verify round, acceptance-aware "
        "cost term prices the k-vs-acceptance knee"},
    "serving.weight_quant": {
        "op": None, "resolver": "heuristic: 'auto' resolves OFF "
        "(engine_v2 — reserved for a measured HBM-pressure rule; every "
        "cold program byte-identical to weight_quant=false)"},
    # serving-fleet router knobs (inference/v2/router.py RouterConfig;
    # heuristic resolvers, no measured op — the lint's construction
    # probes discover them as router.<field>)
    "router.router_queue_depth": {
        "op": None, "resolver": "heuristic: 4x aggregate decode slots "
        "across live replicas (Router.resolved_queue_depth) — "
        "capacity-proportional back-pressure"},
    "router.shed_policy": {
        "op": None, "resolver": "heuristic: lowest-class, newest-first "
        "within the class (Router._shed_victim)"},
    "router.prefix_affinity": {
        "op": None, "resolver": "heuristic: on iff any live replica "
        "runs a prefix cache (Router._affinity_on)"},
    "router.disaggregate": {
        "op": "kv_handoff", "resolver": "heuristic: on iff both phase "
        "roles (prefill + decode) are live in the fleet "
        "(Router._disagg_on, re-resolved every round); the kv_handoff "
        "cost model prices KV wire bytes over DCN against the decode "
        "iterations a colocated prefill chunk steals"},
    "replica.role": {
        "op": "kv_handoff", "resolver": "deployment-time constructor "
        "choice (Replica(role=...)): colocated | prefill | decode — "
        "not auto-resolved; the router's disaggregate knob reads the "
        "fleet's role mix"},
}


# ------------------------------------------------------------ descriptors

@dataclass
class ModelDesc:
    """What the planner needs to know about the model: parameter count
    and the dims that gate axis admissibility (heads for tp, sequence
    for sp, layers for pp, experts for ep)."""
    params: int
    n_layer: int
    d_model: int
    n_head: int
    max_seq_len: int
    vocab_size: int = 0
    experts: int = 0
    param_bytes: int = 4              # working param/activation itemsize
    grad_bytes: int = 4               # grad accumulation itemsize
    name: str = ""

    @classmethod
    def from_model_config(cls, mcfg):
        """Build from a gpt2/mixtral-style model config (None -> a tiny
        placeholder the planner treats as single-chip work)."""
        if mcfg is None:
            return cls(params=1 << 20, n_layer=1, d_model=64, n_head=1,
                       max_seq_len=128, name="unknown")
        count = getattr(mcfg, "num_params", None)
        params = int(count()) if callable(count) else 1 << 20
        dt = str(getattr(mcfg, "dtype", "float32"))
        pb = 2 if ("16" in dt) else 4
        return cls(
            params=params,
            n_layer=int(getattr(mcfg, "n_layer", 1)),
            d_model=int(getattr(mcfg, "d_model", 64)),
            n_head=int(getattr(mcfg, "n_head", 1)),
            max_seq_len=int(getattr(mcfg, "max_seq_len", 128)),
            vocab_size=int(getattr(mcfg, "vocab_size", 0)),
            experts=int(getattr(mcfg, "num_experts", 0) or 0),
            param_bytes=pb,
            name=type(mcfg).__name__)


@dataclass
class PodDesc:
    """What the planner needs to know about the cluster: chip count and
    HBM (the pruning constraint), slice structure (what DCN crosses),
    and nominal link/compute speeds (the alpha-beta fallbacks when no
    measured ``comm_link`` rows exist)."""
    n_chips: int
    hbm_bytes: int
    n_slices: int = 1                 # data_outer may only split slices
    chip_flops: float = 2.0e14        # peak per-chip FLOP/s (relative)
    ici_gbps: float = 100.0           # per-link ICI bandwidth
    dcn_gbps: float = 12.5            # per-host DCN bandwidth
    ici_alpha_us: float = 1.0         # per-collective ICI launch cost
    dcn_alpha_us: float = 25.0
    host_gbps: float = 10.0           # HBM<->host staging bandwidth
    host_offload: bool = True         # backend has a host memory kind
    device_kind: str = ""             # "" = the local jax device kind

    @classmethod
    def from_devices(cls):
        """Describe the pod jax actually sees (the engine's
        ``parallelism: 'auto'`` path). HBM honors the DSTPU_HBM_BYTES
        override like the engine's own heuristic."""
        import os
        import jax
        devs = jax.devices()
        hbm = 0
        env = os.environ.get("DSTPU_HBM_BYTES")
        if env:
            try:
                hbm = int(float(env))
            except ValueError:
                hbm = 0
        if not hbm:
            try:
                stats = devs[0].memory_stats()
                hbm = int(stats["bytes_limit"]) if stats else 0
            except Exception:  # noqa: BLE001 - CPU/older backends
                hbm = 0
        try:
            n_slices = len({getattr(d, "slice_index", 0) for d in devs})
        except Exception:  # noqa: BLE001
            n_slices = 1
        from .kernel_dispatch import device_kind
        return cls(n_chips=len(devs), hbm_bytes=hbm,
                   n_slices=max(1, n_slices), device_kind=device_kind())


@dataclass
class Plan:
    """One scored candidate: a full mesh assignment plus the pipe
    schedule/microbatch/offload choice and the wall-model breakdown."""
    mesh: dict                        # axis -> size over MESH_AXES
    schedule: str                     # gpipe | 1f1b | zb | none (pp=1)
    micro_batches: int
    offload: bool                     # host-offload moments/activations
    wall_ms: float
    breakdown: dict                   # term -> ms
    est_state_bytes: int
    hbm_fits: bool

    def config(self, base=None):
        """Engine-ready config keys for this plan (merged over ``base``
        when given): the topology axis sizes plus the pipeline block."""
        out = dict(base or {})
        out["tensor_parallel"] = {"size": self.mesh["tensor"]}
        out["sequence_parallel_size"] = self.mesh["seq"]
        out["expert_parallel_size"] = self.mesh["expert"]
        pipe = dict(out.get("pipeline", {}))
        pipe["stages"] = self.mesh["pipe"]
        if self.schedule != "none":
            pipe["schedule"] = self.schedule
            pipe["micro_batches"] = self.micro_batches
        pipe["offload_activations"] = bool(self.offload)
        pipe["offload_moments"] = bool(self.offload)
        out["pipeline"] = pipe
        if self.mesh["data_outer"] > 1:
            zero = dict(out.get("zero_optimization", {}))
            zero.setdefault("stage", 1)
            zero["mics_shard_size"] = self.mesh["data"]
            out["zero_optimization"] = zero
        return out

    def topology_kwargs(self):
        """Kwargs for utils.groups.TopologyConfig reproducing this
        mesh (data_outer rides on zero_shard_size subdividing DP)."""
        do, dp = self.mesh["data_outer"], self.mesh["data"]
        return dict(
            tensor_parallel_size=self.mesh["tensor"],
            pipe_parallel_size=self.mesh["pipe"],
            seq_parallel_size=self.mesh["seq"],
            expert_parallel_size=self.mesh["expert"],
            zero_shard_size=(dp if do > 1 else -1))


@dataclass
class PlanReport:
    """Ranked plan() output: ``plans[0]`` is the recommendation;
    ``considered``/``pruned`` record the search's shape so a surprising
    answer can be audited."""
    model: ModelDesc
    pod: PodDesc
    plans: list
    considered: int = 0
    pruned_hbm: int = 0
    links: dict = field(default_factory=dict)

    def top(self):
        return self.plans[0] if self.plans else None

    def to_config(self, base=None):
        best = self.top()
        return best.config(base) if best is not None else dict(base or {})

    def table(self):
        """Human-readable ranking (bench/README surface)."""
        lines = [f"{'rank':>4} {'mesh (pp,do,dp,ep,sp,tp)':>26} "
                 f"{'sched':>6} {'M':>4} {'offl':>5} {'wall_ms':>10} "
                 f"{'state_gb':>9}"]
        for i, p in enumerate(self.plans):
            m = p.mesh
            lines.append(
                f"{i + 1:>4} "
                f"{'x'.join(str(m[a]) for a in MESH_AXES):>26} "
                f"{p.schedule:>6} {p.micro_batches:>4} "
                f"{str(bool(p.offload)):>5} {p.wall_ms:>10.3f} "
                f"{p.est_state_bytes / 1e9:>9.2f}")
        return "\n".join(lines)

    def to_dict(self):
        return {
            "model": asdict(self.model), "pod": asdict(self.pod),
            "considered": self.considered, "pruned_hbm": self.pruned_hbm,
            "links": {k: list(v) for k, v in self.links.items()},
            "plans": [asdict(p) for p in self.plans],
        }


# ------------------------------------------------------ link calibration

def calibrate_links(pod, cache=None):
    """(alpha_s, beta_Bps) per link class from the collective cache's
    ``comm_link`` rows (op 'comm_link', bucket '<topo>,k<ici|dcn>',
    params {alpha_us, beta_gbps} — seeded by ``comm_bench --json`` /
    ``--seed-cache``), honoring the device-kind refusal rule; the pod
    descriptor's nominal numbers are the fallback. comm_link rows live
    in the cache file but NOT in the op registry — dispatch never
    consults them, only this calibration does."""
    out = {
        "ici": (pod.ici_alpha_us * 1e-6, pod.ici_gbps * 1e9),
        "dcn": (pod.dcn_alpha_us * 1e-6, pod.dcn_gbps * 1e9),
    }
    if cache is None:
        try:
            from . import kernel_dispatch
            from .kernel_cache import KernelCache
            cache = KernelCache.load(kernel_dispatch.cache_path())
        except Exception:  # noqa: BLE001 - no backend yet
            return out
    want_kind = pod.device_kind
    if not want_kind:
        try:
            from .kernel_dispatch import device_kind
            want_kind = device_kind()
        except Exception:  # noqa: BLE001
            want_kind = ""
    for e in getattr(cache, "entries", {}).values():
        if not isinstance(e, dict) or e.get("op") != "comm_link":
            continue
        if want_kind and e.get("device_kind") != want_kind:
            continue  # the refusal rule: foreign chips don't calibrate
        params = e.get("params") or {}
        kind = params.get("kind") or (
            "dcn" if ",kdcn" in str(e.get("bucket", "")) else "ici")
        try:
            alpha = float(params["alpha_us"]) * 1e-6
            beta = float(params["beta_gbps"]) * 1e9
        except (KeyError, TypeError, ValueError):
            continue
        if beta > 0:
            out[kind] = (max(0.0, alpha), beta)
    return out


def _t_coll(bytes_, world, link, kind="ring"):
    """alpha-beta time of one collective: ring all-reduce moves
    2(W-1)/W x payload, gather/scatter/a2a (W-1)/W, neighbor exchange
    1x."""
    alpha, beta = link
    if world <= 1:
        return 0.0
    factor = {"ring": 2 * (world - 1) / world,
              "shard": (world - 1) / world,
              "exchange": 1.0}[kind]
    return alpha + factor * bytes_ / beta


# ------------------------------------------------------------- scoring

# fraction of comm time the latency-hiding scheduler is assumed to slide
# under compute (the overlap-probe acceptance number's planning-side
# stand-in); the schedule-dependent offload exposure mirrors how zb's
# drain ticks absorb host staging where gpipe's bubble cannot
_HIDDEN_FRAC = 0.75
_OFFLOAD_EXPOSED = {"zb": 0.25, "1f1b": 0.5, "gpipe": 0.5, "none": 0.5}

# every cost term ``_score`` can emit, in reporting order. This is the
# reconciliation vocabulary: ``autotuning/reconcile.py`` pairs each one
# with a measured ``profiling.step_trace`` decomposition key, and the
# two-direction lint in tests/unit/test_reconcile.py greps ``_score``'s
# source to keep this tuple honest.
SCORE_TERMS = ("compute", "grad_reduce", "tp_reduce", "pipe_handoff",
               "ring_rotate", "expert_a2a", "host_offload")


def _estimate_state_bytes(model, mesh, offload):
    """The engine's ``_estimate_pipe_state_bytes`` heuristic on a plan:
    working params+grads divide over (pipe, tensor, expert); the fp32
    master + Adam moments divide over the full ZeRO partition group —
    and move to host entirely under the offload variants."""
    shard = mesh["pipe"] * mesh["tensor"] * max(1, mesh["expert"])
    opt_shard = shard * mesh["data"] * mesh["data_outer"]
    n = model.params
    dev = n * (model.param_bytes + model.grad_bytes) / shard
    if not offload:
        dev += n * 12 / opt_shard
    return int(dev)


def _score(model, pod, mesh, schedule, M, offload, links, batch_tokens,
           dcn_quantize=False):
    """Wall-clock model of one optimizer step (ms) + term breakdown.

    Compute rides the PR-10 lock-step tick model: one unit = one
    microbatch's forward through one stage, backward 2 units, so the
    schedule's ``executor_tick_units`` sum prices its bubble; comm terms
    are alpha-beta per link class, discounted by the overlap fraction
    the latency-hiding scheduler is expected to hide.

    ``dcn_quantize``: price the cross-slice (data_outer) legs with the
    measured 'dcn_int8' link class when the cache holds one (comm_bench
    fits it from the int8 staged-a2a sweep: alpha-beta over LOGICAL
    payload bytes, so the 4x wire shrink + codec cost land in the
    fitted coefficients). Without a measured row the plain dcn link
    stands in — the planner never invents a speedup it hasn't seen."""
    from ..runtime.pipe.schedule import executor_tick_units
    pp, do, dp = mesh["pipe"], mesh["data_outer"], mesh["data"]
    ep, sp, tp = mesh["expert"], mesh["seq"], mesh["tensor"]
    ici, dcn = links["ici"], links["dcn"]
    dcn_q = links.get("dcn_int8", dcn) if dcn_quantize else dcn
    exposed = 1.0 - _HIDDEN_FRAC

    tokens_micro = batch_tokens / (dp * do * M)
    shard = pp * tp * max(1, ep)
    # one tick unit ~ one microbatch forward on one stage's params
    unit_s = 2.0 * (model.params / shard) * (tokens_micro / sp) \
        / pod.chip_flops
    if pp > 1:
        ticks = executor_tick_units(schedule, M, pp)
        t_compute = sum(ticks) * unit_s
        n_ticks = len(ticks)
    else:
        t_compute = 3.0 * M * unit_s
        n_ticks = 0

    terms = {"compute": t_compute}
    # gradient reduction: hierarchical two-stage when do > 1 (the
    # comm_overlap discipline) — inner ring over ICI, the cross-slice
    # hop on the already-scattered shard over DCN
    gbytes = model.grad_bytes * model.params / shard
    layers = max(1, model.n_layer // pp)
    t_grad = _t_coll(gbytes, dp, ici, "ring") \
        + (layers - 1) * ici[0] * (dp > 1)
    if do > 1:
        t_grad += _t_coll(gbytes / max(1, dp), do, dcn_q, "ring")
    terms["grad_reduce"] = t_grad * exposed
    # tensor-parallel activation reductions: ~2 psums per layer over tp
    if tp > 1:
        act_b = tokens_micro / sp * model.d_model * model.param_bytes
        terms["tp_reduce"] = M * layers * 2 \
            * _t_coll(act_b, tp, ici, "ring") * exposed
    # pipe handoffs: one boundary exchange per tick
    if pp > 1:
        act_b = tokens_micro / sp * model.d_model * model.param_bytes
        terms["pipe_handoff"] = n_ticks \
            * _t_coll(act_b, pp, ici, "exchange") * exposed
    # ring-attention KV rotations: (sp-1) per layer per microbatch
    if sp > 1:
        kv_b = 2 * tokens_micro / sp * model.d_model * model.param_bytes
        terms["ring_rotate"] = M * layers * (sp - 1) \
            * _t_coll(kv_b, sp, ici, "exchange") * exposed
    # expert all_to_all: two exchanges per MoE layer per microbatch
    if ep > 1:
        tok_b = tokens_micro * model.d_model * model.param_bytes
        t_one = _t_coll(tok_b, ep, ici, "shard")
        if do > 1:
            t_one += _t_coll(tok_b, do, dcn_q, "shard")
        terms["expert_a2a"] = M * layers * 2 * t_one * exposed
    # host staging of the offloaded fp32 master + moments (and the
    # activation rings the schedule hides inside its drain ticks)
    if offload:
        opt_b = 12.0 * model.params / (shard * dp * do)
        terms["host_offload"] = 2 * opt_b / (pod.host_gbps * 1e9) \
            * _OFFLOAD_EXPOSED.get(schedule, 0.5)

    wall = sum(terms.values())
    return wall * 1e3, {k: round(v * 1e3, 6) for k, v in terms.items()}


# ---------------------------------------------------------- enumeration

def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def _admissible_meshes(model, pod, pp_min=1, pp_max=None):
    """All axis assignments whose product is the chip count and whose
    sizes the model dims admit (tp | heads, sp | seq/2 for the zigzag
    split, pp <= layers, ep | experts, do <= slice count)."""
    n = pod.n_chips
    pp_cap = min(pp_max or n, model.n_layer, n)
    for pp in _divisors(n):
        if pp < pp_min or pp > pp_cap:
            continue
        rest_pp = n // pp
        for do in _divisors(math.gcd(rest_pp, pod.n_slices)):
            rest_do = rest_pp // do
            for tp in _divisors(rest_do):
                if model.n_head % tp or model.d_model % tp:
                    continue
                rest_tp = rest_do // tp
                for sp in _divisors(rest_tp):
                    if sp > 1 and model.max_seq_len % (2 * sp):
                        continue
                    rest_sp = rest_tp // sp
                    eps = [1]
                    if model.experts:
                        eps = [e for e in _divisors(rest_sp)
                               if model.experts % e == 0]
                    for ep in eps:
                        dp = rest_sp // ep
                        yield {"pipe": pp, "data_outer": do, "data": dp,
                               "expert": ep, "seq": sp, "tensor": tp}


def plan(model_desc, pod_desc, *, batch_tokens=None, pp_min=1,
         pp_max=None, schedules=("gpipe", "1f1b", "zb"),
         micro_candidates=None, max_plans=8, cache=None,
         dcn_quantize=False):
    """Enumerate-score-prune: returns a :class:`PlanReport` ranked by
    the modeled step wall. Plans whose device-resident state fails the
    HBM-fit margin are pruned (never ranked); offload variants move the
    optimizer tail to host and pay the modeled staging cost, so when
    both fit, the non-offload plan outranks its offload twin on the
    staging term alone."""
    model, pod = model_desc, pod_desc
    if batch_tokens is None:
        batch_tokens = max(1, 8 * pod.n_chips) * model.max_seq_len
    links = calibrate_links(pod, cache=cache)
    plans, considered, pruned = [], 0, 0
    for mesh in _admissible_meshes(model, pod, pp_min, pp_max):
        pp = mesh["pipe"]
        scheds = list(schedules) if pp > 1 else ["none"]
        micros = micro_candidates or ([2 * pp, 4 * pp] if pp > 1 else [1])
        for schedule, M, offload in itertools.product(
                scheds, micros, (False, True)):
            considered += 1
            if offload and not pod.host_offload:
                continue
            est = _estimate_state_bytes(model, mesh, offload)
            from ..runtime.config import PipelineConfig
            fits = PipelineConfig.hbm_fits(est, pod.hbm_bytes)
            if not fits:
                pruned += 1
                continue
            wall, terms = _score(model, pod, mesh, schedule, M, offload,
                                 links, batch_tokens,
                                 dcn_quantize=dcn_quantize)
            plans.append(Plan(
                mesh=dict(mesh), schedule=schedule, micro_batches=M,
                offload=offload, wall_ms=round(wall, 6),
                breakdown=terms, est_state_bytes=est, hbm_fits=True))
    plans.sort(key=lambda p: (p.wall_ms, p.offload,
                              -p.mesh["data"], p.mesh["pipe"]))
    return PlanReport(model=model, pod=pod, plans=plans[:max_plans],
                      considered=considered, pruned_hbm=pruned,
                      links=links)


def plan_for_engine(model, raw_config):
    """The engine's ``parallelism: "auto"`` entry: describe the model
    and the visible pod, plan, and hand back the report (the engine
    adopts ``report.top()``'s topology kwargs and pipeline choices).
    Returns None when planning is impossible (no devices)."""
    mdesc = ModelDesc.from_model_config(getattr(model, "config", None))
    pdesc = PodDesc.from_devices()
    if pdesc.n_chips < 1:
        return None
    tb = raw_config.get("train_batch_size") \
        or raw_config.get("train_micro_batch_size_per_gpu")
    batch_tokens = (int(tb) * mdesc.max_seq_len) if tb else None
    # DCN-quantized pricing when the config COMMITS to it (True — an
    # "auto" spelling resolves off on a cold cache, so pricing it
    # quantized would rank meshes on a lever the engine may not pull);
    # the quantize-block overrides win over the per-block spellings
    qz = raw_config.get("quantize") or {}
    co = raw_config.get("comm_overlap") or {}
    moe = raw_config.get("moe") or {}
    grad_q = qz.get("grad_dcn")
    if grad_q is None:
        grad_q = co.get("dcn_quantize", False)
    moe_q = qz.get("moe_dcn")
    if moe_q is None:
        moe_q = moe.get("dcn_quantize", False)
    return plan(mdesc, pdesc, batch_tokens=batch_tokens,
                dcn_quantize=(grad_q is True or moe_q is True))
