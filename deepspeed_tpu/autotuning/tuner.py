"""Experiment-ordering tuners (reference autotuning/tuner/
index_based_tuner.py:11,27 GridSearchTuner/RandomTuner and
model_based_tuner.py:19). Each yields experiment configs from a search
space; the model-based tuner's cost model is replaced by a simple
throughput-extrapolation early-stop (the reference uses XGBoost)."""

import itertools
import random


def cartesian(space):
    """{'a': [1,2], 'b': [3]} -> [{'a':1,'b':3}, {'a':2,'b':3}]"""
    keys = list(space)
    return [dict(zip(keys, vals))
            for vals in itertools.product(*(space[k] for k in keys))]


class BaseTuner:
    def __init__(self, space, seed=0):
        self.experiments = cartesian(space)
        self.seed = seed

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.experiments)


class GridSearchTuner(BaseTuner):
    def __iter__(self):
        return iter(self.experiments)


class RandomTuner(BaseTuner):
    def __init__(self, space, seed=0, max_trials=None):
        super().__init__(space, seed)
        self.max_trials = max_trials

    def __len__(self):
        n = len(self.experiments)
        return min(n, self.max_trials) if self.max_trials else n

    def __iter__(self):
        exps = list(self.experiments)
        random.Random(self.seed).shuffle(exps)
        if self.max_trials:
            exps = exps[:self.max_trials]
        return iter(exps)


class CostModel:
    """Fitted performance model over experiment configs (reference
    autotuning/tuner/cost_model.py:14 XGBoostCostModel). Torch/xgboost-free
    realization: one-hot + numeric featurization of config dicts and a
    ridge-regression fit in closed form (numpy) — enough signal to rank a
    ZeRO-stage x micro-batch x buckets space, with none of the
    dependency weight."""

    def __init__(self, ridge=1e-3):
        self.ridge = ridge
        self._feat_keys = None
        self._cat_values = None
        self._w = None

    def _featurize(self, exp):
        vec = []
        for k in self._feat_keys:
            v = exp.get(k)
            if k in self._cat_values:              # categorical: one-hot
                for cv in self._cat_values[k]:
                    vec.append(1.0 if v == cv else 0.0)
            else:
                import math
                x = float(v)
                vec.append(math.log1p(abs(x)) * (1 if x >= 0 else -1))
        vec.append(1.0)                            # bias
        return vec

    def fit(self, experiments, metrics):
        """experiments: list of config dicts; metrics: measured values
        (higher better)."""
        import numpy as np
        keys = sorted({k for e in experiments for k in e})
        self._feat_keys = keys
        self._cat_values = {}
        for k in keys:
            vals = {e.get(k) for e in experiments}
            if any(not isinstance(v, (int, float, bool)) or
                   isinstance(v, bool) for v in vals):
                self._cat_values[k] = sorted(vals, key=repr)
        X = np.asarray([self._featurize(e) for e in experiments])
        y = np.asarray(metrics, float)
        A = X.T @ X + self.ridge * np.eye(X.shape[1])
        self._w = np.linalg.solve(A, X.T @ y)
        return self

    def predict(self, experiments):
        import numpy as np
        assert self._w is not None, "fit() first"
        X = np.asarray([self._featurize(e) for e in experiments])
        return X @ self._w


class ModelBasedTuner(BaseTuner):
    """Sequential model-based search (reference
    tuner/model_based_tuner.py:19): seed with a few random trials, then
    alternate fit -> propose the best predicted untried config, with
    epsilon-greedy exploration. Drive it with::

        tuner = ModelBasedTuner(space)
        for exp in tuner:
            tuner.record(exp, measure(exp))
    """

    def __init__(self, space, seed=0, max_trials=None, warmup_trials=3,
                 explore_eps=0.15):
        super().__init__(space, seed)
        self.max_trials = max_trials or len(self.experiments)
        self.warmup = warmup_trials
        self.eps = explore_eps
        self.rng = random.Random(seed)
        self.observed = []                # (exp, metric)
        self._pending = []                # yielded, not yet recorded
        self.model = CostModel()

    def __len__(self):
        return min(self.max_trials, len(self.experiments))

    def record(self, exp, metric):
        self.observed.append((exp, float(metric)))
        if exp in self._pending:
            self._pending.remove(exp)

    def _untried(self):
        # exclude BOTH recorded and yielded-but-unrecorded experiments:
        # otherwise skipping record() hands the same config back forever
        seen = [e for e, _ in self.observed] + self._pending
        return [e for e in self.experiments if e not in seen]

    def __iter__(self):
        # a fresh iteration may retry configs abandoned (yielded, never
        # recorded) by a crashed/stopped earlier loop
        self._pending = []
        count = 0
        order = list(self.experiments)
        self.rng.shuffle(order)
        while count < len(self):
            untried = self._untried()
            if not untried:
                return
            if len(self.observed) < self.warmup or \
                    self.rng.random() < self.eps:
                exp = next(e for e in order if e in untried)
            else:
                if not self.observed:
                    raise RuntimeError(
                        "ModelBasedTuner with warmup_trials=0 requires "
                        "record(exp, metric) before model-guided picks")
                self.model.fit(*zip(*self.observed))
                preds = self.model.predict(untried)
                exp = untried[int(max(range(len(untried)),
                                      key=lambda i: preds[i]))]
            count += 1
            self._pending.append(exp)
            yield exp

    def best(self):
        return max(self.observed, key=lambda em: em[1])
