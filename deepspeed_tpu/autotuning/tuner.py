"""Experiment-ordering tuners (reference autotuning/tuner/
index_based_tuner.py:11,27 GridSearchTuner/RandomTuner and
model_based_tuner.py:19). Each yields experiment configs from a search
space; the model-based tuner's cost model is replaced by a simple
throughput-extrapolation early-stop (the reference uses XGBoost)."""

import itertools
import random


def cartesian(space):
    """{'a': [1,2], 'b': [3]} -> [{'a':1,'b':3}, {'a':2,'b':3}]"""
    keys = list(space)
    return [dict(zip(keys, vals))
            for vals in itertools.product(*(space[k] for k in keys))]


class BaseTuner:
    def __init__(self, space, seed=0):
        self.experiments = cartesian(space)
        self.seed = seed

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.experiments)


class GridSearchTuner(BaseTuner):
    def __iter__(self):
        return iter(self.experiments)


class RandomTuner(BaseTuner):
    def __init__(self, space, seed=0, max_trials=None):
        super().__init__(space, seed)
        self.max_trials = max_trials

    def __len__(self):
        n = len(self.experiments)
        return min(n, self.max_trials) if self.max_trials else n

    def __iter__(self):
        exps = list(self.experiments)
        random.Random(self.seed).shuffle(exps)
        if self.max_trials:
            exps = exps[:self.max_trials]
        return iter(exps)
