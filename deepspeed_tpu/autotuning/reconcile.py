"""Modeled-vs-measured planner reconciliation: align a
``profiling.step_trace.StepDecomposition`` with the ``_score`` term
breakdown of ``autotuning/planner.py`` and close ROADMAP item 1's open
thread — feed *measured* costs back into the planner.

Three pieces:

  * :func:`reconcile` — run the planner's ``_score`` for the mesh the
    trace was captured on and pair every cost term with the measured
    decomposition key (``TERM_MAP``; the two-direction lint in
    ``tests/unit/test_reconcile.py`` keeps planner and tracer
    vocabularies aligned). The result is a :class:`DriftReport` ranked
    by absolute modeled-vs-measured error — "where is the model most
    wrong" is the first question every perf PR asks.
  * :func:`seed_rows` / :func:`seed_cache` — distill the measured run
    into winner-cache rows via the existing
    ``kernel_cache.seed_entries`` path: ``comm_link`` rows whose
    alpha-beta is refit from measured exposed collective time against
    the planner's own wire-byte model (``calibrate_links`` picks them
    up on the next ``plan()``), and ``op_cost`` rows carrying measured
    per-step unit costs for each Pallas tunable op plus the compute
    tick. Both are cache-file-only pseudo-ops exactly like
    ``comm_bench``'s ``comm_link``: never in the op REGISTRY, invisible
    to dispatch, device-kind refusal rules intact.
  * :func:`from_engine` — the telemetry wiring's entry: build the
    planner descriptors from a live engine and reconcile the trace its
    ``ProfilerControl`` just captured.

Every path here is advisory: parse/model failures degrade to ``None``
with a warning, never an exception into the step path.
"""

from dataclasses import dataclass, field, asdict

from ..utils.logging import logger
from . import planner
from .planner import ModelDesc, PodDesc, calibrate_links
from .kernel_cache import seed_entries

# planner ``_score`` term -> StepDecomposition ``terms`` key. Identity
# today — kept explicit so a future split (e.g. grad_reduce into
# ici/dcn legs) must touch this table and re-run the lint.
TERM_MAP = {t: t for t in planner.SCORE_TERMS}

# terms whose modeled time is communication priced by calibrate_links
# (the comm_link refit's numerator); compute and host_offload are not.
_COMM_TERMS = ("grad_reduce", "tp_reduce", "pipe_handoff",
               "ring_rotate", "expert_a2a")


def topo_bucket(mesh_shape):
    """The collective bucket signature string for a planner mesh dict
    (``ops/pallas/_common.topo_signature`` format — exact axis sizes,
    so a measured row can never steer a different topology)."""
    g = lambda a: int(mesh_shape.get(a, 1))
    return (f"pp{g('pipe')},do{g('data_outer')},dp{g('data')},"
            f"ep{g('expert')},sp{g('seq')},tp{g('tensor')}")


@dataclass
class DriftReport:
    """Modeled vs measured, per term, ranked by absolute error."""
    rows: list                         # [{term, modeled_ms, measured_ms,
    #                                     drift_ms}] worst-first
    modeled_wall_ms: float
    measured_wall_ms: float            # decomposition total device ms
    wall_err_pct: float                # 100*|modeled-measured|/measured
    coverage_pct: float                # from the decomposition
    mesh: dict
    schedule: str
    micro_batches: int
    offload: bool
    steps: int
    links: dict = field(default_factory=dict)
    unmodeled: dict = field(default_factory=dict)

    def top(self):
        return self.rows[0] if self.rows else None

    def summary(self):
        """The compact dict telemetry/flight-recorder surfaces carry
        (term reported both by name and by SCORE_TERMS index — metric
        values are floats)."""
        t = self.top() or {}
        term = t.get("term", "")
        return {
            "top_term": term,
            "top_term_index": (planner.SCORE_TERMS.index(term)
                               if term in planner.SCORE_TERMS else -1),
            "top_drift_ms": round(abs(t.get("drift_ms", 0.0)), 6),
            "wall_err_pct": self.wall_err_pct,
            "coverage_pct": self.coverage_pct,
            "modeled_wall_ms": self.modeled_wall_ms,
            "measured_wall_ms": self.measured_wall_ms,
            "steps": self.steps,
        }

    def table(self):
        """Human-readable drift table (the CLI's default output)."""
        lines = [f"{'term':>14} {'modeled_ms':>12} {'measured_ms':>12} "
                 f"{'drift_ms':>10}"]
        for r in self.rows:
            lines.append(f"{r['term']:>14} {r['modeled_ms']:>12.4f} "
                         f"{r['measured_ms']:>12.4f} "
                         f"{r['drift_ms']:>+10.4f}")
        lines.append(
            f"{'wall':>14} {self.modeled_wall_ms:>12.4f} "
            f"{self.measured_wall_ms:>12.4f} "
            f"{self.measured_wall_ms - self.modeled_wall_ms:>+10.4f}"
            f"   ({self.wall_err_pct:.1f}% off, coverage "
            f"{self.coverage_pct:.1f}%)")
        for k, v in sorted(self.unmodeled.items()):
            lines.append(f"{k:>14} {'(unmodeled)':>12} {v:>12.4f}")
        return "\n".join(lines)

    def to_dict(self):
        return asdict(self)


def reconcile(decomp, model, pod, mesh_shape, *, schedule="none",
              micro_batches=1, offload=False, batch_tokens=None,
              cache=None, links=None):
    """Pair every planner ``_score`` term with its measured
    decomposition value. Every term gets a row — a term the mesh never
    exercises pairs modeled 0.0 against measured 0.0, so "is the model
    silent where the hardware is loud" is visible, not dropped."""
    mesh = {a: int(mesh_shape.get(a, 1)) for a in planner.MESH_AXES}
    if links is None:
        links = calibrate_links(pod, cache=cache)
    if batch_tokens is None:
        batch_tokens = max(1, 8 * pod.n_chips) * model.max_seq_len
    M = max(1, int(micro_batches))
    sched = schedule if mesh["pipe"] > 1 else "none"
    _, terms = planner._score(model, pod, mesh, sched, M, bool(offload),
                              links, batch_tokens)
    rows = []
    for t in planner.SCORE_TERMS:
        modeled = float(terms.get(t, 0.0))
        measured = float(decomp.terms.get(TERM_MAP[t], 0.0))
        rows.append({"term": t, "modeled_ms": round(modeled, 6),
                     "measured_ms": round(measured, 6),
                     "drift_ms": round(measured - modeled, 6)})
    rows.sort(key=lambda r: -abs(r["drift_ms"]))
    modeled_wall = sum(float(terms.get(t, 0.0))
                       for t in planner.SCORE_TERMS)
    measured_wall = float(decomp.total_device_ms)
    err = (100.0 * abs(modeled_wall - measured_wall) / measured_wall
           if measured_wall > 0 else 0.0)
    return DriftReport(
        rows=rows, modeled_wall_ms=round(modeled_wall, 6),
        measured_wall_ms=round(measured_wall, 6),
        wall_err_pct=round(err, 3),
        coverage_pct=float(decomp.coverage_pct),
        mesh=mesh, schedule=sched, micro_batches=M,
        offload=bool(offload), steps=int(decomp.steps),
        links={k: list(v) for k, v in links.items()},
        unmodeled=dict(decomp.unmodeled))


# ------------------------------------------------------------- seeding

def _comm_bytes_by_link(model, mesh, schedule, M, batch_tokens):
    """Per-step wire bytes per link class, mirroring ``_score``'s
    payload formulas (ring 2(W-1)/W, shard (W-1)/W, exchange 1x). The
    denominator of the measured-busbw refit: measured seconds over
    these bytes is the effective beta the run actually achieved."""
    pp, do, dp = mesh["pipe"], mesh["data_outer"], mesh["data"]
    ep, sp, tp = mesh["expert"], mesh["seq"], mesh["tensor"]
    shard = pp * tp * max(1, ep)
    tokens_micro = batch_tokens / (dp * do * M)
    layers = max(1, model.n_layer // pp)
    ici = dcn = 0.0
    gbytes = model.grad_bytes * model.params / shard
    if dp > 1:
        ici += 2 * (dp - 1) / dp * gbytes
    if do > 1:
        dcn += 2 * (do - 1) / do * gbytes / max(1, dp)
    act_b = tokens_micro / sp * model.d_model * model.param_bytes
    if tp > 1:
        ici += M * layers * 2 * 2 * (tp - 1) / tp * act_b
    if pp > 1:
        from ..runtime.pipe.schedule import executor_tick_units
        n_ticks = len(executor_tick_units(schedule, M, pp))
        ici += n_ticks * act_b
    if sp > 1:
        kv_b = 2 * tokens_micro / sp * model.d_model * model.param_bytes
        ici += M * layers * (sp - 1) * kv_b
    if ep > 1:
        tok_b = tokens_micro * model.d_model * model.param_bytes
        ici += M * layers * 2 * (ep - 1) / ep * tok_b
        if do > 1:
            dcn += M * layers * 2 * (do - 1) / do * tok_b
    return {"ici": ici, "dcn": dcn}


def seed_rows(decomp, report, device_kind=None):
    """Winner-cache rows distilled from one reconciled run, in the
    exact shape ``kernel_cache.seed_entries`` ingests:

      * one ``comm_link`` row per link class with measured time on it —
        beta refit as (modeled wire bytes) / (measured exposed seconds)
        with the calibrated alpha carried over; ``calibrate_links``
        reads these on the next ``plan()``;
      * one ``op_cost`` row per Pallas tunable op the trace attributed
        time to, plus the measured compute tick — the measured per-op
        unit costs a later planner iteration prices ticks from.

    Both ops are cache-file-ONLY pseudo-ops (the comm_bench precedent):
    never registered in the op REGISTRY, never consulted by dispatch.
    """
    if device_kind is None:
        from .kernel_dispatch import device_kind as dk
        device_kind = dk()
    mesh = report.mesh
    topo = topo_bucket(mesh)
    rows = []

    # measured collective seconds per leg — TOTAL wall, not just
    # exposed, because ``_t_coll`` models raw alpha-beta time before
    # the overlap discount; legless collectives (no replica-group text
    # in the trace) default to the ICI class — the DCN leg is only ever
    # credited on positive evidence
    measured_s = {"ici": 0.0, "dcn": 0.0}
    for c in decomp.collectives:
        leg = c.get("leg") or "ici"
        measured_s[leg] += float(c.get("total_ms", 0.0)) / 1e3

    # recover the model/batch scale _score used from the report itself:
    # re-derive wire bytes with the same inputs reconcile() scored with
    model = report._model if hasattr(report, "_model") else None
    if model is not None:
        wire = _comm_bytes_by_link(model, mesh, report.schedule,
                                   report.micro_batches,
                                   report._batch_tokens)
        for kind in ("ici", "dcn"):
            t = measured_s[kind]
            b = wire[kind]
            if t <= 0 or b <= 0:
                continue
            alpha = float(report.links.get(kind, (0.0, 0.0))[0])
            beta_eff = b / t
            rows.append({
                "device_kind": device_kind, "op": "comm_link",
                "bucket": f"{topo},k{kind}", "dtype": "float32",
                "params": {
                    "kind": kind,
                    "alpha_us": round(alpha * 1e6, 3),
                    "beta_gbps": round(beta_eff / 1e9, 3),
                    "busbw_gbps": round(beta_eff / 1e9, 3),
                    "source": "reconcile",
                },
                "measured_ms": round(t * 1e3, 4),
            })

    # per-op unit costs: every Pallas tunable op with attributed time,
    # plus the compute tick itself
    unit = dict(decomp.kernels)
    unit["compute_step"] = float(decomp.terms.get("compute", 0.0))
    for op_name, ms in sorted(unit.items()):
        if ms <= 0:
            continue
        rows.append({
            "device_kind": device_kind, "op": "op_cost",
            "bucket": f"{topo},{op_name}", "dtype": "float32",
            "params": {"op": op_name, "ms_per_step": round(ms, 4),
                       "source": "reconcile"},
            "measured_ms": round(ms, 4),
        })
    return rows


def seed_cache(rows, path=None):
    """Merge rows into the winner cache (atomic; returns count)."""
    return seed_entries(rows, path=path)


# ------------------------------------------------------------ wiring

def reconcile_trace(trace_dir, *, steps=1, model, pod, mesh_shape,
                    schedule="none", micro_batches=1, offload=False,
                    batch_tokens=None, mesh=None, cache=None):
    """Parse + reconcile in one call (the CLI / engine entry). Returns
    (decomp, report) or (None, None) when the trace yields no
    decomposition — one warning, never an exception."""
    from ..profiling import step_trace
    decomp = step_trace.decompose_dir(trace_dir, steps=steps, mesh=mesh)
    if decomp is None:
        return None, None
    try:
        report = reconcile(decomp, model, pod, mesh_shape,
                           schedule=schedule,
                           micro_batches=micro_batches, offload=offload,
                           batch_tokens=batch_tokens, cache=cache)
    except Exception as e:  # noqa: BLE001 - advisory, never fatal
        logger.warning(f"reconcile: scoring failed "
                       f"({type(e).__name__}: {e})")
        return decomp, None
    # stash the scoring inputs seed_rows needs to re-derive wire bytes
    report._model = model
    report._batch_tokens = (batch_tokens if batch_tokens is not None
                            else max(1, 8 * pod.n_chips)
                            * model.max_seq_len)
    return decomp, report


def from_engine(engine, trace_dir, steps=1):
    """Reconcile a live engine's freshly captured trace: descriptors
    from the engine's model/config, the mesh from its topology, the
    schedule/microbatch/offload facts from its pipeline state. Returns
    (decomp, report) or (None, None)."""
    model = ModelDesc.from_model_config(
        getattr(engine.model, "config", None))
    pod = PodDesc.from_devices()
    mesh_shape = dict(engine.mesh.shape)
    pinfo = engine.pipeline_report() or {}
    schedule = pinfo.get("schedule", "none") or "none"
    micro = int(pinfo.get("micro_batches", 1) or 1)
    offload = bool(getattr(engine, "offload_enabled", False))
    batch_tokens = int(engine.config.train_batch_size) \
        * model.max_seq_len
    return reconcile_trace(
        trace_dir, steps=steps, model=model, pod=pod,
        mesh_shape=mesh_shape, schedule=schedule, micro_batches=micro,
        offload=offload, batch_tokens=batch_tokens, mesh=engine.mesh)
