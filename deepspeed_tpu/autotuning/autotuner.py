"""Autotuner — search ZeRO stage x micro-batch (x user axes) for the
fastest config that fits.

Counterpart of reference ``autotuning/autotuner.py:42 Autotuner``: it
profiles model info (params -> per-stage memory estimates), prunes the
micro-batch space, runs short experiments, and reports the best config.
The reference launches each experiment as a separate ``deepspeed``
job via its ResourceManager; here experiments run in-process — an engine
is built, stepped ``steps`` times with synthetic or provided data, timed,
and torn down (XLA frees device buffers when the arrays die). Results and
the tuned config are written as json like the reference's
``autotuning_results/``.
"""

import json
import os
import time

import numpy as np
import jax

from ..utils.logging import logger
from .tuner import GridSearchTuner, ModelBasedTuner, RandomTuner


class ModelInfo:
    """reference autotuner model_info: parameter count drives memory
    estimates (ZeRO-stage state factors from the ZeRO paper)."""

    def __init__(self, num_params, dtype_bytes=2):
        self.num_params = int(num_params)
        self.dtype_bytes = dtype_bytes

    def memory_per_chip(self, stage, dp_world):
        p, b = self.num_params, self.dtype_bytes
        opt = 12 * p        # fp32 master + m + v  (bytes: 4 each)
        grad = 4 * p        # fp32 grads
        params = b * p
        if stage == 0:
            return params + grad + opt
        if stage == 1:
            return params + grad + opt // dp_world
        if stage == 2:
            return params + (grad + opt) // dp_world
        return (params + grad + opt) // dp_world


class Autotuner:
    def __init__(self, model, base_config, model_info=None,
                 tuner_type="gridsearch", steps=5, warmup=2,
                 results_dir="autotuning_results", max_trials=None,
                 batch_fn=None):
        """model: zoo model (init/loss/partition_specs). base_config: the
        user's config dict (tuned fields overridden per experiment).
        batch_fn(batch_size) -> batch pytree; defaults to synthetic
        input_ids using model.config."""
        self.model = model
        self.base_config = dict(base_config)
        self.steps = steps
        self.warmup = warmup
        self.results_dir = results_dir
        self.tuner_type = tuner_type
        self.max_trials = max_trials
        self.batch_fn = batch_fn
        if model_info is None and hasattr(model, "config") and hasattr(
                model.config, "num_params"):
            model_info = ModelInfo(model.config.num_params())
        self.model_info = model_info
        self.results = []

    # ------------------------------------------------------------ space
    def search_space(self, zero_stages=(0, 1, 2, 3),
                     micro_batches=(1, 2, 4, 8)):
        return {"zero_stage": list(zero_stages),
                "micro_batch": list(micro_batches)}

    def _default_batch(self, batch_size):
        cfg = self.model.config
        seq = min(getattr(cfg, "max_seq_len", 128), 128)
        vocab = getattr(cfg, "vocab_size", 1000)
        return {"input_ids": np.random.RandomState(0).randint(
            0, vocab, (batch_size, seq)).astype(np.int32)}

    def _exp_config(self, exp):
        """Experiment dict -> full engine config. zero_stage (if tuned)
        merges into the user's zero_optimization block (preserving its
        sub-options); micro_batch (if tuned) sets the micro batch; any
        OTHER search-space key is written into the config verbatim, so
        user axes like gradient_accumulation_steps really vary."""
        config = dict(self.base_config)
        if "zero_stage" in exp:
            config["zero_optimization"] = {
                **config.get("zero_optimization", {}),
                "stage": exp["zero_stage"]}
        if "micro_batch" in exp:
            config["train_micro_batch_size_per_gpu"] = exp["micro_batch"]
        config.setdefault("train_micro_batch_size_per_gpu", 1)
        for k, v in exp.items():
            if k not in ("zero_stage", "micro_batch"):
                config[k] = v
        config.pop("train_batch_size", None)
        config.setdefault("steps_per_print", 0)
        return config

    # ------------------------------------------------------- experiments
    def run_experiment(self, exp):
        """-> result dict with samples_per_sec or error."""
        import deepspeed_tpu
        from ..utils import groups
        groups.reset()
        result = dict(exp)
        try:
            config = self._exp_config(exp)
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=self.model, config=config)
            bsz = engine.config.train_batch_size
            batch = (self.batch_fn or self._default_batch)(bsz)
            for _ in range(self.warmup):
                loss = engine.train_batch(batch)
            jax.block_until_ready(engine.state["params"])
            t0 = time.perf_counter()
            for _ in range(self.steps):
                loss = engine.train_batch(batch)
            jax.block_until_ready(engine.state["params"])
            dt = time.perf_counter() - t0
            result.update(samples_per_sec=bsz * self.steps / dt,
                          train_batch_size=bsz, loss=float(loss),
                          error=None)
        except Exception as e:  # noqa: BLE001 - OOM/invalid configs are data
            result.update(samples_per_sec=0.0, error=f"{type(e).__name__}: {e}")
        finally:
            groups.reset()
        return result

    def tune(self, space=None):
        """Run the search; returns (best_config_dict, all_results).
        tuner_type: 'gridsearch' | 'random' | 'model' (cost-model-guided
        sequential search, reference tuner/model_based_tuner.py:19 — the
        fitted ridge CostModel proposes the best predicted untried
        config after warmup; see also scheduler.ResourceManager.
        run_model_based for pool-parallel rounds)."""
        space = space or self.search_space()
        if self.tuner_type == "model":
            tuner = ModelBasedTuner(space, max_trials=self.max_trials)
        elif self.tuner_type == "random":
            tuner = RandomTuner(space, max_trials=self.max_trials)
        else:
            tuner = GridSearchTuner(space)
        logger.info(f"autotuning over {len(tuner)} experiments")
        self.results = []
        for exp in tuner:
            res = self.run_experiment(exp)
            if isinstance(tuner, ModelBasedTuner) and not res["error"]:
                # failed trials stay unrecorded -> pending-forever ->
                # excluded from the cost-model fit and best()
                tuner.record(exp, res["samples_per_sec"])
            self.results.append(res)
            logger.info(f"  exp {exp}: "
                        f"{res['samples_per_sec']:.1f} samples/s"
                        + (f" [{res['error']}]" if res["error"] else ""))
        ok = [r for r in self.results if not r["error"]]
        if not ok:
            raise RuntimeError("autotuning: every experiment failed; see "
                               "results")
        best = max(ok, key=lambda r: r["samples_per_sec"])
        best_config = self._exp_config(
            {k: v for k, v in best.items() if k in set(space)})
        self._write_results(best_config, best)
        return best_config, self.results

    def _write_results(self, best_config, best):
        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "results.json"), "w") as f:
            json.dump(self.results, f, indent=2)
        with open(os.path.join(self.results_dir, "best_config.json"),
                  "w") as f:
            json.dump({"config": best_config, "result": best}, f, indent=2)
