"""Op builder: JIT-compile C++ host extensions, register Pallas kernels.

Counterpart of reference ``op_builder/builder.py:108 OpBuilder`` (jit_load at
:480 via torch.utils.cpp_extension). On TPU there are two kinds of "op":
  * host C++ extensions (checkpoint writer, async IO) — compiled here with
    g++ into a shared library loaded via ctypes (no pybind11 in-image);
  * Pallas kernels — pure python, "building" = importing; the builder
    exists so ``create_op_builder(name).load()`` works uniformly, matching
    the reference's accelerator seam
    (abstract_accelerator.py:274 create_op_builder).
"""

import ctypes
import hashlib
import os
import shutil
import subprocess

from ..utils.logging import logger

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc")
def _host_isa_tag():
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    return line.strip()
    except OSError:
        pass
    import platform
    return platform.processor() or platform.machine()


_DEFAULT_BUILD_DIR = os.environ.get(
    "DSTPU_BUILD_DIR",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "build"))


class OpBuilder:
    NAME = None

    def sources(self):
        return []

    def include_paths(self):
        return [_CSRC]

    def cxx_args(self):
        return ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread"]

    def is_compatible(self):
        return shutil.which("g++") is not None

    def absolute_sources(self):
        return [s if os.path.isabs(s) else os.path.join(_CSRC, s)
                for s in self.sources()]

    def _build_hash(self):
        h = hashlib.sha256()
        for s in self.absolute_sources():
            with open(s, "rb") as f:
                h.update(f.read())
        for s in self.header_deps():
            if os.path.exists(s):
                with open(s, "rb") as f:
                    h.update(f.read())
        h.update(" ".join(self.cxx_args()).encode())
        if "-march=native" in self.cxx_args():
            # ISA-specific builds must not be served to other hosts from a
            # shared cache (NFS homes under the ssh/pdsh launcher)
            h.update(_host_isa_tag().encode())
        return h.hexdigest()[:16]

    def header_deps(self):
        """Headers whose changes must invalidate the cache."""
        return [os.path.join(_CSRC, "pool.h")]

    def load(self):
        """Compile (if needed) and return the loaded ctypes CDLL."""
        if not self.is_compatible():
            raise RuntimeError(f"op '{self.NAME}' not buildable: g++ missing")
        os.makedirs(_DEFAULT_BUILD_DIR, exist_ok=True)
        so_path = os.path.join(_DEFAULT_BUILD_DIR,
                               f"{self.NAME}-{self._build_hash()}.so")
        if not os.path.exists(so_path):
            cmd = (["g++"] + self.cxx_args()
                   + [f"-I{p}" for p in self.include_paths()]
                   + self.absolute_sources() + ["-o", so_path + ".tmp"])
            logger.info(f"building op '{self.NAME}': {' '.join(cmd)}")
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(so_path + ".tmp", so_path)
        return ctypes.CDLL(so_path)


class CkptWriterBuilder(OpBuilder):
    NAME = "ckpt_writer"

    def sources(self):
        return ["ckpt_writer.cpp"]


class AsyncIOBuilder(OpBuilder):
    """reference op_builder/async_io.py AsyncIOBuilder (csrc/aio/)."""
    NAME = "async_io"

    def sources(self):
        return ["aio.cpp"]


class CPUAdamBuilder(OpBuilder):
    """reference op_builder/cpu_adam.py (csrc/adam/ SIMD kernels)."""
    NAME = "cpu_adam"

    def sources(self):
        return ["cpu_adam.cpp"]

    def cxx_args(self):
        # -march=native for auto-vectorization; NOT -ffast-math — Inf/NaN
        # grads must propagate so overflow checks downstream see them
        return super().cxx_args() + ["-march=native", "-fno-math-errno"]


class _PallasBuilder(OpBuilder):
    """Pallas kernels: load() imports the python module."""
    MODULE = None

    def is_compatible(self):
        return True

    def load(self):
        import importlib
        return importlib.import_module(self.MODULE)


class FlashAttnBuilder(_PallasBuilder):
    NAME = "flash_attn"
    MODULE = "deepspeed_tpu.ops.pallas.flash_attention"


class FusedAdamBuilder(_PallasBuilder):
    NAME = "fused_adam"
    MODULE = "deepspeed_tpu.ops.optimizers"


class QuantizerBuilder(_PallasBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.pallas.quantization"


BUILDERS = {
    b.NAME: b for b in (CkptWriterBuilder, AsyncIOBuilder, CPUAdamBuilder,
                        FlashAttnBuilder, FusedAdamBuilder,
                        QuantizerBuilder)
}


def create_op_builder(name):
    """reference accelerator/abstract_accelerator.py:274 contract."""
    if name not in BUILDERS:
        raise ValueError(f"unknown op builder '{name}'; "
                         f"available: {sorted(BUILDERS)}")
    return BUILDERS[name]()
