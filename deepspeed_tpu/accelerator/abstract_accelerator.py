"""Accelerator abstraction — the hardware seam.

Counterpart of reference ``accelerator/abstract_accelerator.py:10
DeepSpeedAccelerator`` (~70 abstract methods). Every subsystem reaches
hardware through ``get_accelerator()`` so a backend swap is one class.

TPU-idiomatic deltas from the CUDA ABC:
  * Streams/Events (reference :91-111) have no raw analogue — XLA dispatch
    is already async.  ``Stream`` is a no-op context; ``Event`` records via
    ``jax.block_until_ready`` fencing.  ``synchronize()`` is a real barrier.
  * Pinned memory (reference :258-267) maps to ordinary host numpy — TPU
    D2H goes through the runtime's own staging buffers.
  * Graphs (reference :209-219): ``jax.jit`` IS the graph capture; the
    graph API here just tags functions.
"""

import abc


class DeepSpeedAccelerator(abc.ABC):
    """Reference accelerator/abstract_accelerator.py:10."""

    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # ------------------------------------------------------- device mgmt
    # reference :33-59
    @abc.abstractmethod
    def is_available(self):
        ...

    @abc.abstractmethod
    def device_name(self, device_index=None):
        ...

    @abc.abstractmethod
    def device(self, device_index=None):
        ...

    @abc.abstractmethod
    def device_count(self):
        ...

    @abc.abstractmethod
    def current_device(self):
        ...

    @abc.abstractmethod
    def current_device_name(self):
        ...

    def set_device(self, device_index):
        """No-op under SPMD: jax owns device placement."""
        return None

    @abc.abstractmethod
    def synchronize(self, device_index=None):
        ...

    # ------------------------------------------------------------- RNG
    # reference :62-88 — jax PRNG keys are functional; the accelerator
    # carries a convenience root key for non-functional call sites.
    @abc.abstractmethod
    def manual_seed(self, seed):
        ...

    @abc.abstractmethod
    def initial_seed(self):
        ...

    @abc.abstractmethod
    def default_generator(self):
        """Returns the current root PRNG key."""
        ...

    # --------------------------------------------------- streams/events
    # reference :91-111
    def stream(self, stream=None):
        return _NullStream()

    def current_stream(self, device_index=None):
        return _NullStream()

    def default_stream(self, device_index=None):
        return _NullStream()

    def Stream(self, *args, **kwargs):
        return _NullStream()

    def Event(self, *args, **kwargs):
        return _NullEvent()

    # ------------------------------------------------------ memory stats
    # reference :114-164
    @abc.abstractmethod
    def memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def max_memory_allocated(self, device_index=None):
        ...

    @abc.abstractmethod
    def total_memory(self, device_index=None):
        ...

    @abc.abstractmethod
    def available_memory(self, device_index=None):
        ...

    def memory_stats(self, device_index=None):
        return {}

    def reset_peak_memory_stats(self, device_index=None):
        return None

    def empty_cache(self):
        return None

    # ----------------------------------------------------- dtype support
    # reference :167-177
    @abc.abstractmethod
    def is_bf16_supported(self):
        ...

    @abc.abstractmethod
    def is_fp16_supported(self):
        ...

    @abc.abstractmethod
    def supported_dtypes(self):
        ...

    # ----------------------------------------------------------- naming
    # reference :201
    def communication_backend_name(self):
        return self._communication_backend_name

    # ------------------------------------------------------------ graphs
    # reference :209-219
    def create_graph(self):
        return None

    def capture_to_graph(self, graph, **kwargs):
        import contextlib
        return contextlib.nullcontext()

    def replay_graph(self, graph):
        return None

    # ----------------------------------------------------- profiler tags
    # reference :189-194 range_push/pop (NVTX)
    def range_push(self, msg):
        return None

    def range_pop(self):
        return None

    # ------------------------------------------------------ pinned memory
    # reference :258-267
    def pin_memory(self, tensor, align_bytes=1):
        return tensor

    def is_pinned(self, tensor):
        return True

    # -------------------------------------------------------- op builders
    # reference :270-289
    @abc.abstractmethod
    def op_builder_dir(self):
        ...

    @abc.abstractmethod
    def create_op_builder(self, op_name):
        ...

    @abc.abstractmethod
    def get_op_builder(self, op_name):
        ...


class _NullStream:
    """XLA dispatch is already asynchronous; a stream is a no-op scope."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def synchronize(self):
        import jax
        jax.effects_barrier()

    def wait_event(self, event):
        return None

    def wait_stream(self, stream):
        return None


class _NullEvent:
    """Event semantics via value fencing (jax.block_until_ready)."""

    def __init__(self):
        self._fence = None

    def record(self, stream=None, value=None):
        self._fence = value

    def synchronize(self):
        if self._fence is not None:
            import jax
            jax.block_until_ready(self._fence)

    def wait(self, stream=None):
        self.synchronize()

    def query(self):
        return True

    def elapsed_time(self, end_event):
        return 0.0
