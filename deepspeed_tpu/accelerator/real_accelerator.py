"""Runtime accelerator detection.

Counterpart of reference ``accelerator/real_accelerator.py:51
get_accelerator()``: env override (``DS_ACCELERATOR``, reference :59-102)
else probe (reference order xpu→npu→mps→hpu→cuda→cpu, :106-162; here
tpu→cpu — gpu-via-jax would slot in between).
"""

import os

_accelerator = None


def get_accelerator():
    global _accelerator
    if _accelerator is not None:
        return _accelerator

    override = os.environ.get("DS_ACCELERATOR",
                              os.environ.get("DSTPU_ACCELERATOR"))
    if override:
        set_accelerator(_make(override))
        return _accelerator

    from .tpu_accelerator import TpuAccelerator
    acc = TpuAccelerator()
    if not acc.is_available():
        acc = _make("cpu")
    set_accelerator(acc)
    return _accelerator


def set_accelerator(accel):
    """Reference real_accelerator.py:30 set_accelerator."""
    global _accelerator
    _accelerator = accel
    return _accelerator


def _make(name):
    from .tpu_accelerator import CpuAccelerator, TpuAccelerator
    name = name.lower()
    if name == "tpu":
        return TpuAccelerator()
    if name == "cpu":
        return CpuAccelerator()
    raise ValueError(
        f"DS_ACCELERATOR='{name}' not supported; expected 'tpu' or 'cpu'")


def is_current_accelerator_supported():
    return get_accelerator().is_available()
