"""Accelerator abstraction package (reference ``accelerator/``)."""

from .abstract_accelerator import DeepSpeedAccelerator
from .real_accelerator import get_accelerator, set_accelerator
from .tpu_accelerator import CpuAccelerator, TpuAccelerator

__all__ = ["DeepSpeedAccelerator", "get_accelerator", "set_accelerator",
           "TpuAccelerator", "CpuAccelerator"]
