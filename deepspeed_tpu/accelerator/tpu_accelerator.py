"""TPU (and CPU-mesh) accelerator implementations.

Counterpart of reference ``accelerator/cuda_accelerator.py`` (~360 LoC) —
the jax backend fills the role torch.cuda does there. A single class body
serves both platforms; only the platform string and comm backend name
differ (reference keeps per-backend files: cuda:27 nccl, cpu:18 ccl).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from .abstract_accelerator import DeepSpeedAccelerator


class TpuAccelerator(DeepSpeedAccelerator):
    _PLATFORM = "tpu"

    def __init__(self):
        super().__init__()
        self._name = self._PLATFORM
        # XLA collectives over ICI/DCN — the role NCCL plays on CUDA.
        self._communication_backend_name = "xla"
        self._seed = 0
        self._root_key = jax.random.key(0)

    # ------------------------------------------------------- device mgmt
    def _devices(self):
        try:
            return jax.devices(self._PLATFORM)
        except RuntimeError:
            return []

    def is_available(self):
        return len(self._devices()) > 0

    def device_name(self, device_index=None):
        if device_index is None:
            return self._PLATFORM
        return f"{self._PLATFORM}:{device_index}"

    def device(self, device_index=None):
        devs = self._devices()
        return devs[device_index or 0] if devs else None

    def device_count(self):
        return len(self._devices())

    def current_device(self):
        return 0  # SPMD: one process drives all local devices

    def current_device_name(self):
        return self.device_name(0)

    def synchronize(self, device_index=None):
        jax.effects_barrier()

    # ------------------------------------------------------------- RNG
    def manual_seed(self, seed):
        self._seed = int(seed)
        self._root_key = jax.random.key(self._seed)

    def initial_seed(self):
        return self._seed

    def default_generator(self):
        return self._root_key

    def split_key(self):
        """Functional convenience: advance and return a fresh subkey."""
        self._root_key, sub = jax.random.split(self._root_key)
        return sub

    # ------------------------------------------------------ memory stats
    def _stats(self, device_index=None):
        dev = self.device(device_index)
        if dev is None:
            return {}
        try:
            return dev.memory_stats() or {}
        except (AttributeError, jax.errors.JaxRuntimeError):
            return {}

    def memory_allocated(self, device_index=None):
        return self._stats(device_index).get("bytes_in_use", 0)

    def max_memory_allocated(self, device_index=None):
        return self._stats(device_index).get("peak_bytes_in_use", 0)

    def total_memory(self, device_index=None):
        return self._stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        s = self._stats(device_index)
        return s.get("bytes_limit", 0) - s.get("bytes_in_use", 0)

    def memory_stats(self, device_index=None):
        return self._stats(device_index)

    # ----------------------------------------------------- dtype support
    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8,
                jnp.int32, jnp.float8_e4m3fn, jnp.float8_e5m2]

    # ------------------------------------------------------ pinned memory
    def pin_memory(self, tensor, align_bytes=1):
        # Host staging: contiguous numpy is what the TPU runtime DMAs from.
        return np.ascontiguousarray(tensor)

    # -------------------------------------------------------- op builders
    def op_builder_dir(self):
        return "deepspeed_tpu.op_builder"

    def create_op_builder(self, op_name):
        from ..op_builder.builder import create_op_builder
        return create_op_builder(op_name)

    def get_op_builder(self, op_name):
        from ..op_builder.builder import BUILDERS
        return BUILDERS.get(op_name)


class CpuAccelerator(TpuAccelerator):
    """CPU mesh (tests, virtual-device sharding validation).

    Reference accelerator/cpu_accelerator.py — comm backend 'ccl' (:18);
    here the same XLA collectives run over the host backend.
    """
    _PLATFORM = "cpu"

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla"

    def is_bf16_supported(self):
        return True  # emulated, numerically correct

    def total_memory(self, device_index=None):
        try:
            return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
        except (ValueError, OSError):
            return 0

    def available_memory(self, device_index=None):
        try:
            return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_AVPHYS_PAGES")
        except (ValueError, OSError):
            return 0
