"""Environment/compatibility report — the ``dstpu_report`` CLI.

Counterpart of reference ``deepspeed/env_report.py`` (``ds_report``):
versions, detected hardware, and an op-compatibility matrix (there: which
CUDA extensions build; here: which Pallas kernels and native host
extensions are usable on this machine).
"""

import sys


GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _try(fn):
    try:
        fn()
        return True, ""
    except Exception as e:  # noqa: BLE001 - report, don't crash
        return False, f"{type(e).__name__}: {e}"


def op_compatibility():
    """[(op_name, ok, detail)]. Mirrors ds_report's op matrix."""
    import numpy as np

    def flash():
        import jax.numpy as jnp
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
        q = jnp.zeros((1, 8, 1, 8), jnp.float32)
        flash_attention(q, q, q)

    def quant():
        import jax.numpy as jnp
        from deepspeed_tpu.ops.pallas.quantization import quantize_blockwise
        quantize_blockwise(jnp.zeros((256,), jnp.float32))

    def native_ckpt():
        from deepspeed_tpu.ops.native.ckpt_writer import Writer
        w = Writer(threads=1)
        w.close()

    rows = []
    for name, fn in [("pallas_flash_attention", flash),
                     ("pallas_quantizer", quant),
                     ("native_ckpt_writer", native_ckpt)]:
        ok, detail = _try(fn)
        rows.append((name, ok, detail))
    return rows


def report(file=sys.stdout):
    import jax
    import jaxlib
    import numpy as np

    p = lambda *a: print(*a, file=file)
    p("-" * 64)
    p("DeepSpeed-TPU environment report")
    p("-" * 64)
    import deepspeed_tpu
    p(f"deepspeed_tpu ........ {deepspeed_tpu.__version__}")
    p(f"python ............... {sys.version.split()[0]}")
    p(f"jax .................. {jax.__version__}")
    p(f"jaxlib ............... {jaxlib.__version__}")
    p(f"numpy ................ {np.__version__}")
    p("-" * 64)
    try:
        devs = jax.devices()
        p(f"default backend ...... {jax.default_backend()}")
        p(f"devices .............. {len(devs)} x {devs[0].platform}"
          f" ({devs[0].device_kind})")
        p(f"process count ........ {jax.process_count()}")
    except Exception as e:  # noqa: BLE001
        p(f"device probe failed .. {e}")
    p("-" * 64)
    p("op compatibility")
    for name, ok, detail in op_compatibility():
        mark = GREEN_OK if ok else RED_NO
        p(f"  {name:28s} {mark}{'  ' + detail if detail else ''}")
    p("-" * 64)
    p("launcher")
    from shutil import which
    p(f"  ssh runner ................. {GREEN_OK}")
    p(f"  pdsh runner ................ "
      f"{GREEN_OK if which('pdsh') else RED_NO}")
    p(f"  slurm (srun) ............... "
      f"{GREEN_OK if which('srun') else RED_NO}")
    p("  elastic supervision ........ dstpu --elastic "
      "[--max_elastic_restarts N --min_hosts M] (whole-world restart "
      "on membership change)")
    p("-" * 64)


def main():
    report()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
