"""Elastic agent: supervise the worker world, restart on membership change.

Counterpart of reference ``elasticity/elastic_agent.py:28 DSElasticAgent``
(a torch-elastic LocalElasticAgent subclass: rendezvous, health watch,
restart-on-membership-change) and the ``bin/ds_elastic`` CLI. The TPU
realization supervises the launcher's worker processes directly:
jax.distributed worlds cannot survive a member loss (the coordinator and
every collective assume a fixed world), so the recovery unit is the WHOLE
world — on any worker failure the agent tears the remaining workers down,
recomputes the world from the surviving hosts (validated against the
elastic config's admissible chip counts when one is given), and
relaunches. Workers resume from the latest checkpoint (the engine's
durable-`latest` pointer), which is the reference's recovery model too.
"""

import os
import re
import time

from ..utils.logging import logger
from .elasticity import compute_elastic_config, ElasticityError


class WorldFailure(Exception):
    """Raised when the world cannot be restarted (too few hosts /
    restart budget exhausted / inadmissible world size)."""


class DSElasticAgent:
    """Drive ``launch_fn(hosts) -> [(host, subprocess.Popen), ...]``
    through failures.

    Args:
      launch_fn: starts one worker per host for the CURRENT world and
        returns (host, proc) pairs. Each relaunch gets env/rendezvous for
        the new world size (the launcher rebuilds worker commands).
      hosts: initial host list.
      ds_config: optional config dict with an 'elasticity' block — used to
        validate shrunken world sizes (reference compute_elastic_config).
      chips_per_host: multiplied into world size for validation.
      max_restarts: restart budget (reference torch-elastic semantics).
      min_hosts: refuse to shrink below this.
      poll_s: liveness poll interval.
      on_restart(gen, hosts): hook (tests observe membership changes).
      heartbeat_timeout_s: when set, a worker whose heartbeat file
        (``heartbeat_path(host)``; workers beat via
        ``DSTPU_HEARTBEAT_FILE`` -> utils.touch_heartbeat, once per
        train_batch) goes stale for longer than this is treated as HUNG:
        killed and routed through the same restart-from-latest path as a
        worker that died. A worker that never beats is measured from its
        launch time. None (default) disables hang detection.
      heartbeat_dir: where heartbeat files live (created on demand;
        default ``/tmp/dstpu_heartbeats_<pid>``). The launcher must
        export ``DSTPU_HEARTBEAT_FILE=agent.heartbeat_path(host)`` into
        each worker's env for beats to land. IMPORTANT: the agent stats
        these files on ITS host — with remote (e.g. ssh-launched)
        workers, heartbeat_dir must be on a filesystem shared between
        the agent and every worker (the same shared-FS assumption the
        checkpoint 'latest' protocol already makes); the /tmp default
        is only correct for local workers. A non-shared dir would make
        every healthy remote worker look hung.
    """

    def __init__(self, launch_fn, hosts, ds_config=None, chips_per_host=1,
                 max_restarts=10, min_hosts=1, poll_s=0.5,
                 on_restart=None, heartbeat_timeout_s=None,
                 heartbeat_dir=None):
        self.launch_fn = launch_fn
        self.hosts = list(hosts)
        self.ds_config = ds_config
        self.chips_per_host = chips_per_host
        self.max_restarts = max_restarts
        self.min_hosts = min_hosts
        self.poll_s = poll_s
        self.on_restart = on_restart
        self.restart_count = 0
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.heartbeat_dir = heartbeat_dir or os.path.join(
            "/tmp", f"dstpu_heartbeats_{os.getpid()}")

    # ------------------------------------------------------------ heartbeat
    def heartbeat_path(self, host):
        """Heartbeat file for ``host`` — export as DSTPU_HEARTBEAT_FILE
        in that worker's env."""
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", str(host))
        return os.path.join(self.heartbeat_dir, f"{safe}.hb")

    def _clear_heartbeats(self, hosts):
        """Before (re)launch: stale beats from the previous generation
        must not count for — or against — the new one."""
        if self.heartbeat_timeout_s is None:
            return
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        for h in hosts:
            try:
                os.remove(self.heartbeat_path(h))
            except OSError:
                pass

    def _hung(self, host, launched_at):
        """True when hang detection is on and ``host`` has not beaten
        (or been launched) within the timeout."""
        if self.heartbeat_timeout_s is None:
            return False
        beat = launched_at
        try:
            beat = max(beat, os.path.getmtime(self.heartbeat_path(host)))
        except OSError:
            pass
        return (time.time() - beat) > self.heartbeat_timeout_s

    # ------------------------------------------------------------ internals
    def _validate_world(self, hosts):
        if len(hosts) < max(1, self.min_hosts):
            raise WorldFailure(
                f"only {len(hosts)} hosts left (< min_hosts="
                f"{max(1, self.min_hosts)})")
        if self.ds_config and "elasticity" in self.ds_config:
            world = len(hosts) * self.chips_per_host
            try:
                compute_elastic_config(self.ds_config, world_size=world)
            except ElasticityError as e:
                raise WorldFailure(
                    f"world size {world} not admissible under the elastic "
                    f"config: {e}") from e

    def _supervise(self, procs):
        """Block until every worker exits. On the FIRST failure, terminate
        the rest (a jax.distributed world is all-or-nothing). A worker
        that HANGS (no heartbeat within heartbeat_timeout_s) is killed
        and counted as failed — same recovery path as a dead one.
        Returns (ok, failed_hosts)."""
        live = dict(procs)
        failed = []
        launched_at = time.time()
        while live:
            for host, p in list(live.items()):
                rc = p.poll()
                if rc is None:
                    if self._hung(host, launched_at):
                        logger.warning(
                            f"elastic agent: worker on {host} missed its "
                            f"heartbeat for > {self.heartbeat_timeout_s}s"
                            f"; killing hung worker")
                        try:
                            p.kill()
                            p.wait(timeout=5)   # reap, no zombie
                        except Exception:  # noqa: BLE001
                            pass
                        del live[host]
                        failed.append(host)
                    continue
                del live[host]
                if rc != 0:
                    logger.warning(
                        f"elastic agent: worker on {host} exited rc={rc}")
                    failed.append(host)
            if failed and live:
                logger.warning(
                    f"elastic agent: tearing down {len(live)} surviving "
                    "workers for world restart")
                for p in live.values():
                    p.terminate()
                deadline = time.time() + 10
                for p in live.values():
                    try:
                        p.wait(timeout=max(0.1, deadline - time.time()))
                    except Exception:  # noqa: BLE001
                        p.kill()
                live.clear()
            if live:
                time.sleep(self.poll_s)
        return (not failed), failed

    # ---------------------------------------------------------------- run
    def run(self):
        """Launch and supervise until clean exit. Returns the final host
        list. Raises WorldFailure when recovery is impossible."""
        self._validate_world(self.hosts)
        while True:
            gen = self.restart_count
            logger.info(
                f"elastic agent: launching generation {gen} on "
                f"{len(self.hosts)} hosts")
            self._clear_heartbeats(self.hosts)
            procs = self.launch_fn(list(self.hosts))
            ok, failed = self._supervise(procs)
            if ok:
                return list(self.hosts)
            # membership change: drop the failed hosts, restart the rest
            self.hosts = [h for h in self.hosts if h not in failed]
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                raise WorldFailure(
                    f"restart budget exhausted ({self.max_restarts})")
            self._validate_world(self.hosts)
            if self.on_restart is not None:
                self.on_restart(self.restart_count, list(self.hosts))
