"""Elastic agent: supervise the worker world, restart on membership change.

Counterpart of reference ``elasticity/elastic_agent.py:28 DSElasticAgent``
(a torch-elastic LocalElasticAgent subclass: rendezvous, health watch,
restart-on-membership-change) and the ``bin/ds_elastic`` CLI. The TPU
realization supervises the launcher's worker processes directly:
jax.distributed worlds cannot survive a member loss (the coordinator and
every collective assume a fixed world), so the recovery unit is the WHOLE
world — on any worker failure the agent tears the remaining workers down,
recomputes the surviving admissible TOPOLOGY (not just a world size: dp
is re-derived with the configured tp/ep/pp/sp factors held fixed, then
validated against the elastic config's admissible chip counts), and
relaunches. Workers resume from the newest checkpoint generation through
the tiered load path — the in-memory hot tier's surviving peer replicas
first (runtime/checkpoint_engine/hot_tier.py; the agent purges dead
hosts' stores so replicas a lost host held can never serve a restore),
then the durable 'latest' pointer.

Failures are CLASSIFIED, with per-class restart backoff:

  ``dead``          the worker process exited non-zero — the host is
                    dropped and the world shrinks;
  ``hung``          the worker stopped beating (heartbeat_timeout_s) —
                    killed and dropped like a dead one;
  ``corrupt_ckpt``  the worker exited with CORRUPT_CKPT_EXIT_CODE
                    (the engine found generations but none loadable).
                    The HOST is healthy — it is kept, and the same
                    world relaunches after the (longer) corrupt-class
                    backoff, giving shared storage time to settle.
  ``dead_slice``    slice-aware refinement of dead/hung: EVERY host of
                    one slice failed together (slice preemption, ICI
                    fabric loss). The whole slice is dropped, its
                    hot-tier stores purged, and the world relaunches at
                    ``data_outer - 1`` — surviving slices keep their
                    intra-slice dp; the cross-slice replicas they hold
                    (hot_tier ``replica-from-*`` / ``zero-replica-*``)
                    are exactly what the relaunch restores from.
  ``preempted``     the worker exited PREEMPTED_EXIT_CODE after a
                    graceful SIGTERM drain (it finished the in-flight
                    step, forced a hot+replica push, dumped its flight
                    recorder). The host is healthy and KEPT; the
                    relaunch takes no backoff penalty. The agent
                    forwards its own SIGTERM to the workers, so a
                    maintenance notice delivered to the agent drains
                    the whole world.
"""

import inspect
import os
import re
import socket
import time

from ..utils import fault_injection
from ..utils.logging import logger
from .elasticity import compute_elastic_config, ElasticityError

# Workers exit with this code when checkpoint generations exist but NONE
# is loadable: engine.load_checkpoint translates its
# CheckpointCorruptionError into SystemExit(44) whenever
# ELASTIC_GENERATION is in the env (the launcher's elastic launch_fn
# exports it), so any agent-supervised worker reaches this path without
# writing translation code itself. Distinct from a crash: the host is
# fine, the CHECKPOINT tier is not — the agent keeps the world and
# backs off instead of shrinking it.
CORRUPT_CKPT_EXIT_CODE = 44

# Workers exit with this code after a preemption-graceful drain: the
# engine's SIGTERM handler sets a flag, the in-flight train_batch
# finishes, _preempt_drain forces one hot+replica push plus a flight
# dump, then SystemExit(43). Distinct from both a crash and a corrupt
# checkpoint: the host is healthy AND the newest generation is already
# in the hot tier — keep the host, relaunch with zero backoff.
PREEMPTED_EXIT_CODE = 43

FAILURE_DEAD = "dead"
FAILURE_HUNG = "hung"
FAILURE_CORRUPT = "corrupt_ckpt"
FAILURE_DEAD_SLICE = "dead_slice"
FAILURE_PREEMPTED = "preempted"

_LOCAL_HOST_NAMES = ("localhost", "127.0.0.1", "::1", "")


def _host_is_local(host):
    h = str(host)
    if h in _LOCAL_HOST_NAMES:
        return True
    try:
        return h in (socket.gethostname(), socket.getfqdn())
    except OSError:
        return False


class WorldFailure(Exception):
    """Raised when the world cannot be restarted (too few hosts /
    restart budget exhausted / inadmissible world size)."""


class DSElasticAgent:
    """Drive ``launch_fn(hosts) -> [(host, subprocess.Popen), ...]``
    through failures.

    Args:
      launch_fn: starts one worker per host for the CURRENT world and
        returns (host, proc) pairs. Each relaunch gets env/rendezvous for
        the new world size (the launcher rebuilds worker commands). A
        two-argument ``launch_fn(hosts, topology)`` also receives the
        surviving topology dict computed by :meth:`compute_topology`.
      hosts: initial host list.
      ds_config: optional config dict with an 'elasticity' block — used to
        validate shrunken world sizes (reference compute_elastic_config).
      chips_per_host: multiplied into world size for validation.
      tensor_parallel / expert_parallel / pipe_parallel / seq_parallel:
        fixed model-sharding factors of the topology; the surviving dp is
        ``world // (tp*ep*pp*sp)`` and a surviving world these do not
        divide is inadmissible (a host loss cannot shrink tensor
        parallelism — only dp shrinks).
      max_restarts: restart budget (reference torch-elastic semantics).
      min_hosts: refuse to shrink below this.
      poll_s: liveness poll interval.
      on_restart(gen, hosts): hook (tests observe membership changes).
      restart_backoff_s: per-failure-class seconds to wait before the
        relaunch, e.g. ``{"dead": 0, "hung": 0, "corrupt_ckpt": 5}``
        (the defaults). Corrupt-checkpoint failures keep the SAME world;
        dead/hung drop the failed hosts.
      heartbeat_timeout_s: when set, a worker whose heartbeat file
        (``heartbeat_path(host)``; workers beat via
        ``DSTPU_HEARTBEAT_FILE`` -> utils.touch_heartbeat, once per
        train_batch) goes stale for longer than this is treated as HUNG:
        killed and routed through the same restart-from-latest path as a
        worker that died. A worker that never beats is measured from its
        launch time. None (default) disables hang detection.
      heartbeat_dir: where heartbeat files live (created on demand;
        default ``/tmp/dstpu_heartbeats_<pid>``). The launcher must
        export ``DSTPU_HEARTBEAT_FILE=agent.heartbeat_path(host)`` into
        each worker's env for beats to land. IMPORTANT: the agent stats
        these files on ITS host — with remote (e.g. ssh-launched)
        workers, heartbeat_dir must be on a filesystem shared between
        the agent and every worker (the same shared-FS assumption the
        checkpoint 'latest' protocol already makes). The /tmp default
        is only correct for local workers — a non-shared dir makes
        every healthy remote worker look hung, so the agent REFUSES to
        start when hang detection is on, any host is non-local, and
        heartbeat_dir was left at its default (an explicitly-given dir
        is trusted, with a one-time shared-FS warning).
      flightrec_root: flight-recorder dump dir (monitor/
        flight_recorder.py). When set, the agent (a) exports
        ``DSTPU_FLIGHTREC_DIR`` / ``DSTPU_FLIGHTREC_NODE`` to workers
        (which also arms telemetry's 'auto' resolution), and (b) on a
        membership change reads each failed host's dump and attaches
        its event tail to the failure classification
        (``last_failure_records``) — so "why did host 3 die" starts
        from the victim's own black box: the last steps it completed,
        the fault points that fired, and the checkpoint tier its
        generation restored from.
      hot_root: hot-tier store root (checkpoint_engine/hot_tier.py).
        When set, the agent (a) exports the replica ring to workers via
        ``DSTPU_HOT_TIER_ROOT`` / ``DSTPU_HOT_NODE`` / ``DSTPU_HOT_PEERS``
        expectations (the launcher copies agent.worker_env(host) into
        each worker's env) and (b) purges a failed host's store on
        membership change — a dead host's RAM is gone; its replicas on
        survivors are exactly what the relaunched world restores from.
      slices: optional ``{host: slice_id}`` membership map (hostfile
        ``slice=K`` tokens via the launcher). With more than one
        distinct slice the agent becomes SLICE-AWARE: worker_env
        additionally exports ``DSTPU_HOT_SLICE`` / ``DSTPU_HOT_SLICES``
        so the hot tier places replicas cross-slice, compute_topology
        reports (and shrinks) ``do`` = surviving data_outer degree, and
        a failure that takes out EVERY host of one slice is classified
        ``dead_slice`` (firing the 'slice_loss' fault point once per
        lost slice) instead of N independent host losses.
    """

    def __init__(self, launch_fn, hosts, ds_config=None, chips_per_host=1,
                 max_restarts=10, min_hosts=1, poll_s=0.5,
                 on_restart=None, heartbeat_timeout_s=None,
                 heartbeat_dir=None, tensor_parallel=1, expert_parallel=1,
                 pipe_parallel=1, seq_parallel=1, restart_backoff_s=None,
                 hot_root=None, flightrec_root=None, slices=None):
        self.launch_fn = launch_fn
        self.hosts = list(hosts)
        self.ds_config = ds_config
        self.chips_per_host = chips_per_host
        self.tensor_parallel = int(tensor_parallel)
        self.expert_parallel = int(expert_parallel)
        self.pipe_parallel = int(pipe_parallel)
        self.seq_parallel = int(seq_parallel)
        self.max_restarts = max_restarts
        self.min_hosts = min_hosts
        self.poll_s = poll_s
        self.on_restart = on_restart
        self.restart_count = 0
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self._heartbeat_dir_defaulted = heartbeat_dir is None
        self.heartbeat_dir = heartbeat_dir or os.path.join(
            "/tmp", f"dstpu_heartbeats_{os.getpid()}")
        backoff = {FAILURE_DEAD: 0.0, FAILURE_HUNG: 0.0,
                   FAILURE_CORRUPT: 5.0, FAILURE_DEAD_SLICE: 0.0,
                   FAILURE_PREEMPTED: 0.0}
        backoff.update(restart_backoff_s or {})
        self.restart_backoff_s = backoff
        self.hot_root = hot_root
        self.flightrec_root = flightrec_root
        self.slice_of = {str(h): str(s)
                         for h, s in (slices or {}).items()}
        self.slice_aware = len({self._slice_of(h)
                                for h in self.hosts}) > 1
        # live worker procs of the current generation — the SIGTERM
        # forwarding handler terminates these so a maintenance notice
        # to the AGENT drains every worker
        self._live_procs = {}
        self._preempt_notice = False
        self.topology = self.compute_topology(self.hosts, validate=False)
        # host -> failure class of the most recent membership change
        self.last_failures = {}
        # host -> parsed flight-recorder dump of the most recent
        # membership change (only hosts whose dump was readable)
        self.last_failure_records = {}
        self._check_heartbeat_dir()

    # ------------------------------------------------------------ heartbeat
    def _check_heartbeat_dir(self):
        """The documented /tmp pitfall, enforced: hang detection against
        a non-shared heartbeat dir makes every healthy remote worker
        look hung — fail fast instead of killing a healthy world."""
        if self.heartbeat_timeout_s is None:
            return
        remote = [h for h in self.hosts if not _host_is_local(h)]
        if not remote:
            return
        if self._heartbeat_dir_defaulted:
            raise WorldFailure(
                f"heartbeat hang detection is enabled with remote hosts "
                f"{remote[:3]}{'...' if len(remote) > 3 else ''} but "
                f"heartbeat_dir was left at its /tmp default "
                f"({self.heartbeat_dir}), which is host-local: every "
                f"healthy remote worker would look hung and be killed. "
                f"Pass heartbeat_dir on a filesystem shared between the "
                f"agent and every worker (the same shared-FS assumption "
                f"the checkpoint 'latest' protocol makes)")
        logger.warning(
            f"heartbeat hang detection with remote hosts: "
            f"heartbeat_dir={self.heartbeat_dir} must be on a filesystem "
            f"shared between the agent and every worker, or healthy "
            f"workers will be killed as hung")

    def heartbeat_path(self, host):
        """Heartbeat file for ``host`` — export as DSTPU_HEARTBEAT_FILE
        in that worker's env."""
        safe = re.sub(r"[^A-Za-z0-9_.-]", "_", str(host))
        return os.path.join(self.heartbeat_dir, f"{safe}.hb")

    def _clear_heartbeats(self, hosts):
        """Before (re)launch: stale beats from the previous generation
        must not count for — or against — the new one."""
        if self.heartbeat_timeout_s is None:
            return
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        for h in hosts:
            try:
                os.remove(self.heartbeat_path(h))
            except OSError:
                pass

    def _hung(self, host, launched_at):
        """True when hang detection is on and ``host`` has not beaten
        (or been launched) within the timeout."""
        if self.heartbeat_timeout_s is None:
            return False
        beat = launched_at
        try:
            beat = max(beat, os.path.getmtime(self.heartbeat_path(host)))
        except OSError:
            pass
        return (time.time() - beat) > self.heartbeat_timeout_s

    # ------------------------------------------------------------- topology
    def _slice_of(self, host):
        return self.slice_of.get(str(host), "0")

    def compute_topology(self, hosts, validate=True):
        """The surviving admissible topology for ``hosts`` — not just a
        world size. The model-sharding factors (tp/ep/pp/sp) are FIXED
        (a host loss cannot shrink tensor parallelism); what shrinks is
        dp — and, slice-aware, ``do``: the surviving data_outer degree
        is the number of slices still holding hosts, so a dead slice
        shrinks do by one while each surviving slice keeps its
        intra-slice dp. -> dict(world, dp, do, tp, ep, pipe, seq,
        hosts). ``validate`` raises WorldFailure when the factors do
        not divide the world, surviving slices are ragged (a data_outer
        mesh needs equal slice populations), or the elastic config
        rejects it."""
        world = len(hosts) * self.chips_per_host
        fixed = (self.tensor_parallel * self.expert_parallel
                 * self.pipe_parallel * self.seq_parallel)
        slice_pop = {}
        for h in hosts:
            sl = self._slice_of(h)
            slice_pop[sl] = slice_pop.get(sl, 0) + 1
        do = len(slice_pop) if self.slice_of else 1
        topo = {"world": world, "dp": world // fixed if fixed else 0,
                "do": do,
                "tp": self.tensor_parallel, "ep": self.expert_parallel,
                "pipe": self.pipe_parallel, "seq": self.seq_parallel,
                "hosts": list(hosts)}
        if not validate:
            return topo
        if fixed <= 0 or world % fixed != 0 or world // fixed < 1:
            raise WorldFailure(
                f"surviving world size {world} ({len(hosts)} hosts x "
                f"{self.chips_per_host} chips) is not divisible by the "
                f"fixed model-sharding factors tp*ep*pp*sp={fixed}: no "
                f"admissible topology")
        if self.slice_of and len(set(slice_pop.values())) > 1:
            raise WorldFailure(
                f"surviving slices are ragged ({slice_pop}): a "
                f"data_outer mesh needs equal slice populations — a "
                f"PARTIAL slice loss must drop the whole slice before "
                f"relaunch")
        return topo

    def worker_env(self, host):
        """Env the launcher should copy into ``host``'s worker so the
        engine's hot tier and heartbeat line up with the agent's view
        of the ring."""
        env = {}
        if self.heartbeat_timeout_s is not None:
            env["DSTPU_HEARTBEAT_FILE"] = self.heartbeat_path(host)
        if self.hot_root:
            env["DSTPU_HOT_TIER_ROOT"] = self.hot_root
            env["DSTPU_HOT_NODE"] = str(host)
            env["DSTPU_HOT_PEERS"] = ",".join(str(h) for h in self.hosts)
            if self.slice_of:
                env["DSTPU_HOT_SLICE"] = self._slice_of(host)
                env["DSTPU_HOT_SLICES"] = ",".join(
                    self._slice_of(h) for h in self.hosts)
        if self.flightrec_root:
            env["DSTPU_FLIGHTREC_DIR"] = self.flightrec_root
            env["DSTPU_FLIGHTREC_NODE"] = str(host)
        return env

    # ------------------------------------------------------------ internals
    def _validate_world(self, hosts):
        if len(hosts) < max(1, self.min_hosts):
            raise WorldFailure(
                f"only {len(hosts)} hosts left (< min_hosts="
                f"{max(1, self.min_hosts)})")
        self.topology = self.compute_topology(hosts)
        if self.ds_config and "elasticity" in self.ds_config:
            world = len(hosts) * self.chips_per_host
            try:
                compute_elastic_config(self.ds_config, world_size=world)
            except ElasticityError as e:
                raise WorldFailure(
                    f"world size {world} not admissible under the elastic "
                    f"config: {e}") from e

    @staticmethod
    def _classify(rc, hung):
        if hung:
            return FAILURE_HUNG
        if rc == CORRUPT_CKPT_EXIT_CODE:
            return FAILURE_CORRUPT
        if rc == PREEMPTED_EXIT_CODE:
            return FAILURE_PREEMPTED
        return FAILURE_DEAD

    def _supervise(self, procs):
        """Block until every worker exits. On the FIRST failure, terminate
        the rest (a jax.distributed world is all-or-nothing). A worker
        that HANGS (no heartbeat within heartbeat_timeout_s) is killed
        and counted as failed — same recovery path as a dead one.
        Returns (ok, failures) with failures a dict host -> class."""
        live = dict(procs)
        self._live_procs = live
        failures = {}
        launched_at = time.time()
        while live:
            for host, p in list(live.items()):
                rc = p.poll()
                if rc is None:
                    if self._hung(host, launched_at):
                        logger.warning(
                            f"elastic agent: worker on {host} missed its "
                            f"heartbeat for > {self.heartbeat_timeout_s}s"
                            f"; killing hung worker")
                        try:
                            p.kill()
                            p.wait(timeout=5)   # reap, no zombie
                        except Exception:  # noqa: BLE001
                            pass
                        del live[host]
                        failures[host] = FAILURE_HUNG
                    continue
                del live[host]
                if rc != 0:
                    kind = self._classify(rc, hung=False)
                    logger.warning(
                        f"elastic agent: worker on {host} exited "
                        f"rc={rc} ({kind})")
                    failures[host] = kind
            if failures and live:
                logger.warning(
                    f"elastic agent: tearing down {len(live)} surviving "
                    "workers for world restart")
                for p in live.values():
                    p.terminate()
                deadline = time.time() + 10
                for p in live.values():
                    try:
                        p.wait(timeout=max(0.1, deadline - time.time()))
                    except Exception:  # noqa: BLE001
                        p.kill()
                live.clear()
            if live:
                time.sleep(self.poll_s)
        self._live_procs = {}
        return (not failures), failures

    def install_sigterm_forwarding(self):
        """Forward a SIGTERM delivered to the AGENT to every live
        worker: each worker's preempt-drain handler finishes its
        in-flight step, forces a hot+replica push + flight dump, and
        exits PREEMPTED_EXIT_CODE — which this agent classifies as
        'preempted' (host kept, zero backoff). Main-thread only (signal
        module restriction); ``run()`` calls this, and it is safe to
        call when no workers are live. Returns True when installed."""
        import signal
        import threading
        if threading.current_thread() is not threading.main_thread():
            return False

        def _forward(signum, frame):
            # signal context: flag + kill only, no logging/IO
            self._preempt_notice = True
            for p in list(self._live_procs.values()):
                try:
                    p.terminate()
                except Exception:  # noqa: BLE001
                    pass

        try:
            signal.signal(signal.SIGTERM, _forward)
            return True
        except (ValueError, OSError):
            return False

    def _attach_flight_records(self, failures):
        """Read each failed host's flight-recorder dump and attach the
        event tail to the classification: the victim's last completed
        steps, fired fault points, and the tier its generation restored
        from — the difference between 'host 3 exited 1' and a lead."""
        self.last_failure_records = {}
        if not self.flightrec_root:
            return
        from ..monitor import flight_recorder
        for host, kind in failures.items():
            rec = flight_recorder.read_dump(self.flightrec_root, host)
            if rec is None:
                logger.info(
                    f"elastic agent: no flight-recorder dump for failed "
                    f"host {host} under {self.flightrec_root}")
                continue
            self.last_failure_records[host] = rec
            tail = rec.get("events", [])[-8:]
            summary = ", ".join(
                e.get("kind", "?")
                + (f"({e['point']})" if e.get("kind") == "fault_point"
                   else f"(tier={e['tier']})" if e.get("kind") == "restore"
                   else "")
                for e in tail)
            logger.warning(
                f"elastic agent: flight record of {host} ({kind}, "
                f"dump reason={rec.get('reason')!r}): last events "
                f"[{summary}]")

    def _handle_membership_change(self, failures):
        """Classify, drop dead/hung hosts (keeping corrupt-checkpoint
        and preempted ones — their HOST is healthy), refine host losses
        into slice losses when slice-aware, purge the hot-tier stores
        of the hosts whose RAM is gone, and apply the per-class
        backoff. Slice refinement: a slice whose EVERY host failed is a
        ``dead_slice`` (one 'slice_loss' fault point per slice, do
        shrinks by one); a slice that lost only SOME hosts is dropped
        WHOLE anyway — a data_outer mesh needs equal slice populations,
        so the stranded healthy hosts cannot rejoin this world."""
        failures = dict(failures)
        lost = {h for h, kind in failures.items()
                if kind in (FAILURE_DEAD, FAILURE_HUNG)}
        if self.slice_of and lost:
            by_slice = {}
            for h in self.hosts:
                by_slice.setdefault(self._slice_of(h), []).append(h)
            for sl, members in sorted(by_slice.items()):
                hit = [h for h in members if h in lost]
                if not hit:
                    continue
                if len(hit) == len(members):
                    fault_injection.fire("slice_loss")
                    for h in members:
                        failures[h] = FAILURE_DEAD_SLICE
                    logger.warning(
                        f"elastic agent: slice {sl} fully lost "
                        f"({members}): dead_slice — data_outer shrinks "
                        f"by one; surviving slices' replicas are the "
                        f"restore source")
                else:
                    stranded = [h for h in members if h not in lost]
                    lost.update(stranded)
                    logger.warning(
                        f"elastic agent: slice {sl} partially lost "
                        f"({hit} of {members}): dropping the whole "
                        f"slice — a data_outer mesh needs equal slice "
                        f"populations, so {stranded} cannot rejoin "
                        f"this world")
                lost.update(members)
        self.last_failures = dict(failures)
        self._attach_flight_records(failures)
        for h in sorted(lost):
            fault_injection.fire("host_loss")
            if self.hot_root:
                from ..runtime.checkpoint_engine import hot_tier
                hot_tier.purge_node(self.hot_root, h)
                logger.info(
                    f"elastic agent: purged hot-tier store of lost host "
                    f"{h} (its replicas on survivors are the restore "
                    f"source)")
        self.hosts = [h for h in self.hosts if h not in lost]
        backoff = max((self.restart_backoff_s.get(kind, 0.0)
                       for kind in failures.values()), default=0.0)
        if backoff > 0:
            kinds = sorted(set(failures.values()))
            logger.warning(
                f"elastic agent: backing off {backoff:.1f}s before "
                f"relaunch (failure classes: {kinds})")
            time.sleep(backoff)

    def _launch(self, hosts):
        """Call launch_fn with the surviving topology when it accepts a
        second POSITIONAL argument (back-compat: single-argument
        launchers — including ones with **kwargs or keyword-only extras
        — are still called with hosts alone)."""
        try:
            params = inspect.signature(self.launch_fn).parameters
            positional = [
                p for p in params.values()
                if p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                              inspect.Parameter.POSITIONAL_OR_KEYWORD)]
            takes_topology = len(positional) >= 2
        except (TypeError, ValueError):
            takes_topology = False
        if takes_topology:
            return self.launch_fn(list(hosts), dict(self.topology))
        return self.launch_fn(list(hosts))

    # ---------------------------------------------------------------- run
    def run(self):
        """Launch and supervise until clean exit. Returns the final host
        list. Raises WorldFailure when recovery is impossible."""
        self._validate_world(self.hosts)
        self.install_sigterm_forwarding()
        while True:
            gen = self.restart_count
            logger.info(
                f"elastic agent: launching generation {gen} on "
                f"{len(self.hosts)} hosts "
                f"(dp={self.topology['dp']} tp={self.topology['tp']} "
                f"ep={self.topology['ep']})")
            self._clear_heartbeats(self.hosts)
            procs = self._launch(self.hosts)
            ok, failures = self._supervise(procs)
            if self._preempt_notice:
                self._preempt_notice = False
                logger.warning(
                    "elastic agent: SIGTERM forwarded to workers "
                    "(preemption notice); drained workers relaunch "
                    "with zero backoff")
            if ok:
                return list(self.hosts)
            self._handle_membership_change(failures)
            self.restart_count += 1
            if self.restart_count > self.max_restarts:
                raise WorldFailure(
                    f"restart budget exhausted ({self.max_restarts})")
            self._validate_world(self.hosts)
            if self.on_restart is not None:
                self.on_restart(self.restart_count, list(self.hosts))
