from .elasticity import (compute_elastic_config, get_compatible_chips_v01,
                         get_compatible_chips_v02, ElasticityError,
                         ElasticityConfig, ElasticityIncompatibleWorldSize)
