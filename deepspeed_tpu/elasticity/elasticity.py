"""Elastic training configuration.

Counterpart of reference ``elasticity/elasticity.py``
(``_get_compatible_gpus_v01:83``, ``v02:126``,
``compute_elastic_config:233``): given the set of acceptable micro-batch
sizes and a max acceptable global batch, compute the global batch size
compatible with the largest set of chip counts, so training can restart at
a different pod size without changing the effective batch (the reference's
enforced-immutability contract). Pure arithmetic — ports semantically.

v0.2 adds slice granularity (``chips_per_slice``, the analogue of
num_gpus_per_node) and model-parallel divisibility.
"""

import math
from dataclasses import dataclass, field

import numpy as np


class ElasticityError(Exception):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


@dataclass
class ElasticityConfig:
    """reference elasticity/config.py ElasticityConfig."""
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: list = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.2
    prefer_larger_batch: bool = True
    ignore_non_elastic_batch_info: bool = False
    # v0.2 knobs (reference num_gpus_per_node / model_parallel_size)
    num_gpus_per_node: int = 1
    model_parallel_size: int = 1
    # non-reference escape hatch: admit world sizes smaller than one
    # slice/node (single-host debugging); the reference accepts whole-node
    # multiples only
    allow_partial_slice: bool = False

    @classmethod
    def from_dict(cls, d):
        known = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in (d or {}).items() if k in known})


def _candidate_batch_sizes(base_list, max_batch):
    candidates = set()
    for base in base_list:
        if base <= 0 or base > max_batch:
            continue
        candidates.add((max_batch // base) * base)
    return sorted(candidates)


def _valid_chip_counts(batch_size, micro_batches, min_chips, max_chips):
    """Chip counts n where batch_size == micro * grad_accum * n for some
    acceptable micro batch (reference get_valid_gpus)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        total_steps = batch_size // mb  # micro-steps across chips
        for n in range(min_chips, min(max_chips, total_steps) + 1):
            if total_steps % n == 0:
                valid.add(n)
    return sorted(valid)


def get_compatible_chips_v01(micro_batches, max_acceptable_batch_size,
                             min_chips=None, max_chips=None,
                             prefer_larger=True):
    """reference _get_compatible_gpus_v01: candidate batches from each
    micro batch and their LCM; pick the one compatible with the most chip
    counts (ties: larger/smaller batch per ``prefer_larger``)."""
    min_chips = min_chips or 1
    if max_chips is None:
        max_chips = max_acceptable_batch_size // min(micro_batches)
    # max_chips == 0 is a REAL bound (e.g. max_gpus < model_parallel_size
    # rescaled to DP units) and yields an empty valid set, not the default
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ElasticityError(
            "all micro batches must be <= max_acceptable_batch_size "
            f"{max_acceptable_batch_size}")
    lcm = int(np.lcm.reduce(micro_batches))
    base_list = list(micro_batches) + [lcm]
    best = (None, [])
    for cand in _candidate_batch_sizes(base_list,
                                       max_acceptable_batch_size):
        valid = _valid_chip_counts(cand, micro_batches, min_chips,
                                   max_chips)
        better = len(valid) > len(best[1])
        tie = len(valid) == len(best[1]) and best[0] is not None
        if better or (tie and ((cand > best[0]) == prefer_larger)):
            best = (cand, valid)
    return best


def get_compatible_chips_v02(micro_batches, max_acceptable_batch_size,
                             current_num_chips, min_chips=None,
                             max_chips=None, prefer_larger=True,
                             chips_per_slice=1, model_parallel_size=1,
                             allow_partial_slice=False):
    """reference _get_compatible_gpus_v02: v0.1 math over DP-equivalent
    chips, then rescale by model parallelism and keep only counts that are
    whole slices (``allow_partial_slice`` additionally admits sub-slice
    worlds for single-host debugging; the reference accepts whole-node
    multiples only)."""
    if model_parallel_size > 1:
        group_size = chips_per_slice * model_parallel_size
        if current_num_chips % group_size != 0:
            raise ElasticityIncompatibleWorldSize(
                f"world size {current_num_chips} not divisible by "
                f"chips_per_slice*mp = {group_size}")
        # chip bounds rescale to DP-replica units under model parallelism
        mp = model_parallel_size
        min_dp = -(-(min_chips or 1) // mp)
        max_dp = (max_chips // mp) if max_chips is not None else None
        batch, valid_dp = get_compatible_chips_v01(
            micro_batches, max_acceptable_batch_size,
            min_chips=min_dp, max_chips=max_dp,
            prefer_larger=prefer_larger)
        valid = [v * mp for v in valid_dp]
    else:
        batch, valid = get_compatible_chips_v01(
            micro_batches, max_acceptable_batch_size,
            min_chips=min_chips, max_chips=max_chips,
            prefer_larger=prefer_larger)
    valid = [v for v in valid
             if v % chips_per_slice == 0
             or (allow_partial_slice and v < chips_per_slice)]
    return batch, valid


def compute_elastic_config(ds_config, target_version=0.2, world_size=0,
                           return_microbatch=False):
    """reference compute_elastic_config:233 — resolve (final batch,
    valid chip counts[, micro batch for this world size]) from the
    'elasticity' block of a config dict."""
    if "elasticity" not in ds_config:
        raise ElasticityError("no 'elasticity' block in config")
    cfg = ElasticityConfig.from_dict(ds_config["elasticity"])
    if not cfg.enabled:
        raise ElasticityError("elasticity.enabled is false")
    if float(cfg.version) >= 0.2:
        final_batch, valid = get_compatible_chips_v02(
            cfg.micro_batch_sizes, cfg.max_train_batch_size,
            current_num_chips=world_size or cfg.min_gpus,
            min_chips=cfg.min_gpus, max_chips=cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch,
            chips_per_slice=cfg.num_gpus_per_node,
            model_parallel_size=cfg.model_parallel_size,
            allow_partial_slice=cfg.allow_partial_slice)
    else:
        final_batch, valid = get_compatible_chips_v01(
            cfg.micro_batch_sizes, cfg.max_train_batch_size,
            min_chips=cfg.min_gpus, max_chips=cfg.max_gpus,
            prefer_larger=cfg.prefer_larger_batch)
    if world_size > 0 and world_size not in valid:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not in valid set {valid} for batch "
            f"{final_batch}")
    if not return_microbatch:
        return final_batch, valid
    # largest acceptable micro batch that divides a DP replica's share
    # (the batch splits over DP replicas, not over model-parallel chips)
    micro = None
    if world_size > 0:
        mp = cfg.model_parallel_size if float(cfg.version) >= 0.2 else 1
        dp = max(1, world_size // mp)
        per_replica = final_batch // dp
        for mb in sorted(cfg.micro_batch_sizes, reverse=True):
            if per_replica % mb == 0:
                micro = mb
                break
    return final_batch, valid, micro
