"""Multi-host launcher — the ``dstpu`` CLI.

Counterpart of reference ``launcher/runner.py:388 main`` (the ``deepspeed``
command): parse a hostfile (fetch_hostfile:200), apply --include/--exclude
filters (:255), pick a multi-node runner (PDSH/ssh), and start one worker
per HOST. TPU difference from the CUDA design: JAX is one PROCESS per host
driving all local chips (multi-controller SPMD), so there is no per-rank
``launch.py`` fan-out — each host runs the user script once with
``COORDINATOR_ADDRESS/NUM_PROCESSES/PROCESS_ID`` env for
``jax.distributed.initialize`` (comm/comm.py:130 init_distributed reads
these). ``--num_hosts 1`` (default with no hostfile) just execs locally.
"""

import argparse
import os
import shlex
import subprocess
import sys

from ..utils.logging import logger

DEFAULT_COORD_PORT = 8476


def fetch_hostfile(path, with_slices=False):
    """Parse a DeepSpeed-style hostfile: ``hostname slots=N [slice=K]``
    per line, '#' comments. Returns ordered {hostname: slots} (slots =
    TPU chips on that host; informational for JAX, which discovers local
    chips itself). The optional ``slice=K`` token records which TPU
    slice the host belongs to (multi-slice pods over DCN); with
    ``with_slices=True`` the return is ``({host: slots}, {host: slice})``
    where the slice map only holds hosts that declared one — the
    elastic agent uses it for cross-slice replica placement and
    dead-slice classification.
    """
    resource_pool = {}
    slice_map = {}
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            host = parts[0]
            slots = 0
            for tok in parts[1:]:
                if tok.startswith("slots="):
                    slots = int(tok.split("=", 1)[1])
                elif tok.startswith("slice="):
                    slice_map[host] = tok.split("=", 1)[1]
                else:
                    raise ValueError(
                        f"{path}:{ln}: malformed line {line!r} "
                        "(want 'host slots=N [slice=K]')")
            if host in resource_pool:
                raise ValueError(f"{path}:{ln}: duplicate host {host}")
            resource_pool[host] = slots
    if with_slices:
        return resource_pool, slice_map
    return resource_pool


def parse_inclusion_exclusion(resource_pool, include_str="",
                              exclude_str=""):
    """Apply ``--include``/``--exclude`` host filters (reference
    runner.py:255 parse_resource_filter, host-granularity; TPU chips are
    not individually maskable from the launcher). Syntax:
    ``host1@host2`` selects hosts; '@' separates entries."""
    if include_str and exclude_str:
        raise ValueError("--include and --exclude are mutually exclusive")
    hosts = list(resource_pool)

    def split(s):
        out = []
        for part in s.split("@"):
            part = part.strip()
            if not part:
                continue
            if part not in resource_pool:
                raise ValueError(f"unknown host {part!r} in filter")
            out.append(part)
        return out

    if include_str:
        keep = split(include_str)
        return {h: resource_pool[h] for h in hosts if h in keep}
    if exclude_str:
        drop = split(exclude_str)
        return {h: resource_pool[h] for h in hosts if h not in drop}
    return dict(resource_pool)


def build_worker_cmds(hosts, coordinator, script, script_args,
                      env_passthrough=(), extra_env=None,
                      per_host_env=None):
    """One (host, argv, env) per host. env carries the jax.distributed
    rendezvous triplet. ``per_host_env``: optional ``host -> dict``
    (the elastic agent's ``worker_env`` — heartbeat file + hot-tier
    ring — differs per host)."""
    cmds = []
    n = len(hosts)
    for pid, host in enumerate(hosts):
        env = {
            "COORDINATOR_ADDRESS": coordinator,
            "NUM_PROCESSES": str(n),
            "PROCESS_ID": str(pid),
        }
        if extra_env:
            env.update(extra_env)
        if per_host_env is not None:
            env.update(per_host_env(host))
        for k in env_passthrough:
            if k in os.environ:
                env[k] = os.environ[k]
        argv = [sys.executable, script] + list(script_args)
        cmds.append((host, argv, env))
    return cmds


def _compose_remote_cmd(argv, env, extra_prefix=""):
    """'cd <cwd> && EXPORTS [prefix] argv...' — the one remote command
    string every runner hands to its transport."""
    exports = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
    return (f"cd {shlex.quote(os.getcwd())} && {exports} "
            + (extra_prefix + " " if extra_prefix else "")
            + " ".join(shlex.quote(a) for a in argv))


class PDSHRunner:
    """reference multinode_runner.py:51 — pdsh fan-out."""

    def __init__(self, args):
        self.args = args

    def available(self):
        from shutil import which
        return which("pdsh") is not None

    def launch(self, cmds):
        procs = []
        for host, argv, env in cmds:
            remote = _compose_remote_cmd(argv, env)
            procs.append(subprocess.Popen(
                ["pdsh", "-R", "ssh", "-w", host, remote]))
        return procs


class SSHRunner:
    """Plain ssh fan-out (covers the reference's OpenMPI/MVAPICH role of
    'just start my processes' without an MPI dependency)."""

    def __init__(self, args):
        self.args = args

    def available(self):
        return True

    def launch(self, cmds):
        procs = []
        for host, argv, env in cmds:
            remote = _compose_remote_cmd(argv, env)
            if host in ("localhost", "127.0.0.1"):
                procs.append(subprocess.Popen(
                    ["bash", "-c", remote]))
            else:
                # -tt forces a pty so killing the local ssh client HUPs the
                # remote session (otherwise a compute-bound worker only
                # dies on its next write to the closed socket)
                procs.append(subprocess.Popen(["ssh", "-tt", host, remote]))
        return procs


class SlurmRunner:
    """reference multinode_runner.py:340 SlurmRunner — one ``srun`` fans
    the whole job out instead of per-host ssh sessions. Per-process rank
    comes from ``SLURM_PROCID`` at runtime (srun starts all tasks with
    identical argv), so the worker env maps it onto ``PROCESS_ID`` for
    ``jax.distributed.initialize``."""

    def __init__(self, args):
        self.args = args

    def available(self):
        from shutil import which
        return which("srun") is not None

    def build_cmd(self, cmds):
        """Compose the single srun invocation from per-host worker cmds.

        Rank AND coordinator both come from Slurm's runtime view: srun
        orders --nodelist nodes its own way (sorted, not as given), so a
        statically chosen coordinator host could differ from the node
        SLURM_PROCID 0 lands on — and jax.distributed starts the
        coordinator service on process 0. Resolving the first job node
        via scontrol inside the task keeps the two consistent."""
        hosts = [h for h, _, _ in cmds]
        _, argv, env = cmds[0]
        port = env.get("COORDINATOR_ADDRESS", ":8476").rsplit(":", 1)[-1]
        env = {k: v for k, v in env.items()
               if k not in ("PROCESS_ID", "COORDINATOR_ADDRESS")}
        prefix = ("PROCESS_ID=$SLURM_PROCID COORDINATOR_ADDRESS="
                  '$(scontrol show hostnames "$SLURM_JOB_NODELIST" '
                  f"| head -n1):{port} exec")
        inner = _compose_remote_cmd(argv, env, extra_prefix=prefix)
        return ["srun", f"--nodes={len(hosts)}", f"--ntasks={len(hosts)}",
                "--ntasks-per-node=1", f"--nodelist={','.join(hosts)}",
                "bash", "-c", inner]

    def launch(self, cmds):
        return [subprocess.Popen(self.build_cmd(cmds))]


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="dstpu", description="DeepSpeed-TPU multi-host launcher")
    parser.add_argument("-H", "--hostfile", default=None,
                        help="'host slots=N' lines; omit for single-host")
    parser.add_argument("-i", "--include", default="",
                        help="host filter, e.g. host1@host2")
    parser.add_argument("-e", "--exclude", default="",
                        help="host filter, e.g. host3")
    parser.add_argument("--master_addr", default=None,
                        help="coordinator host (default: first host)")
    parser.add_argument("--master_port", type=int,
                        default=DEFAULT_COORD_PORT)
    parser.add_argument("--launcher", default="ssh",
                        choices=["ssh", "pdsh", "slurm"])
    parser.add_argument("--env", action="append", default=[],
                        help="env var names to pass through to workers")
    parser.add_argument("--elastic", action="store_true",
                        help="supervise workers and restart the world on "
                             "membership change (reference ds_elastic / "
                             "DSElasticAgent)")
    parser.add_argument("--max_elastic_restarts", type=int, default=10)
    parser.add_argument("--elastic_hot_root", default="",
                        help="hot-tier store root exported to workers "
                             "(DSTPU_HOT_TIER_ROOT/NODE/PEERS; the "
                             "agent purges a dead host's store on "
                             "membership change). Empty = no hot-tier "
                             "ring wiring")
    parser.add_argument("--elastic_flightrec_root", default="",
                        help="flight-recorder dump dir exported to "
                             "workers (DSTPU_FLIGHTREC_DIR/NODE; also "
                             "arms telemetry 'auto'). On a membership "
                             "change the agent reads the failed hosts' "
                             "dumps and logs their event tails. Must "
                             "be on a shared filesystem with remote "
                             "hosts. Empty = no flight-record wiring")
    parser.add_argument("--elastic_heartbeat_timeout", type=float,
                        default=None,
                        help="seconds without a worker heartbeat before "
                             "it is killed as hung (default: hang "
                             "detection off)")
    parser.add_argument("--elastic_heartbeat_dir", default=None,
                        help="heartbeat file dir — MUST be on a "
                             "filesystem shared between the agent and "
                             "every worker; the agent refuses the /tmp "
                             "default with remote hosts")
    parser.add_argument("--min_hosts", type=int, default=1)
    parser.add_argument(
        "--autotuning", choices=["tune", "run"], default=None,
        help="autotune the script's config before (run) or instead of "
             "(tune) launching it (reference launcher/runner.py:359 "
             "deepspeed --autotuning). The script must accept "
             "--exp '<json>' and print one JSON result line — bench.py "
             "does.")
    parser.add_argument(
        "--autotuning_space", default=None,
        help="JSON file {knob: [values...]}; default: micro-batch + "
             "remat policy + flash block sizes for bench.py")
    parser.add_argument(
        "--autotuning_metric", default="value",
        help="result-JSON key to maximize (bench.py: 'value' = "
             "tokens/sec/chip)")
    parser.add_argument("--autotuning_trials", type=int, default=12)
    parser.add_argument("--autotuning_results",
                        default="autotuning_results")
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


# the VERDICT-named bench knobs: micro-batch, remat, flash blocks
DEFAULT_TUNING_SPACE = {
    "BENCH_MICRO_BS": [16, 24, 32],
    "BENCH_REMAT_POLICY": ["save_flash", "save_mid"],
    "BENCH_FLASH_BQ": [512, 1024],
    "BENCH_FLASH_BK": [512, 1024],
}


def run_autotuning(args, hosts=None):
    """``dstpu --autotuning {tune,run} script`` — drive the Autotuner's
    search through the ResourceManager over the host pool (localhost
    when no hostfile), each trial a subprocess of ``script --exp
    '<json>'`` whose last JSON stdout line is the result (reference
    launcher/runner.py:359-386 + autotuning/scheduler.py). Writes
    ``exps.jsonl``, ``best_config.json`` and ``report.txt`` under
    --autotuning_results; 'run' mode then launches the script with the
    winning knobs exported."""
    import json as _json
    from ..autotuning.scheduler import (Node, ResourceManager,
                                        SubprocessRunner)
    if args.autotuning_space:
        with open(args.autotuning_space) as f:
            space = _json.load(f)
    else:
        space = dict(DEFAULT_TUNING_SPACE)
    nodes = [Node(h, 1) for h in (hosts or ["localhost"])]
    rm = ResourceManager(nodes)
    runner = SubprocessRunner(args.script)
    best_exp, best_res, all_results = rm.run_model_based(
        space, runner, metric=args.autotuning_metric,
        max_trials=args.autotuning_trials)
    os.makedirs(args.autotuning_results, exist_ok=True)
    with open(os.path.join(args.autotuning_results, "exps.jsonl"),
              "w") as f:
        for exp, res in all_results:
            f.write(_json.dumps({"exp": exp, "result": res}) + "\n")
    with open(os.path.join(args.autotuning_results,
                           "best_config.json"), "w") as f:
        _json.dump(best_exp, f, indent=1)
    lines = [f"autotuning: {len(all_results)} trials over "
             f"{len(nodes)} node(s); metric={args.autotuning_metric}"]
    for exp, res in sorted(
            all_results,
            key=lambda er: float(er[1].get(args.autotuning_metric,
                                           float("-inf"))),
            reverse=True):
        val = res.get(args.autotuning_metric, res.get("error", "?"))
        lines.append(f"  {val}  {exp}")
    lines.append(f"best: {best_exp} -> "
                 f"{best_res.get(args.autotuning_metric)}")
    report = "\n".join(lines)
    with open(os.path.join(args.autotuning_results, "report.txt"),
              "w") as f:
        f.write(report + "\n")
    logger.info(report)
    return best_exp


def main(argv=None):
    args = parse_args(argv)
    if args.autotuning:
        hosts = None
        if args.hostfile is not None:
            pool = parse_inclusion_exclusion(
                fetch_hostfile(args.hostfile), args.include, args.exclude)
            hosts = list(pool)
        best = run_autotuning(args, hosts)
        if args.autotuning == "tune":
            return 0
        # 'run': export the winning knobs and FALL THROUGH to the normal
        # launch path — single-host exec or the hostfile ssh launch (env
        # passthrough carries the knobs to every worker)
        os.environ.update({k: str(v) for k, v in best.items()})
        args.env = list(args.env) + list(best.keys())
    if args.hostfile is None:
        # single host: exec in place; jax discovers local chips
        os.execvpe(sys.executable,
                   [sys.executable, args.script] + args.script_args,
                   os.environ.copy())

    pool, slice_map = fetch_hostfile(args.hostfile, with_slices=True)
    pool = parse_inclusion_exclusion(pool, args.include, args.exclude)
    if not pool:
        raise SystemExit("no hosts left after filters")
    hosts = list(pool)
    slice_map = {h: s for h, s in slice_map.items() if h in pool}
    coordinator = (f"{args.master_addr or hosts[0]}:{args.master_port}")
    cmds = build_worker_cmds(
        hosts, coordinator, args.script, args.script_args,
        env_passthrough=tuple(args.env) + ("PYTHONPATH", "JAX_PLATFORMS",
                                           "XLA_FLAGS"))
    if args.launcher == "slurm" and args.master_addr:
        logger.warning(
            "--master_addr is ignored with --launcher slurm: the "
            "coordinator must live where SLURM_PROCID 0 runs, which "
            "Slurm decides (resolved from SLURM_JOB_NODELIST at task "
            "startup)")
    if args.elastic and args.launcher == "slurm":
        # one srun proc stands for N hosts: per-host supervision (and
        # per-host blame on failure) is impossible — Slurm's own
        # requeue/--no-kill machinery owns that role there
        raise SystemExit(
            "--elastic requires a per-host launcher (ssh/pdsh); "
            "with SLURM use its native requeue instead")
    runner = {"pdsh": PDSHRunner, "slurm": SlurmRunner,
              "ssh": SSHRunner}[args.launcher](args)
    if not runner.available():
        raise SystemExit(f"launcher {args.launcher} not available")
    if args.elastic:
        from ..elasticity.elastic_agent import DSElasticAgent

        def launch_fn(world_hosts):
            coord = f"{args.master_addr or world_hosts[0]}:{args.master_port}"
            wc = build_worker_cmds(
                world_hosts, coord, args.script, args.script_args,
                env_passthrough=tuple(args.env) + (
                    "PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS"),
                extra_env={"ELASTIC_GENERATION": str(agent.restart_count)},
                # heartbeat file + hot-tier ring (DSTPU_HOT_*) — the
                # agent-side contract its docstring promises
                per_host_env=agent.worker_env)
            return list(zip(world_hosts, runner.launch(wc)))

        # hostfile slots = chips per host (uniform pods; the agent
        # validates the surviving world with them)
        slots = {pool[h] for h in hosts}
        agent = DSElasticAgent(launch_fn, hosts,
                               max_restarts=args.max_elastic_restarts,
                               min_hosts=args.min_hosts,
                               chips_per_host=(slots.pop() if
                                               len(slots) == 1 else 1),
                               hot_root=args.elastic_hot_root or None,
                               flightrec_root=(
                                   args.elastic_flightrec_root or None),
                               heartbeat_timeout_s=(
                                   args.elastic_heartbeat_timeout),
                               heartbeat_dir=args.elastic_heartbeat_dir,
                               # hostfile slice=K tokens: cross-slice
                               # replica placement + dead_slice class
                               slices=slice_map or None)
        agent.run()
        return 0
    logger.info(f"launching on {len(hosts)} hosts via {args.launcher}; "
                f"coordinator {coordinator}")
    procs = runner.launch(cmds)
    rc = 0
    try:
        for p in procs:
            rc |= p.wait()
    except KeyboardInterrupt:
        # kill-switch semantics (reference launch.py:118): tear everyone
        # down on interrupt so no stragglers hold the TPU
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait()
        raise
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
