"""deepspeed_tpu — TPU-native distributed training & inference framework.

Brand-new JAX/XLA/Pallas implementation of the capabilities of the
reference DeepSpeed fork (mauryaavinash95/DeepSpeed v0.13.3 +
VELOC/DataStates async checkpointing). Public surface mirrors the
reference's ``deepspeed/__init__.py``: ``initialize`` (:69),
``init_distributed`` (:42), ``add_config_arguments`` (:245).
"""

__version__ = "0.1.0"

from .utils import compat as _compat  # noqa: F401  (older-jax shims)

# DSTPU_COMM_OVERLAP=1: apply the comm-overlap XLA flag set (latency-
# hiding scheduler + async collectives; runtime/zero/overlap.py) NOW,
# before anything can initialize the backend — the only reliable point
# for launcher/bench subprocesses. No-op without the env var.
from .runtime.zero import overlap as _overlap
_overlap.apply_env_overlap_flags()

from . import comm
from .accelerator import get_accelerator
from .comm import init_distributed
from .runtime.config import DeepSpeedConfig
from .runtime.engine import DeepSpeedEngine
from .utils import groups, logger


def initialize(args=None, model=None, optimizer=None, model_parameters=None,
               training_data=None, lr_scheduler=None, topology=None,
               config=None, config_params=None, seed=0,
               dist_init_required=None):
    """Initialize the engine (reference deepspeed/__init__.py:69).

    Returns the reference's 4-tuple ``(engine, optimizer, dataloader,
    lr_scheduler)``. ``model`` is a functional model object
    (``init(rng) -> params``, ``loss(params, batch, rng=, train=)``,
    ``partition_specs(topology)``) — see ``deepspeed_tpu.models``.
    """
    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError("deepspeed_tpu.initialize needs a config "
                         "(dict or json path)")
    if dist_init_required is None or dist_init_required:
        init_distributed()

    engine = DeepSpeedEngine(model=model, config=config, optimizer=optimizer,
                             lr_scheduler=lr_scheduler, topology=topology,
                             seed=seed)

    dataloader = None
    if training_data is not None:
        from .runtime.dataloader import DeepSpeedDataLoader
        dataloader = DeepSpeedDataLoader(
            training_data, batch_size=engine.config.train_batch_size)
    return engine, engine.optimizer, dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Build an InferenceEngine (reference deepspeed/__init__.py:268).

    ``deepspeed_tpu.init_inference(model, tensor_parallel={"tp_size": 2},
    dtype="bfloat16")`` — TP sharding comes from the model's declarative
    ``partition_specs`` (the module_inject/AutoTP equivalent)."""
    from .inference.engine import InferenceEngine
    return InferenceEngine(model, config=config, **kwargs)


def add_config_arguments(parser):
    """argparse passthrough (reference deepspeed/__init__.py:245)."""
    group = parser.add_argument_group("DeepSpeed-TPU",
                                      "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="DeepSpeed-TPU json configuration file")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="accepted for launcher compatibility")
    return parser
