"""Mixtral-class model: Llama attention (GQA + rope) with a sparse MoE
SwiGLU FFN per block.

Counterpart of reference ``inference/v2/model_implementations/mixtral``
(FastGen's Mixtral support over moe_gather/moe_scatter + cutlass
moe_gemm). Here the expert FFN is the dropless grouped-GEMM pattern
(``lax.ragged_dot`` — the moe_gemm role): tokens sort by routed expert,
each expert multiplies exactly its contiguous group, outputs unsort and
combine by the top-k router weights. The same ``_mlp`` serves training,
the contiguous-cache decode, and ALL THREE v2 paged serving programs
(inherited from Llama — apply_paged_prefill/apply_paged_chunk/
apply_paged_decode call ``_mlp`` per layer, so the engine's
``expert_parallel > 1`` mesh routes every serving dispatch through the
ragged EP all_to_all below; attention rides Llama's paged Pallas
kernels under the same engine ``paged_kernel`` knob).

Training note: the router's load-balance aux loss is not threaded through
Llama's apply (serving-first model); use GPT2MoE for aux-loss-supervised
MoE training parity tests.
"""

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .llama import Llama, LlamaConfig, _rms_norm


@dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    moe_top_k: int = 2

    def num_params(self):
        base = super().num_params()
        # replace the dense SwiGLU (3 * D * F) with E experts + router
        L, D, F, E = self.n_layer, self.d_model, self.ffn_dim, \
            self.num_experts
        return base - L * 3 * D * F + L * (D * E + E * 3 * D * F)


MIXTRAL_TINY = MixtralConfig(n_layer=2, n_head=4, n_kv_heads=2, d_model=128,
                             max_seq_len=128, vocab_size=512, remat=False,
                             num_experts=4, moe_top_k=2)
MIXTRAL_8X7B = MixtralConfig(n_layer=32, n_head=32, n_kv_heads=8,
                             d_model=4096, d_ff=14336, max_seq_len=8192,
                             vocab_size=32000, num_experts=8, moe_top_k=2)


class Mixtral(Llama):
    """Params: Llama attention tensors; blocks swap wgate/wup/wdown for
      moe_gate (L,D,E), moe_w1 (L,E,D,F), moe_w3 (L,E,D,F),
      moe_w2 (L,E,F,D)   (w1=gate, w3=up, w2=down — Mixtral naming)."""

    def init(self, rng):
        cfg = self.config
        dt = jnp.dtype(cfg.dtype)
        params = super().init(rng)
        blocks = params["blocks"]
        for k in ("wgate", "wup", "wdown"):
            del blocks[k]
        L, D, F, E = cfg.n_layer, cfg.d_model, cfg.ffn_dim, cfg.num_experts
        ks = jax.random.split(jax.random.fold_in(rng, 17), 4)
        std = 0.02
        res_std = std / math.sqrt(2 * L)

        def nrm(key, shape, s=std):
            return (jax.random.normal(key, shape, jnp.float32) * s).astype(dt)

        # router stays fp32 (routing is precision-sensitive)
        blocks["moe_gate"] = (jax.random.normal(
            ks[0], (L, D, E), jnp.float32) * std)
        blocks["moe_w1"] = nrm(ks[1], (L, E, D, F))
        blocks["moe_w3"] = nrm(ks[2], (L, E, D, F))
        blocks["moe_w2"] = nrm(ks[3], (L, E, F, D), res_std)
        return params

    # fused weight-quant serving keeps the expert FFN weights quantized
    # (consumed by _grouped_swiglu_ffn -> grouped_swiglu_wq)
    _WQ_KEEP = ("moe_w1", "moe_w3", "moe_w2")

    def _moe_knobs(self):
        """(grouped_kernel, hierarchical, dcn_quantize, int8_matmul)
        from the engine-installed ``moe`` config block plus the
        QuantizeConfig int8-compute lever; module defaults when no
        engine installed one (direct model use)."""
        cfg = getattr(self, "_moe_cfg", None)
        q8 = getattr(self, "_moe_int8", False)
        if cfg is None:
            return "auto", "auto", False, q8
        return (cfg.grouped_kernel, cfg.hierarchical_a2a,
                cfg.dcn_quantize, q8)

    def partition_specs(self, topology=None):
        specs = super().partition_specs(topology)
        blocks = specs["blocks"]
        for k in ("wgate", "wup", "wdown"):
            del blocks[k]
        blocks["moe_gate"] = P(None, None, None)
        # experts over 'expert', FFN dim over 'tensor' (EP x TP); at pod
        # scale — a data_outer (DCN) axis and the hierarchical a2a
        # engaged — experts span the combined (outer, expert) shard grid
        # so the weight layout matches the two-stage exchange's in_specs
        # (the exchange reshards on mismatch, but then every serving
        # dispatch would pay the gather)
        eaxis = "expert"
        if topology is not None:
            from ..moe.sharded_moe import resolve_hierarchical_a2a
            _, hier_knob, _, _ = self._moe_knobs()
            if resolve_hierarchical_a2a(
                    hier_knob, topology.axis_size("data_outer"),
                    self.config.num_experts,
                    topology.axis_size("expert")):
                eaxis = ("data_outer", "expert")
        blocks["moe_w1"] = P(None, eaxis, None, "tensor")
        blocks["moe_w3"] = P(None, eaxis, None, "tensor")
        blocks["moe_w2"] = P(None, eaxis, "tensor", None)
        return specs

    def _mlp(self, x, layer):
        """Dropless top-k SwiGLU MoE over the flattened tokens.

        With an expert mesh axis > 1 the FFN routes through the explicit
        shard_map all_to_all path (moe/sharded_moe.py
        ``moe_swiglu_ragged_ep``): GSPMD silently mis-partitions
        ``lax.ragged_dot`` over expert-sharded weights (off-shard
        experts' rows come back garbage), so EP must be manual. TP-only
        ('tensor') sharding stays on the dense path — GSPMD handles it."""
        cfg = self.config
        B, T, D = x.shape
        E, k = cfg.num_experts, cfg.moe_top_k
        h = _rms_norm(x, layer["rms2"], cfg.rms_eps)
        grouped, hier, dcn_q, q8 = self._moe_knobs()
        mesh = jax.sharding.get_abstract_mesh()
        if not mesh.empty and mesh.shape.get("expert", 1) > 1:
            from ..moe.sharded_moe import moe_swiglu_ragged_ep
            y = moe_swiglu_ragged_ep(
                h, layer["moe_gate"], layer["moe_w1"], layer["moe_w3"],
                layer["moe_w2"], k=k, hierarchical=hier,
                dcn_quantize=dcn_q, grouped_kernel=grouped,
                int8_matmul=q8)
            return y.astype(x.dtype)
        xs = h.reshape(-1, D)
        S = xs.shape[0]

        logits = xs.astype(jnp.float32) @ layer["moe_gate"]
        probs = jax.nn.softmax(logits, axis=-1)
        weights, experts = jax.lax.top_k(probs, k)
        weights = weights / jnp.sum(weights, axis=-1, keepdims=True)

        flat_exp = experts.reshape(-1).astype(jnp.int32)
        flat_w = weights.reshape(-1).astype(x.dtype)
        x_rep = jnp.repeat(xs, k, axis=0)
        order = jnp.argsort(flat_exp, stable=True)
        xr = x_rep[order]
        group_sizes = jnp.bincount(flat_exp, length=E).astype(jnp.int32)

        from ..moe.sharded_moe import (_grouped_swiglu_ffn,
                                       resolve_grouped_params,
                                       resolve_moe_int8)
        w1 = layer["moe_w1"]
        F = w1.scale.shape[-1] if hasattr(w1, "scale") else w1.shape[-1]
        gp = resolve_grouped_params(grouped, S * k, E, D, F, xr.dtype)
        if q8:
            gp = dict(gp, int8=resolve_moe_int8(q8, S * k, E, D, F,
                                                xr.dtype))
        o = _grouped_swiglu_ffn(xr, w1, layer["moe_w3"],
                                layer["moe_w2"], group_sizes, gp)
        unsorted = jnp.zeros_like(o).at[order].set(o)
        y = jnp.sum((unsorted * flat_w[:, None]).reshape(S, k, D), axis=1)
        return y.astype(x.dtype).reshape(B, T, D)
