"""InternLM (v1) family — llama architecture with biased attention.

Counterpart of the reference's InternLM injection support
(module_inject/containers/internlm.py). InternLM-7B is exactly the
llama block with learned biases on the q/k/v AND output projections
(config.json ``bias: true``); the MLP and lm_head stay bias-free — the
granular ``o_bias`` knob expresses that where phi-style ``proj_bias``
would over-reach.
"""

from dataclasses import dataclass

from .llama import Llama, LlamaConfig


@dataclass(frozen=True)
class InternLMConfig(LlamaConfig):
    qkv_bias: bool = True
    o_bias: bool = True
    vocab_size: int = 103168


INTERNLM_TINY = InternLMConfig(n_layer=2, n_head=4, n_kv_heads=4,
                               d_model=128, max_seq_len=128,
                               vocab_size=512, remat=False)
# internlm-7b point (config.json: 32 layers, 32 heads, hidden 4096)
INTERNLM_7B = InternLMConfig(n_layer=32, n_head=32, n_kv_heads=32,
                             d_model=4096, d_ff=11008, max_seq_len=2048,
                             vocab_size=103168)

INTERNLM_PRESETS = {"tiny": INTERNLM_TINY, "internlm-7b": INTERNLM_7B}


class InternLM(Llama):
    """InternLM on the shared Llama machinery (see module docstring)."""

    def __init__(self, config: InternLMConfig):
        super().__init__(config)
